"""Cross-iteration fitness memo bank — the host tier (ISSUE 1 tentpole,
tier 2).

A fixed-capacity LRU mapping 64-bit tree content keys (hashing.py) to
full-dataset losses, scoped by a dataset fingerprint + loss config +
eval-path shape. The host loop (api.py) absorbs each iteration's
POST-SIMPLIFY population snapshot — the full-data rescore through the
scoring path, captured before constant optimization overwrites selected
losses with its own objective's (ULP-different on TPU) values — and
ships a device snapshot of the most-recently-used entries into the next
jitted iteration, where dedup.py answers matching trees without
evaluating them. Populations change slowly between iterations (npop
members, a handful replaced per cycle group), so the per-iteration
full-data rescore (simplify_population_islands) is mostly memo hits
after warm-up.

Keying / invalidation rules (docs/memo_bank.md):

* keys hash the full program INCLUDING constant bits — re-optimizing a
  tree's constants produces a new key, so BFGS passes invalidate
  naturally (the stale entry still correctly describes the OLD program
  and ages out of the LRU);
* the fingerprint covers X/y/weights bytes, the loss config (callables
  by live object identity — a name like '<lambda>' is not an identity),
  the working precision and the eval backend/kernel shape — a memoized
  loss is only ever replayed against the exact evaluation context it
  came from;
* only SCORING-PATH values enter the bank (the post-simplify snapshot);
  optimizer-written f_best values never do, and custom loss_function
  searches get no bank at all;
* minibatch (`batching=True` cycle) losses are NEVER absorbed or served:
  the absorb snapshot is always a full-data rescore, and the memo
  applies only to row_idx=None scoring (enforced in models/fitness.py),
  so a fresh minibatch draw can't collide with a full-data value;
* `invalidate(keys)` / `clear()` exist for callers that rewrite cval in
  place on a tree whose key they computed earlier (set_constants-style
  surgery outside the engine).

Thread-safety: none needed — the bank lives on the host loop's thread,
like the recorder.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

from .dedup import DeviceMemo
from .hashing import split_key, tree_hash_host


def dataset_fingerprint(X, y, weights, options) -> str:
    """Identity of one evaluation context: dataset bytes + loss config +
    working precision + eval-path shape. Two searches sharing a
    fingerprint may share a bank (get_memo_bank); anything that can
    change a full-data loss VALUE — even in ULPs — must change the
    fingerprint, or a served entry would differ from what the evaluator
    computes and break the bit-identity guarantee."""
    h = hashlib.blake2b(digest_size=16)
    for arr in (X, y, weights):
        if arr is None:
            h.update(b"\x00none")
        else:
            a = np.ascontiguousarray(arr)
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
    # op codes in a tree are INDICES into the operator set: identical
    # program bytes mean different programs under different operator
    # lists, so the set is part of the evaluation context
    h.update(repr(options.binary_operators).encode())
    h.update(repr(options.unary_operators).encode())
    loss = options.loss
    if isinstance(loss, str):
        h.update(loss.encode())
    else:
        # a callable's name is not its identity (every lambda is
        # '<lambda>'): key the live object via its process-lifetime
        # token (models/options.py::callable_token). Raw id() would be
        # reused after GC, letting a later distinct loss inherit a dead
        # one's fingerprint (srlint SR011). Tokens are process-local —
        # still wrong for anything persisted.
        from ..models.options import callable_token

        h.update(
            f"callable:{getattr(loss, '__name__', '')}:"
            f"{callable_token(loss)}".encode()
        )
    # a custom objective REPLACES the named loss at evaluation time but
    # lives in its own field — it must split banks too (srkey
    # fingerprint coverage caught this as a gap: two searches differing
    # only in loss_function would otherwise share a bank)
    if options.loss_function is not None:
        from ..models.options import callable_token

        h.update(
            f"loss_function:"
            f"{getattr(options.loss_function, '__name__', '')}:"
            f"{callable_token(options.loss_function)}".encode()
        )
    h.update(options.precision.encode())
    # different eval backends/kernel shapes may differ in reduction order
    # (interpreter vs Pallas, postfix vs instr): ULP-distinct contexts.
    # 'auto' is RESOLVED here the way dispatch_eval resolves it at the
    # bank's one serve/absorb site — the I*npop population rescore —
    # so two searches whose 'auto' lands on different kernels (different
    # npop, or CPU vs TPU process) never share a bank.
    backend = options.eval_backend
    if backend == "auto":
        from ..models.fitness import resolve_eval_backend_pallas

        rescore_batch = options.npopulations * options.npop
        backend = "pallas" if resolve_eval_backend_pallas(
            "auto", options.dtype, rescore_batch,
            int(np.asarray(y).shape[-1]),
            deterministic=options.row_shards > 1,
        ) else "jnp"
    # eval_rows_per_tile changes the jnp reduction order (tile-wise
    # partial sums — fitness._make_eval_loss_fn) so it is part of the
    # context; eval_bucket_ladder is deliberately ABSENT — bucketing is
    # bit-identical to the flat path, so banks are shared across ladders.
    h.update(
        f"{backend}:{options.kernel_program}:"
        f"{options.kernel_leaf_skip}:{options.row_shards}:"
        f"{options.eval_rows_per_tile}".encode()
    )
    # tenant-batched searches (serving/batched.py) rescore under vmap —
    # per-tenant values are bit-identical to the solo program's by the
    # serving contract, but the contexts are kept separate on principle:
    # a bank must never be shared between programs whose equality is a
    # TESTED invariant rather than a structural one
    h.update(f"tenants:{options.tenants}".encode())
    return h.hexdigest()


class FitnessMemoBank:
    """Fixed-capacity LRU of (tree content key -> full-data loss)."""

    def __init__(self, capacity: int = 65536, fingerprint: str = ""):
        if capacity < 1:
            raise ValueError("cache capacity must be >= 1")
        self.capacity = int(capacity)
        self.fingerprint = fingerprint
        self._entries: "OrderedDict[int, float]" = OrderedDict()
        self.n_absorbed = 0  # insert attempts (including refreshes)
        self.n_inserted = 0  # new keys added
        self.n_evicted = 0
        self.n_invalidated = 0

    def __len__(self) -> int:
        return len(self._entries)

    # -- write side ---------------------------------------------------------
    def absorb(self, keys, losses) -> int:
        """Insert/refresh (key, loss) pairs — the post-dispatch side of the
        bank. keys: uint64 array (tree_hash_host) or iterable of ints;
        losses: matching floats (inf is a valid value: a known-bad tree
        stays known-bad). NaN losses are skipped (a NaN never equals the
        evaluator's replayed output, so it must not be served). Returns
        the number of new keys inserted."""
        keys = np.atleast_1d(np.asarray(keys, np.uint64))
        losses = np.atleast_1d(np.asarray(losses, np.float64))
        new = 0
        for k, v in zip(keys.tolist(), losses.tolist()):
            self.n_absorbed += 1
            if v != v:  # NaN
                continue
            if k in self._entries:
                self._entries.move_to_end(k)
                self._entries[k] = v
                continue
            self._entries[k] = v
            self.n_inserted += 1
            new += 1
            if len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.n_evicted += 1
        return new

    def absorb_trees(self, trees, losses) -> int:
        """Hash a host-side TreeBatch and absorb its losses."""
        return self.absorb(tree_hash_host(trees), losses)

    # -- read side ----------------------------------------------------------
    def lookup(self, keys) -> Tuple[np.ndarray, np.ndarray]:
        """Host-side probe: (values float64, hit bool) per key. Hits are
        refreshed to most-recently-used."""
        keys = np.atleast_1d(np.asarray(keys, np.uint64))
        vals = np.zeros(keys.shape, np.float64)
        hits = np.zeros(keys.shape, bool)
        for i, k in enumerate(keys.tolist()):
            v = self._entries.get(k)
            if v is not None:
                self._entries.move_to_end(k)
                vals[i] = v
                hits[i] = True
        return vals, hits

    def device_snapshot(self, slots: int, dtype=np.float32) -> DeviceMemo:
        """The `slots` most-recently-used entries as a DeviceMemo (numpy
        leaves; jit consumes them as traced arguments, so a refreshed
        snapshot each iteration costs zero recompiles)."""
        import jax.numpy as jnp

        n = min(len(self._entries), int(slots))
        h1 = np.zeros((slots,), np.uint32)
        h2 = np.zeros((slots,), np.uint32)
        loss = np.zeros((slots,), dtype)
        if n:
            # newest n in oldest->newest order, without materializing the
            # whole LRU (O(n), not O(capacity), per iteration)
            from itertools import islice

            items = list(islice(reversed(self._entries.items()), n))[::-1]
            keys = np.array([k for k, _ in items], np.uint64)
            h1[:n], h2[:n] = split_key(keys)
            loss[:n] = np.array([v for _, v in items], np.float64).astype(
                dtype
            )
        return DeviceMemo(
            h1=h1, h2=h2, loss=loss, count=jnp.int32(n)
        )

    # -- invalidation -------------------------------------------------------
    def invalidate(self, keys) -> int:
        """Drop entries whose keys are listed (e.g. trees about to get
        their constants rewritten in place). Returns entries dropped."""
        keys = np.atleast_1d(np.asarray(keys, np.uint64))
        dropped = 0
        for k in keys.tolist():
            if self._entries.pop(k, None) is not None:
                dropped += 1
        self.n_invalidated += dropped
        return dropped

    def invalidate_trees(self, trees) -> int:
        return self.invalidate(tree_hash_host(trees))

    def clear(self) -> None:
        self.n_invalidated += len(self._entries)
        self._entries.clear()

    @property
    def stats(self) -> Dict[str, int]:
        return {
            "size": len(self._entries),
            "capacity": self.capacity,
            "absorbed": self.n_absorbed,
            "inserted": self.n_inserted,
            "evicted": self.n_evicted,
            "invalidated": self.n_invalidated,
        }


# -- bank registry: one bank per evaluation context, shared across searches
_BANKS: Dict[str, FitnessMemoBank] = {}
_MAX_BANKS = 8  # oldest context dropped past this (host memory bound)


def get_memo_bank(
    fingerprint: str, capacity: int = 65536
) -> FitnessMemoBank:
    """Bank for an evaluation context, created on first use. Repeated
    searches on the same (dataset, loss, precision) share one bank, so the
    cache is warm across equation_search calls, not just iterations."""
    bank = _BANKS.get(fingerprint)
    if bank is None:
        if len(_BANKS) >= _MAX_BANKS:
            _BANKS.pop(next(iter(_BANKS)))
        bank = _BANKS[fingerprint] = FitnessMemoBank(
            capacity=capacity, fingerprint=fingerprint
        )
    elif capacity > bank.capacity:
        # honor a raised cache_capacity knob on re-use (grow-only: a
        # lowered knob must not silently evict a warmer sibling's
        # entries mid-flight)
        bank.capacity = int(capacity)
    return bank


def clear_memo_banks() -> None:
    """Drop every registered bank (tests / benchmarks)."""
    _BANKS.clear()
