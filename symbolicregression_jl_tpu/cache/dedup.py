"""Intra-batch dedup + device memo lookup — the jitted tier of the memo
bank (ISSUE 1 tentpole, tier 1).

In tree-based GP a large fraction of each generation's candidates are
structural duplicates of trees already in the batch (tournament winners
repeat; do_nothing/failed mutations resubmit parents; crossover clones
subtrees). The reference tolerates this — per-tree Julia evals are cheap —
but here every duplicate burns a slot in the batched eval launch. This
module removes them *inside* the jitted cycle with static shapes:

    hash -> stable lexicographic (length, hash) sort -> exact-equality
    segmenting -> compact unique representatives to the front ->
    device-memo lookup on the representatives -> evaluate the remainder
    -> scatter every segment's loss back to all duplicates.

The sort is length-major (see _lex_order): the representative buffer
comes out grouped by program length, so the length-bucketed evaluator
(models/fitness.py) runs on it without a second sort.

Shape discipline: XLA needs static shapes, so the compact buffer keeps the
full batch size N; slots past the unique count U (and memo-hit slots) hold
`filler_trees` — length-1 constant programs. The lockstep jnp interpreter
prices every tree identically so fillers save nothing there, but the
Pallas kernel's length-bounded slot loop (ops/pallas_eval.py design note
3b) runs fillers in ONE step instead of ceil(max_len/4): on TPU the
eval-batch shrinkage telemetry translates into proportional kernel-time
shrinkage. Either way the dedup guarantees bit-identical losses — each
duplicate receives exactly the value the deterministic evaluator produces
for that program (per-tree computation is position-independent in both
backends).

Collision safety: the 64-bit hash is only the SORT KEY. Segment boundaries
come from exact comparison of the canonicalized program bytes, so two
distinct trees with equal hashes land in different segments and are both
evaluated — a collision costs a missed dedup, never a wrong loss. The
device-memo tier matches on the full 64-bit key (see hashing.py).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from .hashing import canonical_fields_device, tree_hash_device

Array = jax.Array


class DeviceMemo(NamedTuple):
    """Device-resident snapshot of the host LRU's most-recent entries.

    Fixed-capacity (K static, part of the compiled graph); `count` live
    entries occupy slots [0, count) — dead slots are excluded by index
    masking, so no hash sentinel can collide with a real key."""

    h1: Array  # (K,) uint32 — key lane 1
    h2: Array  # (K,) uint32 — key lane 2
    loss: Array  # (K,) working dtype — memoized full-data loss
    count: Array  # () int32 — live entries


class DedupStats(NamedTuple):
    """Per-call counters (int32 scalars; (I,) under per-island vmap)."""

    total: Array  # trees submitted for scoring
    unique: Array  # distinct programs found (segments)
    memo_hits: Array  # unique programs answered by the device memo


def empty_device_memo(slots: int, dtype=jnp.float32) -> DeviceMemo:
    return DeviceMemo(
        h1=jnp.zeros((slots,), jnp.uint32),
        h2=jnp.zeros((slots,), jnp.uint32),
        loss=jnp.zeros((slots,), dtype),
        count=jnp.int32(0),
    )


def _lex_order(length: Array, h1: Array, h2: Array) -> Array:
    """Stable argsort by (length, h1, h2) lexicographic — equal 64-bit
    keys (hence all copies of one program) end up adjacent, ties broken
    by original index so the permutation is deterministic.

    `length` is the OUTERMOST key on purpose: identical programs have
    identical lengths, so segmenting is unaffected, but the compacted
    representative buffer comes out grouped by program length — the exact
    ordering the length-bucketed evaluator wants (models/fitness.py
    eval_loss_trees_bucketed presorted=True). One sort serves both the
    dedup and the bucketing."""
    order = jnp.argsort(h2, stable=True)
    order = order[jnp.argsort(h1[order], stable=True)]
    return order[jnp.argsort(length[order], stable=True)]


def dedup_eval_losses(
    trees,
    eval_loss_fn: Callable,
    memo: Optional[DeviceMemo] = None,
):
    """Evaluate per-tree losses for a flat (N,) TreeBatch with intra-batch
    dedup and optional device-memo prefill. Jittable / vmappable.

    eval_loss_fn: TreeBatch (N,) -> loss (N,) — the full scoring closure
    (dispatch_eval + elementwise loss + aggregation + inf-on-incomplete).
    Returns (loss (N,), DedupStats). loss is bit-identical to
    eval_loss_fn(trees) as long as eval_loss_fn is deterministic per tree
    and memo entries hold values that evaluator produced (both hold for
    the interpreter/Pallas paths and the memo bank's absorb discipline).
    """
    from ..ops.interpreter import filler_trees

    N = trees.length.shape[0]
    h1, h2 = tree_hash_device(trees)
    order = _lex_order(trees.length, h1, h2)

    # exact-equality segmenting over the canonical program bytes
    kindm, opm, featm, cwords, length = canonical_fields_device(trees)
    kind_s, op_s, feat_s = kindm[order], opm[order], featm[order]
    cw_s, len_s = cwords[order], length[order]
    eq = (len_s[1:] == len_s[:-1])
    eq &= jnp.all(kind_s[1:] == kind_s[:-1], axis=-1)
    eq &= jnp.all(op_s[1:] == op_s[:-1], axis=-1)
    eq &= jnp.all(feat_s[1:] == feat_s[:-1], axis=-1)
    eq &= jnp.all(cw_s[1:] == cw_s[:-1], axis=(-2, -1))
    is_head = jnp.concatenate([jnp.ones((1,), jnp.bool_), ~eq])
    seg = jnp.cumsum(is_head.astype(jnp.int32)) - 1  # (N,) segment per pos
    n_unique = seg[-1] + 1

    # original index of each segment's representative, compacted to the
    # front of an N-slot buffer (heads scatter to their segment slot;
    # non-heads aim past the end and fall off)
    rep_src = (
        jnp.zeros((N + 1,), jnp.int32)
        .at[jnp.where(is_head, seg, N)]
        .set(order.astype(jnp.int32))[:N]
    )
    slot_live = jnp.arange(N, dtype=jnp.int32) < n_unique

    # device memo: answer representatives whose 64-bit key is memoized
    if memo is not None and memo.h1.shape[0] > 0:
        rh1, rh2 = h1[rep_src], h2[rep_src]
        live_k = jnp.arange(memo.h1.shape[0], dtype=jnp.int32) < memo.count
        m = (
            (rh1[:, None] == memo.h1[None, :])
            & (rh2[:, None] == memo.h2[None, :])
            & live_k[None, :]
        )
        hit = jnp.any(m, axis=1) & slot_live
        memo_loss = memo.loss[jnp.argmax(m, axis=1)]
    else:
        hit = jnp.zeros((N,), jnp.bool_)
        memo_loss = jnp.zeros((N,), trees.cval.dtype)

    # evaluate only live, non-hit representatives; everything else is the
    # cheapest valid program (see module note on the Pallas length bound)
    eval_mask = slot_live & ~hit
    fillers = filler_trees((N,), trees.kind.shape[-1], trees.cval.dtype)
    rep_trees = jax.tree_util.tree_map(lambda x: x[rep_src], trees)
    buf = jax.tree_util.tree_map(
        lambda r, f: jnp.where(
            jnp.reshape(eval_mask, eval_mask.shape + (1,) * (r.ndim - 1)),
            r, f,
        ),
        rep_trees,
        fillers,
    )
    loss_buf = eval_loss_fn(buf)  # (N,)
    seg_loss = jnp.where(hit, memo_loss.astype(loss_buf.dtype), loss_buf)

    # scatter each segment's loss to every duplicate's original position
    loss = jnp.zeros((N,), loss_buf.dtype).at[order].set(seg_loss[seg])
    stats = DedupStats(
        total=jnp.int32(N),
        unique=n_unique.astype(jnp.int32),
        memo_hits=jnp.sum(hit).astype(jnp.int32),
    )
    return loss, stats
