"""Warm-compile job server: admit -> pad -> bucket -> batch -> dispatch.

The front door of the serving tier (docs/serving.md). Jobs enter
through :meth:`JobServer.submit`, which runs the hostile-data front
door (``validate_dataset`` + ``sanitize_dataset`` under the server
Options' data_policy), quantizes the dataset onto a small pad ladder
(rows padded with explicit ZERO-WEIGHT rows — the weighted loss
normalizes by ``sum(weights)``, so zero-weight padding is exact;
features padded with zero rows), and files the job into a bucket keyed
by::

    (padded rows, padded features, opset, Options graph key,
     traced scalars)

Everything in the key shapes or parameterizes the compiled program:
two jobs sharing a bucket are served by ONE warm compile (the api.py
jit factories are lru-cached on exactly the Options graph key + mesh),
and the traced scalars are in the key because a batch shares one
scalar vector — without them, job 0's parsimony would silently apply
to everyone in the bucket.

:meth:`JobServer.flush` drains every bucket that has reached
``max_tenants`` fill, and (on timeout or ``force=True``) partially
filled buckets too; each batch dispatches through
:func:`..batched.batched_equation_search` (a 1-job batch routes
through the solo front door). Per-job results come back as
:class:`JobResult` with the job's own run id registered in the fleet
index (telemetry/fleet.py), and the queue exports
``srtpu_serve_queue_depth`` / ``srtpu_serve_bucket_fill`` /
``srtpu_serve_warm_hit_rate`` / ``srtpu_serve_job_latency_seconds``
through the OpenMetrics endpoint. :meth:`JobServer.alert_row` feeds
the ``queue_stalled`` rule (telemetry/alerts.py) a row describing the
oldest unbatched job.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models.dataset import sanitize_dataset, validate_dataset
from ..models.options import (
    TRACED_SCALAR_FIELDS,
    Options,
    make_options,
)
from .batched import batched_equation_search

# pad ladders: small enough that real traffic actually buckets, big
# enough that padding waste stays bounded (< 2x rows, < 2x features)
DEFAULT_ROW_LADDER: Tuple[int, ...] = (
    32, 64, 128, 256, 512, 1024, 2048, 4096, 8192,
)
DEFAULT_FEATURE_LADDER: Tuple[int, ...] = (1, 2, 4, 8, 16, 32)

_LATENCY_EDGES = (0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


def pad_to_ladder(n: int, ladder: Sequence[int]) -> int:
    """Smallest ladder rung >= n; past the last rung, the next power of
    two (quantization must never reject a job, only stop deduplicating
    compiles for outliers)."""
    if n <= 0:
        raise ValueError(f"size must be positive, got {n}")
    for rung in ladder:
        if n <= rung:
            return int(rung)
    p = 1
    while p < n:
        p *= 2
    return p


@dataclasses.dataclass
class _QueuedJob:
    job_id: str
    X: np.ndarray          # padded (f_pad, n_pad)
    y: np.ndarray          # padded (n_pad,)
    weights: np.ndarray    # padded (n_pad,), zeros on pad rows
    seed: int
    options: Options
    bucket: tuple
    submitted_at: float
    rows: int              # pre-pad
    features: int          # pre-pad
    diagnostics: dict


@dataclasses.dataclass
class JobResult:
    """One completed job: the solo-equivalent search result plus the
    serving provenance (bucket, batch fill, warm-compile flag, queue
    wait and end-to-end latency)."""

    job_id: str
    result: Any            # api.EquationSearchResult
    bucket: tuple
    tenants: int           # batch fill this job dispatched with
    warm: bool             # served by an already-warm compile
    queue_wait_s: float
    latency_s: float       # submit -> result


class JobServer:
    """Multi-tenant SR job queue over the batched engine.

    options: the server's per-tenant search Options (jobs may override
    via ``submit(..., options=)`` — different graph keys land in
    different buckets). niterations: iterations per job.
    max_tenants: bucket fill that triggers an immediate dispatch.
    flush_timeout_s: age at which a partially-filled bucket flushes.
    fleet_root: fleet directory — every job's run id is registered
    there (telemetry/fleet.py) and dispatch event logs land under it.
    registry: telemetry.metrics.MetricsRegistry for the
    ``srtpu_serve_*`` exposition. clock: injectable monotonic clock
    (tests drive timeout flushes without sleeping).
    """

    def __init__(
        self,
        options: Optional[Options] = None,
        *,
        niterations: int = 10,
        max_tenants: int = 4,
        flush_timeout_s: float = 2.0,
        row_ladder: Sequence[int] = DEFAULT_ROW_LADDER,
        feature_ladder: Sequence[int] = DEFAULT_FEATURE_LADDER,
        fleet_root: Optional[str] = None,
        registry=None,
        clock=time.monotonic,
        **option_kwargs,
    ):
        if options is None:
            options = make_options(**option_kwargs)
        elif option_kwargs:
            raise ValueError(
                "Pass either options= or option kwargs, not both"
            )
        if max_tenants < 1:
            raise ValueError("max_tenants must be >= 1")
        self.options = options
        self.niterations = int(niterations)
        self.max_tenants = int(max_tenants)
        self.flush_timeout_s = float(flush_timeout_s)
        self.row_ladder = tuple(row_ladder)
        self.feature_ladder = tuple(feature_ladder)
        self.fleet_root = fleet_root
        self.registry = registry
        self.clock = clock
        self._queue: Dict[tuple, List[_QueuedJob]] = {}
        self._ids = itertools.count()
        self._seen: set = set()      # (bucket, tenants) already compiled
        self._dispatches = 0
        self._warm_hits = 0
        self._completed: List[JobResult] = []
        if registry is not None:
            self._g_depth = registry.gauge(
                "serve_queue_depth",
                help="jobs admitted and not yet dispatched",
            )
            self._g_fill = registry.gauge(
                "serve_bucket_fill",
                help="fill ratio (tenants/max_tenants) of the last "
                     "dispatched batch",
            )
            self._g_warm = registry.gauge(
                "serve_warm_hit_rate",
                help="fraction of dispatches served by an "
                     "already-warm compile",
            )
            self._h_latency = registry.histogram(
                "serve_job_latency_seconds",
                list(_LATENCY_EDGES),
                help="submit-to-result latency per job",
            )
            self._g_depth.set(0)

    # ------------------------------------------------------------------
    def submit(
        self,
        X,
        y,
        weights=None,
        *,
        seed: Optional[int] = None,
        job_id: Optional[str] = None,
        options: Optional[Options] = None,
    ) -> str:
        """Admit one job; returns its job id (also its fleet run id).

        The dataset passes the hostile-data front door under the
        job Options' data_policy, then pads onto the ladder: rows with
        zero-weight rows (exact under the weighted loss), features
        with zero feature rows (a caveat, not exact: the mutation
        feature sampler sees the padded feature count —
        docs/serving.md)."""
        opts = options if options is not None else self.options
        host_dtype = (
            np.float64 if opts.precision == "float64" else np.float32
        )
        X = np.asarray(X, host_dtype)
        y = np.asarray(y, host_dtype)
        if X.ndim != 2:
            raise ValueError("X must be (nfeatures, n)")
        if y.ndim != 1:
            raise ValueError(
                "serving jobs are single-output: y must be (n,)"
            )
        if weights is not None:
            weights = np.asarray(weights, host_dtype)
        diags = validate_dataset(X, y[None, :], weights)
        X, ys, weights, diags = sanitize_dataset(
            X, y[None, :], weights, opts.data_policy, diags
        )
        X = np.asarray(X, host_dtype)
        y = np.asarray(ys[0], host_dtype)
        nfeat, n = X.shape

        # ---- shape quantization onto the pad ladder ----
        f_pad = pad_to_ladder(nfeat, self.feature_ladder)
        n_pad = pad_to_ladder(n, self.row_ladder)
        w = (
            weights if weights is not None
            else np.ones(n, host_dtype)
        )
        Xp = np.zeros((f_pad, n_pad), host_dtype)
        Xp[:nfeat, :n] = X
        yp = np.zeros(n_pad, host_dtype)
        yp[:n] = y
        wp = np.zeros(n_pad, host_dtype)
        wp[:n] = w

        opset = (
            tuple(opts.binary_operators), tuple(opts.unary_operators)
        )
        # traced scalars (parsimony etc.) don't shape the graph, but a
        # batch shares ONE scalar vector — jobs differing in any of
        # them must land in different buckets (host floats: the jnp
        # leaves traced_scalars() returns are unhashable)
        scalar_key = tuple(
            float(getattr(opts, f)) for f in TRACED_SCALAR_FIELDS
        )
        bucket = (
            n_pad, f_pad, opset, opts._graph_key(), scalar_key,
        )
        if job_id is None:
            job_id = f"job-{next(self._ids):06d}"
        job = _QueuedJob(
            job_id=job_id,
            X=Xp, y=yp, weights=wp,
            seed=int(seed if seed is not None else opts.seed),
            options=opts,
            bucket=bucket,
            submitted_at=self.clock(),
            rows=n, features=nfeat,
            diagnostics=diags.to_dict(),
        )
        self._queue.setdefault(bucket, []).append(job)
        self._set_queue_gauges()
        return job_id

    # ------------------------------------------------------------------
    def pending(self) -> int:
        return sum(len(v) for v in self._queue.values())

    def oldest_wait_s(self) -> Optional[float]:
        """Age of the oldest unbatched job (the queue_stalled signal)."""
        now = self.clock()
        ages = [
            now - j.submitted_at
            for jobs in self._queue.values() for j in jobs
        ]
        return max(ages) if ages else None

    @property
    def warm_hit_rate(self) -> float:
        return (
            self._warm_hits / self._dispatches if self._dispatches
            else 0.0
        )

    @property
    def completed(self) -> List[JobResult]:
        return list(self._completed)

    def stats(self) -> dict:
        return {
            "queue_depth": self.pending(),
            "oldest_wait_s": self.oldest_wait_s(),
            "dispatches": self._dispatches,
            "warm_hits": self._warm_hits,
            "warm_hit_rate": self.warm_hit_rate,
            "completed": len(self._completed),
            "buckets": len(self._queue),
        }

    def alert_row(self) -> dict:
        """One fleet-index-shaped row describing the queue, for
        telemetry.alerts.evaluate_alerts — the ``queue_stalled`` rule
        reads ``serve_queue_oldest_wait_s``."""
        return {
            "run_id": "srserve-queue",
            "serve_queue_depth": self.pending(),
            "serve_queue_oldest_wait_s": self.oldest_wait_s(),
            "serve_flush_timeout_s": self.flush_timeout_s,
        }

    # ------------------------------------------------------------------
    def flush(self, force: bool = False) -> List[JobResult]:
        """Dispatch every full bucket, plus (timeout or force) the
        partial ones; returns the newly completed jobs."""
        out: List[JobResult] = []
        now = self.clock()
        for bucket in list(self._queue):
            jobs = self._queue[bucket]
            while len(jobs) >= self.max_tenants:
                batch, self._queue[bucket] = (
                    jobs[: self.max_tenants], jobs[self.max_tenants:]
                )
                jobs = self._queue[bucket]
                out.extend(self._dispatch(bucket, batch))
            if jobs and (
                force
                or now - jobs[0].submitted_at >= self.flush_timeout_s
            ):
                self._queue[bucket] = []
                out.extend(self._dispatch(bucket, jobs))
            if not self._queue.get(bucket):
                self._queue.pop(bucket, None)
        self._set_queue_gauges()
        self._completed.extend(out)
        return out

    def drain(self) -> List[JobResult]:
        """Force-flush until the queue is empty; returns everything
        completed by this call."""
        out: List[JobResult] = []
        while self.pending():
            out.extend(self.flush(force=True))
        return out

    # ------------------------------------------------------------------
    def _dispatch(
        self, bucket: tuple, batch: List[_QueuedJob]
    ) -> List[JobResult]:
        T = len(batch)
        # a warm dispatch reuses a compiled program: the jit factories
        # are lru-cached on (Options graph key incl. tenants, shapes),
        # so the first (bucket, T) pays the compile and every later one
        # is a cache hit — the whole point of bucketing
        warm = (bucket, T) in self._seen
        self._seen.add((bucket, T))
        self._dispatches += 1
        self._warm_hits += int(warm)
        t0 = self.clock()
        telemetry_dir = None
        if self.fleet_root is not None:
            import os

            telemetry_dir = os.path.join(self.fleet_root, "srserve")
        results = batched_equation_search(
            [(j.X, j.y, j.weights) for j in batch],
            options=batch[0].options,
            seeds=[j.seed for j in batch],
            niterations=self.niterations,
            registry=self.registry,
            telemetry_dir=telemetry_dir,
        )
        t1 = self.clock()
        if self.registry is not None:
            self._g_fill.set(T / self.max_tenants)
            self._g_warm.set(self.warm_hit_rate)
        out = []
        for job, res in zip(batch, results):
            wait = t0 - job.submitted_at
            latency = t1 - job.submitted_at
            if self.registry is not None:
                self._h_latency.observe(latency)
            if self.fleet_root is not None:
                from ..telemetry.fleet import register_run

                best = [float(c.loss) for c in res.frontier()]
                register_run(
                    self.fleet_root,
                    source="srserve",
                    run_id=job.job_id,
                    telemetry_dir=telemetry_dir,
                    tenants=T,
                    bucket_rows=bucket[0],
                    bucket_features=bucket[1],
                    warm=warm,
                    queue_wait_s=wait,
                    latency_s=latency,
                    best_loss=min(best) if best else None,
                )
            out.append(
                JobResult(
                    job_id=job.job_id,
                    result=res,
                    bucket=bucket,
                    tenants=T,
                    warm=warm,
                    queue_wait_s=wait,
                    latency_s=latency,
                )
            )
        return out

    # ------------------------------------------------------------------
    def _set_queue_gauges(self):
        if self.registry is not None:
            self._g_depth.set(self.pending())
