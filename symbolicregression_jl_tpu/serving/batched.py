"""Tenant-batched search engine: many same-shape jobs, ONE program.

``batched_equation_search`` stacks T independent ``(X, y, weights)``
problems along a leading tenants axis and drives the SAME iteration
programs the solo search uses — ``Options.tenants > 1`` makes the
api.py jit factories vmap their per-tenant bodies, and
``parallel/mesh.py`` builds a ``(tenants, islands)`` mesh whose state
sharding composes as ``P('tenants', 'islands')``. The vmapped pattern
is the one Kozax demonstrates for many small GP searches in JAX: the
per-program fixed cost (dispatch, compile, host loop) is paid once for
the whole batch instead of once per job.

The contract that makes this a serving tier rather than an
approximation (docs/serving.md):

* **Bit-identity** — tenant t's hall of fame equals the solo
  ``equation_search`` run of the same Options (``tenants=1``) with
  ``seed=seeds[t]``, bit for bit, fused and chunked drivers alike.
  Threefry is elementwise in the key, so vmapping the unchanged
  per-tenant body over a batch of per-tenant key chains reproduces
  each tenant's solo draws exactly; migration/merge sharding
  constraints are dropped inside the vmapped body (constraints pin
  layout, never values) and tenant placement rides the jit in/out
  shardings.
* **Per-tenant PRNG chains** — tenant t's master key is
  ``PRNGKey(seeds[t])``, split per iteration exactly as the solo host
  loop splits its per-output key.
* **Per-tenant memo banks** — fingerprints carry ``options.tenants``
  (cache/memo.py), so batched banks never serve values into solo
  searches; each tenant absorbs only its own scoring-path snapshot.
* **Per-tenant telemetry** — one fused reduction per observed
  iteration yields every tenant's best loss and eval count; gauges are
  tenant-indexed (``serve_tenant_best_loss_<t>``) and the event log
  carries the arrays.

Same-Options only: a batch shares one compiled program, so every
tenant runs the same graph-shaping Options; per-job knobs that are
traced scalars would silently apply tenant 0's values to everyone,
which is why the job server keys buckets on the traced scalars too.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.dataset import (
    make_dataset,
    sanitize_dataset,
    update_baseline_loss,
    validate_dataset,
)
from ..models.options import Options, make_options
from ..parallel.mesh import (
    describe_mesh,
    make_mesh,
    shard_dataset,
    shard_island_states,
)
from ..parallel.migration import merge_hofs_across_islands
from ..utils.output import hof_to_candidates

Array = jax.Array


def _normalize_datasets(datasets) -> List[Tuple[Any, Any, Any]]:
    out = []
    for d in datasets:
        if isinstance(d, dict):
            out.append((d["X"], d["y"], d.get("weights")))
        elif len(d) == 3:
            out.append(tuple(d))
        elif len(d) == 2:
            out.append((d[0], d[1], None))
        else:
            raise ValueError(
                "each dataset must be (X, y), (X, y, weights), or a "
                "dict with keys X/y[/weights]"
            )
    if not out:
        raise ValueError("batched_equation_search needs >= 1 dataset")
    return out


def _slice_tree(tree, t: int):
    return jax.tree_util.tree_map(lambda a: a[t], tree)


@jax.jit
def _tenant_summary(states, ghof):
    """ONE fused reduction over the whole batch: per-tenant best HoF
    loss (inf when no slot exists yet) and per-tenant eval counts —
    the telemetry fan-out reads these two (T,) vectors, never the full
    state."""
    best = jnp.min(
        jnp.where(ghof.exists, ghof.losses, jnp.inf), axis=-1
    )
    evals = jnp.sum(states.num_evals, axis=-1)
    return best, evals


def batched_equation_search(
    datasets: Sequence,
    *,
    options: Optional[Options] = None,
    seeds: Optional[Sequence[int]] = None,
    niterations: int = 10,
    variable_names: Optional[Sequence[str]] = None,
    registry=None,
    telemetry_dir: Optional[str] = None,
    return_state: bool = False,
    runtests: bool = False,
    **option_kwargs,
) -> List["Any"]:
    """Run T same-shape symbolic-regression jobs as one batched search.

    datasets: sequence of ``(X, y)`` / ``(X, y, weights)`` tuples (or
    dicts) — every X must share one (nfeatures, n) shape, every y one
    (n,), and weights are all-or-none (mixing would silently change
    the unweighted tenants' loss reduction; the job server pads with
    explicit weights for exactly this reason). seeds: per-tenant seeds
    (default ``options.seed + t``); tenant t is bit-identical to the
    solo search of ``seed=seeds[t]``. registry: a
    telemetry.metrics.MetricsRegistry for tenant-indexed gauges;
    telemetry_dir: event-log directory (one ``serve_run`` log for the
    whole batch, per-tenant arrays on each event).

    Returns one ``EquationSearchResult`` per tenant, in input order.
    """
    from ..api import (  # local: api imports nothing from serving
        EquationSearchResult,
        SearchState,
        _curmaxsize,
        _donation_enabled,
        _make_init_fn,
        _make_iteration_driver,
        equation_search,
    )

    jobs = _normalize_datasets(datasets)
    T = len(jobs)
    if options is None:
        option_kwargs.setdefault("tenants", max(T, 1))
        options = make_options(**option_kwargs)
    elif option_kwargs:
        raise ValueError("Pass either options= or option kwargs, not both")
    if options.tenants != T:
        options = dataclasses.replace(options, tenants=max(T, 1))
    if seeds is None:
        seeds = [options.seed + t for t in range(T)]
    if len(seeds) != T:
        raise ValueError(f"seeds has {len(seeds)} entries for {T} datasets")

    if T == 1:
        # one tenant IS a solo search — route through the front door so
        # the single-job path carries every solo feature (and the warm
        # jit cache of tenants=1 programs)
        solo = dataclasses.replace(options, tenants=1, seed=int(seeds[0]))
        X0, y0, w0 = jobs[0]
        res = equation_search(
            X0, y0, weights=w0, options=solo, niterations=niterations,
            variable_names=variable_names, return_state=return_state,
            runtests=runtests,
        )
        return [res]

    # ---- admission: every tenant through the hostile-data front door
    # (validate -> Options.data_policy), then the shape contract ----
    host_dtype = (
        np.float64 if options.precision == "float64" else np.float32
    )
    Xs, ys_, ws, diags = [], [], [], []
    for t, (X, y, w) in enumerate(jobs):
        X = np.asarray(X, host_dtype)
        y = np.asarray(y, host_dtype)
        if y.ndim != 1:
            raise ValueError(
                f"dataset {t}: serving jobs are single-output (y must "
                f"be 1-D, got shape {y.shape})"
            )
        if w is not None:
            w = np.asarray(w, host_dtype)
        d = validate_dataset(X, y[None, :], w)
        X, y2, w, d = sanitize_dataset(
            X, y[None, :], w, options.data_policy, d
        )
        Xs.append(np.asarray(X, host_dtype))
        ys_.append(np.asarray(y2[0], host_dtype))
        ws.append(None if w is None else np.asarray(w, host_dtype))
        diags.append(d)
    shape0 = Xs[0].shape
    for t, X in enumerate(Xs):
        if X.shape != shape0:
            raise ValueError(
                f"dataset {t} has X shape {X.shape}, tenant 0 has "
                f"{shape0}: a batch shares ONE padded shape — use the "
                "job server's pad ladder (serving.jobs) to quantize"
            )
    has_w = [w is not None for w in ws]
    if any(has_w) and not all(has_w):
        raise ValueError(
            "weights must be all-or-none across a batch: an unweighted "
            "tenant's loss reduction (jnp.mean) differs bitwise from "
            "ones-weights — pad with explicit weights (serving.jobs "
            "does) or drop them everywhere"
        )
    has_weights = all(has_w)
    nfeatures = shape0[0]
    I = options.npopulations

    # ---- per-tenant baselines + the stacked device-ready batch ----
    bls = []
    for t in range(T):
        ds = make_dataset(
            Xs[t], ys_[t], ws[t], variable_names, dtype=options.dtype
        )
        ds = update_baseline_loss(ds, options)
        bls.append(float(ds.baseline_loss))
    Xb = np.stack(Xs)                       # (T, nfeat, n)
    yb = np.stack(ys_)                      # (T, n)
    wb = np.stack(ws) if has_weights else None
    bl = jnp.asarray(np.asarray(bls, host_dtype), options.dtype)

    mesh = make_mesh(options, I, tenants=T)
    Xb, yb, wb = shard_dataset(Xb, yb, wb, mesh, options)
    donate = _donation_enabled()
    scalars = options.traced_scalars()
    t_start = time.time()

    sink = None
    if telemetry_dir is not None:
        from ..telemetry.events import open_event_log

        sink = open_event_log(telemetry_dir)
        sink.emit(
            "run_start",
            run_id=options.telemetry_run_id or sink.run_id,
            backend=jax.default_backend(),
            tenants=T,
            seeds=[int(s) for s in seeds],
            niterations=niterations,
            x_shape=[int(s) for s in shape0],
            **describe_mesh(mesh),
            dataset_diagnostics=[d.to_dict() for d in diags],
        )

    # ---- per-tenant PRNG chains: tenant t's master key is exactly the
    # solo search's PRNGKey(seed_t); the vmapped split below computes
    # each tenant's solo splits bit-for-bit (threefry is elementwise in
    # the key) ----
    masters = jnp.stack([jax.random.PRNGKey(int(s)) for s in seeds])
    ks = jax.vmap(lambda k: jax.random.split(k))(masters)   # (T, 2, 2)
    k_init, keys = ks[:, 0], ks[:, 1]
    init_keys = jax.vmap(lambda k: jax.random.split(k, I))(k_init)

    init_fn = _make_init_fn(options, nfeatures, has_weights, donate, mesh)
    if has_weights:
        states = init_fn(init_keys, Xb, yb, wb, bl, scalars)
    else:
        states = init_fn(init_keys, Xb, yb, bl, scalars)
    states = shard_island_states(states, mesh, options)
    ghof = jax.vmap(merge_hofs_across_islands)(states.hof)

    iteration_fn = _make_iteration_driver(
        options, has_weights, donate, spans=None, mesh=mesh
    )

    # ---- per-tenant memo banks (options.cache_fitness) ----
    use_cache = (
        options.cache_fitness
        and jax.process_count() == 1
        and options.loss_function is None
    )
    banks: List[Optional[object]] = []
    if use_cache:
        from ..cache.memo import dataset_fingerprint, get_memo_bank

        for t in range(T):
            banks.append(
                get_memo_bank(
                    dataset_fingerprint(Xs[t], ys_[t], ws[t], options),
                    options.cache_capacity,
                )
            )

    early_stop = options.early_stop_fn()
    it_done = 0
    for it in range(niterations):
        cm = jnp.int32(_curmaxsize(options, it, max(niterations, 1)))
        ks = jax.vmap(lambda k: jax.random.split(k))(keys)
        keys, k_it = ks[:, 0], ks[:, 1]
        if use_cache:
            memo_snaps = [
                b.device_snapshot(
                    options.cache_device_slots, options.dtype
                )
                for b in banks
            ]
            memo_args = (
                jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *memo_snaps
                ),
            )
        else:
            memo_args = ()
        if has_weights:
            out = iteration_fn(
                states, k_it, cm, Xb, yb, wb, bl, scalars, *memo_args
            )
        else:
            out = iteration_fn(
                states, k_it, cm, Xb, yb, bl, scalars, *memo_args
            )
        if options.cache_fitness:
            absorb_snap = out[-1]
            out = out[:-1]
        else:
            absorb_snap = None
        states, ghof = out
        jax.block_until_ready(ghof.losses)
        it_done = it + 1

        if use_cache and absorb_snap is not None:
            from ..cache.hashing import tree_hash_host

            snap_trees, snap_losses = absorb_snap
            snap_trees = jax.tree_util.tree_map(np.asarray, snap_trees)
            snap_losses = np.asarray(snap_losses)
            for t in range(T):
                banks[t].absorb(
                    tree_hash_host(
                        _slice_tree(snap_trees, t)
                    ).ravel(),
                    snap_losses[t].ravel(),
                )

        observe = (
            (sink is not None or registry is not None)
            and it % max(options.telemetry_every, 1) == 0
        )
        if observe:
            best, evals = _tenant_summary(states, ghof)
            best = np.asarray(best, np.float64)
            evals = np.asarray(evals, np.float64)
            if registry is not None:
                for t in range(T):
                    registry.gauge(
                        f"serve_tenant_best_loss_{t}",
                        help="best HoF loss of tenant t in the "
                             "current batched search",
                    ).set(float(best[t]))
                registry.gauge(
                    "serve_tenants",
                    help="tenant count of the current batched search",
                ).set(T)
            if sink is not None:
                sink.emit(
                    "serve_metrics",
                    iteration=it,
                    best_loss=[
                        float(b) if np.isfinite(b) else None
                        for b in best
                    ],
                    num_evals=[float(e) for e in evals],
                )

        if early_stop is not None:
            done = True
            for t in range(T):
                cands_t = hof_to_candidates(
                    _slice_tree(ghof, t), options, variable_names
                )
                if not any(
                    early_stop(c.loss, c.complexity) for c in cands_t
                ):
                    done = False
                    break
            if done:
                break

    # ---- per-tenant result assembly ----
    search_time_s = time.time() - t_start
    results: List[EquationSearchResult] = []
    evals_host = np.asarray(jnp.sum(states.num_evals, axis=-1))
    keys_host = np.asarray(keys)
    for t in range(T):
        ghof_t = _slice_tree(ghof, t)
        cands = hof_to_candidates(ghof_t, options, variable_names)
        state = None
        if return_state:
            state = [
                SearchState(
                    island_states=_slice_tree(states, t),
                    global_hof=ghof_t,
                    iteration=it_done,
                    rng_key=jnp.asarray(keys_host[t]),
                )
            ]
        results.append(
            EquationSearchResult(
                candidates=[cands],
                options=options,
                variable_names=variable_names,
                state=state,
                num_evals=float(evals_host[t]),
                search_time_s=search_time_s,
                cache_stats=(
                    {"banks": [banks[t].stats]} if use_cache else None
                ),
                dataset_diagnostics=diags[t].to_dict(),
            )
        )

    if sink is not None:
        sink.emit(
            "run_end",
            tenants=T,
            iterations=it_done,
            search_time_s=search_time_s,
            num_evals=[float(e) for e in evals_host],
            best_loss=[
                (lambda ls: float(min(ls)) if ls else None)(
                    [float(c.loss) for c in r.frontier()]
                )
                for r in results
            ],
        )
        sink.close()
    return results
