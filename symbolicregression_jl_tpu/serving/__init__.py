"""srserve — the multi-tenant serving tier (docs/serving.md).

Two layers over the solo search engine:

* :mod:`.batched` — ``batched_equation_search(datasets, options=...)``:
  stacks same-shape ``(X, y, weights)`` problems along a leading
  ``tenants`` axis and runs ONE jitted search over all of them (the
  api.py jit factories vmap their per-tenant bodies when
  ``Options.tenants > 1``; the device mesh becomes
  ``(tenants, islands)``). Each tenant's hall of fame is bit-identical
  to running its job alone under the same Options and seed — the
  serving bit-identity contract, pinned by tests/test_serving.py.
* :mod:`.jobs` — :class:`~.jobs.JobServer`: a queue that admits jobs
  through the hostile-data front door, quantizes shapes onto a pad
  ladder, buckets by ``(padded shape, opset, Options graph key)`` so
  one warm compile serves a whole bucket, flushes batches by fill or
  timeout through the batched engine, and returns per-job results with
  per-job run ids registered in the fleet index. ``scripts/srserve.py``
  is the CLI front end; queue depth / bucket fill / warm-hit rate /
  job latency export through the OpenMetrics endpoint as
  ``srtpu_serve_*``.
"""

from .batched import batched_equation_search
from .jobs import (
    DEFAULT_FEATURE_LADDER,
    DEFAULT_ROW_LADDER,
    JobResult,
    JobServer,
    pad_to_ladder,
)

__all__ = [
    "batched_equation_search",
    "JobServer",
    "JobResult",
    "pad_to_ladder",
    "DEFAULT_ROW_LADDER",
    "DEFAULT_FEATURE_LADDER",
]
