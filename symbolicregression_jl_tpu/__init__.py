"""symbolicregression_jl_tpu — a TPU-native symbolic regression framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
SymbolicRegression.jl (reference mounted at /root/reference): genetic-
programming equation search with island populations, tournament selection,
9-way weighted mutation, crossover, simulated annealing, adaptive parsimony,
constraint checking, on-device BFGS constant optimization, migration as mesh
collectives, and a per-complexity hall of fame / Pareto frontier.

Layout:
  models/    expression encoding, options, evolution, constant optimization
  ops/       operators, losses, batched tree interpreter, Pallas kernels
  parallel/  mesh/sharding, migration collectives, multi-host runtime
  utils/     printing, export, checkpointing, recorder, progress
"""

from .models.dataset import (
    Dataset,
    DatasetDiagnostics,
    HostileDatasetError,
    load_csv_dataset,
    make_dataset,
    sanitize_dataset,
    update_baseline_loss,
    validate_dataset,
)
from .models.options import (
    GRAPH_FIELDS,
    ORCHESTRATION_FIELDS,
    TRACED_SCALAR_FIELDS,
    ComplexityMapping,
    MutationWeights,
    Options,
    callable_token,
    make_options,
)
from .models.trees import (
    Expr,
    TreeBatch,
    decode_tree,
    encode_tree,
    parse_expression,
    tree_hash,
    tree_to_string,
)
from .ops.interpreter import (
    eval_diff_tree,
    eval_grad_constants,
    eval_grad_variables,
    eval_loss_trees_fused,
    eval_tree,
    eval_trees,
)
from .ops.losses import LOSS_REGISTRY, contain_nonfinite, pairwise_sum
from .utils.export import (
    from_sympy,
    sympy_simplify_tree,
    to_callable,
    to_latex,
    to_sympy,
)
from .ops.operators import (
    OperatorSet,
    make_operator_set,
    register_binary,
    register_unary,
)
# Evolution-layer types and helpers the reference exports publicly
# (reference src/SymbolicRegression.jl:4-31: Population, HallOfFame,
# s_r_cycle, calculate_pareto_frontier, compute_complexity,
# gen_random_tree_fixed_size, simplify_tree, combine_operators).
from .models.complexity import compute_complexity
from .models.evolve import s_r_cycle
from .models.mutate_device import (
    combine_operators,
    gen_random_tree_fixed_size,
    simplify_tree,
)
from .models.population import (
    HallOfFame,
    Population,
    calculate_pareto_frontier,
    init_hall_of_fame,
    init_population,
)

# Evaluation memo bank (opt-in via Options.cache_fitness).
from .cache import FitnessMemoBank, clear_memo_banks, tree_hash_host

# Unified search telemetry (opt-in via Options.telemetry) + the offline
# run doctor over its event logs. analyze_run/compare_runs resolve
# lazily (PEP 562, below) so the documented CLI
# `python -m symbolicregression_jl_tpu.telemetry.analyze` never
# double-imports the module it is about to execute.
from .telemetry import (
    EventLog,
    MetricsRegistry,
    SpanRecorder,
    hypervolume_2d,
    open_event_log,
    validate_events_file,
)


def __getattr__(name):
    if name in ("analyze_run", "compare_runs", "profile_report",
                "device_peaks",
                # fleet layer (telemetry/fleet.py, alerts.py,
                # export.py — docs/observability.md "Fleet")
                "FleetScanner", "register_run", "AlertRule",
                "DEFAULT_ALERT_RULES", "evaluate_alerts",
                "render_openmetrics", "validate_exposition",
                "write_textfile", "serve_metrics"):
        from . import telemetry

        return getattr(telemetry, name)
    # multi-tenant serving tier (serving/, docs/serving.md): lazy so
    # `import symbolicregression_jl_tpu` stays light for solo users
    if name in ("batched_equation_search", "JobServer", "JobResult",
                "pad_to_ladder"):
        from . import serving

        return getattr(serving, name)
    # persistent kernel autotuner (tune/, docs/kernel_tuning.md): lazy —
    # fitness.py consults the cache on its own; importing the package
    # must not touch the tuner machinery
    if name in ("current_device_kind", "default_cache_path",
                "load_tune_cache", "lookup_kernel_config",
                "model_ranked_sweep", "save_tune_cache", "sweep_to_cache",
                "tuned_min_work", "update_tune_cache",
                "validate_tune_cache"):
        from . import tune

        return getattr(tune, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )

__version__ = "0.1.0"

# Populated lazily to avoid importing heavy modules at package import:
from .api import EquationSearchResult, equation_search  # noqa: E402
from .sklearn import SymbolicRegressor  # noqa: E402
from .utils.checkpoint import (  # noqa: E402
    load_search_state,
    save_search_state,
)

# Preemption-tolerant search (docs/resilience.md): periodic snapshots
# (Options.snapshot_path/snapshot_every_dispatches), deterministic
# fault injection, and the auto-resume supervisor.
from .resilience import (  # noqa: E402
    FaultInjected,
    FaultPlan,
    SupervisedResult,
    clear_fault_plan,
    set_fault_plan,
    supervised_search,
)
from .utils.precompile import (  # noqa: E402
    do_precompilation,
    enable_compilation_cache,
)

EquationSearch = equation_search

__all__ = [
    "Dataset",
    "DatasetDiagnostics",
    "HostileDatasetError",
    "contain_nonfinite",
    "pairwise_sum",
    "sanitize_dataset",
    "validate_dataset",
    "load_csv_dataset",
    "make_dataset",
    "update_baseline_loss",
    "Options",
    "make_options",
    "MutationWeights",
    "ComplexityMapping",
    "GRAPH_FIELDS",
    "TRACED_SCALAR_FIELDS",
    "ORCHESTRATION_FIELDS",
    "callable_token",
    "Expr",
    "TreeBatch",
    "encode_tree",
    "decode_tree",
    "tree_to_string",
    "tree_hash",
    "parse_expression",
    "eval_tree",
    "eval_trees",
    "eval_loss_trees_fused",
    "eval_diff_tree",
    "eval_grad_constants",
    "eval_grad_variables",
    "OperatorSet",
    "make_operator_set",
    "register_unary",
    "register_binary",
    "LOSS_REGISTRY",
    "to_sympy",
    "from_sympy",
    "to_latex",
    "to_callable",
    "sympy_simplify_tree",
    "equation_search",
    "SymbolicRegressor",
    "EquationSearch",
    "EquationSearchResult",
    "do_precompilation",
    "save_search_state",
    "load_search_state",
    "enable_compilation_cache",
    "Population",
    "HallOfFame",
    "init_population",
    "init_hall_of_fame",
    "calculate_pareto_frontier",
    "compute_complexity",
    "gen_random_tree_fixed_size",
    "simplify_tree",
    "combine_operators",
    "s_r_cycle",
    "FitnessMemoBank",
    "clear_memo_banks",
    "tree_hash_host",
    "FaultInjected",
    "FaultPlan",
    "SupervisedResult",
    "supervised_search",
    "set_fault_plan",
    "clear_fault_plan",
    "EventLog",
    "MetricsRegistry",
    "SpanRecorder",
    "analyze_run",
    "compare_runs",
    "device_peaks",
    "hypervolume_2d",
    "open_event_log",
    "profile_report",
    "validate_events_file",
    "AlertRule",
    "DEFAULT_ALERT_RULES",
    "FleetScanner",
    "evaluate_alerts",
    "register_run",
    "render_openmetrics",
    "serve_metrics",
    "validate_exposition",
    "write_textfile",
    "batched_equation_search",
    "JobServer",
    "JobResult",
    "pad_to_ladder",
    "current_device_kind",
    "default_cache_path",
    "load_tune_cache",
    "lookup_kernel_config",
    "model_ranked_sweep",
    "save_tune_cache",
    "sweep_to_cache",
    "tuned_min_work",
    "update_tune_cache",
    "validate_tune_cache",
]
