"""Typed metrics registry + the per-iteration search collector.

The registry is deliberately tiny — three instrument kinds with the
semantics everyone expects from them:

* :class:`Counter` — monotone non-decreasing total (``inc``);
* :class:`Gauge` — last-write-wins scalar (``set``);
* :class:`Histogram` — fixed integer-edge buckets fed either one
  observation at a time (``observe``) or from a device-computed count
  vector (``add_counts`` — how the population length distribution
  arrives without a per-member host loop).

:class:`SearchMetrics` is the search-specific feeder: once per
``telemetry_every`` iterations it runs ONE fused jitted device reduction
over the island states (per-island best/mean loss, population length
bincount) — a single extra dispatch off the hot path, zero primitives
added to the search programs — and combines it with values the host
already holds (memo-bank counters, annealing temperature, hall-of-fame
Pareto size and a dominated-hypervolume proxy, device HBM stats). The
snapshot is emitted to the event sink as one ``metrics`` event per
iteration (docs/observability.md lists the full catalog).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Counter:
    """Monotone total. ``inc`` with a negative amount is a bug upstream
    and raises rather than silently un-counting."""

    name: str
    help: str = ""
    value: float = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        self.value += amount


@dataclasses.dataclass
class Gauge:
    """Last-write-wins scalar; None means 'not yet observed'."""

    name: str
    help: str = ""
    value: Optional[float] = None

    def set(self, value) -> None:
        self.value = None if value is None else float(value)


@dataclasses.dataclass
class Histogram:
    """Fixed-bucket histogram. ``edges`` are inclusive upper bounds of
    each bucket; an implicit overflow bucket catches the rest."""

    name: str
    edges: List[float]
    help: str = ""
    counts: List[int] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        if list(self.edges) != sorted(self.edges):
            raise ValueError(f"histogram {self.name}: edges not ascending")
        if not self.counts:
            self.counts = [0] * (len(self.edges) + 1)

    def observe(self, value: float) -> None:
        for i, edge in enumerate(self.edges):
            if value <= edge:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def add_counts(self, counts) -> None:
        """Merge a per-bucket count vector (len(edges) or len(edges)+1
        entries; a missing overflow bucket means zero overflow)."""
        counts = [int(c) for c in counts]
        if len(counts) == len(self.edges):
            counts = counts + [0]
        if len(counts) != len(self.counts):
            raise ValueError(
                f"histogram {self.name}: got {len(counts)} buckets, "
                f"want {len(self.counts)}"
            )
        self.counts = [a + b for a, b in zip(self.counts, counts)]

    @property
    def total(self) -> int:
        return sum(self.counts)


class MetricsRegistry:
    """Name-keyed instrument store. Re-requesting a name returns the
    existing instrument (so feeders never lose accumulated state);
    requesting an existing name as a different kind raises."""

    def __init__(self):
        self._instruments: Dict[str, Any] = {}

    def _get(self, cls, name: str, **kwargs):
        inst = self._instruments.get(name)
        if inst is None:
            inst = cls(name=name, **kwargs)
            self._instruments[name] = inst
            return inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(inst).__name__}, not {cls.__name__}"
            )
        return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help=help)

    def histogram(self, name: str, edges, help: str = "") -> Histogram:
        return self._get(Histogram, name, edges=list(edges), help=help)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """JSON-ready state of every instrument (non-finite floats become
        None — the event log writes strict JSON)."""

        def _clean(v):
            if v is None:
                return None
            v = float(v)
            return v if math.isfinite(v) else None

        out: Dict[str, Dict[str, Any]] = {
            "counters": {}, "gauges": {}, "histograms": {},
        }
        for name, inst in sorted(self._instruments.items()):
            if isinstance(inst, Counter):
                out["counters"][name] = _clean(inst.value)
            elif isinstance(inst, Gauge):
                out["gauges"][name] = _clean(inst.value)
            else:
                out["histograms"][name] = {
                    "edges": [float(e) for e in inst.edges],
                    "counts": [int(c) for c in inst.counts],
                }
        return out


# ---------------------------------------------------------------------------
# search-specific collector
# ---------------------------------------------------------------------------


def _hypervolume_proxy(hof_losses, hof_exists, baseline: float) -> float:
    """Dominated-hypervolume proxy of the hall-of-fame frontier in [0, 1]:
    the mean over complexity slots 1..S of the baseline-normalized loss
    improvement ``max(0, 1 - best_loss_at_or_below(c) / baseline)`` —
    i.e. the area (in normalized-loss x complexity-fraction units) the
    frontier dominates w.r.t. the reference point (maxsize, baseline
    loss). Cheap, monotone under frontier improvement, and comparable
    across iterations of one run (NOT across datasets)."""
    import numpy as np

    losses = np.asarray(hof_losses, np.float64)
    exists = np.asarray(hof_exists, bool)
    if baseline is None or not np.isfinite(baseline) or baseline <= 0:
        return 0.0
    best = np.where(exists & np.isfinite(losses), losses, np.inf)
    runmin = np.minimum.accumulate(best)
    gain = np.where(
        np.isfinite(runmin), np.clip(1.0 - runmin / baseline, 0.0, 1.0), 0.0
    )
    return float(gain.mean())


class SearchMetrics:
    """Feeds a :class:`MetricsRegistry` once per observed iteration and
    emits the snapshot to the event sink. One instance per search run."""

    #: population length histogram bucket width (slots)
    LENGTH_BUCKET = 4

    def __init__(self, options, sink=None):
        self.options = options
        self.sink = sink
        self.registry = MetricsRegistry()
        self._reduce = None  # jitted on first use (needs array shapes)

    def _reduction_fn(self):
        if self._reduce is not None:
            return self._reduce
        import jax
        import jax.numpy as jnp

        max_len = self.options.max_len

        def reduce_states(losses, lengths, hof_losses, hof_exists,
                          num_evals):
            # (I, npop) losses / lengths; (S,) hof. ONE fused program,
            # outputs a few KB — a single dispatch + fetch per snapshot
            # (the hof arrays pass through so the host-side hypervolume
            # proxy reads the same fetch instead of syncing again; on a
            # tunneled TPU each extra round trip is ~70 ms).
            finite = jnp.isfinite(losses)
            big = jnp.asarray(jnp.finfo(jnp.float32).max, losses.dtype)
            best = jnp.min(jnp.where(finite, losses, big), axis=1)
            n_fin = jnp.sum(finite, axis=1)
            mean = jnp.sum(
                jnp.where(finite, losses, 0.0), axis=1
            ) / jnp.maximum(n_fin, 1)
            len_counts = jnp.bincount(
                lengths.astype(jnp.int32).ravel(), length=max_len + 1
            )
            mean_len = jnp.mean(lengths.astype(jnp.float32))
            hof_size = jnp.sum(hof_exists.astype(jnp.int32))
            return {
                "island_best_loss": best,
                "island_mean_loss": mean,
                "island_finite_frac": n_fin / losses.shape[1],
                "length_counts": len_counts,
                "mean_length": mean_len,
                "hof_size": hof_size,
                "hof_losses": hof_losses,
                "hof_exists": hof_exists,
                "num_evals": jnp.sum(num_evals),
            }

        self._reduce = jax.jit(reduce_states)
        return self._reduce

    def observe_iteration(
        self,
        states,
        ghof,
        *,
        output: int,
        iteration: int,
        baseline: Optional[float] = None,
        temperature: Optional[float] = None,
        curmaxsize: Optional[int] = None,
        cache_row: Optional[dict] = None,
        cycles_per_second: Optional[float] = None,
        device_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """One iteration's metric snapshot: ONE fused device reduction
        (single dispatch + single fetch) + host-side values -> registry
        -> one ``metrics`` event. Returns the emitted snapshot dict."""
        import jax
        import numpy as np

        vals = jax.device_get(
            self._reduction_fn()(
                states.pop.losses, states.pop.trees.length,
                ghof.losses, ghof.exists, states.num_evals,
            )
        )
        reg = self.registry
        reg.counter(
            "iterations_total", "host-loop iterations observed"
        ).inc()
        reg.gauge("best_loss", "global best population loss").set(
            float(np.min(vals["island_best_loss"]))
        )
        reg.gauge("mean_loss", "mean finite population loss").set(
            float(np.mean(vals["island_mean_loss"]))
        )
        reg.gauge(
            "population_finite_frac",
            "fraction of members with finite loss",
        ).set(float(np.mean(vals["island_finite_frac"])))
        reg.gauge("mean_tree_length", "mean program length (slots)").set(
            float(vals["mean_length"])
        )
        reg.gauge("hof_size", "occupied hall-of-fame complexity slots").set(
            int(vals["hof_size"])
        )
        reg.gauge(
            "hof_hypervolume_proxy",
            "dominated-hypervolume proxy of the HoF frontier [0,1]",
        ).set(_hypervolume_proxy(
            vals["hof_losses"], vals["hof_exists"], baseline
        ))
        reg.gauge("num_evals_total", "cumulative equation evaluations").set(
            float(vals["num_evals"])
        )
        if temperature is not None:
            reg.gauge(
                "annealing_temperature",
                "mean annealing temperature of this iteration's schedule",
            ).set(temperature)
        if curmaxsize is not None:
            reg.gauge(
                "curmaxsize", "warm-up complexity cap this iteration"
            ).set(curmaxsize)
        if cycles_per_second is not None:
            reg.gauge(
                "cycles_per_second", "progress-window cycles/second"
            ).set(cycles_per_second)
        if device_s is not None:
            reg.gauge(
                "iteration_device_s", "last iteration's dispatch wall time"
            ).set(device_s)
        if cache_row is not None:
            reg.gauge(
                "memo_hit_rate", "memo-bank hit fraction of scored trees"
            ).set(cache_row.get("memo_hit_rate"))
            reg.gauge(
                "dedup_unique_ratio", "unique fraction of scored trees"
            ).set(cache_row.get("unique_ratio"))
            reg.gauge(
                "eval_batch_fill",
                "fraction of eval-batch slots that needed evaluation",
            ).set(cache_row.get("eval_batch_fill"))
        hist = reg.histogram(
            "population_length",
            list(range(
                self.LENGTH_BUCKET, self.options.max_len + 1,
                self.LENGTH_BUCKET,
            )),
            "program length distribution (slots)",
        )
        counts = np.asarray(vals["length_counts"])
        bucketed = [
            int(counts[max(0, e - self.LENGTH_BUCKET + 1):e + 1].sum())
            for e in [int(b) for b in hist.edges]
        ]
        bucketed.append(int(counts.sum()) - sum(bucketed))
        hist.counts = [0] * len(hist.counts)  # gauge-like: this iteration
        hist.add_counts(bucketed)

        # device HBM, where the backend reports it (CPU usually doesn't)
        try:
            from ..utils.profiling import device_memory_stats

            stats = device_memory_stats()
            in_use = [
                s.get("bytes_in_use") for s in stats.values()
                if isinstance(s, dict) and s.get("bytes_in_use") is not None
            ]
            if in_use:
                reg.gauge(
                    "hbm_bytes_in_use", "max live device bytes"
                ).set(max(in_use))
        except Exception:  # pragma: no cover - defensive
            pass

        snap = reg.snapshot()
        if self.sink is not None:
            self.sink.emit(
                "metrics",
                output=output,
                iteration=iteration,
                snapshot=snap,
                per_island={
                    "best_loss": [
                        float(v) for v in np.asarray(
                            vals["island_best_loss"], np.float64
                        )
                    ],
                    "mean_loss": [
                        float(v) for v in np.asarray(
                            vals["island_mean_loss"], np.float64
                        )
                    ],
                },
            )
        return snap
