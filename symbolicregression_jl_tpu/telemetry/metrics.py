"""Typed metrics registry + the per-iteration search collector.

The registry is deliberately tiny — three instrument kinds with the
semantics everyone expects from them:

* :class:`Counter` — monotone non-decreasing total (``inc``);
* :class:`Gauge` — last-write-wins scalar (``set``);
* :class:`Histogram` — fixed integer-edge buckets fed either one
  observation at a time (``observe``) or from a device-computed count
  vector (``add_counts`` — how the population length distribution
  arrives without a per-member host loop).

:class:`SearchMetrics` is the search-specific feeder: once per
``telemetry_every`` iterations it runs ONE fused jitted device reduction
over the island states (per-island best/mean loss, population length
bincount, and the search-dynamics signals below) — a single extra
dispatch off the hot path, zero primitives added to the search programs
— and combines it with values the host already holds (memo-bank
counters, annealing temperature, device HBM stats). The snapshot is
emitted to the event sink as one ``metrics`` event per iteration
(docs/observability.md lists the full catalog).

Search-dynamics signals (GP-dynamics literature — TensorGP, arxiv
2103.07512; Kozax, arxiv 2502.03047 — names diversity collapse and
operator-acceptance drift as the leading indicators of wasted
tensorized-GP compute), all folded into the same fused reduction:

* **per-island population diversity** — the unique-tree fraction of each
  island's population, keyed on the same two-lane FNV-64 content hash
  the memo bank uses (``cache.hashing.tree_hash_device``): a sort plus
  one adjacent-difference count per island, entirely on device;
* **per-mutation-type proposal/acceptance counters** — the cumulative
  ``IslandState.mut_counts`` aggregates summed across islands and
  published per kind (``models.evolve.mutation_counts_table``);
* **Pareto frontier snapshot + exact hypervolume** — the merged
  hall of fame's (complexity, loss) frontier rides along in the same
  fetch and the event carries both the raw frontier vector and the
  EXACT dominated 2-D hypervolume (:func:`hypervolume_2d`, w.r.t. the
  reference point (maxsize+1, baseline loss)) — replacing the
  slot-scan proxy earlier revisions emitted.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Counter:
    """Monotone total. ``inc`` with a negative amount is a bug upstream
    and raises rather than silently un-counting."""

    name: str
    help: str = ""
    value: float = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        self.value += amount


@dataclasses.dataclass
class Gauge:
    """Last-write-wins scalar; None means 'not yet observed'."""

    name: str
    help: str = ""
    value: Optional[float] = None

    def set(self, value) -> None:
        self.value = None if value is None else float(value)


@dataclasses.dataclass
class Histogram:
    """Fixed-bucket histogram. ``edges`` are inclusive upper bounds of
    each bucket; an implicit overflow bucket catches the rest."""

    name: str
    edges: List[float]
    help: str = ""
    counts: List[int] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        if list(self.edges) != sorted(self.edges):
            raise ValueError(f"histogram {self.name}: edges not ascending")
        if not self.counts:
            self.counts = [0] * (len(self.edges) + 1)

    def observe(self, value: float) -> None:
        for i, edge in enumerate(self.edges):
            if value <= edge:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def add_counts(self, counts) -> None:
        """Merge a per-bucket count vector (len(edges) or len(edges)+1
        entries; a missing overflow bucket means zero overflow)."""
        counts = [int(c) for c in counts]
        if len(counts) == len(self.edges):
            counts = counts + [0]
        if len(counts) != len(self.counts):
            raise ValueError(
                f"histogram {self.name}: got {len(counts)} buckets, "
                f"want {len(self.counts)}"
            )
        self.counts = [a + b for a, b in zip(self.counts, counts)]

    @property
    def total(self) -> int:
        return sum(self.counts)


class MetricsRegistry:
    """Name-keyed instrument store. Re-requesting a name returns the
    existing instrument (so feeders never lose accumulated state);
    requesting an existing name as a different kind raises."""

    def __init__(self):
        self._instruments: Dict[str, Any] = {}

    def _get(self, cls, name: str, **kwargs):
        inst = self._instruments.get(name)
        if inst is None:
            inst = cls(name=name, **kwargs)
            self._instruments[name] = inst
            return inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(inst).__name__}, not {cls.__name__}"
            )
        return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help=help)

    def histogram(self, name: str, edges, help: str = "") -> Histogram:
        return self._get(Histogram, name, edges=list(edges), help=help)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """JSON-ready state of every instrument (non-finite floats become
        None — the event log writes strict JSON)."""

        def _clean(v):
            if v is None:
                return None
            v = float(v)
            return v if math.isfinite(v) else None

        out: Dict[str, Dict[str, Any]] = {
            "counters": {}, "gauges": {}, "histograms": {},
        }
        for name, inst in sorted(self._instruments.items()):
            if isinstance(inst, Counter):
                out["counters"][name] = _clean(inst.value)
            elif isinstance(inst, Gauge):
                out["gauges"][name] = _clean(inst.value)
            else:
                out["histograms"][name] = {
                    "edges": [float(e) for e in inst.edges],
                    "counts": [int(c) for c in inst.counts],
                }
        return out


# ---------------------------------------------------------------------------
# search-specific collector
# ---------------------------------------------------------------------------


def hypervolume_2d(
    complexities,
    losses,
    ref_complexity: float,
    ref_loss: float,
    c_floor: float = 1.0,
) -> float:
    """EXACT dominated 2-D hypervolume of a (complexity, loss) point set,
    both objectives minimized, w.r.t. the reference point
    ``(ref_complexity, ref_loss)`` — normalized to [0, 1] by the
    reference box ``(ref_complexity - c_floor) * ref_loss``.

    The staircase sum: points are sorted by complexity, dominated points
    drop out via a running loss minimum, and each frontier member
    contributes ``(next_complexity - complexity) * (ref_loss - loss)``.
    Points at/beyond the reference in either objective contribute
    nothing; losses are clipped at 0 (a loss cannot dominate below the
    origin in baseline-normalized units).

    For the hall of fame (one slot per integer complexity ``1..S``,
    reference ``(S+1, baseline)``, ``c_floor=1``) this equals the mean
    over slots of the clipped normalized improvement — the quantity
    earlier revisions approximated with a slot scan — but it is computed
    from the actual frontier points, so it stays exact for any point
    spacing. Monotone under frontier improvement; comparable across
    iterations of one run (NOT across datasets — it is normalized by
    the run's own baseline loss)."""
    import numpy as np

    if (
        ref_loss is None
        or not np.isfinite(ref_loss)
        or ref_loss <= 0
        or ref_complexity <= c_floor
    ):
        return 0.0
    c = np.asarray(complexities, np.float64)
    l = np.asarray(losses, np.float64)
    keep = np.isfinite(c) & np.isfinite(l) & (c < ref_complexity)
    c, l = c[keep], np.clip(l[keep], 0.0, None)
    if c.size == 0:
        return 0.0
    order = np.argsort(c, kind="stable")
    c, l = c[order], l[order]
    runmin = np.minimum.accumulate(l)
    # one step per distinct complexity: the best (lowest-runmin) entry
    # is the last one at that complexity after the running minimum
    last = np.r_[c[1:] != c[:-1], True]
    c, runmin = c[last], runmin[last]
    widths = np.diff(np.r_[c, ref_complexity])
    heights = np.clip(ref_loss - runmin, 0.0, None)
    hv = float(np.sum(widths * heights))
    return hv / ((ref_complexity - c_floor) * ref_loss)


class SearchMetrics:
    """Feeds a :class:`MetricsRegistry` once per observed iteration and
    emits the snapshot to the event sink. One instance per search run."""

    #: population length histogram bucket width (slots)
    LENGTH_BUCKET = 4

    def __init__(self, options, sink=None):
        self.options = options
        self.sink = sink
        self.registry = MetricsRegistry()
        self._reduce = None  # jitted on first use (needs array shapes)

    def _reduction_fn(self):
        if self._reduce is not None:
            return self._reduce
        import jax
        import jax.numpy as jnp

        max_len = self.options.max_len

        def reduce_states(trees, losses, hof_losses, hof_exists,
                          num_evals, mut_counts):
            # trees: TreeBatch with leading (I, npop); (I, npop) losses;
            # (S,) hof; (I, K, 2) mut_counts. ONE fused program, outputs
            # a few KB — a single dispatch + fetch per snapshot (the hof
            # arrays pass through so the host-side exact hypervolume
            # reads the same fetch instead of syncing again; on a
            # tunneled TPU each extra round trip is ~70 ms).
            from ..cache.hashing import tree_hash_device

            lengths = trees.length
            finite = jnp.isfinite(losses)
            big = jnp.asarray(jnp.finfo(jnp.float32).max, losses.dtype)
            best = jnp.min(jnp.where(finite, losses, big), axis=1)
            n_fin = jnp.sum(finite, axis=1)
            # numeric-containment census (ISSUE 15): population slots
            # carrying the inf sentinel — every non-finite evaluation
            # is clamped to +inf by ops/losses.py::contain_nonfinite,
            # so this count IS the per-island clamp counter
            nonfinite = losses.shape[1] - n_fin
            mean = jnp.sum(
                jnp.where(finite, losses, 0.0), axis=1
            ) / jnp.maximum(n_fin, 1)
            len_counts = jnp.bincount(
                lengths.astype(jnp.int32).ravel(), length=max_len + 1
            )
            mean_len = jnp.mean(lengths.astype(jnp.float32))
            hof_size = jnp.sum(hof_exists.astype(jnp.int32))

            # per-island diversity: unique-tree fraction on the memo
            # bank's 64-bit content hash (two uint32 lanes; a collision
            # needs a 2^-64 pair — docs/memo_bank.md). Sort the lanes
            # lexicographically per island, count adjacent differences.
            h1, h2 = tree_hash_device(trees)  # (I, npop) uint32 each

            def _unique_frac(a, b):
                sa, sb = jax.lax.sort((a, b), num_keys=2)
                neq = (sa[1:] != sa[:-1]) | (sb[1:] != sb[:-1])
                return (1 + jnp.sum(neq.astype(jnp.int32))) / a.shape[0]

            diversity = jax.vmap(_unique_frac)(h1, h2)  # (I,) in (0, 1]

            return {
                "island_best_loss": best,
                "island_mean_loss": mean,
                "island_finite_frac": n_fin / losses.shape[1],
                "island_nonfinite": nonfinite,
                "island_diversity": diversity,
                "length_counts": len_counts,
                "mean_length": mean_len,
                "hof_size": hof_size,
                "hof_losses": hof_losses,
                "hof_exists": hof_exists,
                "num_evals": jnp.sum(num_evals),
                # cumulative per-kind (proposed, accepted) over islands
                "mut_counts": jnp.sum(mut_counts, axis=0),
            }

        self._reduce = jax.jit(reduce_states)
        return self._reduce

    def observe_iteration(
        self,
        states,
        ghof,
        *,
        output: int,
        iteration: int,
        baseline: Optional[float] = None,
        temperature: Optional[float] = None,
        curmaxsize: Optional[int] = None,
        cache_row: Optional[dict] = None,
        cycles_per_second: Optional[float] = None,
        device_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """One iteration's metric snapshot: ONE fused device reduction
        (single dispatch + single fetch) + host-side values -> registry
        -> one ``metrics`` event. Returns the emitted snapshot dict."""
        import jax
        import numpy as np

        vals = jax.device_get(
            self._reduction_fn()(
                states.pop.trees, states.pop.losses,
                ghof.losses, ghof.exists, states.num_evals,
                states.mut_counts,
            )
        )
        reg = self.registry
        reg.counter(
            "iterations_total", "host-loop iterations observed"
        ).inc()
        reg.gauge("best_loss", "global best population loss").set(
            float(np.min(vals["island_best_loss"]))
        )
        reg.gauge("mean_loss", "mean finite population loss").set(
            float(np.mean(vals["island_mean_loss"]))
        )
        reg.gauge(
            "population_finite_frac",
            "fraction of members with finite loss",
        ).set(float(np.mean(vals["island_finite_frac"])))
        # containment counters (ISSUE 15, docs/robustness_numeric.md):
        # the non-finite fraction is the run doctor's
        # numerically-degenerate signal and the fleet alert input; the
        # counter accumulates clamped (inf-sentinel) slots observed
        # across snapshots — a monotone "how much work is evaluation
        # throwing away" figure
        nonfinite_total = int(np.sum(vals["island_nonfinite"]))
        reg.gauge(
            "population_nonfinite_fraction",
            "fraction of population losses clamped to the inf sentinel "
            "(contain_nonfinite)",
        ).set(1.0 - float(np.mean(vals["island_finite_frac"])))
        reg.counter(
            "contained_losses_total",
            "cumulative inf-sentinel (clamped) population slots "
            "observed over metric snapshots",
        ).inc(nonfinite_total)
        reg.gauge("mean_tree_length", "mean program length (slots)").set(
            float(vals["mean_length"])
        )
        reg.gauge("hof_size", "occupied hall-of-fame complexity slots").set(
            int(vals["hof_size"])
        )
        reg.gauge(
            "population_diversity",
            "mean unique-tree fraction across islands (FNV-64 keyed)",
        ).set(float(np.mean(vals["island_diversity"])))

        # Pareto frontier of the merged HoF: (complexity, loss) for the
        # occupied finite slots (slot i holds complexity i+1), plus the
        # EXACT dominated hypervolume w.r.t. (maxsize+1, baseline)
        hof_losses = np.asarray(vals["hof_losses"], np.float64)
        hof_exists = np.asarray(vals["hof_exists"], bool)
        front = hof_exists & np.isfinite(hof_losses)
        pareto_c = (np.where(front)[0] + 1).tolist()
        pareto_l = hof_losses[front].tolist()
        S = hof_losses.shape[0]
        reg.gauge(
            "hof_hypervolume",
            "exact dominated 2-D hypervolume of the HoF frontier [0,1]",
        ).set(hypervolume_2d(
            pareto_c, pareto_l, ref_complexity=S + 1,
            ref_loss=baseline if baseline is not None else float("nan"),
        ))

        # per-mutation proposal/acceptance (cumulative device counters)
        from ..models.evolve import mutation_counts_table

        mutations = mutation_counts_table(vals["mut_counts"])
        tot_prop = sum(m["proposed"] for m in mutations.values())
        tot_acc = sum(m["accepted"] for m in mutations.values())
        reg.gauge(
            "mutation_accept_rate",
            "cumulative accepted/proposed over all mutation kinds",
        ).set(tot_acc / tot_prop if tot_prop else None)
        reg.gauge("num_evals_total", "cumulative equation evaluations").set(
            float(vals["num_evals"])
        )
        if temperature is not None:
            reg.gauge(
                "annealing_temperature",
                "mean annealing temperature of this iteration's schedule",
            ).set(temperature)
        if curmaxsize is not None:
            reg.gauge(
                "curmaxsize", "warm-up complexity cap this iteration"
            ).set(curmaxsize)
        if cycles_per_second is not None:
            reg.gauge(
                "cycles_per_second", "progress-window cycles/second"
            ).set(cycles_per_second)
        if device_s is not None:
            reg.gauge(
                "iteration_device_s", "last iteration's dispatch wall time"
            ).set(device_s)
        if cache_row is not None:
            reg.gauge(
                "memo_hit_rate", "memo-bank hit fraction of scored trees"
            ).set(cache_row.get("memo_hit_rate"))
            reg.gauge(
                "dedup_unique_ratio", "unique fraction of scored trees"
            ).set(cache_row.get("unique_ratio"))
            reg.gauge(
                "eval_batch_fill",
                "fraction of eval-batch slots that needed evaluation",
            ).set(cache_row.get("eval_batch_fill"))
        hist = reg.histogram(
            "population_length",
            list(range(
                self.LENGTH_BUCKET, self.options.max_len + 1,
                self.LENGTH_BUCKET,
            )),
            "program length distribution (slots)",
        )
        counts = np.asarray(vals["length_counts"])
        bucketed = [
            int(counts[max(0, e - self.LENGTH_BUCKET + 1):e + 1].sum())
            for e in [int(b) for b in hist.edges]
        ]
        bucketed.append(int(counts.sum()) - sum(bucketed))
        hist.counts = [0] * len(hist.counts)  # gauge-like: this iteration
        hist.add_counts(bucketed)

        # device HBM, where the backend reports it (CPU usually doesn't)
        try:
            from ..utils.profiling import device_memory_stats

            stats = device_memory_stats()
            in_use = [
                s.get("bytes_in_use") for s in stats.values()
                if isinstance(s, dict) and s.get("bytes_in_use") is not None
            ]
            if in_use:
                reg.gauge(
                    "hbm_bytes_in_use", "max live device bytes"
                ).set(max(in_use))
        except Exception:  # pragma: no cover - defensive
            pass

        snap = reg.snapshot()
        if self.sink is not None:
            self.sink.emit(
                "metrics",
                output=output,
                iteration=iteration,
                snapshot=snap,
                per_island={
                    "best_loss": [
                        float(v) for v in np.asarray(
                            vals["island_best_loss"], np.float64
                        )
                    ],
                    "mean_loss": [
                        float(v) for v in np.asarray(
                            vals["island_mean_loss"], np.float64
                        )
                    ],
                    "diversity": [
                        float(v) for v in np.asarray(
                            vals["island_diversity"], np.float64
                        )
                    ],
                    # additive (ISSUE 15): inf-sentinel slot count per
                    # island — the containment clamp census
                    "nonfinite": [
                        int(v) for v in np.asarray(
                            vals["island_nonfinite"], np.int64
                        )
                    ],
                },
                pareto={"complexity": pareto_c, "loss": pareto_l},
                mutations=mutations,
            )
        return snap
