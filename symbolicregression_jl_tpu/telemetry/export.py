"""OpenMetrics/Prometheus text exposition of the telemetry state.

The metrics registry (:class:`..metrics.MetricsRegistry`) and the fleet
index (:mod:`.fleet`) are JSON-shaped; the monitoring world scrapes the
Prometheus text exposition format. This module renders both into one
exposition, two delivery paths:

* **textfile** — :func:`write_textfile`: atomic (temp + ``os.replace``)
  write for node-exporter-style textfile collectors; the write
  self-checks through :func:`validate_exposition` first, so a malformed
  exposition can never land on disk;
* **HTTP** — :func:`serve_metrics`: an optional stdlib-only
  ``http.server`` ``/metrics`` endpoint (background thread, ephemeral
  port by default) for direct Prometheus scrapes — no third-party
  dependency, matching the container constraint.

Rendering rules (the subset of the format the validator then enforces):
one ``# TYPE`` (and optional ``# HELP``) line per family before its
samples; counters named ``*_total``; histograms as cumulative
``_bucket{le=...}`` + ``_count`` (no ``_sum`` — the registry's
fixed-bucket histograms do not track one, and a fabricated 0 would be a
lie); ``None``/non-finite values are SKIPPED, never rendered as ``NaN``
(a gauge that was never observed has no sample — the absence IS the
signal); label values escaped per the spec; the exposition ends with
``# EOF`` (the OpenMetrics terminator).

:func:`validate_exposition` is the self-check: a minimal parser of
exactly the grammar the renderer emits (metric-name/label syntax,
TYPE-before-samples, duplicate detection, float-parseable values,
``# EOF`` last). It exists so the lint gate (``scripts/lint.py``), the
suite ``fleet`` case, and the writer itself can all assert "this scrape
target is well-formed" without a Prometheus binary in the container.

Host-side only; no jax import. See docs/observability.md "Fleet".
"""

from __future__ import annotations

import math
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

#: content type Prometheus accepts for the text format
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def _valid_name(name: str) -> str:
    """Coerce an arbitrary metric name into the exposition charset."""
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", str(name))
    if not name or not _NAME_RE.match(name):
        name = "_" + name
    return name


def escape_label_value(v: Any) -> str:
    """Backslash-escape a label value per the exposition format
    (backslash, double-quote, newline)."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_value(v: float) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Families:
    """Ordered family collector: TYPE/HELP once per family, samples
    appended under it — the invariant the validator then checks."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        self._order: List[str] = []
        self._fam: Dict[str, dict] = {}

    def family(self, name: str, mtype: str, help: str = "") -> dict:
        name = _valid_name(self.prefix + name)
        if name not in self._fam:
            self._fam[name] = {"type": mtype, "help": help, "samples": []}
            self._order.append(name)
        return self._fam[name]

    def sample(
        self,
        name: str,
        value,
        *,
        mtype: str = "gauge",
        help: str = "",
        labels: Optional[Dict[str, Any]] = None,
        suffix: str = "",
    ) -> None:
        """Add one sample (skipped when the value is None/non-finite:
        an unobserved gauge has no sample, never a NaN)."""
        if value is None:
            return
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            if not math.isfinite(float(value)):
                return
        fam = self.family(name, mtype, help)
        fam["samples"].append((suffix, labels or {}, value))

    def render(self) -> str:
        lines: List[str] = []
        for name in self._order:
            fam = self._fam[name]
            if not fam["samples"]:
                continue
            if fam["help"]:
                lines.append(
                    f"# HELP {name} "
                    + fam["help"].replace("\\", "\\\\").replace("\n", " ")
                )
            lines.append(f"# TYPE {name} {fam['type']}")
            for suffix, labels, value in fam["samples"]:
                label_s = ""
                if labels:
                    inner = ",".join(
                        f'{_valid_name(k)}="{escape_label_value(v)}"'
                        for k, v in labels.items()
                    )
                    label_s = "{" + inner + "}"
                lines.append(f"{name}{suffix}{label_s} {_fmt_value(value)}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


def _render_registry(fams: _Families, registry) -> None:
    """Every instrument of a MetricsRegistry -> families. Counters gain
    the ``_total`` convention; histograms render cumulative buckets +
    ``_count``."""
    from .metrics import Counter, Gauge, Histogram

    for name, inst in sorted(registry._instruments.items()):
        if isinstance(inst, Counter):
            n = name if name.endswith("_total") else name + "_total"
            fams.sample(n, inst.value, mtype="counter", help=inst.help)
        elif isinstance(inst, Gauge):
            fams.sample(name, inst.value, mtype="gauge", help=inst.help)
        elif isinstance(inst, Histogram):
            fam = fams.family(name, "histogram", inst.help)
            cum = 0
            for edge, count in zip(inst.edges, inst.counts):
                cum += int(count)
                fam["samples"].append(
                    ("_bucket", {"le": _fmt_value(edge)}, cum)
                )
            cum += int(inst.counts[-1])
            fam["samples"].append(("_bucket", {"le": "+Inf"}, cum))
            fam["samples"].append(("_count", {}, cum))


def _render_fleet(fams: _Families, index: dict) -> None:
    """Fleet index rollups + per-run gauges -> families (the serving
    health plane ROADMAP #1/#3 sit on, scrapeable)."""
    rollup = index.get("rollup", {}) or {}
    fams.sample(
        "fleet_runs", rollup.get("runs"),
        help="logical runs in the fleet index",
    )
    for verdict, n in (rollup.get("verdicts") or {}).items():
        fams.sample(
            "fleet_runs_by_verdict", n,
            help="fleet runs per run-doctor verdict",
            labels={"verdict": verdict},
        )
    for key, help_s in (
        ("fault_rate", "fraction of runs with a dispatch_fault"),
        ("resume_success_rate",
         "fraction of resumable runs whose final verdict is healthy"),
        ("live_runs", "in-flight runs with recent events"),
        ("stale_runs",
         "in-flight runs silent past the staleness threshold"),
        ("oldest_last_event_age_s",
         "oldest last-event age among in-flight runs"),
        ("throughput_trees_rows_per_s",
         "aggregate eval-stage trees-rows/s over runs reporting one"),
        ("pending_runs", "registered runs with no events yet"),
        ("vanished_logs", "event logs that disappeared between scans"),
        ("alerts_firing", "alert rules currently firing"),
        ("events", "events parsed across every run"),
        ("skipped_lines", "unparseable lines skipped across every run"),
    ):
        fams.sample("fleet_" + key, rollup.get(key), help=help_s)

    for row in index.get("runs", []):
        rid = row.get("run_id")
        if not rid:
            continue
        labels = {"run_id": rid}
        fams.sample(
            "run_info", 1,
            help="one series per run; verdict/backend ride as labels",
            labels={
                "run_id": rid,
                "verdict": str(row.get("verdict")),
                "backend": str(row.get("backend")),
            },
        )
        fams.sample(
            "run_last_event_age_s", row.get("last_event_age_s"),
            help="seconds since the run's newest event", labels=labels,
        )
        fams.sample(
            "run_best_loss", row.get("best_loss"),
            help="latest best population loss", labels=labels,
        )
        fams.sample(
            "run_throughput_trees_rows_per_s",
            row.get("throughput_trees_rows_per_s"),
            help="eval-stage trees-rows/s", labels=labels,
        )
        fams.sample(
            "run_attempts", len(row.get("attempts") or []),
            help="supervisor attempts collapsed into this row",
            labels=labels,
        )
        fams.sample(
            "run_faults", row.get("faults"),
            help="dispatch_fault events across the run's attempts",
            labels=labels,
        )
        fams.sample(
            "run_alerts_firing", len(row.get("alerts") or []),
            help="alert rules currently firing for this run",
            labels=labels,
        )


def render_openmetrics(
    registry=None,
    fleet_index: Optional[dict] = None,
    prefix: str = "srtpu_",
) -> str:
    """Render a MetricsRegistry and/or a fleet index dict into one
    Prometheus/OpenMetrics text exposition (ends with ``# EOF``)."""
    fams = _Families(prefix)
    if registry is not None:
        _render_registry(fams, registry)
    if fleet_index is not None:
        _render_fleet(fams, fleet_index)
    return fams.render()


# ---------------------------------------------------------------------------
# self-check validator
# ---------------------------------------------------------------------------

_VALUE_RE = re.compile(
    r"^[+-]?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf|NaN)$"
)


def _parse_labels(block: str, path: str, problems: List[str]) -> str:
    """Validate one ``{...}`` label block; returns a canonical string
    for duplicate detection."""
    inner = block[1:-1]
    pairs: List[Tuple[str, str]] = []
    i = 0
    while i < len(inner):
        m = re.match(r'([a-zA-Z_][a-zA-Z0-9_]*)="', inner[i:])
        if not m:
            problems.append(f"{path}: bad label syntax at {inner[i:]!r}")
            return block
        name = m.group(1)
        j = i + m.end()
        val = []
        while j < len(inner):
            c = inner[j]
            if c == "\\":
                if j + 1 >= len(inner) or inner[j + 1] not in '\\"n':
                    problems.append(f"{path}: bad escape in label {name}")
                    return block
                val.append(inner[j:j + 2])
                j += 2
                continue
            if c == '"':
                break
            if c == "\n":
                problems.append(f"{path}: raw newline in label {name}")
                return block
            val.append(c)
            j += 1
        else:
            problems.append(f"{path}: unterminated label value ({name})")
            return block
        pairs.append((name, "".join(val)))
        j += 1  # closing quote
        if j < len(inner) and inner[j] == ",":
            j += 1
        i = j
    names = [n for n, _ in pairs]
    if len(set(names)) != len(names):
        problems.append(f"{path}: duplicate label name")
    return "{" + ",".join(f'{n}="{v}"' for n, v in sorted(pairs)) + "}"


def validate_exposition(text: str, max_problems: int = 20) -> List[str]:
    """Problems (empty = valid) for one text exposition: every line is
    a comment (``# HELP``/``# TYPE``/``# EOF``) or a sample; ``# TYPE``
    at most once per family and before any of its samples; sample names
    belong to a declared family's sample set (``name``, and for
    histograms ``_bucket``/``_count``/``_sum``); label syntax and value
    floats parse; no duplicate (name, labels) sample; the last line is
    ``# EOF`` with nothing after it."""
    problems: List[str] = []
    types: Dict[str, str] = {}
    sampled_names: set = set()
    seen_samples: set = set()
    eof_seen = False

    def _family_of(name: str) -> Optional[str]:
        if name in types:
            return name
        for suffix in ("_bucket", "_count", "_sum"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                base = name[: -len(suffix)]
                if types[base] in ("histogram", "summary"):
                    return base
        return None

    lines = text.split("\n")
    for lineno, line in enumerate(lines, 1):
        if len(problems) >= max_problems:
            problems.append("... (truncated)")
            break
        path = f"line {lineno}"
        if line == "":
            # only legal as the trailing newline's split artifact
            if lineno != len(lines):
                problems.append(f"{path}: blank line inside exposition")
            continue
        if eof_seen:
            problems.append(f"{path}: content after # EOF")
            continue
        if line.startswith("#"):
            if line == "# EOF":
                eof_seen = True
                continue
            m = re.match(r"^# (HELP|TYPE) ([a-zA-Z_:][a-zA-Z0-9_:]*)(?: (.*))?$", line)
            if not m:
                problems.append(f"{path}: malformed comment {line!r}")
                continue
            kind, name = m.group(1), m.group(2)
            if kind == "TYPE":
                t = (m.group(3) or "").strip()
                if t not in _TYPES:
                    problems.append(f"{path}: unknown type {t!r}")
                if name in types:
                    problems.append(f"{path}: duplicate TYPE for {name}")
                if name in sampled_names:
                    problems.append(
                        f"{path}: TYPE for {name} after its samples"
                    )
                types[name] = t
            continue
        m = re.match(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)(?:\s+(\S+))?$",
            line,
        )
        if not m:
            problems.append(f"{path}: not a sample line {line!r}")
            continue
        name, labels, value, ts = m.groups()
        sampled_names.add(name)
        if _family_of(name) is None:
            problems.append(f"{path}: sample {name} has no TYPE")
        canon = _parse_labels(labels, path, problems) if labels else ""
        if not _VALUE_RE.match(value):
            problems.append(f"{path}: unparseable value {value!r}")
        if ts is not None and not re.match(r"^-?[0-9]+(\.[0-9]+)?$", ts):
            problems.append(f"{path}: unparseable timestamp {ts!r}")
        key = (name, canon)
        if key in seen_samples:
            problems.append(f"{path}: duplicate sample {name}{canon}")
        seen_samples.add(key)
    if not eof_seen:
        problems.append("missing # EOF terminator")
    return problems


# ---------------------------------------------------------------------------
# delivery: atomic textfile + stdlib HTTP endpoint
# ---------------------------------------------------------------------------


def write_textfile(path: str, text: str, validate: bool = True) -> None:
    """Atomically write one exposition for a textfile collector: temp
    file in the target directory, fsync, ``os.replace`` — a scraper can
    never observe a torn file. ``validate=True`` (default) self-checks
    the exposition first and raises ``ValueError`` on problems: a
    malformed exposition must never reach the scrape path."""
    if validate:
        problems = validate_exposition(text)
        if problems:
            raise ValueError(
                f"invalid exposition ({len(problems)} problem(s)): "
                + problems[0]
            )
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def serve_metrics(render_fn, host: str = "127.0.0.1", port: int = 0):
    """Start a background stdlib HTTP server exposing ``GET /metrics``.

    ``render_fn()`` is called per scrape and must return the exposition
    text (e.g. ``lambda: render_openmetrics(fleet_index=scanner.refresh())``).
    Returns the server; ``server.server_address[1]`` is the bound port
    (``port=0`` picks an ephemeral one). Stop with ``server.shutdown()``
    then ``server.server_close()``. A render failure answers 500 with
    the error text — the scrape target degrades, the fleet process does
    not die."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API name)
            if self.path.split("?")[0].rstrip("/") not in ("", "/metrics"):
                self.send_error(404)
                return
            try:
                body = render_fn().encode()
            except Exception as e:
                msg = f"render failed: {type(e).__name__}: {e}\n".encode()
                self.send_response(500)
                self.send_header("Content-Length", str(len(msg)))
                self.end_headers()
                self.wfile.write(msg)
                return
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes must not spam stderr
            pass

    server = ThreadingHTTPServer((host, port), _Handler)
    thread = threading.Thread(
        target=server.serve_forever, name="srtpu-metrics", daemon=True
    )
    thread.start()
    server._srtpu_thread = thread
    return server
