"""Declarative alert rules over the fleet index rows.

The fleet scanner (:mod:`.fleet`) turns many event logs into index
rows; this module is the policy layer over them: a small vocabulary of
threshold rules, each a pure function of ``(row, ctx)``, evaluated on
every refresh. A firing rule yields one alert dict; the scanner appends
each NEW firing to the alerts log as a schema-v1 ``alert`` event and
``scripts/srfleet.py --once`` exits nonzero iff any rule fires — the
CI/pager form of "is the fleet healthy?".

Rule vocabulary (:data:`DEFAULT_ALERT_RULES`, docs/observability.md
"Fleet"):

* ``stalled_run`` — the run doctor read the run as ``stalled``
  (best-loss plateau with collapsed diversity): its islands are burning
  compute that will not help;
* ``diverging_run`` — doctor verdict ``diverging`` (NaN/Inf flood);
* ``fault_unresumable`` — a ``dispatch_fault`` with NO ``saved_state``
  to resume from: work is actually lost, not just interrupted (the
  resumable complement is the supervisor's normal recovery path and
  does not alert);
* ``stale_run`` — an in-flight run whose last event is older than
  ``ctx["stale_after_s"]`` (default 600 s): either the process is dead
  (killed without a fault event — the line-buffered log just stops) or
  it is wedged on a hung tunnel; both need a human or the supervisor;
* ``queue_stalled`` — the srserve admission queue
  (:meth:`..serving.jobs.JobServer.alert_row` rows) holds a job older
  than ``ctx["queue_deadline_s"]`` (default 4x the server's flush
  timeout): the batcher stopped dispatching;
* ``compile_bound`` — the doctor's compile-share flag (> 50% of
  measured wall in first-dispatch compilation): warm the compilation
  cache before trusting any timing from this run. Severity ``info``, a
  note rather than a page: every cold-start smoke run is legitimately
  compile-bound, and srfleet's ``--once`` gate only fails at
  ``--fail-on`` severity or above (default ``warning``);
* ``throughput_regression`` — the run's eval-stage trees-rows/s sits
  more than ``ctx["regression_threshold"]`` (default 10%) below the
  best SAME-PLATFORM round in ``ctx["trajectory"]`` (a TRAJECTORY.json
  payload). Opt-in: it only evaluates when a trajectory is supplied
  (``srfleet --trajectory``) — tiny smoke searches would otherwise
  drown the fleet in false regressions.

Severities: ``critical`` (work lost / wasted), ``warning`` (needs a
look), ``info`` (a note). Custom policies pass their own rule tuple to
:class:`..fleet.FleetScanner` — a rule is just
``AlertRule(name, severity, description, check)``.

Host-side only; no jax import.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative rule: ``check(row, ctx)`` returns None (not
    firing) or a dict with at least ``message`` (optionally ``value`` /
    ``threshold`` for the exposition and the alert event)."""

    name: str
    severity: str  # "critical" | "warning" | "info"
    description: str
    check: Callable[[Dict[str, Any], Dict[str, Any]], Optional[dict]]


def _stalled(row, ctx):
    if row.get("verdict") == "stalled":
        return {"message": "; ".join(row.get("reasons") or ["stalled"])}
    return None


def _diverging(row, ctx):
    if row.get("verdict") == "diverging":
        return {"message": "; ".join(row.get("reasons") or ["diverging"])}
    return None


def _fault_unresumable(row, ctx):
    if row.get("verdict") == "faulted" and not row.get("resumable"):
        return {
            "message": (
                f"{row.get('faults', 0)} fault(s) with no saved_state "
                "to resume from — work lost"
            ),
            "value": float(row.get("faults") or 0),
        }
    return None


def _stale(row, ctx):
    age = row.get("last_event_age_s")
    limit = float(ctx.get("stale_after_s") or 0.0)
    if (
        row.get("verdict") == "incomplete"
        and age is not None
        and limit > 0
        and age > limit
    ):
        return {
            "message": (
                f"in-flight run silent for {age:.0f}s "
                f"(> {limit:.0f}s): dead or wedged"
            ),
            "value": age,
            "threshold": limit,
        }
    return None


def _compile_bound(row, ctx):
    if row.get("compile_bound"):
        share = row.get("compile_share")
        return {
            "message": (
                f"{(share or 0.0):.0%} of measured wall went to "
                "first-dispatch compilation — warm the cache before "
                "reading timings"
            ),
            "value": share,
            "threshold": 0.5,
        }
    return None


def trajectory_best_throughput(trajectory: Optional[dict]) -> Dict[str, float]:
    """Best recorded trees-rows/s per platform from a TRAJECTORY.json
    payload (scripts/bench_trajectory.py schema) — the regression bar
    the ``throughput_regression`` rule compares against."""
    best: Dict[str, float] = {}
    if not isinstance(trajectory, dict):
        return best
    for p in trajectory.get("series", {}).get("throughput", []):
        v, plat = p.get("value"), p.get("platform")
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and isinstance(plat, str):
            if plat not in best or v > best[plat]:
                best[plat] = float(v)
    return best


def _numerically_degenerate(row, ctx):
    """The containment layer is clamping most of the run's population
    to the inf sentinel (ISSUE 15): either the tenant's dataset is
    hostile past what the data policy absorbed, or the opset/scale
    combination overflows on most trees. Threshold overridable via
    ctx['nonfinite_threshold'] (default: the run doctor's
    NONFINITE_DEGENERATE, carried on the row via the doctor flag)."""
    frac = row.get("nonfinite_fraction")
    thr = ctx.get("nonfinite_threshold")
    if thr is not None:
        if frac is not None and frac > float(thr):
            return {
                "message": (
                    f"{frac:.0%} of population losses carry the inf "
                    f"sentinel (> {float(thr):.0%}): evaluation is "
                    "discarding most trees — check the run's "
                    "dataset_diagnostics"
                ),
                "value": frac,
                "threshold": float(thr),
            }
        return None
    if row.get("numerically_degenerate"):
        return {
            "message": (
                f"run doctor flagged numerically-degenerate "
                f"({(frac or 0.0):.0%} inf-sentinel population losses)"
                " — hostile data or overflow-heavy opset"
            ),
            "value": frac,
        }
    return None


def _queue_stalled(row, ctx):
    """The srserve admission queue holds a job older than the flush
    deadline (ISSUE 16): the batcher stopped dispatching — a wedged
    in-flight batch, a dead worker loop, or a flush timer that never
    fires. Evaluates only on rows that carry the queue fields
    (:meth:`..serving.jobs.JobServer.alert_row`); the deadline comes
    from ``ctx['queue_deadline_s']`` (default 4x the server's own
    ``flush_timeout_s`` when the row carries it, else 30 s)."""
    wait = row.get("serve_queue_oldest_wait_s")
    if wait is None:
        return None
    limit = ctx.get("queue_deadline_s")
    if limit is None:
        ft = row.get("serve_flush_timeout_s")
        limit = 4.0 * float(ft) if ft else 30.0
    limit = float(limit)
    if limit > 0 and float(wait) > limit:
        depth = row.get("serve_queue_depth")
        return {
            "message": (
                f"oldest queued job waiting {float(wait):.0f}s "
                f"(> {limit:.0f}s) with {depth or 0} job(s) pending — "
                "the batcher is not flushing"
            ),
            "value": float(wait),
            "threshold": limit,
        }
    return None


def _throughput_regression(row, ctx):
    best = trajectory_best_throughput(ctx.get("trajectory"))
    plat = row.get("backend")
    tp = row.get("throughput_trees_rows_per_s")
    thr = float(ctx.get("regression_threshold") or 0.10)
    bar = best.get(plat)
    if bar and tp is not None and tp < bar * (1.0 - thr):
        return {
            "message": (
                f"eval throughput {tp:.3g} trees-rows/s is "
                f"{1.0 - tp / bar:.0%} below the best {plat} round's "
                f"{bar:.3g} (threshold {thr:.0%})"
            ),
            "value": tp,
            "threshold": bar * (1.0 - thr),
        }
    return None


DEFAULT_ALERT_RULES: Sequence[AlertRule] = (
    AlertRule(
        "fault_unresumable", "critical",
        "dispatch_fault with no saved_state in the trail — work lost",
        _fault_unresumable,
    ),
    AlertRule(
        "diverging_run", "critical",
        "run doctor verdict diverging (NaN/Inf flood)",
        _diverging,
    ),
    AlertRule(
        "stalled_run", "warning",
        "run doctor verdict stalled (plateau + diversity collapse)",
        _stalled,
    ),
    AlertRule(
        "stale_run", "warning",
        "in-flight run with no events for stale_after_s seconds",
        _stale,
    ),
    AlertRule(
        "numerically_degenerate", "warning",
        "most population losses clamped to the inf sentinel "
        "(containment layer discarding the search's work — hostile "
        "data or overflow-heavy opset)",
        _numerically_degenerate,
    ),
    AlertRule(
        "queue_stalled", "warning",
        "srserve admission queue holds a job past the flush deadline "
        "(the batcher stopped dispatching)",
        _queue_stalled,
    ),
    AlertRule(
        "compile_bound", "info",
        "more than half the measured wall time was compilation "
        "(every cold-start smoke run trips this — info, not a page)",
        _compile_bound,
    ),
    AlertRule(
        "throughput_regression", "warning",
        "eval throughput below the best same-platform trajectory round "
        "(requires a trajectory in ctx)",
        _throughput_regression,
    ),
)


def evaluate_alerts(
    rows: Sequence[Dict[str, Any]],
    ctx: Dict[str, Any],
    rules: Optional[Sequence[AlertRule]] = None,
) -> List[dict]:
    """Evaluate every rule against every row (``rules=None`` means
    :data:`DEFAULT_ALERT_RULES`). Returns the firing alerts
    (severity-major order: critical first, then by rule/run for a
    stable rendering). A rule that raises is reported as an alert about
    ITSELF (``rule_error``) rather than silently skipped — a broken
    pager rule is an outage of the pager."""
    if rules is None:
        rules = DEFAULT_ALERT_RULES
    sev_rank = {"critical": 0, "warning": 1, "info": 2}
    out: List[dict] = []
    for row in rows:
        for rule in rules:
            try:
                hit = rule.check(row, ctx)
            except Exception as e:
                hit = {
                    "message": (
                        f"alert rule {rule.name!r} raised "
                        f"{type(e).__name__}: {e}"
                    ),
                }
                out.append({
                    "rule": "rule_error",
                    "severity": "warning",
                    "run_id": row.get("run_id"),
                    **hit,
                })
                continue
            if hit is None:
                continue
            out.append({
                "rule": rule.name,
                "severity": rule.severity,
                "run_id": row.get("run_id"),
                "message": hit.get("message", rule.description),
                "value": hit.get("value"),
                "threshold": hit.get("threshold"),
            })
    out.sort(key=lambda a: (
        sev_rank.get(a["severity"], 3), a["rule"], str(a["run_id"])
    ))
    return out
