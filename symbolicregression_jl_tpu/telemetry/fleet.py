"""Fleet telemetry: the multi-run index over one root of event logs.

Every observability artifact so far is *per-run* — the event log (one
``events-<run>.jsonl`` per search), the run doctor's verdict over it,
srtop's live tail, srprof's roofline join. Production is *many
concurrent runs*: watcher steps, supervisor attempts, suite cases, and
(ROADMAP #1) tenant jobs, all writing into directories under one root
(``SRTPU_BENCH_TELEMETRY_DIR`` already funnels the watcher's steps
there). This module is the layer that reads them all:

* :class:`FleetScanner` — discovers every ``events-*.jsonl`` under a
  fleet root (recursively), tails each **incrementally** with srtop's
  byte-offset/partial-line discipline (a refresh costs only the new
  bytes; a half-written last line is held until its newline lands; a
  truncated/rotated file resets its tail; a file or directory that
  disappears between scans drops out without an error), summarizes
  every run through the run doctor (:func:`..analyze.analyze_run`), and
  collapses a supervised run's multi-attempt trail into ONE row keyed
  on the ``run_start`` event's stable ``run_id`` (the resilience
  supervisor threads one id through every attempt — the
  ``resumable`` -> resumed lineage is exact, not filename-inferred);
* ``fleet_index.json`` — the crash-safe (write-to-temp + atomic
  ``os.replace``) machine-readable index the scanner maintains: one row
  per logical run (verdict, backend, throughput, stage/compile shares,
  modeled roofline fraction, fault/resume timeline, last-event age)
  plus fleet rollups (verdict histogram, fault rate, resume-success
  rate, aggregate trees-rows/s, staleness);
* the alert loop — every refresh evaluates the declarative rules in
  :mod:`.alerts` over the rows and appends each NEWLY-firing alert to
  ``fleet_alerts.jsonl`` as an additive schema-v1 ``alert`` event (the
  envelope ``run`` carries the run_id the rule fired for); an alert
  that stops firing re-arms, so a later recurrence is logged again;
* :func:`register_run` — producers (the resilience supervisor, the TPU
  watcher, bench) announce runs into ``fleet_registry.jsonl`` under the
  root, so the index can show what was *launched*, not only what has
  already written events. One strict-JSON line per registration,
  append-only and crash-safe like the event log itself. The watcher
  writes the same line format inline (it must never import this
  package — importing jax at the tunnel is exactly what it guards
  against), so the format here is a compatibility contract: keep it to
  the documented keys.

Everything here is host-side file reading — no jax import, zero
primitives added to any jitted program, and registration on/off leaves
the hall of fame bit-identical (it is a file append).

Consumers: ``scripts/srfleet.py`` (the live dashboard + ``--once`` CI
gate), ``telemetry/export.py`` (the OpenMetrics exposition of the
rollups), ``benchmark/suite.py``'s ``fleet`` case, and
``scripts/lint.py``'s fleet-exposition gate. See docs/observability.md
"Fleet".
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from typing import Dict, List, Optional

from .events import SCHEMA_VERSION
from .analyze import analyze_run

#: file names the fleet layer owns under the root
INDEX_NAME = "fleet_index.json"
REGISTRY_NAME = "fleet_registry.jsonl"
ALERTS_LOG_NAME = "fleet_alerts.jsonl"

#: default seconds of last-event silence after which an incomplete run
#: is considered stale (the `stale_run` alert; srfleet `--stall-after`)
STALE_AFTER_S = 600.0


def _finite(v) -> Optional[float]:
    if isinstance(v, (int, float)) and not isinstance(v, bool) and \
            math.isfinite(v):
        return float(v)
    return None


class _LogTail:
    """Incremental reader of one JSONL event log, retaining the parsed
    events. Same discipline as srtop's tail: ``poll()`` reads only the
    NEW bytes; a partial trailing line (mid-write) stays buffered until
    its newline arrives; a file rewritten shorter (rotation) resets the
    tail and the retained events; a vanished file returns False so the
    scanner can drop it without an error."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self.buf = ""
        self.events: List[dict] = []
        self.skipped = 0

    def poll(self) -> bool:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return False  # vanished between scans
        if size < self.offset:
            # rewritten/rotated shorter: everything retained came from a
            # file that no longer exists — start over
            self.offset, self.buf = 0, ""
            self.events, self.skipped = [], 0
        try:
            with open(self.path) as f:
                f.seek(self.offset)
                chunk = f.read()
                self.offset = f.tell()
        except OSError:
            return False
        self.buf += chunk
        while "\n" in self.buf:
            line, self.buf = self.buf.split("\n", 1)
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                self.skipped += 1  # corrupt line: count, keep tailing
                continue
            if isinstance(e, dict):
                self.events.append(e)
            else:
                self.skipped += 1
        return True


def discover_logs(root: str) -> List[str]:
    """Every ``events-*.jsonl`` under `root`, recursively (the watcher,
    the supervisor, the suite, and bench each write into their own
    subdirectory of one telemetry root). The fleet's own files
    (registry/alerts/index) deliberately do not match the pattern."""
    out: List[str] = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for f in filenames:
            if f.startswith("events-") and f.endswith(".jsonl"):
                out.append(os.path.join(dirpath, f))
    return sorted(out)


def register_run(
    fleet_root: str,
    *,
    source: str,
    run_id: Optional[str] = None,
    telemetry_dir: Optional[str] = None,
    attempt: Optional[int] = None,
    **extra,
) -> Optional[dict]:
    """Append one registration line to ``<fleet_root>/fleet_registry.jsonl``.

    Producers call this when they LAUNCH a run, so the fleet index can
    distinguish "registered but no events yet" from "nothing there".
    One strict-JSON line per call (append-only, crash-safe — a SIGKILL
    loses at most the line in flight); never raises: observability must
    not kill the run it observes. Returns the written record (None on
    failure). Keys are a compatibility contract with
    ``scripts/tpu_watcher.py``, which writes the same lines inline:
    ``t`` / ``source`` / ``run_id`` / ``telemetry_dir`` / ``attempt``.
    """
    rec = {
        "t": time.time(),
        "source": str(source),
        "run_id": run_id,
        "telemetry_dir": telemetry_dir,
        "attempt": attempt,
    }
    for k, v in extra.items():
        rec[str(k)] = v
    try:
        os.makedirs(fleet_root, exist_ok=True)
        with open(
            os.path.join(fleet_root, REGISTRY_NAME), "a", buffering=1
        ) as f:
            f.write(json.dumps(rec, allow_nan=False) + "\n")
    except (OSError, ValueError, TypeError) as e:
        print(f"fleet: registration failed ({e})", file=sys.stderr)
        return None
    return rec


def load_registry(fleet_root: str) -> List[dict]:
    """Tolerant reader of the registration trail (unparsable lines —
    e.g. the one a killed producer left half-written — are skipped)."""
    path = os.path.join(fleet_root, REGISTRY_NAME)
    out: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def load_fleet_index(path: str) -> Optional[dict]:
    """Read one ``fleet_index.json``: absent returns None; a corrupt
    file raises ValueError so a consumer knows the index is damaged
    rather than silently empty (the writer is atomic — corruption means
    something other than the scanner touched it)."""
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# per-log summaries -> per-run rows
# ---------------------------------------------------------------------------


def _log_summary(events: List[dict], skipped: int, path: str) -> dict:
    """One log -> {run_id, attempt, report, ...}: the doctor's report
    plus the fleet join keys and the throughput/roofline extractions the
    doctor does not compute."""
    report = analyze_run(events)
    start = next(
        (e for e in events if e.get("type") == "run_start"), {}
    )
    run_env = start.get("run") or (
        events[0].get("run") if events else None
    )
    run_id = start.get("run_id") or run_env or os.path.basename(path)
    attempt = start.get("attempt")
    if not isinstance(attempt, int) or attempt < 1:
        attempt = 1

    # eval-stage throughput: bench stamps the overhead-subtracted
    # trees_rows_per_s on its eval span; searches carry trees/rows on
    # the one-shot eval probe span — derive from the last one present
    throughput = None
    for e in events:
        if e.get("type") != "span" or e.get("name") != "eval":
            continue
        attrs = e.get("attrs") or {}
        v = _finite(attrs.get("trees_rows_per_s"))
        if v is None:
            trees = _finite(attrs.get("trees"))
            rows = _finite(attrs.get("rows"))
            dur = _finite(e.get("duration_s"))
            if trees and rows and dur:
                v = trees * rows / dur
        if v is not None:
            throughput = v

    # modeled roofline: bench's `roofline` event carries it directly;
    # searches emit per-stage srprof `profile` events — take the eval
    # stage's fraction (the scoring program is the roofline the repo
    # tracks, TRAJECTORY.md's roofline_modeled column)
    roofline_modeled = None
    for e in events:
        if e.get("type") == "roofline":
            v = _finite(e.get("modeled_fraction"))
            if v is not None:
                roofline_modeled = v
        elif e.get("type") == "profile" and e.get("stage") == "eval":
            v = _finite(e.get("roofline_fraction"))
            if v is not None:
                roofline_modeled = v

    return {
        "run_id": str(run_id),
        "run": run_env,
        "attempt": attempt,
        "log": path,
        "events": len(events),
        "skipped_lines": skipped,
        "report": report,
        "throughput": throughput,
        "roofline_modeled": roofline_modeled,
    }


def _timeline(events_by_attempt: List[dict]) -> List[dict]:
    """The fault/resume timeline across a run's attempts, in time
    order: every dispatch_fault, saved_state, resume (run_start with
    resume_from), and run_end — the compact story srfleet and the index
    row render."""
    out: List[dict] = []
    for s in events_by_attempt:
        r = s["report"]
        for f in r.get("faults", []):
            out.append({
                "t": _finite(f.get("t")), "attempt": s["attempt"],
                "kind": "fault", "error_type": f.get("error_type"),
            })
        saved = r.get("last_saved_state")
        if saved:
            out.append({
                "t": _finite(saved.get("t")), "attempt": s["attempt"],
                "kind": "saved_state",
                "iteration": saved.get("iteration"),
            })
        resume = (r.get("run") or {}).get("resume_from")
        if resume:
            out.append({
                "t": r.get("t_first"), "attempt": s["attempt"],
                "kind": "resume", "iteration": resume.get("iteration"),
            })
        if r.get("complete"):
            out.append({
                "t": r.get("t_last"), "attempt": s["attempt"],
                "kind": "run_end",
            })
    out.sort(key=lambda e: (e["t"] is None, e["t"] or 0.0))
    return out


def _build_row(summaries: List[dict], now: float) -> dict:
    """Collapse one logical run's per-attempt summaries (sorted) into
    one index row. The NEWEST attempt drives the verdict; the lineage
    list keeps every attempt's verdict so a resumable->resumed story is
    readable straight off the row."""
    latest = summaries[-1]
    report = latest["report"]
    run = report.get("run", {}) or {}
    stages = report.get("stages", {}) or {}
    stage_total = sum(v.get("total_s", 0.0) for v in stages.values())
    stage_shares = {
        k: round(v.get("total_s", 0.0) / stage_total, 4)
        for k, v in stages.items()
    } if stage_total > 0 else {}
    t_last = max(
        (s["report"].get("t_last") for s in summaries
         if s["report"].get("t_last") is not None),
        default=None,
    )
    t_first = min(
        (s["report"].get("t_first") for s in summaries
         if s["report"].get("t_first") is not None),
        default=None,
    )
    resumed = len(summaries) > 1 or bool(run.get("resume_from"))
    return {
        "run_id": latest["run_id"],
        "verdict": report.get("verdict"),
        "reasons": report.get("reasons", []),
        "backend": run.get("backend"),
        "device_kind": run.get("device_kind"),
        "nout": run.get("nout"),
        "niterations": run.get("niterations"),
        "attempt": latest["attempt"],
        "attempts": [
            {
                "attempt": s["attempt"],
                "run": s["run"],
                "log": s["log"],
                "verdict": s["report"].get("verdict"),
                "resumable": bool(s["report"].get("resumable")),
                "complete": bool(s["report"].get("complete")),
            }
            for s in summaries
        ],
        "resumed": resumed,
        "resume_from": run.get("resume_from"),
        "complete": bool(report.get("complete")),
        "resumable": bool(report.get("resumable")),
        "faults": sum(len(s["report"].get("faults", []))
                      for s in summaries),
        "saved_states": sum(s["report"].get("saved_states", 0)
                            for s in summaries),
        "timeline": _timeline(summaries),
        "best_loss": (report.get("best_loss") or {}).get("last"),
        "throughput_trees_rows_per_s": latest["throughput"],
        "evals_per_s": (
            report["num_evals"] / report["wall_s"]
            if report.get("num_evals") and report.get("wall_s")
            else None
        ),
        "stage_shares": stage_shares,
        "compile_share": report.get("compile_share"),
        "compile_bound": bool(report.get("compile_bound")),
        # numeric-containment health (ISSUE 15): latest population
        # inf-sentinel fraction + the doctor's degenerate flag — the
        # numerically_degenerate alert rule's inputs
        "nonfinite_fraction": report.get("nonfinite_fraction"),
        "numerically_degenerate": bool(
            report.get("numerically_degenerate")
        ),
        "roofline_modeled": latest["roofline_modeled"],
        "t_first": t_first,
        "t_last": t_last,
        "last_event_age_s": (
            round(now - t_last, 3) if t_last is not None else None
        ),
        "events": sum(s["events"] for s in summaries),
        "skipped_lines": sum(s["skipped_lines"] for s in summaries),
        "logs": [s["log"] for s in summaries],
    }


def _rollup(rows: List[dict], now: float, stale_after_s: float) -> dict:
    """Fleet-level aggregates over the rows — the numbers the
    OpenMetrics exposition and the srfleet header render."""
    verdicts: Dict[str, int] = {}
    for r in rows:
        v = str(r.get("verdict"))
        verdicts[v] = verdicts.get(v, 0) + 1
    n = len(rows)
    faulted_rows = [r for r in rows if r["faults"]]
    # resume-success: among runs that ever were resumable (a fault or
    # kill with a snapshot banked) or actually resumed, the fraction
    # whose FINAL verdict is healthy — the fleet-level answer to "does
    # the resume loop actually recover work?"
    resumable_rows = [
        r for r in rows
        if r["resumed"] or any(a["resumable"] for a in r["attempts"])
    ]
    resumed_ok = [
        r for r in resumable_rows if r["verdict"] == "healthy"
    ]
    incomplete = [r for r in rows if not r["complete"]
                  and r["verdict"] not in ("faulted", "empty")]
    ages = [r["last_event_age_s"] for r in incomplete
            if r["last_event_age_s"] is not None]
    throughputs = [
        r["throughput_trees_rows_per_s"] for r in rows
        if r["throughput_trees_rows_per_s"] is not None
    ]
    return {
        "runs": n,
        "verdicts": dict(sorted(verdicts.items())),
        "fault_rate": round(len(faulted_rows) / n, 4) if n else None,
        "resumable_runs": len(resumable_rows),
        "resume_success_rate": (
            round(len(resumed_ok) / len(resumable_rows), 4)
            if resumable_rows else None
        ),
        "live_runs": sum(1 for a in ages if a <= stale_after_s),
        "stale_runs": sum(1 for a in ages if a > stale_after_s),
        "oldest_last_event_age_s": (
            round(max(ages), 3) if ages else None
        ),
        "throughput_trees_rows_per_s": (
            sum(throughputs) if throughputs else None
        ),
        "events": sum(r["events"] for r in rows),
        "skipped_lines": sum(r["skipped_lines"] for r in rows),
    }


def write_index_atomic(path: str, index: dict) -> None:
    """Crash-safe index write: temp file in the same directory, fsync,
    atomic ``os.replace`` — a reader (or a kill) can never observe a
    torn ``fleet_index.json``."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(index, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class FleetScanner:
    """Incremental multi-run scanner over one fleet root.

    ``refresh()`` re-discovers logs, tails each for new bytes, rebuilds
    the per-run rows and rollups, evaluates the alert rules, appends
    newly-firing alerts to the alerts log, atomically rewrites
    ``fleet_index.json``, and returns the index dict. Designed to be
    called in a loop (srfleet) or once (CI): state (tails, fired-alert
    set) lives on the instance, so repeated refreshes cost only the new
    bytes and re-log only state CHANGES.
    """

    def __init__(
        self,
        root: str,
        *,
        stale_after_s: float = STALE_AFTER_S,
        alert_rules=None,
        trajectory: Optional[dict] = None,
        regression_threshold: float = 0.10,
        index_path: Optional[str] = None,
        alerts_log_path: Optional[str] = None,
        write_index: bool = True,
        emit_alert_events: bool = True,
    ):
        self.root = root
        self.stale_after_s = float(stale_after_s)
        self.alert_rules = alert_rules
        self.trajectory = trajectory
        self.regression_threshold = float(regression_threshold)
        self.index_path = index_path or os.path.join(root, INDEX_NAME)
        self.alerts_log_path = alerts_log_path or os.path.join(
            root, ALERTS_LOG_NAME
        )
        self.write_index = write_index
        self.emit_alert_events = emit_alert_events
        self._tails: Dict[str, _LogTail] = {}
        # per-log summary cache keyed by (events, skipped) counts: a
        # refresh that read zero new bytes re-runs NO analyze_run — the
        # "repeated refreshes cost only the new bytes" contract covers
        # the analysis, not just the I/O
        self._summaries: Dict[str, tuple] = {}
        self._fired: set = set()
        self._vanished = 0

    def refresh(self, now: Optional[float] = None) -> dict:
        from .alerts import DEFAULT_ALERT_RULES, evaluate_alerts

        now = time.time() if now is None else now
        paths = set(discover_logs(self.root))
        for p in paths:
            self._tails.setdefault(p, _LogTail(p))
        for p, tail in list(self._tails.items()):
            if not tail.poll():
                # the file (or its whole run directory) disappeared
                # between scans: drop the tail, count the loss — never
                # an error, never a stale ghost row
                del self._tails[p]
                self._summaries.pop(p, None)
                self._vanished += 1

        groups: Dict[str, List[dict]] = {}
        for p, tail in sorted(self._tails.items()):
            if not tail.events:
                continue  # nothing parseable yet (mid-create)
            key = (len(tail.events), tail.skipped)
            cached = self._summaries.get(p)
            if cached is None or cached[0] != key:
                cached = (key, _log_summary(tail.events, tail.skipped, p))
                self._summaries[p] = cached
            groups.setdefault(cached[1]["run_id"], []).append(cached[1])
        rows = []
        for key, summaries in sorted(groups.items()):
            summaries.sort(key=lambda s: (
                s["attempt"],
                s["report"].get("t_first") or 0.0,
                s["log"],
            ))
            rows.append(_build_row(summaries, now))
        rows.sort(key=lambda r: (-(r["t_last"] or 0.0), r["run_id"]))

        registry = load_registry(self.root)
        seen_ids = {r["run_id"] for r in rows}
        # a run is "pending" while it is registered but silent — the
        # launched-but-no-events state the registry exists to expose.
        # Id-stamped registrations (the supervisor) join exactly;
        # anonymous ones (watcher steps launch MANY searches and cannot
        # pre-know their ids) stay pending until any log under their
        # telemetry_dir (or anywhere, when unset) starts at/after the
        # registration time.
        log_starts = [
            (os.path.abspath(s["log"]), s["report"].get("t_first"))
            for _, s in self._summaries.values()
        ]
        pending = []
        for rec in registry:
            rid = rec.get("run_id")
            if rid:
                if rid not in seen_ids:
                    pending.append(rec)
                continue
            t_reg = rec.get("t") or 0.0
            d = rec.get("telemetry_dir")
            prefix = os.path.abspath(d) + os.sep if d else None
            satisfied = any(
                # 1s grace for clock fuzz between registrar and run
                t_first is not None and t_first >= t_reg - 1.0
                and (prefix is None or path.startswith(prefix))
                for path, t_first in log_starts
            )
            if not satisfied:
                pending.append(rec)

        rollup = _rollup(rows, now, self.stale_after_s)
        rollup["vanished_logs"] = self._vanished
        rollup["registered"] = len(registry)
        rollup["pending_runs"] = len(pending)

        ctx = {
            "now": now,
            "stale_after_s": self.stale_after_s,
            "trajectory": self.trajectory,
            "regression_threshold": self.regression_threshold,
        }
        rules = (
            DEFAULT_ALERT_RULES if self.alert_rules is None
            else self.alert_rules
        )
        alerts = evaluate_alerts(rows, ctx, rules=rules)
        by_run: Dict[str, List[str]] = {}
        for a in alerts:
            by_run.setdefault(a["run_id"], []).append(a["rule"])
        for r in rows:
            r["alerts"] = by_run.get(r["run_id"], [])
        rollup["alerts_firing"] = len(alerts)

        if self.emit_alert_events:
            self._emit_alert_events(alerts, now)

        index = {
            "generated_by": "symbolicregression_jl_tpu.telemetry.fleet",
            "v": 1,
            "t": now,
            "root": self.root,
            "stale_after_s": self.stale_after_s,
            "runs": rows,
            "rollup": rollup,
            "alerts": alerts,
            "pending": pending,
        }
        if self.write_index:
            try:
                write_index_atomic(self.index_path, index)
            except OSError as e:  # pragma: no cover - defensive
                print(f"fleet: index write failed ({e})", file=sys.stderr)
        return index

    def _emit_alert_events(self, alerts: List[dict], now: float) -> None:
        """Append each NEWLY-firing (rule, run_id) pair to the alerts
        log as one schema-v1 ``alert`` event. An alert that stops firing
        re-arms — a later recurrence logs again (the log is the history;
        the index's ``alerts`` field is the current state)."""
        keys = {(a["rule"], a["run_id"]) for a in alerts}
        new = [a for a in alerts
               if (a["rule"], a["run_id"]) not in self._fired]
        self._fired = keys
        if not new:
            return
        try:
            with open(self.alerts_log_path, "a", buffering=1) as f:
                for a in new:
                    event = {
                        "v": SCHEMA_VERSION,
                        "t": now,
                        "run": a["run_id"],
                        "type": "alert",
                        "rule": a["rule"],
                        "severity": a["severity"],
                        "message": a["message"],
                        "value": _finite(a.get("value")),
                        "threshold": _finite(a.get("threshold")),
                        "fleet": self.root,
                    }
                    f.write(json.dumps(event, allow_nan=False) + "\n")
        except (OSError, ValueError) as e:  # pragma: no cover
            print(f"fleet: alert log write failed ({e})", file=sys.stderr)
