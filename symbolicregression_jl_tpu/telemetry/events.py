"""Append-only JSONL event log — the machine-readable trail of one run.

One file per run, one strict-JSON object per line, schema-versioned
(``event_schema_v1.json`` next to this module is the checked-in
contract; ``scripts/lint.py`` validates a golden fixture against it so
the writer and the schema cannot drift apart silently).

Design constraints, in order:

* **crash-safe**: the file is opened line-buffered and every event is
  one ``write()`` of one ``\\n``-terminated line — a SIGKILL mid-run
  loses at most the event being written, never the file (this is the
  trail the resume-not-restart story of ROADMAP item 4 needs after a
  mid-run UNAVAILABLE fault or a tunnel drop);
* **strict JSON**: ``json.dumps(allow_nan=False)`` after a sanitizer
  that converts numpy scalars/arrays to Python and non-finite floats to
  null — every line parses with any JSON reader, unlike the recorder's
  bare-``Infinity`` output;
* **never fatal**: a failed write disables the log with one stderr
  warning; observability must not kill the search it observes.

Event vocabulary (see the schema file / docs/observability.md):
``run_start``, ``span``, ``metrics``, ``progress``, ``dispatch_fault``,
``tunnel_state``, ``saved_state``, ``checkpoint``, ``resource_warning``,
``recorder_saved``, ``probe_error``, ``run_end``.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1

SCHEMA_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "event_schema_v1.json"
)


def _sanitize(obj):
    """Recursively convert to strict-JSON-serializable Python values:
    numpy scalars/arrays -> Python, tuples/sets -> lists, non-finite
    floats -> None, dict keys -> str. The non-finite coercion applies at
    EVERY nesting level — a metrics snapshot is a dict of dicts of
    gauges, and an Inf three levels down must become null exactly like a
    top-level one (unit-tested), or json.dumps(allow_nan=False) would
    disable the log."""
    import numpy as np

    if obj is None or isinstance(obj, (bool, str)):
        return obj
    if isinstance(obj, (int, np.integer)):
        return int(obj)
    if isinstance(obj, (float, np.floating)):
        f = float(obj)
        return f if math.isfinite(f) else None
    if isinstance(obj, complex):
        # complex is numeric enough that np.asarray would wrap it as a
        # non-object array whose tolist() hands it straight back — the
        # one numeric type that used to recurse without terminating
        return str(obj)
    if isinstance(obj, (set, frozenset)):
        # sets used to fall through to np.asarray (a 0-d object array)
        # and stringify wholesale; coerce the MEMBERS instead
        try:
            members = sorted(obj)
        except TypeError:
            members = list(obj)
        return [_sanitize(v) for v in members]
    if isinstance(obj, np.ndarray):
        if obj.dtype == object:
            # tolist() of an object array hands the wrapped Python
            # objects straight back — stringify instead of recursing
            return [str(v) for v in obj.ravel().tolist()]
        return _sanitize(obj.tolist())
    if isinstance(obj, dict):
        return {str(k): _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    # jax arrays and anything else NUMERIC-array-like; arbitrary objects
    # (np.asarray wraps them as 0-d object arrays, whose tolist() would
    # return the object itself and recurse forever) fall through to str
    try:
        arr = np.asarray(obj)
        if arr.dtype != object:
            return _sanitize(arr.tolist())
    except Exception:
        pass
    return str(obj)


class EventLog:
    """Writer for one run's event log. Also the event *sink* the rest of
    the telemetry subsystem (spans, metrics, progress, recorder) emits
    through — ``emit(type, **fields)`` is the whole interface."""

    def __init__(self, path: str, run_id: Optional[str] = None):
        self.path = path
        self.run_id = run_id or _default_run_id()
        # line-buffered text: one flush per event line (crash-safe)
        self._f = open(path, "w", buffering=1)
        self._dead = False

    def emit(self, type: str, **fields) -> Optional[dict]:
        """Append one event; returns the emitted dict (None if the log
        is disabled after a write failure)."""
        if self._dead:
            return None
        event = {
            "v": SCHEMA_VERSION,
            "t": time.time(),
            "run": self.run_id,
            "type": type,
        }
        try:
            # sanitize INSIDE the guard: a hostile field value must
            # disable the log, never raise into the observed search
            event.update(_sanitize(fields))
            self._f.write(json.dumps(event, allow_nan=False) + "\n")
        except (OSError, ValueError, RecursionError, TypeError) as e:
            self._dead = True
            print(
                f"telemetry: event log disabled ({type}: {e})",
                file=sys.stderr,
            )
            return None
        return event

    def close(self) -> None:
        if not self._dead:
            try:
                self._f.close()
            except OSError:  # pragma: no cover
                pass
        self._dead = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


_RUN_SEQ = iter(range(1, 1 << 31))


def _default_run_id() -> str:
    # second-resolution timestamp + pid + an in-process sequence number:
    # two sub-second runs in one process (a parameter sweep) must never
    # collide on the log path and truncate each other's trail
    return (
        time.strftime("%Y%m%dT%H%M%S")
        + f"-{os.getpid():x}-{next(_RUN_SEQ)}"
    )


def open_event_log(
    telemetry_dir: Optional[str], run_id: Optional[str] = None
) -> EventLog:
    """Create ``<telemetry_dir>/events-<run_id>.jsonl`` (directory
    created if needed; default directory: cwd)."""
    d = telemetry_dir or "."
    os.makedirs(d, exist_ok=True)
    rid = run_id or _default_run_id()
    return EventLog(os.path.join(d, f"events-{rid}.jsonl"), run_id=rid)


# ---------------------------------------------------------------------------
# schema validation
# ---------------------------------------------------------------------------

_JSON_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
    "null": type(None),
}


def load_schema() -> dict:
    with open(SCHEMA_PATH) as f:
        return json.load(f)


def _type_ok(value, tname: str) -> bool:
    if tname == "number":
        return isinstance(value, (int, float)) and not isinstance(
            value, bool
        )
    if tname == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    return isinstance(value, _JSON_TYPES[tname])


def _check_subschema(value, sub: dict, path: str, problems: List[str]):
    """Minimal JSON-Schema interpreter covering the keywords the
    checked-in schema uses: type (name or list), const, enum, required,
    properties, items. Unknown keywords are ignored (forward-compatible
    with validating the same file under a full validator)."""
    if "const" in sub and value != sub["const"]:
        problems.append(f"{path}: expected {sub['const']!r}, got {value!r}")
        return
    if "enum" in sub and value not in sub["enum"]:
        problems.append(f"{path}: {value!r} not one of {sub['enum']}")
        return
    t = sub.get("type")
    if t is not None:
        names = t if isinstance(t, list) else [t]
        if not any(_type_ok(value, n) for n in names):
            problems.append(
                f"{path}: expected {'|'.join(names)}, got "
                f"{type(value).__name__}"
            )
            return
    if isinstance(value, dict):
        for req in sub.get("required", ()):
            if req not in value:
                problems.append(f"{path}: missing required field {req!r}")
        for k, psub in sub.get("properties", {}).items():
            if k in value:
                _check_subschema(value[k], psub, f"{path}.{k}", problems)
    if isinstance(value, list) and "items" in sub:
        for i, item in enumerate(value):
            _check_subschema(item, sub["items"], f"{path}[{i}]", problems)


def validate_event(event: dict, schema: Optional[dict] = None) -> List[str]:
    """Problems (empty = valid) for one event object: the schema's common
    envelope plus the per-type definition selected by ``event.type``."""
    schema = schema or load_schema()
    problems: List[str] = []
    if not isinstance(event, dict):
        return [f"event is {type(event).__name__}, not object"]
    _check_subschema(event, schema, "$", problems)
    etype = event.get("type")
    defs = schema.get("definitions", {})
    if isinstance(etype, str):
        sub = defs.get(etype)
        if sub is None:
            problems.append(f"$.type: unknown event type {etype!r}")
        else:
            _check_subschema(event, sub, f"$({etype})", problems)
    return problems


def _strict_loads(line: str):
    """json.loads that REJECTS the NaN/Infinity extensions (the log
    promises strict JSON; accepting them here would hide a writer bug)."""

    def _bad(tok):
        raise ValueError(f"non-strict JSON token {tok!r}")

    return json.loads(line, parse_constant=_bad)


def validate_events_file(path: str, max_problems: int = 20) -> dict:
    """Validate one JSONL event log end to end.

    Returns ``{"ok", "events", "problems"}``: every line must parse as
    strict JSON and validate against the schema; the first event must be
    ``run_start`` (consumers key run metadata off it)."""
    schema = load_schema()
    problems: List[str] = []
    n = 0
    first_type = None
    try:
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                n += 1
                try:
                    event = _strict_loads(line)
                except ValueError as e:
                    problems.append(f"line {lineno}: not strict JSON ({e})")
                    continue
                if first_type is None:
                    first_type = event.get("type") if isinstance(
                        event, dict
                    ) else None
                for p in validate_event(event, schema):
                    problems.append(f"line {lineno}: {p}")
                if len(problems) >= max_problems:
                    problems.append("... (truncated)")
                    break
    except OSError as e:
        problems.append(f"unreadable: {e}")
    if n == 0:
        problems.append("empty event log")
    elif first_type != "run_start":
        problems.append(
            f"first event is {first_type!r}, expected 'run_start'"
        )
    return {"ok": not problems, "events": n, "problems": problems}
