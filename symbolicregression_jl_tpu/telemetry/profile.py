"""srprof — modeled-vs-measured per-stage profiler with roofline
attribution.

The closing of the loop ROADMAP #2 asks for: ``analysis/cost.py``
models what every search stage SHOULD cost (element-ops, bytes moved,
padded-waste fraction); PR 6's ``SpanRecorder`` measures what each
stage's dispatches actually TOOK; this module joins the two against a
per-device-kind peak table into per-stage achieved throughput,
arithmetic intensity, and a **modeled roofline fraction** — emitted as
additive schema-v1 ``profile`` events at the end of every telemetry run
and rendered by the report CLI:

    python -m symbolicregression_jl_tpu.telemetry.profile LOG
        [--format json|text]

Peak numbers: TPU kinds are TABLED (coarse public VPU-issue and HBM
figures — scale anchors, not promises; the same convention as
benchmark/roofline.py, whose v5e VPU number this table reuses). The CPU
entry is MEASURED by a one-shot calibration microbench (a fused
multiply-add chain for the element-op rate, a streaming add for
bandwidth; ~1s, cached per process) — so a CPU-only image still gets a
meaningful denominator instead of a null.

The roofline join is the standard one: ``attainable = min(peak_ops,
intensity * peak_bandwidth)``; ``fraction = achieved / attainable``,
clamped into (0, 1] (the analytic model over-counts what fusion
eliminates, so raw fractions can exceed 1 on tiny programs —
``fraction_raw`` keeps the unclamped value).

The report additionally joins srshard's checked-in communication model
(analysis/shard_baseline.json, canonical mesh4x2 config): each stage
row carries ``modeled_comms_fraction`` — the modeled share of that
stage's step time spent in collectives on the production mesh — so the
profile answers "is this stage compute- or comms-dominated when
sharded" next to "how close to roof is it here". Best-effort: a
missing baseline simply leaves the column blank (docs/multichip.md).

Everything here is host-side orchestration: the modeled half is
trace-only (``jax.make_jaxpr``), the measured half reads spans already
taken — zero primitives are added to any jitted search program and the
hall of fame is bit-identical with profiling on or off (asserted in
tests). See docs/observability.md "Profiling (srprof)".
"""

from __future__ import annotations

import json
import math
import sys
from typing import Any, Dict, List, Optional, Tuple, Union

#: Coarse per-device-kind peaks: VPU f32 element-op issue rate (op/s)
#: and HBM bandwidth (B/s). Matched by substring against
#: ``jax.Device.device_kind`` (first hit wins, longest keys first).
#: v5e VPU reuses benchmark/roofline.py's V5E_VPU_OPS derivation
#: (8 sublanes x 128 lanes x 4 SIMD subunits x ~0.94 GHz).
TPU_PEAKS: Dict[str, Dict[str, float]] = {
    "v5 lite": {"flops_per_s": 3.9e12, "bytes_per_s": 8.2e11},
    "v5e": {"flops_per_s": 3.9e12, "bytes_per_s": 8.2e11},
    "v5p": {"flops_per_s": 4.7e12, "bytes_per_s": 2.77e12},
    "v6 lite": {"flops_per_s": 7.0e12, "bytes_per_s": 1.6e12},
    "v6e": {"flops_per_s": 7.0e12, "bytes_per_s": 1.6e12},
    "v4": {"flops_per_s": 3.2e12, "bytes_per_s": 1.2e12},
    "v3": {"flops_per_s": 1.6e12, "bytes_per_s": 9.0e11},
    "v2": {"flops_per_s": 1.3e12, "bytes_per_s": 7.0e11},
}

#: fallback for an unrecognized accelerator kind: the v5e row (the
#: fleet's common denominator), tagged so the report says it guessed.
_DEFAULT_TPU = {"flops_per_s": 3.9e12, "bytes_per_s": 8.2e11}

_CPU_PEAKS: Optional[Dict[str, float]] = None


def _calibrate_cpu_peaks() -> Dict[str, float]:
    """One-shot CPU peak measurement (cached per process).

    Element-op rate: a jitted 64-deep fused multiply-add chain over a
    2^20-element f32 vector (2 ops per element per link; long enough
    that dispatch overhead amortizes, small enough to stay in cache —
    this measures ISSUE rate, which is what the model's element-ops are
    priced in). Bandwidth: a streaming ``x + 1.0`` over 2^23 elements
    (read + write = 8 bytes/element, too large for cache). Both are
    medians of 3 timed reps after a warmup."""
    global _CPU_PEAKS
    if _CPU_PEAKS is not None:
        return _CPU_PEAKS
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        n_c = 1 << 20
        chain = 64

        def _chain(x):
            def body(i, v):
                return v * jnp.float32(1.0000001) + jnp.float32(1e-9)
            return jax.lax.fori_loop(0, chain, body, x)

        f = jax.jit(_chain)
        x = jnp.ones((n_c,), jnp.float32)
        jax.block_until_ready(f(x))
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(f(x))
            ts.append(time.perf_counter() - t0)
        flops_per_s = 2.0 * chain * n_c / float(np.median(ts))

        n_b = 1 << 23
        g = jax.jit(lambda x: x + jnp.float32(1.0))
        xb = jnp.ones((n_b,), jnp.float32)
        jax.block_until_ready(g(xb))
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(g(xb))
            ts.append(time.perf_counter() - t0)
        bytes_per_s = 8.0 * n_b / float(np.median(ts))
    _CPU_PEAKS = {
        "flops_per_s": float(flops_per_s),
        "bytes_per_s": float(bytes_per_s),
    }
    return _CPU_PEAKS


def device_peaks(device=None) -> Dict[str, Any]:
    """Peak table entry for ``device`` (default: ``jax.devices()[0]``):
    ``{"device_kind", "flops_per_s", "bytes_per_s", "source"}`` where
    ``source`` says whether the numbers were tabled
    (``table:<key>``), guessed (``table:default``), or measured
    (``calibrated:cpu``)."""
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "") or ""
    if device.platform == "cpu":
        peaks = _calibrate_cpu_peaks()
        return {"device_kind": kind or "cpu", "source": "calibrated:cpu",
                **peaks}
    low = kind.lower()
    for key in sorted(TPU_PEAKS, key=len, reverse=True):
        if key in low:
            return {"device_kind": kind, "source": f"table:{key}",
                    **TPU_PEAKS[key]}
    return {"device_kind": kind, "source": "table:default",
            **_DEFAULT_TPU}


def roofline_join(
    flops: float, bytes_moved: float, measured_s: float,
    peaks: Dict[str, Any], io_bytes: Optional[float] = None,
) -> Dict[str, Any]:
    """The modeled roofline attribution of one program dispatch:
    achieved rate vs the attainable bound at the program's arithmetic
    intensity. ``fraction`` is clamped into (0, 1] (``fraction_raw``
    unclamped — a clamped 1.0 with raw >> 1 flags a measurement the
    model cannot resolve: a sub-millisecond dispatch, or execution
    overlapped with the compile window on the first call).

    Intensity for the attainable bound uses ``io_bytes`` (the program's
    fused lower bound on HBM traffic — top-level inputs + outputs) when
    given: the analytic ``bytes_moved`` counts every unfused
    intermediate, and pricing the memory ceiling off it would misread
    anything XLA fuses well as memory-bound with an absurdly low
    ceiling. ``bytes_moved`` still prices ``achieved_bytes_per_s`` and
    the reported ``arithmetic_intensity`` context."""
    if measured_s <= 0 or flops <= 0:
        return {
            "achieved_flops_per_s": None,
            "achieved_bytes_per_s": None,
            "arithmetic_intensity": None,
            "attainable_flops_per_s": None,
            "fraction": None,
            "fraction_raw": None,
            "bound": None,
        }
    ai = flops / max(bytes_moved, 1.0)
    ai_roof = flops / max(
        io_bytes if io_bytes is not None else bytes_moved, 1.0
    )
    attainable = min(
        peaks["flops_per_s"], ai_roof * peaks["bytes_per_s"]
    )
    achieved = flops / measured_s
    raw = achieved / attainable
    return {
        "achieved_flops_per_s": achieved,
        "achieved_bytes_per_s": bytes_moved / measured_s,
        "arithmetic_intensity": ai,
        "attainable_flops_per_s": attainable,
        "fraction": min(max(raw, 1e-12), 1.0),
        "fraction_raw": raw,
        "bound": (
            "compute"
            if peaks["flops_per_s"] <= ai_roof * peaks["bytes_per_s"]
            else "memory"
        ),
    }


# ---------------------------------------------------------------------------
# run-end emission (called by api.equation_search when telemetry is on)
# ---------------------------------------------------------------------------


def emit_profile_events(
    sink,
    span_totals: Dict[str, Tuple[float, int]],
    options,
    nfeatures: int,
    nrows: int,
    device=None,
    compile_totals: Optional[Dict[str, float]] = None,
) -> List[dict]:
    """Model every stage's cost at this run's shapes, join it with the
    measured span totals, and emit one ``profile`` event per stage.

    ``span_totals`` is ``SpanRecorder.stage_totals()``. The modeled
    numbers are per DISPATCH (one stage program execution), so the join
    divides each stage's total by its span count; the two in-scan
    stages (mutate / eval) join against their one-shot probe spans.
    ``compile_totals`` (``SpanRecorder.compile_s``) is subtracted from
    the matching stage's span total first — a first dispatch's span
    includes its compile, and on a short run that would swamp the
    steady-state rate the roofline describes. Trace-only + host
    arithmetic: nothing is added to any jitted search program. Returns
    the emitted rows (also useful to tests)."""
    from ..analysis.cost import stage_costs

    peaks = device_peaks(device)
    compile_totals = compile_totals or {}
    rows: List[dict] = []
    for stage, cost in stage_costs(options, nfeatures, nrows).items():
        tot = span_totals.get(stage)
        raw_total_s, count = (tot if tot else (None, 0))
        measured_total_s = (
            max(raw_total_s - compile_totals.get(stage, 0.0), 0.0)
            if raw_total_s is not None else None
        )
        measured_s = (
            measured_total_s / count if count else None
        )
        join = roofline_join(
            cost["flops"], cost["bytes"], measured_s or 0.0, peaks,
            io_bytes=cost.get("io_bytes"),
        )
        row = {
            "stage": stage,
            "flops": cost["flops"],
            "bytes": cost["bytes"],
            "io_bytes": cost.get("io_bytes"),
            "padded_waste_fraction": cost["padded_waste_fraction"],
            "while_loops": cost["while_loops"],
            "measured_s": measured_s,
            "measured_total_s": measured_total_s,
            "compile_s": compile_totals.get(stage),
            "count": count,
            "roofline_fraction": join["fraction"],
            "roofline_fraction_raw": join["fraction_raw"],
            "achieved_flops_per_s": join["achieved_flops_per_s"],
            "achieved_bytes_per_s": join["achieved_bytes_per_s"],
            "arithmetic_intensity": join["arithmetic_intensity"],
            "attainable_flops_per_s": join["attainable_flops_per_s"],
            "bound": join["bound"],
            "device_kind": peaks["device_kind"],
            "peak_source": peaks["source"],
            "peak_flops_per_s": peaks["flops_per_s"],
            "peak_bytes_per_s": peaks["bytes_per_s"],
        }
        rows.append(row)
        if sink is not None:
            sink.emit("profile", **row)
    return rows


# ---------------------------------------------------------------------------
# report (CLI over an event log)
# ---------------------------------------------------------------------------


def profile_report(source: Union[str, List[dict]]) -> Dict[str, Any]:
    """One event log (path or pre-loaded event list) -> the srprof
    report: per-stage modeled cost, measured wall time, roofline
    fraction (from the run's ``profile`` events), the per-stage compile
    wall time (``compile`` events), and the utilization skew — the
    stages whose share of measured wall time far exceeds their share of
    modeled cost (the profile's "look here first" column)."""
    from .analyze import _finite, load_events
    from .spans import STAGES

    if isinstance(source, str):
        events, skipped = load_events(source)
        path = source
    else:
        events, skipped, path = list(source), 0, None

    stages: Dict[str, dict] = {}
    compile_by: Dict[str, dict] = {}
    run = {}
    for e in events:
        typ = e.get("type")
        if typ == "run_start":
            run = {
                k: e.get(k)
                for k in ("run", "backend", "device_kind", "nout",
                          "niterations")
                if e.get(k) is not None
            }
        elif typ == "profile" and isinstance(e.get("stage"), str):
            stages[e["stage"]] = e  # last write wins (one run = one set)
        elif typ == "compile" and isinstance(e.get("name"), str):
            row = compile_by.setdefault(
                e["name"], {"total_s": 0.0, "count": 0}
            )
            d = _finite(e.get("duration_s"))
            if d is not None:
                row["total_s"] += d
                row["count"] += 1

    # modeled share weights per-dispatch flops by the stage's DISPATCH
    # COUNT (the wall side, measured_total_s, is count-multiplied too —
    # sharing a per-dispatch numerator with a total denominator would
    # inflate every per-iteration stage's skew by niterations relative
    # to the one-shot probe stages and invert the "look here first"
    # column)
    def _work(s) -> float:
        f = _finite(s.get("flops")) or 0.0
        n = s.get("count") or 0
        return f * n

    total_work = sum(_work(s) for s in stages.values())
    total_wall = sum(
        _finite(s.get("measured_total_s")) or 0.0
        for s in stages.values()
    )
    for s in stages.values():
        w = _finite(s.get("measured_total_s")) or 0.0
        s["modeled_share"] = (
            _work(s) / total_work if total_work else None
        )
        s["wall_share"] = w / total_wall if total_wall else None
        # utilization skew: wall share over modeled share — >> 1 means
        # the stage burns far more wall time than its modeled work
        # justifies (dispatch overhead, poor kernel, host sync)
        ms, ws = s["modeled_share"], s["wall_share"]
        s["skew"] = (ws / ms) if (ms and ws is not None) else None

    # srshard join: annotate each stage with the statically-modeled
    # communication share from the checked-in shard baseline (canonical
    # mesh4x2 config). Best-effort — a missing/stale baseline or an
    # import failure leaves the rows unannotated rather than breaking
    # the report (the profile is about THIS run; the comms column is
    # cross-referenced context from the static engine).
    try:
        from ..analysis.shard import baseline_stage_comms

        comms = baseline_stage_comms()
    except Exception:
        comms = {}
    for name, s in stages.items():
        if name in comms:
            s["modeled_comms_fraction"] = comms[name]

    missing = [s for s in STAGES if s not in stages]
    return {
        "path": path,
        "run": run,
        "events": len(events),
        "skipped_lines": skipped,
        "stages": {s: stages[s] for s in STAGES if s in stages},
        "missing_stages": missing,
        "complete": not missing,
        "compile": compile_by,
        "compile_total_s": round(
            sum(v["total_s"] for v in compile_by.values()), 6
        ),
        "measured_total_s": round(total_wall, 6),
    }


def render_text(report: Dict[str, Any]) -> str:
    """Human rendering of one profile_report."""
    lines = []
    run = report.get("run", {})
    lines.append(
        f"srprof — run {run.get('run', '?')} [{run.get('backend', '?')}]"
        f" stages {len(report.get('stages', {}))}/7"
        + (f" MISSING {report['missing_stages']}"
           if report.get("missing_stages") else "")
    )
    stages = report.get("stages", {})
    if stages:
        any_row = next(iter(stages.values()))
        lines.append(
            f"peaks [{any_row.get('peak_source')}] "
            f"{any_row.get('device_kind')}: "
            f"{_fmt(any_row.get('peak_flops_per_s'))} op/s, "
            f"{_fmt(any_row.get('peak_bytes_per_s'))} B/s"
        )
        lines.append(
            f"{'stage':>14} {'el-ops':>9} {'bytes':>9} {'AI':>6} "
            f"{'waste':>6} {'wall s':>9} {'share':>6} {'roofline':>8} "
            f"{'skew':>6} {'comms':>6}"
        )
        for name, s in stages.items():
            lines.append(
                f"{name:>14} {_fmt(s.get('flops')):>9} "
                f"{_fmt(s.get('bytes')):>9} "
                f"{_fmt(s.get('arithmetic_intensity'), '.2f'):>6} "
                f"{_pct(s.get('padded_waste_fraction')):>6} "
                f"{_fmt(s.get('measured_total_s'), '.4f'):>9} "
                f"{_pct(s.get('wall_share')):>6} "
                f"{_pct(s.get('roofline_fraction')):>8} "
                f"{_fmt(s.get('skew'), '.1f'):>6} "
                f"{_pct(s.get('modeled_comms_fraction')):>6}"
            )
    comp = report.get("compile", {})
    if comp:
        total = report.get("compile_total_s", 0.0)
        parts = ", ".join(
            f"{k} {v['total_s']:.2f}s" for k, v in sorted(comp.items())
        )
        lines.append(f"compile: {total:.2f}s ({parts})")
    return "\n".join(lines)


def _fmt(v, spec=".3g") -> str:
    if isinstance(v, (int, float)) and math.isfinite(v):
        return format(v, spec)
    return "-"


def _pct(v) -> str:
    if isinstance(v, (int, float)) and math.isfinite(v):
        return f"{100 * v:.0f}%"
    return "-"


def main(argv=None) -> int:
    import argparse

    from .analyze import resolve_log

    ap = argparse.ArgumentParser(
        prog="python -m symbolicregression_jl_tpu.telemetry.profile",
        description=(
            "srprof report over a telemetry event log: per-stage "
            "modeled element-ops/bytes, measured wall time, and the "
            "modeled roofline fraction (docs/observability.md). Exit 0 "
            "iff the log carries profile rows for all 7 stages."
        ),
    )
    ap.add_argument(
        "log",
        help="event log path, or a telemetry dir (newest events-*.jsonl)",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ns = ap.parse_args(argv)

    report = profile_report(resolve_log(ns.log))
    print(
        json.dumps(report, indent=2) if ns.format == "json"
        else render_text(report)
    )
    if not report["stages"]:
        print(
            "srprof: no profile events in this log (telemetry runs "
            "emit them at run end since schema additions v1/PR 10)",
            file=sys.stderr,
        )
    return 0 if report["complete"] else 1


if __name__ == "__main__":
    sys.exit(main())
