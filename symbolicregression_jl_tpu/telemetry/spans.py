"""Host-side span timers for the search stages.

The search's wall time lives in a handful of device dispatches; knowing
*which stage* owns it is the difference between guessing and fixing
(TensorGP, arxiv 2103.07512: tensorized-GP perf work is dominated by
attributing padded/lockstep time). A span is one timed host-side region
with explicit ``jax.block_until_ready`` fencing — without the fence an
async dispatch returns immediately and the time would be charged to
whichever later call happens to synchronize.

Stage vocabulary: :data:`STAGES` — the same seven names
``analysis/memory.py::build_stage_programs`` decomposes the iteration
into, so span timings, srmem per-stage HBM attribution, and XLA-profile
regions all join on one key. Every span also nests inside a
``profiling.annotate`` region, so when a ``profiling.trace`` capture is
active the spans appear on the XLA/Perfetto timeline under
``srtpu/<name>``.

Two stages (``mutate`` / ``eval``) live *inside* the fused cycle scan and
cannot be fenced from the host per-iteration; :func:`probe_mutate_eval`
times them as standalone one-shot programs (the exact decomposition
srmem's stage programs use), recorded with ``probe: true`` so consumers
can tell a measured sub-dispatch from an in-loop phase.

Everything here is host-side orchestration: no primitive is added to any
jitted search program (the compile-surface baseline stays byte-identical
with telemetry on).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any, Dict, List, Optional, Tuple

#: The per-iteration stage vocabulary, shared with
#: ``analysis.memory.build_stage_programs`` (asserted there) and the
#: ``srtpu/<stage>`` profiler annotations.
STAGES = (
    "init",
    "cycle",
    "mutate",
    "eval",
    "simplify",
    "optimize",
    "merge_migrate",
)


@dataclasses.dataclass
class Span:
    """One completed timed region."""

    name: str
    t_start: float  # unix seconds (event-log joinable)
    duration_s: float
    attrs: Dict[str, Any]


class _SpanBox:
    """Mutable handle yielded by ``SpanRecorder.span``: set ``fence`` to a
    jax pytree to block on before the clock stops; add result-dependent
    attributes via ``attrs``."""

    __slots__ = ("fence", "attrs")

    def __init__(self):
        self.fence = None
        self.attrs: Dict[str, Any] = {}


class SpanRecorder:
    """Collects spans and forwards each to an event sink (events.EventLog)
    the moment it closes.

    ``set_context`` attaches ambient attributes (output/iteration) to
    every span recorded until the next call — the host loop updates it
    once per iteration instead of threading ids through the driver."""

    #: retained-span cap: every span is forwarded to the sink the moment
    #: it closes, so in-memory retention is a convenience for direct
    #: consumers (bench reads its one eval span; tests inspect a few) —
    #: a 10k-iteration search must not accumulate unbounded host memory
    MAX_RETAINED = 4096

    def __init__(self, sink=None, max_retained: Optional[int] = None):
        self.sink = sink
        self.max_retained = (
            self.MAX_RETAINED if max_retained is None else max_retained
        )
        self.spans: List[Span] = []
        self._ctx: Dict[str, Any] = {}
        # cumulative per-name (total_s, count): unlike `spans`, never
        # truncated — the srprof modeled-vs-measured join at run end
        # needs every dispatch's time, not the last MAX_RETAINED
        self._totals: Dict[str, List[float]] = {}
        # per-stage first-dispatch compile seconds (note_compile): the
        # share of the stage's span total that was compilation, which
        # the srprof join subtracts before computing achieved rates
        self.compile_s: Dict[str, float] = {}

    def set_context(self, **ctx) -> None:
        """Merge ambient span attributes; a value of None removes the key."""
        for k, v in ctx.items():
            if v is None:
                self._ctx.pop(k, None)
            else:
                self._ctx[k] = v

    @contextlib.contextmanager
    def span(self, name: str, fence=None, **attrs):
        """Time the enclosed block as stage `name`.

        ``fence`` (or ``box.fence`` set inside the block) is passed to
        ``jax.block_until_ready`` before the clock stops, so queued device
        work is charged to THIS span. The wait happens inside the
        ``profiling.annotate`` region — on an XLA trace the annotation
        covers dispatch + device completion, same extent as the span."""
        import jax

        from ..utils.profiling import annotate

        box = _SpanBox()
        err: Optional[BaseException] = None
        with annotate(f"srtpu/{name}"):
            t_wall = time.time()
            t0 = time.perf_counter()
            try:
                yield box
            except BaseException as e:
                err = e
                raise
            finally:
                try:
                    if err is None:
                        for val in (fence, box.fence):
                            if val is not None:
                                jax.block_until_ready(val)
                finally:
                    duration = time.perf_counter() - t0
                    a = {**self._ctx, **attrs, **box.attrs}
                    if err is not None:
                        a["error"] = type(err).__name__
                    self._record(Span(name, t_wall, duration, a))

    def _record(self, sp: Span) -> None:
        tot = self._totals.setdefault(sp.name, [0.0, 0])
        tot[0] += sp.duration_s
        tot[1] += 1
        self.spans.append(sp)
        if len(self.spans) > self.max_retained:
            del self.spans[0]  # oldest out; the sink has the full trail
        if self.sink is not None:
            self.sink.emit(
                "span",
                name=sp.name,
                t_start=sp.t_start,
                duration_s=sp.duration_s,
                attrs=sp.attrs,
            )

    def total_s(self, name: str) -> float:
        """Summed duration of every span named `name`."""
        return sum(s.duration_s for s in self.spans if s.name == name)

    def stage_totals(self) -> Dict[str, Tuple[float, int]]:
        """Cumulative ``{name: (total_s, count)}`` over every span ever
        recorded (survives the retained-span cap) — the measured half of
        the srprof join (telemetry.profile)."""
        return {k: (v[0], int(v[1])) for k, v in self._totals.items()}

    def note_compile(self, name: str, seconds: float) -> None:
        """Record first-dispatch compile wall time charged to stage
        `name` (the api drivers call this alongside emitting the
        `compile` event, so the srprof join can subtract it)."""
        self.compile_s[name] = self.compile_s.get(name, 0.0) + seconds


class NullSpanRecorder(SpanRecorder):
    """No-op recorder: ``span`` yields a box and records nothing — the
    phased iteration driver uses it when telemetry is off so the chunked
    dispatch path carries zero instrumentation (no fence, no timing)."""

    def __init__(self):
        super().__init__(sink=None)

    @contextlib.contextmanager
    def span(self, name: str, fence=None, **attrs):
        yield _SpanBox()


NULL = NullSpanRecorder()


def probe_mutate_eval(
    recorder: SpanRecorder, options, states, X, y, weights, baseline,
    scalars,
) -> None:
    """One-shot measured spans for the two in-scan stages.

    Runs the standalone ``mutate`` (tree surgery over all islands) and
    ``eval`` (fused flat scoring of the children batch) programs —
    the same decomposition ``analysis.memory.build_stage_programs``
    traces — once on real data, fenced, after a warmup call so the span
    measures the steady-state dispatch, not compilation. Each probe
    program is its own jit: nothing is added to the production search
    programs. Called once per run by the host loop (probe cost ~= one
    evolution cycle); any failure is reported to the sink as a
    ``probe_error`` event, never raised into the search."""
    import jax
    import jax.numpy as jnp

    from ..models import evolve
    from ..models.fitness import score_trees

    sink = recorder.sink
    try:
        nfeatures = int(X.shape[0])
        cm = jnp.int32(options.maxsize)

        def mutate_fn(sts, cm, sc):
            o = options.bind_scalars(sc)
            return jax.vmap(
                lambda st: evolve._propose_children(
                    st, jnp.float32(1.0), cm, nfeatures, o
                )
            )(sts)

        mutate_jit = jax.jit(mutate_fn)
        props = jax.block_until_ready(mutate_jit(states, cm, scalars))
        with recorder.span("mutate", probe=True) as sp:
            props = mutate_jit(states, cm, scalars)
            sp.fence = props.children

        children = jax.tree_util.tree_map(
            lambda x: x.reshape((-1,) + x.shape[2:]), props.children
        )
        n_trees = int(children.length.shape[0])
        n_rows = int(X.shape[1])

        def eval_fn(ch, X, y, w, bl, sc):
            o = options.bind_scalars(sc)
            return score_trees(ch, X, y, w, bl, o)

        eval_jit = jax.jit(eval_fn)
        out = jax.block_until_ready(
            eval_jit(children, X, y, weights, baseline, scalars)
        )
        # trees/rows ride along so consumers (bench roofline, suite
        # stage-time rows) can derive trees-rows/s from the duration
        with recorder.span(
            "eval", probe=True, trees=n_trees, rows=n_rows
        ) as sp:
            out = eval_jit(children, X, y, weights, baseline, scalars)
            sp.fence = out
    except Exception as e:  # pragma: no cover - defensive
        if sink is not None:
            sink.emit(
                "probe_error",
                error=f"{type(e).__name__}: {str(e)[:200]}",
            )
