"""Offline run doctor: turn one (or two) telemetry event logs into an
operational verdict.

PR 6 made every run leave a crash-safe JSONL trail; this module is the
layer that *interprets* it — the answer to "is this 64x1000 run stalled,
diverging, or healthy?" without eyeballing raw JSONL (ROADMAP #4: the
watcher must distinguish a mid-run fault from a dead run; GP-dynamics
literature — TensorGP arxiv 2103.07512, Kozax arxiv 2502.03047 — names
diversity collapse and operator-acceptance drift as the leading
indicators of wasted tensorized-GP compute, which is exactly what the
``metrics`` events now carry).

Entry points:

* :func:`load_events` — tolerant loader: a mid-write run's truncated
  last line (or a corrupted line) is skipped and counted, never fatal —
  the doctor and ``scripts/srtop.py`` both read *live* logs;
* :func:`analyze_run` — one log -> a structured report with a verdict
  from :data:`VERDICTS` plus the evidence (best-loss trajectory,
  diversity, per-mutation acceptance drift, exact hypervolume, stage
  wall-time breakdown, fault/tunnel timeline, saved-state points);
* :func:`compare_runs` — A/B of two logs on the shared summary metrics
  (wall time, evals/s, final best loss, hypervolume, stage split);
* :func:`self_check` — the lint-gate form: schema-validate a log AND
  assert the doctor produces a verdict on it;
* CLI — ``python -m symbolicregression_jl_tpu.telemetry.analyze LOG
  [LOG2] [--format json|text]`` (two logs -> comparison). Exit 0 iff
  the verdict is ``healthy`` (or the comparison/self-check succeeded),
  so CI can gate on it directly.

Verdict vocabulary (:data:`VERDICTS`, documented in
docs/observability.md):

* ``healthy`` — no fault, run completed (or still progressing), best
  loss improving or diversity above the floor;
* ``stalled`` — best-loss plateau (relative improvement below
  ``stall_tol`` across the trailing ``stall_window`` metric snapshots)
  AND population diversity at/below ``diversity_floor`` — the
  diversity-collapse signature: more iterations are unlikely to help;
* ``diverging`` — the population's finite fraction collapsed or the
  best loss lost finiteness (NaN/Inf flood);
* ``faulted`` — a ``dispatch_fault`` was recorded; the report's
  ``resumable`` flag says whether a ``saved_state`` event exists to
  resume from (fault-with-recent-saved-state is "resumable", not
  "dead" — ROADMAP #4);
* ``incomplete`` — no ``run_end`` and no fault: the run is either
  still in flight or was killed; pair with the log's mtime/last event
  age to tell which (srtop shows it live);
* ``empty`` — no parseable events.

Everything here is host-side file reading — no jax import.
"""

from __future__ import annotations

import json
import math
import os
import sys
from typing import Any, Dict, List, Optional, Tuple, Union

#: the complete verdict vocabulary, in precedence order (first match
#: wins): faulted > diverging > stalled > incomplete > healthy; empty
#: when there is nothing to judge.
VERDICTS = (
    "faulted", "diverging", "stalled", "incomplete", "healthy", "empty",
)

#: defaults for the stall detector (overridable per call / via CLI)
STALL_WINDOW = 5      # trailing metric snapshots the plateau must span
STALL_TOL = 1e-3      # relative best-loss improvement below this = flat
DIVERSITY_FLOOR = 0.2  # unique-tree fraction at/below this = collapsed
#: population non-finite (inf-sentinel) fraction above which the run is
#: flagged ``numerically-degenerate`` (a reason, not a verdict — like
#: compile_bound): most of the population is being clamped by the
#: containment layer, so the search is burning evals on poisoned trees
#: without (yet) meeting the `diverging` verdict's 0.9 collapse bar.
NONFINITE_DEGENERATE = 0.5


def load_events(
    path: str, max_skipped: Optional[int] = None
) -> Tuple[List[dict], int]:
    """Parse one JSONL event log, skipping (and counting) unparsable
    lines. A truncated final line — the normal state of a log being
    written, or of a run killed mid-write — is a skip, not an error.
    Returns ``(events, skipped_lines)``."""
    events: List[dict] = []
    skipped = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                skipped += 1
                if max_skipped is not None and skipped > max_skipped:
                    break
                continue
            if isinstance(e, dict):
                events.append(e)
            else:
                skipped += 1
    return events, skipped


def _finite(v) -> Optional[float]:
    if isinstance(v, (int, float)) and not isinstance(v, bool) and \
            math.isfinite(v):
        return float(v)
    return None


def _gauge(e: dict, name: str) -> Optional[float]:
    return _finite(
        ((e.get("snapshot") or {}).get("gauges") or {}).get(name)
    )


def _series(metrics: List[dict], name: str) -> List[Optional[float]]:
    return [_gauge(m, name) for m in metrics]


def _summary(values: List[Optional[float]]) -> Optional[dict]:
    vals = [v for v in values if v is not None]
    if not vals:
        return None
    return {
        "first": vals[0],
        "last": vals[-1],
        "min": min(vals),
        "max": max(vals),
    }


def _stall(
    best: List[Optional[float]], window: int, tol: float
) -> Optional[bool]:
    """True when the best-loss series is flat over its trailing `window`
    observations (None = not enough evidence to call it either way).

    A plateau AT zero loss is NOT a stall: the search converged onto the
    exact equation — there is nothing left to improve, and calling a
    successful run 'stalled' would fail the CLI's exit-0-iff-healthy
    contract on precisely the best outcome."""
    vals = [v for v in best if v is not None]
    if len(vals) < window + 1:
        return None
    head, tail = vals[-(window + 1)], vals[-1]
    if head <= 0:
        return False  # converged to (or below) zero: solved, not stuck
    return (head - tail) / abs(head) < tol


def analyze_run(
    source: Union[str, List[dict]],
    *,
    stall_window: int = STALL_WINDOW,
    stall_tol: float = STALL_TOL,
    diversity_floor: float = DIVERSITY_FLOOR,
) -> Dict[str, Any]:
    """One event log (path or pre-loaded event list) -> structured
    verdict + evidence. Never raises on content (only on an unreadable
    path): a malformed or partial log yields a report saying so."""
    if isinstance(source, str):
        events, skipped = load_events(source)
        path = source
    else:
        events, skipped, path = list(source), 0, None

    report: Dict[str, Any] = {
        "path": path,
        "events": len(events),
        "skipped_lines": skipped,
        "verdict": "empty",
        "reasons": [],
        "complete": False,
        "resumable": False,
    }
    if not events:
        report["reasons"].append("no parseable events")
        return report

    by_type: Dict[str, List[dict]] = {}
    for e in events:
        by_type.setdefault(str(e.get("type")), []).append(e)

    start = (by_type.get("run_start") or [{}])[0]
    report["run"] = {
        k: start.get(k)
        for k in (
            "run", "config_fingerprint", "backend", "niterations",
            "nout", "mesh_shape", "n_devices", "device_kind",
            # fleet provenance (ISSUE 13): the stable logical run id
            # the fleet index joins a supervised run's attempts on,
            # and this log's 1-based attempt index
            "run_id", "attempt",
            # resilience provenance (ISSUE 11): snapshot cadence and,
            # on a resumed run, where its saved_state came from
            "snapshot", "resume_from",
            # hostile-data front-door census (ISSUE 15)
            "dataset_diagnostics",
        )
        if start.get(k) is not None
    }
    ends = by_type.get("run_end") or []
    report["complete"] = bool(ends)
    if ends:
        report["wall_s"] = _finite(ends[-1].get("search_time_s"))
        report["num_evals"] = _finite(ends[-1].get("num_evals"))
    ts = [e["t"] for e in events if _finite(e.get("t")) is not None]
    if ts:
        report["t_first"], report["t_last"] = min(ts), max(ts)
        report.setdefault("wall_s", max(ts) - min(ts))

    # ---- stage wall-time breakdown + span completeness ----
    from .spans import STAGES

    stages: Dict[str, dict] = {}
    for sp in by_type.get("span", []):
        name = sp.get("name")
        d = _finite(sp.get("duration_s"))
        if not isinstance(name, str) or d is None:
            continue
        row = stages.setdefault(name, {"total_s": 0.0, "count": 0})
        row["total_s"] += d
        row["count"] += 1
    # compile events (the phased driver's first-dispatch compile wall
    # times) fold OUT of the stage rows: a first dispatch's span
    # includes its compile, and leaving it there would smear a 40s
    # compile into "the cycle stage is slow". Reported separately, and
    # a run that spent most of its wall on compilation is flagged
    # compile-bound (a 2-iteration smoke run recompiling everything is
    # a different problem than a slow kernel).
    compile_by: Dict[str, float] = {}
    for ce in by_type.get("compile", []):
        name = ce.get("name")
        d = _finite(ce.get("duration_s"))
        if isinstance(name, str) and d is not None:
            compile_by[name] = compile_by.get(name, 0.0) + d
    compile_total = sum(compile_by.values())
    for name, c in compile_by.items():
        if name in stages:
            stages[name]["total_s"] = max(
                stages[name]["total_s"] - c, 0.0
            )
    report["stages"] = {
        k: {"total_s": round(v["total_s"], 6), "count": v["count"]}
        for k, v in sorted(stages.items())
    }
    report["spans_complete"] = all(s in stages for s in STAGES)
    if compile_by:
        report["compile"] = {
            "total_s": round(compile_total, 6),
            "by_stage": {
                k: round(v, 6) for k, v in sorted(compile_by.items())
            },
        }
    stage_total = sum(v["total_s"] for v in stages.values())
    compile_share = (
        compile_total / (compile_total + stage_total)
        if (compile_total + stage_total) > 0 else 0.0
    )
    report["compile_share"] = round(compile_share, 4)
    report["compile_bound"] = compile_share > 0.5

    # ---- fault / tunnel timeline (ROADMAP #4: the machine-readable
    # trail distinguishing a mid-run fault from a dead run) ----
    faults = [
        {
            k: f.get(k)
            for k in ("t", "where", "error_type", "error", "fatal",
                      "output", "iteration")
            if k in f
        }
        for f in by_type.get("dispatch_fault", [])
    ]
    report["faults"] = faults
    tunnel = by_type.get("tunnel_state", [])
    if tunnel:
        report["tunnel_state"] = tunnel[-1].get("state")
    saved = by_type.get("saved_state", [])
    report["saved_states"] = len(saved)
    if saved:
        report["last_saved_state"] = {
            k: saved[-1].get(k)
            for k in ("t", "path", "iteration", "in_memory")
            if k in saved[-1]
        }
    roofs = by_type.get("roofline", [])
    if roofs:
        report["roofline"] = {
            "fraction": _finite(roofs[-1].get("fraction")),
            "skip_reason": roofs[-1].get("skip_reason"),
        }

    # ---- search-dynamics series from the metrics events ----
    # multi-output runs interleave one metrics event per output per
    # iteration: every trajectory judgment below is made PER OUTPUT
    # (a zigzag across outputs would fake plateaus and divergence)
    metrics = by_type.get("metrics", [])
    report["metric_snapshots"] = len(metrics)
    by_out: Dict[int, List[dict]] = {}
    for m in metrics:
        j = m.get("output")
        by_out.setdefault(j if isinstance(j, int) else 0, []).append(m)
    outputs = sorted(by_out)
    report["best_loss"] = _summary(_series(metrics, "best_loss"))
    report["diversity"] = _summary(
        _series(metrics, "population_diversity")
    )
    report["hypervolume"] = _summary(_series(metrics, "hof_hypervolume"))
    report["mutation_accept_rate"] = _summary(
        _series(metrics, "mutation_accept_rate")
    )
    if metrics and isinstance(metrics[-1].get("mutations"), dict):
        report["mutations"] = metrics[-1]["mutations"]
    if metrics and isinstance(metrics[-1].get("pareto"), dict):
        report["pareto"] = metrics[-1]["pareto"]

    per_out: Dict[int, Dict[str, Any]] = {}
    for j in outputs:
        ms = by_out[j]
        best_j = _series(ms, "best_loss")
        div_j = _series(ms, "population_diversity")
        frac_j = _series(ms, "population_finite_frac")
        nonfin_j = _series(ms, "population_nonfinite_fraction")
        gauges_j = ((ms[-1].get("snapshot") or {}).get("gauges") or {})
        s = _summary(best_j) or {}
        per_out[j] = {
            "last_nonfinite_frac": next(
                (v for v in reversed(nonfin_j) if v is not None), None
            ),
            "flat": _stall(best_j, stall_window, stall_tol),
            "last_diversity": next(
                (v for v in reversed(div_j) if v is not None), None
            ),
            "last_finite_frac": next(
                (v for v in reversed(frac_j) if v is not None), None
            ),
            "latest_best_null": (
                "best_loss" in gauges_j and best_j[-1] is None
            ),
            "last_best": next(
                (v for v in reversed(best_j) if v is not None), None
            ),
            "improvement": (
                (s["first"] - s["last"]) / abs(s["first"])
                if s.get("first") else 0.0
            ) if s else None,
        }
    if report["best_loss"] is not None and per_out:
        # the conservative cross-output figure: the least-improved output
        report["best_loss"]["improvement"] = min(
            (p["improvement"] for p in per_out.values()
             if p["improvement"] is not None),
            default=0.0,
        )
    if len(outputs) > 1:
        report["per_output"] = {
            j: {
                "best_loss": p["last_best"],
                "diversity": p["last_diversity"],
                "improvement": p["improvement"],
            }
            for j, p in per_out.items()
        }

    # ---- verdict (precedence: faulted > diverging > stalled >
    # incomplete > healthy) ----
    reasons = report["reasons"]
    verdict = "healthy"

    # cross-output aggregation: ANY output diverging is a diverging
    # run; a stall needs EVERY output plateaued with EVERY output's
    # diversity at/below the floor (one healthy output = the run can
    # still move, matching the reference's all-outputs stop semantics)
    vals = list(per_out.values())
    flat = bool(vals) and all(p["flat"] is True for p in vals)
    divs = [p["last_diversity"] for p in vals
            if p["last_diversity"] is not None]
    last_div = max(divs) if divs else None
    fracs = [p["last_finite_frac"] for p in vals
             if p["last_finite_frac"] is not None]
    last_frac = min(fracs) if fracs else None
    # NaN-flood detection keys on each output's LATEST snapshot: its
    # gauges must carry best_loss (the writer always emits it; null =
    # non-finite) — a log from a writer that never emitted the gauge
    # stays unjudged
    latest_best_null = any(p["latest_best_null"] for p in vals)

    if faults:
        verdict = "faulted"
        f = faults[-1]
        reasons.append(
            f"dispatch_fault at iteration {f.get('iteration')}: "
            f"{f.get('error_type')}"
        )
        report["resumable"] = bool(saved)
        reasons.append(
            "resumable: saved_state available to resume from"
            if saved else
            "not resumable: no saved_state event in this log"
        )
    elif latest_best_null:
        verdict = "diverging"
        reasons.append("best loss lost finiteness (NaN/Inf flood)")
    elif last_frac is not None and last_frac < 0.1:
        verdict = "diverging"
        reasons.append(
            f"finite-loss fraction collapsed to {last_frac:.3f}"
        )
    elif flat and (last_div is not None and last_div <= diversity_floor):
        verdict = "stalled"
        reasons.append(
            f"best-loss plateau over the last {stall_window} snapshots "
            f"(< {stall_tol:g} relative improvement) with diversity "
            f"{last_div:.3f} <= floor {diversity_floor:g}"
        )
    elif not report["complete"]:
        verdict = "incomplete"
        reasons.append(
            "no run_end event: run still in flight or killed "
            "(check the log's last-event age)"
        )
        report["resumable"] = bool(saved)
    else:
        if vals and all(
            p["last_best"] is not None and p["last_best"] <= 0
            for p in vals
        ):
            reasons.append("best loss at zero — converged")
        if flat:
            reasons.append(
                "best-loss plateau, but diversity above the floor — "
                "search can still move"
            )
        if not report["spans_complete"]:
            missing = [s for s in STAGES if s not in stages]
            reasons.append(f"missing stage spans: {missing}")
        if not reasons:
            reasons.append("completed, loss improving, no faults")
    if report["compile_bound"]:
        # a flag, not a verdict: the run may be perfectly healthy, but
        # its wall time says "compilation", not "search" — warm caches
        # (utils.precompile.enable_compilation_cache) before reading
        # stage times as kernel performance
        reasons.append(
            f"compile-bound: {report['compile_share']:.0%} of "
            "measured wall time went to first-dispatch compilation"
        )
    # numeric-containment flag (ISSUE 15): like compile_bound, a reason
    # riding any verdict — the containment layer is clamping most of
    # the population to the inf sentinel (hostile data, overflow-heavy
    # opset, or scale hazards; see run_start.dataset_diagnostics)
    nonfins = [p["last_nonfinite_frac"] for p in vals
               if p.get("last_nonfinite_frac") is not None]
    worst_nonfin = max(nonfins) if nonfins else None
    report["nonfinite_fraction"] = worst_nonfin
    report["numerically_degenerate"] = bool(
        worst_nonfin is not None and worst_nonfin > NONFINITE_DEGENERATE
    )
    if report["numerically_degenerate"]:
        reasons.append(
            f"numerically-degenerate: {worst_nonfin:.0%} of population "
            f"losses carry the inf sentinel (> {NONFINITE_DEGENERATE:.0%}"
            " threshold) — evaluation is clamping most trees; check "
            "run_start.dataset_diagnostics for scale hazards or "
            "non-finite cells"
        )
    report["verdict"] = verdict
    return report


def compare_runs(
    a: Union[str, List[dict]], b: Union[str, List[dict]], **kw
) -> Dict[str, Any]:
    """A/B two runs on the shared summary metrics. ``delta`` rows are
    ``b - a`` (ratios where a rate is the natural unit); each side's
    full report rides along under ``a``/``b``."""
    ra, rb = analyze_run(a, **kw), analyze_run(b, **kw)

    def _last(r, key):
        s = r.get(key)
        return s["last"] if isinstance(s, dict) else None

    def _evals_per_s(r):
        ev, w = r.get("num_evals"), r.get("wall_s")
        return ev / w if ev and w else None

    rows = {}
    for name, fa, fb in (
        ("wall_s", ra.get("wall_s"), rb.get("wall_s")),
        ("num_evals", ra.get("num_evals"), rb.get("num_evals")),
        ("evals_per_s", _evals_per_s(ra), _evals_per_s(rb)),
        ("best_loss", _last(ra, "best_loss"), _last(rb, "best_loss")),
        ("hypervolume", _last(ra, "hypervolume"),
         _last(rb, "hypervolume")),
        ("diversity", _last(ra, "diversity"), _last(rb, "diversity")),
        ("mutation_accept_rate", _last(ra, "mutation_accept_rate"),
         _last(rb, "mutation_accept_rate")),
    ):
        rows[name] = {
            "a": fa,
            "b": fb,
            "delta": (fb - fa) if (fa is not None and fb is not None)
            else None,
            "ratio": (fb / fa) if (fa and fb is not None) else None,
        }
    stage_rows = {}
    for name in sorted(
        set(ra.get("stages", {})) | set(rb.get("stages", {}))
    ):
        sa = ra.get("stages", {}).get(name, {}).get("total_s")
        sb = rb.get("stages", {}).get(name, {}).get("total_s")
        stage_rows[name] = {
            "a_s": sa, "b_s": sb,
            "ratio": (sb / sa) if (sa and sb is not None) else None,
        }
    return {
        "verdicts": {"a": ra["verdict"], "b": rb["verdict"]},
        "metrics": rows,
        "stages": stage_rows,
        "a": ra,
        "b": rb,
    }


def self_check(path: str, skip_validation: bool = False) -> Dict[str, Any]:
    """The lint-gate form (``--self-check``): the log must schema-validate
    (``events.validate_events_file``) AND the doctor must produce a
    verdict from :data:`VERDICTS` on it without raising. Returns
    ``{"ok", "verdict", "detail"}``. ``skip_validation=True`` is for
    callers that already validated the same file (scripts/lint.py does,
    immediately before) — one schema pass, not two."""
    out: Dict[str, Any] = {"ok": False, "verdict": None, "detail": ""}
    if not skip_validation:
        from .events import validate_events_file

        val = validate_events_file(path)
        if not val["ok"]:
            out["detail"] = f"schema: {val['problems'][0]}"
            return out
    try:
        report = analyze_run(path)
    except Exception as e:  # pragma: no cover - the point of the check
        out["detail"] = f"analyze_run raised {type(e).__name__}: {e}"
        return out
    out["verdict"] = report["verdict"]
    if report["verdict"] not in VERDICTS:
        out["detail"] = f"unknown verdict {report['verdict']!r}"
        return out
    out["ok"] = True
    out["detail"] = f"verdict {report['verdict']} on {report['events']} events"
    return out


def resolve_log(path: str) -> str:
    """A directory argument resolves to its newest ``events-*.jsonl``
    (the run most recently written to); a file passes through."""
    if os.path.isdir(path):
        cands = [
            os.path.join(path, f) for f in os.listdir(path)
            if f.startswith("events-") and f.endswith(".jsonl")
        ]
        if not cands:
            raise FileNotFoundError(f"no events-*.jsonl under {path!r}")
        return max(cands, key=os.path.getmtime)
    return path


def render_text(report: Dict[str, Any]) -> str:
    """Human-oriented rendering of one analyze_run report."""
    lines = []
    run = report.get("run", {})
    lines.append(
        f"run {run.get('run', '?')} [{run.get('backend', '?')}] "
        f"-> verdict: {report['verdict'].upper()}"
    )
    for r in report.get("reasons", []):
        lines.append(f"  - {r}")
    bl = report.get("best_loss")
    if bl:
        lines.append(
            f"best loss: {bl['first']:.6g} -> {bl['last']:.6g} "
            f"(improvement {bl.get('improvement', 0.0) * 100:.1f}%) over "
            f"{report.get('metric_snapshots', 0)} snapshots"
        )
    dv = report.get("diversity")
    if dv:
        lines.append(f"diversity: {dv['first']:.3f} -> {dv['last']:.3f}")
    hv = report.get("hypervolume")
    if hv:
        lines.append(
            f"hypervolume: {hv['first']:.4f} -> {hv['last']:.4f}"
        )
    mr = report.get("mutation_accept_rate")
    if mr:
        lines.append(
            f"mutation accept rate: {mr['first']:.3f} -> {mr['last']:.3f}"
        )
    stages = report.get("stages", {})
    if stages:
        total = sum(v["total_s"] for v in stages.values()) or 1.0
        lines.append("stage wall time (compile excluded):")
        for name, v in sorted(
            stages.items(), key=lambda kv: -kv[1]["total_s"]
        ):
            lines.append(
                f"  {name:>14}: {v['total_s']:9.3f}s "
                f"({100 * v['total_s'] / total:5.1f}%) x{v['count']}"
            )
    comp = report.get("compile")
    if comp:
        lines.append(
            f"compile: {comp['total_s']:.3f}s "
            f"({report.get('compile_share', 0.0) * 100:.0f}% of wall"
            + (", COMPILE-BOUND" if report.get("compile_bound") else "")
            + ") — " + ", ".join(
                f"{k} {v:.2f}s" for k, v in comp["by_stage"].items()
            )
        )
    if report.get("faults"):
        lines.append(f"faults: {len(report['faults'])} "
                     f"(resumable: {report.get('resumable')})")
        for f in report["faults"][-3:]:
            lines.append(
                f"  iteration {f.get('iteration')}: "
                f"{f.get('error_type')} at {f.get('where')}"
            )
    if "tunnel_state" in report:
        lines.append(f"tunnel: {report['tunnel_state']}")
    if report.get("wall_s") is not None:
        lines.append(f"wall: {report['wall_s']:.1f}s, events: "
                     f"{report['events']} "
                     f"(+{report['skipped_lines']} unparseable)")
    return "\n".join(lines)


def render_comparison_text(cmp: Dict[str, Any]) -> str:
    lines = [
        f"A: {cmp['verdicts']['a']}  vs  B: {cmp['verdicts']['b']}",
        f"{'metric':>22} {'A':>12} {'B':>12} {'B/A':>8}",
    ]

    def _fmt(v):
        return f"{v:.6g}" if isinstance(v, float) else str(v)

    for name, row in cmp["metrics"].items():
        ratio = row["ratio"]
        lines.append(
            f"{name:>22} {_fmt(row['a']):>12} {_fmt(row['b']):>12} "
            f"{(f'{ratio:.3f}' if ratio is not None else '-'):>8}"
        )
    for name, row in cmp["stages"].items():
        ratio = row["ratio"]
        lines.append(
            f"{'stage ' + name:>22} {_fmt(row['a_s']):>12} "
            f"{_fmt(row['b_s']):>12} "
            f"{(f'{ratio:.3f}' if ratio is not None else '-'):>8}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m symbolicregression_jl_tpu.telemetry.analyze",
        description=(
            "Run doctor over telemetry event logs: one log -> verdict "
            "(exit 0 iff healthy), two logs -> A/B comparison."
        ),
    )
    ap.add_argument(
        "logs", nargs="+",
        help="1-2 event logs (a directory resolves to its newest "
        "events-*.jsonl)",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--self-check", action="store_true",
        help="schema-validate the log and assert the doctor yields a "
        "verdict (the scripts/lint.py golden-fixture gate)",
    )
    ap.add_argument("--stall-window", type=int, default=STALL_WINDOW)
    ap.add_argument("--stall-tol", type=float, default=STALL_TOL)
    ap.add_argument(
        "--diversity-floor", type=float, default=DIVERSITY_FLOOR
    )
    ns = ap.parse_args(argv)

    paths = [resolve_log(p) for p in ns.logs]
    if ns.self_check:
        out = self_check(paths[0])
        print(
            json.dumps(out) if ns.format == "json"
            else f"self-check: {'OK' if out['ok'] else 'FAIL'} "
                 f"({out['detail']})"
        )
        return 0 if out["ok"] else 1
    kw = dict(
        stall_window=ns.stall_window, stall_tol=ns.stall_tol,
        diversity_floor=ns.diversity_floor,
    )
    if len(paths) == 2:
        cmp = compare_runs(paths[0], paths[1], **kw)
        print(
            json.dumps(cmp, indent=2) if ns.format == "json"
            else render_comparison_text(cmp)
        )
        return 0
    if len(paths) > 2:
        ap.error("pass one log (doctor) or two (comparison)")
    report = analyze_run(paths[0], **kw)
    print(
        json.dumps(report, indent=2) if ns.format == "json"
        else render_text(report)
    )
    return 0 if report["verdict"] == "healthy" else 1


if __name__ == "__main__":
    sys.exit(main())
