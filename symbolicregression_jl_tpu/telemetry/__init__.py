"""Unified search telemetry: per-stage spans, a typed metrics registry,
and an append-only JSONL event log.

Opt-in via ``Options.telemetry`` (+ ``telemetry_dir`` /
``telemetry_every``) and threaded through both search drivers in
``api.py``. Everything here is host-side orchestration — no primitive is
added to any jitted search program, the compile-surface baseline stays
byte-identical, and a telemetry-on search returns a bit-identical
hall of fame (asserted in tests). See docs/observability.md for the span
model, the metric catalog, and the JSONL schema.
"""

from .events import (
    SCHEMA_VERSION,
    EventLog,
    open_event_log,
    validate_event,
    validate_events_file,
)
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    SearchMetrics,
    hypervolume_2d,
)
from .spans import STAGES, Span, SpanRecorder

# The run doctor (analyze.py) and the srprof profiler (profile.py) are
# exported LAZILY (PEP 562): importing either during package init would
# put the module in sys.modules before runpy executes its documented
# CLI (`python -m ...telemetry.analyze` / `...telemetry.profile`),
# tripping the double-import RuntimeWarning on every invocation.
_ANALYZE_EXPORTS = ("VERDICTS", "analyze_run", "compare_runs")
_PROFILE_EXPORTS = ("device_peaks", "profile_report", "roofline_join")


def __getattr__(name):
    if name in _ANALYZE_EXPORTS:
        from . import analyze

        return getattr(analyze, name)
    if name in _PROFILE_EXPORTS:
        from . import profile

        return getattr(profile, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


__all__ = [
    "STAGES",
    "SCHEMA_VERSION",
    "VERDICTS",
    "Counter",
    "EventLog",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "SearchMetrics",
    "Span",
    "SpanRecorder",
    "analyze_run",
    "compare_runs",
    "device_peaks",
    "hypervolume_2d",
    "open_event_log",
    "profile_report",
    "roofline_join",
    "validate_event",
    "validate_events_file",
]
