"""Unified search telemetry: per-stage spans, a typed metrics registry,
and an append-only JSONL event log.

Opt-in via ``Options.telemetry`` (+ ``telemetry_dir`` /
``telemetry_every``) and threaded through both search drivers in
``api.py``. Everything here is host-side orchestration — no primitive is
added to any jitted search program, the compile-surface baseline stays
byte-identical, and a telemetry-on search returns a bit-identical
hall of fame (asserted in tests). See docs/observability.md for the span
model, the metric catalog, and the JSONL schema.
"""

from .events import (
    SCHEMA_VERSION,
    EventLog,
    open_event_log,
    validate_event,
    validate_events_file,
)
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    SearchMetrics,
    hypervolume_2d,
)
from .spans import STAGES, Span, SpanRecorder

# The run doctor (analyze.py), the srprof profiler (profile.py), and
# the fleet layer (fleet.py / alerts.py / export.py) are exported
# LAZILY (PEP 562): importing any of them during package init would put
# the module in sys.modules before runpy executes its documented CLI
# (`python -m ...telemetry.analyze` / `...telemetry.profile`), tripping
# the double-import RuntimeWarning on every invocation — and the fleet
# layer is pure host-side file reading most runs never touch.
_ANALYZE_EXPORTS = ("VERDICTS", "analyze_run", "compare_runs")
_PROFILE_EXPORTS = ("device_peaks", "profile_report", "roofline_join")
_FLEET_EXPORTS = ("FleetScanner", "register_run", "load_fleet_index")
_ALERT_EXPORTS = ("AlertRule", "DEFAULT_ALERT_RULES", "evaluate_alerts")
_EXPORTER_EXPORTS = (
    "render_openmetrics",
    "validate_exposition",
    "write_textfile",
    "serve_metrics",
)


def __getattr__(name):
    if name in _ANALYZE_EXPORTS:
        from . import analyze

        return getattr(analyze, name)
    if name in _PROFILE_EXPORTS:
        from . import profile

        return getattr(profile, name)
    if name in _FLEET_EXPORTS:
        from . import fleet

        return getattr(fleet, name)
    if name in _ALERT_EXPORTS:
        from . import alerts

        return getattr(alerts, name)
    if name in _EXPORTER_EXPORTS:
        from . import export

        return getattr(export, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


__all__ = [
    "STAGES",
    "SCHEMA_VERSION",
    "VERDICTS",
    "AlertRule",
    "Counter",
    "DEFAULT_ALERT_RULES",
    "EventLog",
    "FleetScanner",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "SearchMetrics",
    "Span",
    "SpanRecorder",
    "analyze_run",
    "compare_runs",
    "device_peaks",
    "evaluate_alerts",
    "hypervolume_2d",
    "load_fleet_index",
    "open_event_log",
    "profile_report",
    "register_run",
    "render_openmetrics",
    "roofline_join",
    "serve_metrics",
    "validate_event",
    "validate_events_file",
    "validate_exposition",
    "write_textfile",
]
