"""Unified search telemetry: per-stage spans, a typed metrics registry,
and an append-only JSONL event log.

Opt-in via ``Options.telemetry`` (+ ``telemetry_dir`` /
``telemetry_every``) and threaded through both search drivers in
``api.py``. Everything here is host-side orchestration — no primitive is
added to any jitted search program, the compile-surface baseline stays
byte-identical, and a telemetry-on search returns a bit-identical
hall of fame (asserted in tests). See docs/observability.md for the span
model, the metric catalog, and the JSONL schema.
"""

from .events import (
    SCHEMA_VERSION,
    EventLog,
    open_event_log,
    validate_event,
    validate_events_file,
)
from .metrics import Counter, Gauge, Histogram, MetricsRegistry, SearchMetrics
from .spans import STAGES, Span, SpanRecorder

__all__ = [
    "STAGES",
    "SCHEMA_VERSION",
    "Counter",
    "EventLog",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "SearchMetrics",
    "Span",
    "SpanRecorder",
    "open_event_log",
    "validate_event",
    "validate_events_file",
]
