"""Public search API — `equation_search` (analog of the reference's
`EquationSearch`, src/SymbolicRegression.jl:283-391).

Placeholder while the evolution layers land; filled in by models/evolve.py +
parallel/ in subsequent milestones.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional


@dataclasses.dataclass
class EquationSearchResult:
    hall_of_fame: Any = None
    state: Any = None


def equation_search(X, y, **kwargs):  # pragma: no cover - placeholder
    raise NotImplementedError(
        "equation_search lands with the evolution milestone; "
        "use ops.interpreter.eval_trees / models.* directly for now"
    )
