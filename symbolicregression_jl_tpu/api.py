"""Public search API — the analog of the reference's `EquationSearch`
(src/SymbolicRegression.jl:283-391 dispatchers + :393-940 `_EquationSearch`).

Architecture (SURVEY.md §7): where the reference's head node spawns one task
per (output, population) and merges results through channels, here all
islands advance together inside ONE jitted iteration function:

    s_r_cycle (lax.scan of batched evolution cycles)
    -> simplify_population
    -> optimize_constants_islands         (batched BFGS: vmapped closures
                                           or fused Pallas loss/grad kernels)
    -> merge_halls_of_fame across islands (cross-island reduction)
    -> migrate                            (all-gather topn pool + masked replace)

vmapped over the islands axis and sharded over the device mesh. The host
loop only orchestrates: warm-up curriculum, early stopping, checkpoint CSV,
progress printing, recorder — all off the hot path.

Multi-output (y matrix) runs one island group per output row, like the
reference's per-output populations (src/SymbolicRegression.jl:308-315).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .models.dataset import (
    Dataset,
    make_dataset,
    sanitize_dataset,
    update_baseline_loss,
    validate_dataset,
)
from .models.evolve import (
    IslandState,
    expected_optimize_count,
    init_island_state,
    optimize_islands_constants,
    s_r_cycle_islands,
    simplify_population_islands,
)
from .models.options import Options, make_options
from .models.population import (
    HallOfFame,
    init_hall_of_fame,
    update_hall_of_fame,
)
from .models.trees import TreeBatch
from .ops.interpreter import eval_tree
from .parallel.distributed import initialize_multihost, is_primary_host
from .parallel.mesh import (
    describe_mesh,
    make_mesh,
    search_shardings,
    shard_dataset,
    shard_island_states,
)
from .parallel.migration import merge_hofs_across_islands, migrate
from .resilience import faults as _faults
from .utils.checkpoint import save_search_state
from .utils.output import Candidate, hof_to_candidates, pareto_table, save_hof_csv
from .utils.preflight import preflight_checks
from .utils.progress import (
    ProgressBar,
    QuitWatcher,
    ResourceMonitor,
    SearchProgress,
)
from .utils.recorder import Recorder

Array = jax.Array


@dataclasses.dataclass
class SearchState:
    """Resumable state (analog of StateType,
    reference src/SearchUtils.jl:270-273).

    `rng_key` is the host loop's per-output master PRNG key at the
    serialization point: restoring it makes a resumed search the exact
    continuation of the interrupted one — same iteration key chain,
    same hall of fame as the uninterrupted run (the bit-identity
    contract of docs/resilience.md). None (pre-snapshot states, older
    checkpoints) falls back to re-deriving the key from Options.seed:
    still deterministic, but a different chain than the original run's
    continuation."""

    island_states: IslandState  # leading (I,)
    global_hof: HallOfFame
    iteration: int = 0
    rng_key: Optional[Any] = None


@dataclasses.dataclass
class EquationSearchResult:
    """Hall of fame + Pareto frontier per output."""

    candidates: List[List[Candidate]]  # [output][rank]
    options: Options
    variable_names: Optional[Sequence[str]]
    state: Optional[List[SearchState]] = None
    num_evals: float = 0.0
    search_time_s: float = 0.0
    # evaluation memo-bank telemetry (options.cache_fitness; None
    # otherwise): {"totals": {scored, unique, memo_hits, evaluated,
    # hit_rate, unique_ratio}, "per_iteration": [...], "banks": [...]}
    cache_stats: Optional[dict] = None
    # hostile-data front-door census (models/dataset.py
    # DatasetDiagnostics.to_dict()): what validate_dataset found and
    # what Options.data_policy did about it (docs/robustness_numeric.md)
    dataset_diagnostics: Optional[dict] = None

    @property
    def multi_output(self) -> bool:
        return len(self.candidates) > 1

    def frontier(self, output: int = 0) -> List[Candidate]:
        return self.candidates[output]

    def best(self, output: int = 0) -> Candidate:
        """Best trade-off frontier member by the score column
        -Δlog(loss)/Δcomplexity (the reference's printed selection,
        src/HallOfFame.jl:136-139); ties broken by lower loss."""
        front = self.candidates[output]
        if not front:
            raise ValueError("Search produced no valid equations")
        return max(front, key=lambda c: (c.score, -c.loss))

    def best_loss(self, output: int = 0) -> Candidate:
        """Minimum-loss frontier member (usually the most complex)."""
        front = self.candidates[output]
        if not front:
            raise ValueError("Search produced no valid equations")
        return min(front, key=lambda c: c.loss)

    def predict(
        self, X, output: int = 0, complexity: Optional[int] = None
    ):
        """Evaluate the selected equation on X. Rows where evaluation left
        the operator domain (the reference's `complete=false` flag from
        eval_tree_array) surface as a warning — the returned array may
        contain NaN/Inf there."""
        cand = self._pick(output, complexity)
        X = jnp.asarray(X, self.options.dtype)
        tree = jax.tree_util.tree_map(jnp.asarray, cand.tree)
        y, ok = eval_tree(tree, X, self.options.operators)
        y = np.asarray(y)
        if not bool(np.asarray(ok)):
            import warnings

            warnings.warn(
                "predict: equation evaluation hit NaN/Inf on this input "
                "(operator domain violation); output contains non-finite "
                "values",
                RuntimeWarning,
                stacklevel=2,
            )
        return y

    def sympy(self, output: int = 0, complexity: Optional[int] = None):
        """Best (or complexity-matched) frontier member as a sympy
        expression (analog of node_to_symbolic export)."""
        from .utils.export import to_sympy

        cand = self._pick(output, complexity)
        return to_sympy(cand.tree, self.options, self.variable_names)

    def latex(self, output: int = 0, complexity: Optional[int] = None) -> str:
        from .utils.export import to_latex

        cand = self._pick(output, complexity)
        return to_latex(cand.tree, self.options, self.variable_names)

    def _pick(self, output: int, complexity: Optional[int]) -> Candidate:
        if complexity is None:
            return self.best(output)
        matches = [
            c for c in self.candidates[output] if c.complexity == complexity
        ]
        if not matches:
            raise ValueError(f"No frontier member at complexity {complexity}")
        return matches[0]

    def __repr__(self):
        parts = []
        for j, cands in enumerate(self.candidates):
            title = "Hall of Fame" + (f" (output {j})" if self.multi_output else "")
            parts.append(pareto_table(cands, title))
        return "\n".join(parts)


import functools


def _donation_enabled() -> bool:
    """Whether equation_search requests buffer donation from the jit
    factories (SRTPU_DONATE=0 disables — used by the A/B parity tests and
    as a debugging escape hatch). The production host loop feeds each
    iteration's output IslandState straight back in as the next input, so
    donating the carry lets XLA reuse its HBM in place instead of holding
    the old and new copy live across the dispatch — at the 64x1000 north-
    star shape that is gigabytes of steady-state headroom (see
    docs/static_analysis.md, srmem/SR006). Direct factory callers
    (benchmarks, tests, compile_surface) default to donate=False and keep
    fully functional semantics: a donated call INVALIDATES its input
    buffers on backends that implement donation (TPU, and CPU on this
    jaxlib), so only call sites that never reuse the passed-in carry may
    enable it."""
    return os.environ.get("SRTPU_DONATE", "1") != "0"


def _iteration_shard_kw(options: Options, mesh, has_weights: bool):
    """jit ``in_shardings``/``out_shardings`` for the fused-iteration
    signature — the compiled sharding CONTRACT of the production search
    (docs/multichip.md). Inputs: IslandState carry and the memo snapshot
    island-sharded/replicated, X/y/weights row-sharded, everything scalar
    replicated. Outputs: the carried IslandState PINNED island-sharded
    (a replicated carry would silently serialize every later iteration
    on one device), the merged HallOfFame replicated (host-side
    candidate extraction and migrate()'s HoF sampling both want every
    device holding it whole), recorder events island-sharded on dim 1
    (the cycle scan stacks its axis in front). None mesh -> {} (plain
    jit; the single-device graphs stay byte-identical).

    The vocabulary is written once for both mesh modes: per-tenant
    leaves (iteration key, baseline, merged HoF, memo snapshot) use
    the ``tenant`` spec, which search_shardings aliases to
    ``replicated`` on a solo (islands, rows) mesh — the solo compiled
    contract is unchanged — and to ``P(tenants)`` on a
    (tenants, islands) serving mesh, where ``island`` composes as
    ``P('tenants', 'islands')`` over the (T, I, ...) state leaves."""
    if mesh is None:
        return {}
    sh = search_shardings(mesh, options)
    isl, ten, repl = sh["island"], sh["tenant"], sh["replicated"]
    in_sh = [isl, ten, repl, sh["x"], sh["rows"]]
    if has_weights:
        in_sh.append(sh["rows"])
    in_sh += [ten, repl]
    if options.cache_fitness:
        in_sh.append(ten)
    out_sh = [isl, ten]
    if options.recorder:
        out_sh.append(sh["events"])
    if options.cache_fitness:
        out_sh.append(isl)
    return dict(in_shardings=tuple(in_sh), out_shardings=tuple(out_sh))


def _make_iteration_fn(options: Options, has_weights: bool,
                       donate: bool = False, mesh=None):
    """One jitted function per Options GRAPH (Options hash/eq deliberately
    ignore the TRACED_SCALAR_FIELDS knobs); X/y/weights/baseline AND the
    scalar knobs are traced arguments, so multi-output searches, repeated
    equation_search calls with equal Options, and sweeps over
    parsimony/alpha/annealing/migration fractions all reuse one
    compilation (the 20-40s TPU compile is paid per graph, not per
    config).

    The returned function's REQUIRED trailing argument is
    `options.traced_scalars()` — required precisely because the lru_cache
    may hand this closure to an Options instance that differs in those
    knobs; the caller's own values must flow in at every call.

    With options.recorder the returned function yields a third output:
    the per-cycle MutationEvents for the lineage recorder.

    Evaluation-graph shape: options.eval_bucket_ladder /
    options.eval_rows_per_tile select the length-bucketed / row-tiled
    jnp scoring graphs for every eval inside the iteration (cycle scan,
    simplify rescore, warm-start scoring) — they are part of the Options
    graph key, so flat and bucketed searches compile as distinct
    programs (docs/eval_pipeline.md has the dispatch decision tree and
    the per-path exactness guarantees; the bucketed graph is
    bit-identical to the flat one, asserted in tests).

    With options.cache_fitness the function takes ONE more trailing
    argument — the cache.DeviceMemo snapshot of the host memo bank
    (traced: a refreshed snapshot per iteration costs zero recompiles) —
    and yields ONE more trailing output: the post-simplify
    (trees, losses) absorb snapshot. The snapshot is captured AFTER the
    full-data rescore and BEFORE constant optimization on purpose: the
    optimizer writes its own objective's f_best into pop.losses, and
    that value can differ in ULPs from what the scoring path computes
    for the same tree (different kernel/reduction order on TPU) — the
    bank must only ever hold scoring-path values or a later memo hit
    would break the bit-identity guarantee.

    donate=True donates the IslandState carry (argument 0) to XLA
    (input/output buffer aliasing): the returned function then DELETES
    its input states on donation-capable backends — callers must never
    touch the passed-in states again (equation_search's loop never does;
    see _donation_enabled). Donation changes buffer reuse only, never
    values: tests pin the donated search's HallOfFame bit-identical to
    the non-donated one. The thin wrapper normalizes `donate` so the
    2-arg and explicit-donate=False call forms share one lru_cache entry
    (and one compile).

    mesh: a jax.sharding.Mesh (hashable — part of the cache key) makes
    island-axis sharding a COMPILED CONTRACT of the returned function
    via explicit in_shardings/out_shardings (_iteration_shard_kw): the
    donated sharded carry comes back island-sharded every iteration
    (donation aliases like-sharded buffers shard-for-shard), migration's
    topn pool build lowers to one all-gather + local masked scatter, and
    the merged HoF comes back replicated (no per-iteration device->host
    gather of island state — host consumers read reduced or replicated
    leaves only). mesh=None (the default, and every direct factory
    caller) is the unchanged single-device program."""
    return _make_iteration_fn_cached(options, has_weights, bool(donate),
                                     mesh)


@functools.lru_cache(maxsize=32)
def _make_iteration_fn_cached(options, has_weights, donate, mesh=None):
    # tenant-batched mode (options.tenants > 1, serving/batched.py): the
    # per-tenant body below is vmapped over the leading tenants axis, so
    # merge/migrate must NOT apply with_sharding_constraint inside the
    # vmap (the constraint names a dim the vmapped body cannot see);
    # tenant placement is expressed entirely through the jit in/out
    # shardings (_iteration_shard_kw). Constraints only ever pin layout,
    # never change values, so dropping them inside the batched body
    # keeps the per-tenant math bit-identical to the solo program.
    inner_mesh = None if options.tenants > 1 else mesh

    def one_iteration(
        states: IslandState,
        key: Array,
        curmaxsize: Array,
        X: Array,
        y: Array,
        weights,
        baseline: Array,
        scalars,
        memo=None,
    ):
        options_ = options.bind_scalars(scalars)
        k_mig, k_opt, k_opt_mut = jax.random.split(key, 3)
        # all-island fused forms: one interpreter call per cycle across the
        # whole archipelago (Pallas-sized batches on TPU). Static,
        # graph-shaping decisions (recorder, optimizer gating) read the
        # closure `options`; everything numeric reads the bound copy.
        # The memo is served ONLY to the population rescore below, never
        # to the cycle scan: the rescore's batch shape (I*npop) is the
        # same shape the absorb snapshot was scored at, so with
        # eval_backend='auto' both resolve to the SAME kernel — serving
        # a Pallas-computed value into a jnp-sized children batch would
        # be ULP-wrong on TPU. The cycle scan still dedups intra-batch.
        out = s_r_cycle_islands(
            states, curmaxsize, X, y, weights, baseline, options_,
            collect_events=options.recorder,
        )
        states, events = out if options.recorder else (out, None)
        states = simplify_population_islands(
            states, curmaxsize, X, y, weights, baseline, options_,
            memo=memo,
        )
        # scoring-path-only values for the memo bank (see factory doc)
        absorb_snap = (
            (states.pop.trees, states.pop.losses)
            if options.cache_fitness else None
        )
        if options.should_optimize_constants and options.optimizer_probability > 0:
            I = states.birth_counter.shape[0]
            okeys = jax.random.split(k_opt, I)
            states = optimize_islands_constants(
                okeys, states, X, y, weights, baseline, options_
            )
        # the `optimize` mutation (reference src/Mutate.jl:142-168): one
        # iteration-level pass sized to the expected number of sampled
        # optimize slots, instead of BFGS inside the cycle scan
        n_opt_mut = expected_optimize_count(options)
        if n_opt_mut > 0:
            p_sel = min(1.0, n_opt_mut / options.npop)
            I = states.birth_counter.shape[0]
            okeys2 = jax.random.split(k_opt_mut, I)
            states = optimize_islands_constants(
                okeys2, states, X, y, weights, baseline, options_,
                probability=p_sel, count_optimize_telemetry=True,
            )
        ghof = merge_hofs_across_islands(states.hof, mesh=inner_mesh)
        states = migrate(k_mig, states, ghof, options_, mesh=inner_mesh)
        outs = (states, ghof)
        if options.recorder:
            outs = outs + (events,)
        if options.cache_fitness:
            outs = outs + (absorb_snap,)
        return outs

    if options.tenants > 1:
        # ONE program over the whole tenant batch: states (T, I, ...),
        # per-tenant iteration keys (T, 2), stacked data (T, nfeat, n) /
        # (T, n), per-tenant baselines (T,) and memo snapshots; the
        # curmaxsize curriculum scalar and traced-scalar knobs are
        # shared (same Options for every tenant — the serving bucket
        # contract). vmap of the unchanged per-tenant body: threefry is
        # elementwise in the key, so every tenant's draws — and
        # therefore its HoF — are bit-identical to running that job
        # alone (the serving bit-identity contract, docs/serving.md).
        axes = (0, 0, None, 0, 0, 0 if has_weights else None, 0, None)
        if options.cache_fitness:
            axes = axes + (0,)
        one_iteration = jax.vmap(one_iteration, in_axes=axes)

    # the IslandState carry is argument 0 in every signature variant; the
    # non-donating default keeps functional semantics for direct callers
    # (benchmarks, compile_surface, tests that reuse a states pytree)
    donate_kw = dict(donate_argnums=(0,)) if donate else {}
    donate_kw.update(_iteration_shard_kw(options, mesh, has_weights))
    if options.cache_fitness:
        if has_weights:
            return jax.jit(one_iteration, **donate_kw)
        return jax.jit(
            lambda states, key, cm, X, y, baseline, scalars, memo:
            one_iteration(
                states, key, cm, X, y, None, baseline, scalars, memo
            ),
            **donate_kw,
        )
    if has_weights:
        return jax.jit(one_iteration, **donate_kw)
    return jax.jit(
        lambda states, key, cm, X, y, baseline, scalars: one_iteration(
            states, key, cm, X, y, None, baseline, scalars
        ),
        **donate_kw,
    )


def _make_phase_fns(options: Options, has_weights: bool,
                    donate: bool = False, mesh=None):
    """Jitted per-phase sub-programs of one evolution iteration, for the
    chunked-dispatch driver (options.max_cycles_per_dispatch): cycle
    chunks, simplify, constant-opt passes, and merge+migrate each compile
    as their OWN XLA program so no single device call runs longer than a
    cycle chunk. With batching=False numerics match the fused
    one_iteration exactly — the phases run in the same order on the same
    arrays; the chunked cycle scan receives its slice of the one
    iteration-wide annealing schedule and only the final chunk applies
    the stats-window decay. (Under batching=True the minibatch key chain
    restarts per chunk — deterministic and equally distributed draws,
    but not bit-equal to the fused scan's; see the Options field doc.)

    The phase closures read the same Options as the fused form, so the
    bucketed/row-tiled evaluation graphs (eval_bucket_ladder /
    eval_rows_per_tile) thread through both drivers identically — the
    chunked-vs-fused and bucketed-vs-flat bit-identity guarantees
    compose.

    mesh: every phase carries the same explicit in/out sharding contract
    as the fused iteration (_make_iteration_fn) — in particular each
    phase's IslandState output is pinned island-sharded, so the chunked
    driver's carry round-trips the mesh between dispatches without a
    silent full replication at any phase boundary."""
    return _make_phase_fns_cached(options, has_weights, bool(donate), mesh)


@functools.lru_cache(maxsize=32)
def _make_phase_fns_cached(options, has_weights, donate, mesh=None):
    # tenant-batched mode: same discipline as _make_iteration_fn — the
    # per-tenant phase bodies are vmapped over the leading tenants axis,
    # merge/migrate drop their in-vmap sharding constraints, and tenant
    # placement rides the per-phase jit in/out shardings
    inner_mesh = None if options.tenants > 1 else mesh

    def _bind(scalars):
        return options.bind_scalars(scalars)

    def cycle_chunk(states, curmaxsize, X, y, weights, baseline, scalars,
                    temperatures, is_last):
        # `temperatures` values are traced, but the chunk LENGTH is part
        # of the jit cache key (array shape) and `is_last` is static —
        # so at most three compiles: full chunk, remainder chunk (when k
        # doesn't divide ncycles), and the last chunk's is_last=True
        # variant. The memo bank feeds only the simplify phase (see
        # _make_iteration_fn: cycle batches resolve eval_backend='auto'
        # at a different batch size than the rescore the bank's values
        # came from).
        return s_r_cycle_islands(
            states, curmaxsize, X, y, weights, baseline, _bind(scalars),
            ncycles=temperatures.shape[0],
            collect_events=options.recorder,
            temperatures=temperatures,
            apply_move_window=is_last,
        )

    def simplify(states, curmaxsize, X, y, weights, baseline, scalars,
                 memo=None):
        return simplify_population_islands(
            states, curmaxsize, X, y, weights, baseline, _bind(scalars),
            memo=memo,
        )

    def optimize(okeys, states, X, y, weights, baseline, scalars):
        return optimize_islands_constants(
            okeys, states, X, y, weights, baseline, _bind(scalars)
        )

    # the optimize-mutation pass's selection probability is static (it
    # sizes the selected-member gather): derive it here exactly as the
    # fused one_iteration does
    _n_opt_mut = expected_optimize_count(options)
    _p_sel = min(1.0, _n_opt_mut / options.npop) if _n_opt_mut > 0 else 0.0

    def optimize_mut(okeys, states, X, y, weights, baseline, scalars):
        return optimize_islands_constants(
            okeys, states, X, y, weights, baseline, _bind(scalars),
            probability=_p_sel, count_optimize_telemetry=True,
        )

    def merge_migrate(k_mig, states, scalars):
        ghof = merge_hofs_across_islands(states.hof, mesh=inner_mesh)
        states = migrate(
            k_mig, states, ghof, _bind(scalars), mesh=inner_mesh
        )
        return states, ghof

    if options.tenants > 1:
        # vmap every phase over the tenants axis (chunk temperatures,
        # curmaxsize and the scalar knobs shared; is_last stays an
        # unmapped python bool for the jit static argnum below)
        w_ax = 0 if has_weights else None
        m_ax = 0 if options.cache_fitness else None
        cycle_chunk = jax.vmap(
            cycle_chunk,
            in_axes=(0, None, 0, 0, w_ax, 0, None, None, None),
        )
        simplify = jax.vmap(
            simplify, in_axes=(0, None, 0, 0, w_ax, 0, None, m_ax)
        )
        optimize = jax.vmap(
            optimize, in_axes=(0, 0, 0, 0, w_ax, 0, None)
        )
        optimize_mut = jax.vmap(
            optimize_mut, in_axes=(0, 0, 0, 0, w_ax, 0, None)
        )
        merge_migrate = jax.vmap(merge_migrate, in_axes=(0, 0, None))

    # donate the IslandState carry of every phase (the driver threads one
    # states pytree through the chain and never reuses a consumed one);
    # X/y/weights/scalars/temperatures are reused across calls and the
    # memo snapshot may be served again — never donated
    def _dk(states_argnum: int) -> dict:
        return dict(donate_argnums=(states_argnum,)) if donate else {}

    # per-phase sharding contract (mesh=None -> plain jit): the states
    # carry and per-island key batches island-sharded in AND out, data
    # row-sharded, scalars/keys/memo replicated; the chunked driver then
    # never leaves the mesh between phase dispatches. Per-tenant leaves
    # (baseline, iteration keys, memo, merged HoF) use the "tenant"
    # spec — an alias of "replicated" on a solo mesh, P(tenants) on a
    # serving mesh (see _iteration_shard_kw)
    if mesh is None:
        _sk = lambda in_sh, out_sh: {}
    else:
        _shv = search_shardings(mesh, options)

        def _sk(in_sh, out_sh):
            return dict(
                in_shardings=tuple(_shv[k] for k in in_sh),
                out_shardings=(
                    tuple(_shv[k] for k in out_sh)
                    if isinstance(out_sh, tuple) else _shv[out_sh]
                ),
            )

    _data = ("x", "rows", "rows")  # X, y, weights (None weights: no-op)
    _cycle_out = (
        ("island", "events") if options.recorder else "island"
    )
    return {
        # is_last is static by POSITION: a jit carrying explicit
        # in_shardings rejects every kwarg, static ones included — the
        # driver passes it positionally
        "cycle": jax.jit(
            cycle_chunk, static_argnums=(8,), **_dk(0),
            **_sk(("island", "replicated") + _data
                  + ("tenant", "replicated", "replicated"),
                  _cycle_out),
        ),
        "simplify": jax.jit(
            simplify, **_dk(0),
            **_sk(("island", "replicated") + _data
                  + ("tenant", "replicated", "tenant"),
                  "island"),
        ),
        "optimize": jax.jit(
            optimize, **_dk(1),
            **_sk(("island", "island") + _data
                  + ("tenant", "replicated"), "island"),
        ),
        "optimize_mut": jax.jit(
            optimize_mut, **_dk(1),
            **_sk(("island", "island") + _data
                  + ("tenant", "replicated"), "island"),
        ),
        "merge_migrate": jax.jit(
            merge_migrate, **_dk(1),
            **_sk(("tenant", "island", "replicated"),
                  ("island", "tenant")),
        ),
    }


def _make_iteration_driver(options: Options, has_weights: bool,
                           donate: bool = False, spans=None, mesh=None):
    """The production iteration entry: returns a callable with the same
    signature/outputs as _make_iteration_fn's. With
    options.max_cycles_per_dispatch=None (default) that IS the fused
    single-jit iteration; with an int k it is a host-level driver issuing
    phased dispatches of at most k cycles each (see _make_phase_fns).
    donate=True donates the IslandState carry in either form (see
    _make_iteration_fn doc for the caller contract).

    spans: a telemetry.spans.SpanRecorder (or None). When set, the
    driver always takes the PHASED path — with max_cycles_per_dispatch
    unset the whole cycle scan runs as ONE chunk, which receives the
    full fused-form temperature schedule and derives the identical
    minibatch key chain, so the math is the fused iteration's exactly
    (the chunked-vs-fused bit-identity tests pin this) — and each phase
    dispatch is wrapped in a fenced span (cycle / simplify / optimize /
    merge_migrate; the explicit block_until_ready per phase is what
    attributes device time to the right stage, at the cost of
    serializing the phase dispatches)."""
    k = options.max_cycles_per_dispatch
    if k is None and spans is None:
        return _make_iteration_fn(options, has_weights, donate, mesh)
    if spans is None:
        # chunked dispatch without telemetry: no-op instrumentation
        # (no fences, no timing — the pre-telemetry chunked driver)
        from .telemetry.spans import NULL as spans
    k = k or options.ncycles_per_iteration
    fns = _make_phase_fns(options, has_weights, donate, mesh)
    ncycles = options.ncycles_per_iteration
    # One iteration-wide schedule, built EXACTLY as s_r_cycle_islands
    # builds it (jnp.linspace: f32 math — np.linspace computes in f64 and
    # rounds differently for most lengths), sliced once at driver
    # construction. Each (chunk, is_last) pair is fixed for the life of
    # the driver.
    if options.annealing and ncycles > 1:
        _sched = jnp.linspace(1.0, 0.0, ncycles, dtype=jnp.float32)
    else:
        _sched = jnp.ones((ncycles,), jnp.float32)
    _chunks = [
        (_sched[pos:pos + k], pos + k >= ncycles)
        for pos in range(0, ncycles, k)
    ]

    # first-dispatch compile accounting (telemetry only): jit compiles
    # eagerly at call time and returns before the async execution, so
    # time-to-return of a phase's FIRST dispatch ~= its trace + lower +
    # backend-compile wall time. Emitted as a `compile` event per phase
    # so the run doctor reports compile separately instead of smearing
    # it into the first stage span (a warm lru_cache means a later
    # search in the same process legitimately records ~0s here).
    _phase_stage = {
        "cycle": "cycle", "simplify": "simplify",
        "optimize": "optimize", "optimize_mut": "optimize",
        "merge_migrate": "merge_migrate",
    }
    _uncompiled = set(fns) if spans.sink is not None else set()

    def _call(phase, *args):
        if phase not in _uncompiled:
            return fns[phase](*args)
        _uncompiled.discard(phase)
        t0 = time.perf_counter()
        out = fns[phase](*args)
        dt = time.perf_counter() - t0
        spans.note_compile(_phase_stage[phase], dt)
        spans.sink.emit(
            "compile",
            name=_phase_stage[phase],
            phase=phase if phase != _phase_stage[phase] else None,
            duration_s=dt,
        )
        return out

    # tenant-batched chunked driver: the host-side key splits replicate
    # what the fused form's vmapped body computes — threefry is
    # elementwise in the key, so the vmapped split of the (T, 2) key
    # batch yields each tenant's solo-search splits bit-for-bit
    _tb = options.tenants > 1

    def driver(states, key, curmaxsize, X, y, *rest):
        rest = list(rest)
        memo = rest.pop() if options.cache_fitness else None
        if has_weights:
            weights, baseline, scalars = rest
        else:
            (baseline, scalars), weights = rest, None

        if _tb:
            ks = jax.vmap(lambda k: jax.random.split(k, 3))(key)
            k_mig, k_opt, k_opt_mut = ks[:, 0], ks[:, 1], ks[:, 2]
        else:
            k_mig, k_opt, k_opt_mut = jax.random.split(key, 3)
        events_chunks = []
        with spans.span("cycle", chunks=len(_chunks),
                        ncycles=ncycles) as sp:
            for chunk, is_last in _chunks:
                out = _call(
                    "cycle",
                    states, curmaxsize, X, y, weights, baseline, scalars,
                    chunk, is_last,
                )
                if options.recorder:
                    states, ev = out
                    events_chunks.append(ev)
                else:
                    states = out
            sp.fence = states
        with spans.span("simplify") as sp:
            # memo passed positionally: a jit carrying explicit
            # in_shardings requires every sharded argument positional
            states = _call(
                "simplify",
                states, curmaxsize, X, y, weights, baseline, scalars,
                memo,
            )
            sp.fence = states
        # post-simplify, pre-optimize: scoring-path values only (same
        # capture point as the fused one_iteration's absorb snapshot)
        absorb_snap = (
            (states.pop.trees, states.pop.losses)
            if options.cache_fitness else None
        )
        if absorb_snap is not None and donate:
            # the snapshot aliases leaves of `states`, which the
            # donating optimize/merge_migrate dispatches below delete;
            # copy so the host-side memo-bank absorb can still read it
            absorb_snap = jax.tree_util.tree_map(
                lambda a: jnp.array(a, copy=True), absorb_snap
            )
        # last dim: I both solo (I,) and tenant-batched (T, I)
        I = states.birth_counter.shape[-1]
        if _tb:
            _okeys = lambda k: jax.vmap(
                lambda kk: jax.random.split(kk, I)
            )(k)
        else:
            _okeys = lambda k: jax.random.split(k, I)
        with spans.span("optimize") as sp:
            passes = 0
            if (options.should_optimize_constants
                    and options.optimizer_probability > 0):
                states = _call(
                    "optimize",
                    _okeys(k_opt), states, X, y, weights,
                    baseline, scalars,
                )
                passes += 1
            if expected_optimize_count(options) > 0:
                states = _call(
                    "optimize_mut",
                    _okeys(k_opt_mut), states, X, y,
                    weights, baseline, scalars,
                )
                passes += 1
            sp.fence = states
            sp.attrs["passes"] = passes
        with spans.span("merge_migrate") as sp:
            states, ghof = _call("merge_migrate", k_mig, states, scalars)
            sp.fence = (states, ghof)
        outs = (states, ghof)
        if options.recorder:
            events = jax.tree_util.tree_map(
                lambda *xs: jnp.concatenate(xs, axis=0), *events_chunks
            )
            outs = outs + (events,)
        if options.cache_fitness:
            outs = outs + (absorb_snap,)
        return outs

    return driver


def _make_init_fn(options: Options, nfeatures: int, has_weights: bool,
                  donate: bool = False, mesh=None):
    """Like _make_iteration_fn: the trailing REQUIRED `scalars` argument
    is `options.traced_scalars()` (initial scoring reads parsimony
    through it). donate=True donates the per-island key batch (argument
    0, aliasable onto the returned IslandState.key) — callers must pass
    freshly split keys they never reuse. mesh makes the returned
    IslandState island-sharded BY CONSTRUCTION (keys in and every state
    leaf out pinned to the island axis): the search starts on the mesh
    instead of initializing replicated and paying a reshard. The thin
    wrapper normalizes `donate` so the 3-arg and explicit-donate=False
    call forms share one lru_cache entry (and one compile)."""
    return _make_init_fn_cached(options, nfeatures, has_weights,
                                bool(donate), mesh)


@functools.lru_cache(maxsize=32)
def _make_init_fn_cached(options, nfeatures, has_weights, donate,
                         mesh=None):

    def init(keys, X, y, weights, baseline, scalars):
        options_ = options.bind_scalars(scalars)

        def one_tenant(k, Xt, yt, wt, blt):
            return jax.vmap(
                lambda kk: init_island_state(
                    kk, options_, nfeatures, Xt, yt, wt, blt,
                    dtype=options.dtype,
                )
            )(k)

        if options.tenants > 1:
            # (T, I, 2) key batch over (T, ...) stacked data: each
            # tenant's islands initialize exactly as its solo search
            # would (vmap of the unchanged per-tenant init)
            return jax.vmap(
                one_tenant,
                in_axes=(0, 0, 0, 0 if has_weights else None, 0),
            )(keys, X, y, weights, baseline)
        return one_tenant(keys, X, y, weights, baseline)

    donate_kw = dict(donate_argnums=(0,)) if donate else {}
    if mesh is not None:
        sh = search_shardings(mesh, options)
        in_sh = [sh["island"], sh["x"], sh["rows"]]
        if has_weights:
            in_sh.append(sh["rows"])
        in_sh += [sh["tenant"], sh["replicated"]]
        donate_kw.update(
            in_shardings=tuple(in_sh), out_shardings=sh["island"]
        )
    if has_weights:
        return jax.jit(init, **donate_kw)
    return jax.jit(
        lambda keys, X, y, baseline, scalars: init(
            keys, X, y, None, baseline, scalars
        ),
        **donate_kw,
    )


def _saved_state_compatible(
    state: "SearchState", options: Options, I: int
) -> Tuple[bool, bool]:
    """Shape-compatibility of a saved state with the current Options:
    (populations ok, hall-of-fame ok). The reference recreates any saved
    population whose npop mismatches, with a warning
    (src/SymbolicRegression.jl:532-573)."""
    try:
        pop = state.island_states.pop
        ok_pop = (
            pop.scores.shape[0] == I
            and pop.scores.shape[1] == options.npop
            and pop.trees.kind.shape[-1] == options.max_len
            and state.island_states.hof.losses.shape[-1]
            == options.actual_maxsize
        )
    except Exception:
        ok_pop = False
    try:
        ghof = state.global_hof
        ok_hof = (
            ghof.losses.shape[0] == options.actual_maxsize
            and ghof.trees.kind.shape[-1] == options.max_len
        )
    except Exception:
        ok_hof = False
    return ok_pop, ok_hof


def _seed_hof_islands(
    states: IslandState, source: HallOfFame, options: Options
) -> IslandState:
    """Fold a saved/loaded hall of fame into every island's HoF table
    (non-existing source slots carry inf loss and never insert)."""
    seeded = jax.vmap(
        lambda h: update_hall_of_fame(
            h, source.trees, source.scores, source.losses, options
        )
    )(states.hof)
    return states._replace(hof=seeded)


def _warm_start_hof(
    path: str, options: Options, variable_names, Xj, yj, wj, baseline
) -> Optional[HallOfFame]:
    """Load a hall-of-fame CSV checkpoint and rescore its equations on the
    current dataset, producing a HoF to seed the search (the analog of
    load_saved_hall_of_fame, reference src/SearchUtils.jl:275-301)."""
    import warnings

    from .models.fitness import score_trees
    from .models.trees import stack_trees
    from .utils.output import load_hof_csv

    try:
        cands = load_hof_csv(path, options, variable_names)
    except (OSError, ValueError) as e:
        warnings.warn(f"warm start: could not load {path!r}: {e}")
        return None
    if not cands:
        return None
    trees = stack_trees([c.tree for c in cands])
    scores, losses = score_trees(trees, Xj, yj, wj, baseline, options)
    hof = init_hall_of_fame(options, options.dtype)
    return update_hall_of_fame(hof, trees, scores, losses, options)


def _multi_output_path(path: str, output: int) -> str:
    """Per-output variant of a checkpoint path: base.out{j}.ext (single
    source for the writer and the warm-start reader)."""
    root, ext = os.path.splitext(path)
    return f"{root}.out{output}{ext}"


def _snapshot_due(global_it: int, nout: int, every: int) -> bool:
    """Round-aligned snapshot cadence: True when an every-k-dispatches
    boundary was crossed during the round that just finished (the
    dispatches in (global_it - nout, global_it]). For nout=1 this is
    exactly ``global_it % every == 0``; for multi-output it keeps the
    promised ~k-dispatch cadence — the naive modulo check would only
    fire when a multiple of `every` happens to land on a round
    boundary, silently stretching the cadence to lcm(every, nout)."""
    return (global_it // every) > ((global_it - nout) // every)


def _curmaxsize(
    options: Options, iteration: int, niterations: int
) -> int:
    """Maxsize warm-up curriculum (reference
    src/SymbolicRegression.jl:838-850): with warmup_maxsize_by=w > 0, the
    size cap ramps 3 -> maxsize over the first w fraction of iterations.
    Callers pass the ABSOLUTE planned total (resume start + remaining)
    so a resumed run continues the uninterrupted run's exact ramp."""
    if options.warmup_maxsize_by <= 0:
        return options.maxsize
    frac = (iteration / max(niterations * options.warmup_maxsize_by, 1e-9))
    cur = 3 + int((options.maxsize - 3) * min(frac, 1.0))
    return min(cur, options.maxsize)


def equation_search(X, y, **kwargs) -> EquationSearchResult:
    """Public entry — see :func:`_equation_search_impl` for the full
    signature and docs (the module bottom forwards ``__wrapped__`` and
    the impl docstring, so ``inspect.signature``/``help()`` surface the
    full keyword signature under this public name).

    This thin wrapper owns ONE concern: a ``row_shards > 1`` search runs
    under ``jax_threefry_partitionable=True`` (restored afterwards; the
    flag is part of jax's jit trace context, so cached programs cannot
    serve the wrong lowering). The legacy threefry lowering generates
    DIFFERENT random values depending on how XLA partitions the
    requesting program — measured: `migrate`'s randint/bernoulli draws
    diverged between the (islands, rows) mesh and the single-device run
    of the same Options — which would defeat the deterministic pairwise
    loss reduction's bit-identity contract (docs/robustness_numeric.md).
    The partitionable implementation is partition-invariant by
    construction. It draws a different (equally distributed) stream than
    the legacy one, so it is scoped HERE, to row-sharded searches only:
    row_shards=1 searches keep the exact seed streams every existing
    baseline and golden value was recorded under."""
    options = kwargs.get("options")
    row_shards = (
        options.row_shards if options is not None
        else int(kwargs.get("row_shards", 1))
    )
    if row_shards <= 1:
        return _equation_search_impl(X, y, **kwargs)
    prev = jax.config.jax_threefry_partitionable
    jax.config.update("jax_threefry_partitionable", True)
    try:
        return _equation_search_impl(X, y, **kwargs)
    finally:
        jax.config.update("jax_threefry_partitionable", prev)


def _equation_search_impl(
    X,
    y,
    *,
    weights=None,
    variable_names: Optional[Sequence[str]] = None,
    options: Optional[Options] = None,
    niterations: int = 10,
    saved_state: Optional[List[SearchState]] = None,
    warm_start_file: Optional[str] = None,
    return_state: bool = False,
    runtests: bool = True,
    on_iteration: Optional[Callable] = None,
    parallelism: Optional[str] = None,
    numprocs: Optional[int] = None,
    procs=None,
    addprocs_function=None,
    **option_kwargs,
) -> EquationSearchResult:
    """Search for symbolic expressions f(X) ~= y.

    X: (nfeatures, n); y: (n,) or (nout, n) for multi-output; weights
    optional (n,). Extra kwargs construct Options (e.g.
    binary_operators=..., npop=..., niterations is a search kwarg like the
    reference's). Returns the per-complexity hall of fame; with
    return_state=True the result carries resumable state (the analog of the
    reference's saved_state round-trip). warm_start_file seeds the search
    from a hall-of-fame CSV checkpoint (multi-output runs look for the
    .out{j} variants, mirroring how output_file writes them)."""
    # Reference EquationSearch scheduling kwargs
    # (src/SymbolicRegression.jl:283-297): accepted for drop-in migration,
    # but scheduling here is SPMD over the device mesh — islands are
    # always parallel within one jitted step, and multi-host runs come
    # from launching the same program per host (see README), not from
    # spawning workers out of this process.
    if parallelism is not None:
        if not isinstance(parallelism, str):
            raise ValueError(f"unknown parallelism {parallelism!r}")
        # accept the Julia symbol spelling ":serial" (one leading colon)
        p = parallelism[1:] if parallelism.startswith(":") else parallelism
        if p not in ("serial", "multithreading", "multiprocessing"):
            raise ValueError(f"unknown parallelism {parallelism!r}")
        if p != "multithreading":
            # "multithreading" matches what actually happens (parallel
            # islands in one process); the other modes imply a different
            # execution model and deserve a heads-up
            import warnings

            warnings.warn(
                f"parallelism={parallelism!r} has no effect: the search "
                "is always SPMD over the device mesh in this process "
                "(launch one process per host for multi-host — see "
                "README 'Multi-device and multi-host')",
                stacklevel=2,
            )
    if any(x is not None for x in (numprocs, procs, addprocs_function)):
        import warnings

        warnings.warn(
            "numprocs/procs/addprocs_function have no effect: worker "
            "processes are replaced by SPMD over the device mesh "
            "(launch one process per host for multi-host — see README "
            "'Multi-device and multi-host')",
            stacklevel=2,
        )

    if options is None:
        options = make_options(**option_kwargs)
    elif option_kwargs:
        raise ValueError("Pass either options= or option kwargs, not both")

    if options.tenants > 1:
        raise ValueError(
            "equation_search is the solo front door (one dataset); "
            "Options.tenants > 1 runs many same-shape jobs as ONE "
            "batched program — use "
            "serving.batched_equation_search(datasets, options=...) "
            "or the srserve job queue (serving.jobs)"
        )

    if options.precision == "float64" and not jax.config.jax_enable_x64:
        # The reference's Float64 mode. jax_enable_x64 is process-global and
        # intentionally NOT restored afterwards: the returned trees/arrays
        # (and result.predict) are float64 and need it to stay on.
        print(
            "precision='float64': enabling jax_enable_x64 for this process",
            file=sys.stderr,
        )
        jax.config.update("jax_enable_x64", True)
    host_dtype = np.float64 if options.precision == "float64" else np.float32
    # the precision cast can itself manufacture non-finites (a finite
    # float64 1e40 is inf in float32): count those cells so the front
    # door's diagnosis says "overflowed the precision cast — rescale or
    # use float64" instead of misreporting clean data as containing
    # NaN/Inf (docs/robustness_numeric.md)
    X_raw, y_raw = np.asarray(X), np.asarray(y)
    X = np.asarray(X_raw, host_dtype)
    y = np.asarray(y_raw, host_dtype)
    cast_overflow = 0
    if X_raw.dtype != host_dtype or y_raw.dtype != host_dtype:
        try:
            cast_overflow = int(
                (np.isfinite(X_raw) & ~np.isfinite(X)).sum()
                + (np.isfinite(y_raw) & ~np.isfinite(y)).sum()
            )
        except TypeError:  # non-numeric input: asarray already raised
            cast_overflow = 0
    if X.ndim != 2:
        raise ValueError("X must be (nfeatures, n)")
    multi = y.ndim == 2
    ys = y if multi else y[None, :]
    if ys.shape[1] != X.shape[1]:
        raise ValueError(
            f"y rows {ys.shape[1]} must match X columns {X.shape[1]}"
        )
    nfeatures = X.shape[0]

    # ---- hostile-data front door (models/dataset.py,
    # docs/robustness_numeric.md): validate BEFORE any jitted program
    # sees the data, then apply Options.data_policy — fail fast with a
    # structured report (reject), exclude bad rows through the weights
    # path (mask), or impute bad cells (repair). A clean dataset passes
    # through untouched under every policy (bit-identity). The census
    # lands in the telemetry run_start event and on the result. ----
    if weights is not None:
        weights = np.asarray(weights, host_dtype)
    data_diags = validate_dataset(X, ys, weights)
    data_diags.cast_overflow_cells = cast_overflow
    if cast_overflow:
        data_diags.errors.append(
            f"{cast_overflow} finite value(s) overflowed the "
            f"precision='{options.precision}' cast (|value| beyond the "
            "working dtype's range) — rescale the data or use "
            "precision='float64'; these cells are counted in the "
            "non-finite census above"
        )
    X, ys, weights, data_diags = sanitize_dataset(
        X, ys, weights, options.data_policy, data_diags
    )
    X = np.asarray(X, host_dtype)
    ys = np.asarray(ys, host_dtype)
    if weights is not None:
        weights = np.asarray(weights, host_dtype)

    # multi-host bring-up (no-op on a single host): the analog of the
    # reference's addprocs/worker-setup block
    # (src/SymbolicRegression.jl:500-528) — every host runs this same
    # program, so there is nothing to ship, only the runtime to join.
    # MUST run before preflight: jax.distributed.initialize refuses to run
    # once any backend has executed a computation.
    initialize_multihost()

    if data_diags.warnings and options.verbosity > 0 and is_primary_host():
        for wmsg in data_diags.warnings:
            print(f"dataset warning: {wmsg}", file=sys.stderr)

    if runtests:
        preflight_checks(options, X, ys, weights, pipeline=True)

    I = options.npopulations
    mesh = make_mesh(options, I, row_shards=options.row_shards)
    t_start = time.time()
    early_stop = options.early_stop_fn()
    # the host loop below never reuses a consumed IslandState, so the
    # production jits donate the carry (steady-state HBM drops by one
    # IslandState copy per output on donation-capable backends)
    donate = _donation_enabled()

    # ---- unified telemetry (options.telemetry; docs/observability.md):
    # JSONL event log + per-stage spans + metrics registry, entirely
    # host-side orchestration. Single-controller only, like the recorder
    # and the quit watcher: the phased span driver and the probe/metrics
    # dispatches change the program sequence host 0 issues, and on
    # multi-host SPMD every host must issue the identical sequence or
    # the collective-issuing loops desync. ----
    telemetry_on = (
        options.telemetry
        and is_primary_host()
        and jax.process_count() == 1
    )
    # ---- periodic search-state snapshots (options.snapshot_path /
    # snapshot_every_dispatches; docs/resilience.md): host-side
    # orchestration between dispatches, single-controller only like the
    # recorder (the device->host fetch of a multi-host sharded state is
    # a collective every host would have to issue in lockstep). ----
    snap_every = options.snapshot_every_dispatches
    snapshot_on = (
        options.snapshot_path is not None
        and snap_every > 0
        and is_primary_host()
        and jax.process_count() == 1
    )
    sink = None
    spans_rec = None
    search_metrics = None
    if telemetry_on:
        import hashlib

        from . import __version__ as _pkg_version
        from .telemetry.events import open_event_log
        from .telemetry.metrics import SearchMetrics
        from .telemetry.spans import SpanRecorder
        from .utils.recorder import repr_options

        fingerprint = hashlib.sha256(
            (
                repr_options(options)
                + f"|X{X.shape}|y{ys.shape}|niter{niterations}"
            ).encode()
        ).hexdigest()[:16]
        sink = open_event_log(options.telemetry_dir)
        # fleet provenance (additive schema fields): the stable logical
        # run id the fleet index joins attempts on (the supervisor
        # threads one id through every attempt; standalone runs default
        # to this log's own id) and the 1-based attempt index (the
        # watcher exports SRTPU_RUN_ATTEMPT into retried steps)
        if options.telemetry_attempt is not None:
            run_attempt = int(options.telemetry_attempt)
        else:
            try:
                run_attempt = max(
                    1, int(os.environ.get("SRTPU_RUN_ATTEMPT", "1"))
                )
            except ValueError:
                run_attempt = 1
        sink.emit(
            "run_start",
            run_id=options.telemetry_run_id or sink.run_id,
            attempt=run_attempt,
            config_fingerprint=fingerprint,
            backend=jax.default_backend(),
            devices=[str(d) for d in jax.devices()],
            niterations=niterations,
            nout=int(ys.shape[0]),
            x_shape=[int(s) for s in X.shape],
            package_version=_pkg_version,
            options=repr_options(options),
            # the mesh actually driving this run (None mesh_shape =
            # single-device): a degraded mesh choice (idle devices) is
            # part of the machine-readable record, not just a warning
            **describe_mesh(mesh),
            # hostile-data front-door census + policy provenance
            # (schema-additive; docs/robustness_numeric.md): what the
            # validator found and what sanitize_dataset did about it
            dataset_diagnostics=data_diags.to_dict(),
            # resilience provenance (schema-additive): the snapshot
            # cadence this run writes under, and — on a resumed run —
            # where its saved_state came from (null = fresh start)
            snapshot=(
                {
                    "path": options.snapshot_path,
                    "every_dispatches": snap_every,
                }
                if snapshot_on else None
            ),
            resume_from=(
                {
                    "path": getattr(
                        saved_state[0], "_source_path", None
                    ),
                    "iteration": min(
                        s.iteration for s in saved_state
                    ),
                    "outputs": len(saved_state),
                    # provenance must be truthful: an incompatible
                    # state is RECREATED below (fresh populations,
                    # HoF possibly kept), not resumed — consumers
                    # keying resumed_from off this field need to know
                    "populations_compatible": all(
                        _saved_state_compatible(s, options, I)[0]
                        for s in saved_state
                    ),
                }
                if saved_state else None
            ),
        )
        spans_rec = SpanRecorder(sink)
        search_metrics = SearchMetrics(options, sink)

    # ---- XLA profiler trace capture (options.profile_trace_dir;
    # docs/observability.md "Profiling"): wraps the whole search —
    # init compiles included — so the spans' srtpu/<stage> annotations
    # land on the device timeline. Orchestration-only; a capture
    # failure degrades to no trace, never into the search. Stopped on
    # every dispatch-fault path and on normal completion; an exception
    # escaping elsewhere (e.g. Ctrl-C) can leave the process-wide
    # profiler running, so the start below first reclaims any trace a
    # previous interrupted search leaked — the NEXT profiled search
    # always captures. ----
    _trace = {"on": False}
    if options.profile_trace_dir is not None and is_primary_host():
        try:
            jax.profiler.start_trace(options.profile_trace_dir)
            _trace["on"] = True
        except Exception as e:
            try:  # reclaim a leaked trace and retry once
                jax.profiler.stop_trace()
                jax.profiler.start_trace(options.profile_trace_dir)
                _trace["on"] = True
            except Exception:  # pragma: no cover - defensive
                print(f"profile trace unavailable: {e}", file=sys.stderr)

    def _stop_trace():
        if _trace["on"]:
            _trace["on"] = False
            try:
                jax.profiler.stop_trace()
            except Exception:  # pragma: no cover - defensive
                pass

    iteration_fn = _make_iteration_driver(
        options, weights is not None, donate, spans=spans_rec, mesh=mesh
    )
    # this Options' trace-irrelevant scalar knobs, passed to every jitted
    # call (the factories' lru_caches dedup Options differing only in
    # these, so the values MUST come from here, not the closure)
    scalars = options.traced_scalars()

    results: List[List[Candidate]] = []
    out_states: List[SearchState] = []
    total_evals = 0.0
    # The recorder materializes every island population on the host each
    # iteration — single-controller only (multi-host shards are not
    # addressable from one process).
    record_here = (
        options.recorder and is_primary_host() and jax.process_count() == 1
    )
    recorder = (
        Recorder(options, variable_names, sink=sink) if record_here
        else None
    )
    total_its = niterations * max(ys.shape[0], 1)
    progress = SearchProgress(total_its, options, sink=sink)
    bar = (
        ProgressBar(total_its, width=options.terminal_width)
        if options.terminal_width
        else ProgressBar(total_its)
    )
    monitor = ResourceMonitor(sink=sink, verbosity=options.verbosity)
    # 'q'-to-quit is single-controller only: on multi-host SPMD a break taken
    # on host 0 alone would desync the collective-issuing host loops.
    quit_watcher = QuitWatcher(
        enabled=options.verbosity > 0 and jax.process_count() == 1
    )
    global_it = 0  # host-loop iterations completed across all outputs
    nout = ys.shape[0]

    # ---- per-output setup: every output's islands and hall of fame are
    # initialized up front (the reference's event loop owns all
    # (output, population) tasks the same way —
    # src/SymbolicRegression.jl:539-573), so the joint loop below can
    # stop globally at any moment with every output's frontier live ----
    out_data = []          # (Xj, yj, wj, bl) per output
    live_states = []       # current IslandStates per output
    live_hofs = []         # current merged hall of fame per output
    out_keys = []          # per-output PRNG stream
    start_iters = []
    bl_host = []           # host-side baseline loss per output (metrics)

    # ---- evaluation memo bank (options.cache_fitness) ----
    use_cache = options.cache_fitness
    banks: List[Optional[object]] = []
    if use_cache:
        from .cache.memo import dataset_fingerprint, get_memo_bank

        for j in range(nout):
            # one bank per evaluation context. Multi-host runs keep the
            # intra-batch dedup but skip the host bank (every host must
            # feed the SPMD program an identical memo snapshot, and the
            # empty one is the only snapshot that is free to agree on).
            # A custom full-tree loss_function also skips it: serving is
            # already bypassed in score_trees_cached, and absorbing its
            # objective values under an elementwise-loss fingerprint
            # would poison a later search's bank.
            if jax.process_count() == 1 and options.loss_function is None:
                banks.append(
                    get_memo_bank(
                        dataset_fingerprint(X, ys[j], weights, options),
                        options.cache_capacity,
                    )
                )
            else:
                banks.append(None)
    # cumulative per-output [scored, unique, memo_hits] for per-iteration
    # deltas (IslandState.cache_counts is cumulative on device);
    # cache_base holds the resume baseline so a saved_state's carried
    # counts are excluded from THIS search's reported totals
    cache_prev = [np.zeros(3, np.int64) for _ in range(nout)]
    cache_base = [np.zeros(3, np.int64) for _ in range(nout)]
    cache_iter_rows: List[dict] = []

    for j in range(nout):
        ds = make_dataset(
            X, ys[j], weights, variable_names, dtype=options.dtype
        )
        ds = update_baseline_loss(ds, options)
        Xj, yj, wj = shard_dataset(ds.X, ds.y, ds.weights, mesh, options)

        master_key = jax.random.PRNGKey(options.seed + 7919 * j)
        bl = jnp.asarray(ds.baseline_loss, options.dtype)

        def _fresh_init(key, _j=j):
            k_init, key = jax.random.split(key)
            init_keys = jax.random.split(k_init, I)
            init_fn = _make_init_fn(options, nfeatures, wj is not None,
                                    donate, mesh)
            try:
                if spans_rec is not None:
                    with spans_rec.span("init", output=_j) as sp:
                        t0 = time.perf_counter()
                        if wj is not None:
                            sts = init_fn(
                                init_keys, Xj, yj, wj, bl, scalars
                            )
                        else:
                            sts = init_fn(init_keys, Xj, yj, bl, scalars)
                        if _j == 0 and sink is not None:
                            # first-dispatch compile accounting, like
                            # the phase programs (time-to-return:
                            # compile wall time, async excluded)
                            dt = time.perf_counter() - t0
                            spans_rec.note_compile("init", dt)
                            sink.emit(
                                "compile", name="init", duration_s=dt
                            )
                        sp.fence = sts
                elif wj is not None:
                    sts = init_fn(init_keys, Xj, yj, wj, bl, scalars)
                else:
                    sts = init_fn(init_keys, Xj, yj, bl, scalars)
            except BaseException:
                # the init dispatch is outside the main loop's
                # dispatch-fault handlers — don't leak the trace
                _stop_trace()
                raise
            return sts, key

        if saved_state is not None:
            state = saved_state[j]
            ok_pop, ok_hof = _saved_state_compatible(state, options, I)
            if ok_pop:
                if getattr(state, "rng_key", None) is not None:
                    # restore the host key chain at the serialization
                    # point: the resumed run's iteration keys continue
                    # exactly where the interrupted run's stopped (the
                    # bit-identity contract, docs/resilience.md).
                    # Compatible-state resumes ONLY — the recreate
                    # fallback below stays reproducible from
                    # Options.seed, as SearchState's doc promises.
                    # Absent on pre-snapshot states: the fresh
                    # seed-derived chain above.
                    master_key = jnp.asarray(state.rng_key)
                states, ghof = state.island_states, state.global_hof
                if donate:
                    # iteration 1 will donate (delete) these buffers;
                    # copy so the caller's saved_state stays usable
                    # (resumed twice, inspected after the search)
                    states = jax.tree_util.tree_map(
                        lambda x: jnp.array(x, copy=True), states
                    )
            else:
                # the reference recreates mismatched populations with a
                # warning (src/SymbolicRegression.jl:532-573); the saved
                # hall of fame survives when its shapes still fit
                import warnings

                warnings.warn(
                    "saved_state is incompatible with these Options "
                    "(npopulations/npop/maxsize changed); recreating "
                    "populations"
                    + (" but keeping the saved hall of fame" if ok_hof
                       else " and the hall of fame")
                )
                states, master_key = _fresh_init(master_key)
                if ok_hof:
                    states = _seed_hof_islands(
                        states, state.global_hof, options
                    )
                ghof = merge_hofs_across_islands(states.hof)
            start_iter = state.iteration
        else:
            states, master_key = _fresh_init(master_key)
            if warm_start_file is not None:
                path = warm_start_file
                if multi:
                    path = _multi_output_path(path, j)
                warm = _warm_start_hof(
                    path, options, variable_names, Xj, yj, wj, bl
                )
                if warm is not None:
                    states = _seed_hof_islands(states, warm, options)
            ghof = merge_hofs_across_islands(states.hof)
            start_iter = 0
        states = shard_island_states(states, mesh, options)
        if use_cache:
            # a resumed saved_state carries its run's cumulative counters:
            # baseline both the delta tracking and the totals on them
            cache_prev[j] = np.asarray(
                jnp.sum(states.cache_counts, axis=0), np.int64
            )
            cache_base[j] = cache_prev[j].copy()
        out_data.append((Xj, yj, wj, bl))
        live_states.append(states)
        live_hofs.append(ghof)
        out_keys.append(master_key)
        start_iters.append(start_iter)
        bl_host.append(float(ds.baseline_loss))

    # ---- joint iteration loop: one iteration per output per round
    # (the reference's kappa round-robin over (out, pop) pairs,
    # src/SymbolicRegression.jl:659-694). Global stop semantics match
    # src/SymbolicRegression.jl:899-909: 'q', timeout, and max_evals
    # terminate the WHOLE search the moment they trip; the loss
    # threshold stops only once EVERY output's frontier satisfies it
    # (src/SearchUtils.jl:109-141). ----
    # per-output index of the last EXECUTED iteration (start-1 when none
    # ran, so the saved SearchState.iteration = its[j]+1 counts only real
    # work — an output cut off by a global stop before its first
    # iteration resumes at exactly start_iters[j])
    its = [s - 1 for s in start_iters]
    latest_cands: List[Optional[List[Candidate]]] = [None] * nout
    # host-side cache of each output's num_evals total: only output j's
    # count changes in its own iteration, so the global max_evals check
    # needs ONE device sync per iteration, not nout
    evals_cache = [0.0] * nout
    stop_all = False
    for step in range(niterations):
        for j in range(nout):
            Xj, yj, wj, bl = out_data[j]
            states = live_states[j]
            its[j] = start_iters[j] + step
            it = its[j]
            # curriculum denominator is the ABSOLUTE planned total
            # (start + remaining): identical to niterations on a fresh
            # start, and on a resume it keeps the warm-up ramp exactly
            # where the interrupted run would have had it — a resumed
            # run passing only the remaining count must not re-stretch
            # warmup_maxsize_by over a shorter schedule (bit-identity)
            cm_host = _curmaxsize(
                options, it, max(start_iters[j] + niterations, 1)
            )
            cm = jnp.int32(cm_host)
            out_keys[j], k_it = jax.random.split(out_keys[j])
            if spans_rec is not None:
                spans_rec.set_context(output=j, iteration=it)
                if step == 0 and j == 0:
                    # one-shot measured spans for the two in-scan stages
                    # (mutate / eval): their own jitted programs, run
                    # once — see telemetry.spans.probe_mutate_eval
                    from .telemetry.spans import probe_mutate_eval

                    probe_mutate_eval(
                        spans_rec, options, states, Xj, yj, wj, bl,
                        scalars,
                    )
            t_dev = time.time()
            if use_cache:
                # refreshed device snapshot of the memo bank (traced
                # arguments: same shapes every iteration, no recompile)
                if banks[j] is not None:
                    memo = banks[j].device_snapshot(
                        options.cache_device_slots, options.dtype
                    )
                else:
                    from .cache.dedup import empty_device_memo

                    memo = empty_device_memo(
                        options.cache_device_slots, options.dtype
                    )
                memo_args = (memo,)
            else:
                memo_args = ()
            try:
                # deterministic fault injection (resilience.faults): a
                # no-op without an active plan; raises/kills HERE so an
                # injected failure takes the same dispatch_fault path a
                # real device fault would
                _faults.on_dispatch(global_it)
                if wj is not None:
                    out = iteration_fn(
                        states, k_it, cm, Xj, yj, wj, bl, scalars,
                        *memo_args
                    )
                else:
                    out = iteration_fn(
                        states, k_it, cm, Xj, yj, bl, scalars, *memo_args
                    )
                if use_cache:
                    absorb_snap = out[-1]
                    out = out[:-1]
                else:
                    absorb_snap = None
                if options.recorder:
                    states, ghof, events = out
                else:
                    (states, ghof), events = out, None
                jax.block_until_ready(ghof.losses)
            except Exception as e:
                # the machine-readable fault trail the resume-not-restart
                # story needs (ROADMAP item 4): a mid-run UNAVAILABLE /
                # tunnel fault is recorded with its iteration before the
                # exception propagates (line-buffered log: the event is
                # on disk even if the process dies with us)
                if sink is not None:
                    sink.emit(
                        "dispatch_fault",
                        where="iteration",
                        error_type=type(e).__name__,
                        error=str(e)[:500],
                        output=j,
                        iteration=it,
                        fatal=True,
                    )
                    sink.close()
                _stop_trace()
                raise
            t_host = time.time()
            live_states[j] = states
            live_hofs[j] = ghof

            # ---- host-side orchestration (off the hot path) ----
            cache_row = None
            if use_cache:
                # absorb the post-simplify snapshot — the full-data,
                # SCORING-PATH rescore of every member, captured before
                # constant optimization overwrote selected losses with
                # its own objective's values (see _make_iteration_fn
                # doc: the bank must only ever hold values the scoring
                # path itself produces, bit-for-bit — this also makes
                # the absorb safe under batching=True, where the
                # snapshot is still a full-data rescore).
                if banks[j] is not None and absorb_snap is not None:
                    from .cache.hashing import tree_hash_host

                    snap_trees, snap_losses = absorb_snap
                    snap_trees = jax.tree_util.tree_map(
                        np.asarray, snap_trees
                    )
                    banks[j].absorb(
                        tree_hash_host(snap_trees).ravel(),
                        np.asarray(snap_losses).ravel(),
                    )
                counts = np.asarray(
                    jnp.sum(states.cache_counts, axis=0), np.int64
                )
                delta = counts - cache_prev[j]
                cache_prev[j] = counts
                scored, unique, hits = (int(v) for v in delta)
                evaluated = unique - hits
                cache_row = {
                    "output": j,
                    "iteration": it,
                    "scored": scored,
                    "unique": unique,
                    "memo_hits": hits,
                    "evaluated": evaluated,
                    "unique_ratio": unique / scored if scored else 0.0,
                    "memo_hit_rate": hits / scored if scored else 0.0,
                    # fraction of eval-batch slots that still needed real
                    # evaluation (1 - this = eval-batch shrinkage)
                    "eval_batch_fill": (
                        evaluated / scored if scored else 0.0
                    ),
                }
                cache_iter_rows.append(cache_row)
            progress.note_iteration(I)
            global_it += 1
            if (
                search_metrics is not None
                and (it - start_iters[j]) % options.telemetry_every == 0
            ):
                # one fused device reduction + host-held values -> one
                # `metrics` event (telemetry.metrics.SearchMetrics)
                ncyc = options.ncycles_per_iteration
                search_metrics.observe_iteration(
                    states, ghof, output=j, iteration=it,
                    baseline=bl_host[j],
                    temperature=(
                        0.5 if options.annealing and ncyc > 1 else 1.0
                    ),
                    curmaxsize=cm_host,
                    cache_row=cache_row,
                    cycles_per_second=progress.cycles_per_second,
                    device_s=t_host - t_dev,
                )
            cands = hof_to_candidates(ghof, options, variable_names)
            latest_cands[j] = cands
            if recorder is not None:
                recorder.record_hall_of_fame(j, it, cands)
                if cache_row is not None:
                    recorder.record_cache(j, it, cache_row)
                if events is not None:
                    recorder.record_mutation_events(j, it, events)
                for isl in range(I):
                    recorder.record_population(
                        j, isl, it,
                        jax.tree_util.tree_map(
                            lambda x: x[isl], states.pop.trees
                        ),
                        states.pop.scores[isl], states.pop.losses[isl],
                        states.pop.birth[isl],
                        mut_counts=states.mut_counts[isl],
                    )
            if (options.output_file and options.save_to_file
                    and is_primary_host()):
                path = options.output_file
                if multi:
                    path = _multi_output_path(path, j)
                save_hof_csv(cands, path)
                if sink is not None:
                    sink.emit(
                        "checkpoint", path=path, output=j, iteration=it
                    )
            want_console = options.verbosity > 0 and is_primary_host()
            if want_console or sink is not None:
                best_loss = min((c.loss for c in cands), default=float("inf"))
                evals = float(jnp.sum(states.num_evals))
                prefix = f"[output {j}] " if multi else ""
                # one status, every channel: `progress` event on the
                # sink (even at verbosity 0 — quiet consoles must not
                # silence the machine-readable trail), console line only
                # when verbose
                progress.report(
                    global_it - 1, best_loss, evals,
                    # this search's own work: exclude a resumed
                    # saved_state's carried counters, matching
                    # result.cache_stats["totals"]
                    cache_counts=tuple(cache_prev[j] - cache_base[j])
                    if use_cache else None,
                    prefix=prefix, console=want_console,
                    output=j, search_iteration=it,
                )
                if want_console and options.progress:
                    bar.update(global_it, pareto_table(cands))
            if on_iteration is not None:
                on_iteration(j, it, cands)
            monitor.note(t_host - t_dev, time.time() - t_host)
            monitor.maybe_warn()

            # ---- periodic snapshot: every snap_every dispatches,
            # aligned to round boundaries (last output) so every
            # output's saved iteration counter agrees and the resume
            # math stays exact. Fenced, then fetched to host BEFORE the
            # next dispatch can donate (delete) these buffers. ----
            if (
                snapshot_on
                and j == nout - 1
                and _snapshot_due(global_it, nout, snap_every)
            ):
                snap_states = [
                    SearchState(
                        island_states=live_states[q],
                        global_hof=live_hofs[q],
                        iteration=its[q] + 1,
                        rng_key=out_keys[q],
                    )
                    for q in range(nout)
                ]
                jax.block_until_ready(
                    [s.island_states for s in snap_states]
                )
                try:
                    save_search_state(
                        options.snapshot_path, snap_states, sink=sink,
                        options=options, dispatch=global_it,
                        cause="periodic",
                    )
                except Exception as e:
                    # a dying snapshot write (ENOSPC, injected tear)
                    # must leave the same machine-readable fault trail
                    # a dying dispatch does — without this the log just
                    # stops and the doctor calls the run 'incomplete'
                    # instead of 'faulted'
                    if sink is not None:
                        sink.emit(
                            "dispatch_fault",
                            where="snapshot",
                            error_type=type(e).__name__,
                            error=str(e)[:500],
                            output=j,
                            iteration=it,
                            fatal=True,
                        )
                        sink.close()
                    _stop_trace()
                    raise

            # global immediate stops: any one trips → the whole search
            # ends, all outputs included (src/SymbolicRegression.jl:899-909)
            if (
                options.timeout_in_seconds is not None
                and time.time() - t_start > options.timeout_in_seconds
            ):
                stop_all = True
            elif options.max_evals is not None:
                # the reference sums num_evals over every output
                # (src/SearchUtils.jl:139-141); only output j's count
                # moved since the last check
                evals_cache[j] = float(jnp.sum(states.num_evals))
                if sum(evals_cache) > options.max_evals:
                    stop_all = True
            if quit_watcher.should_quit():
                stop_all = True
            if stop_all:
                break
        if stop_all:
            break
        # loss threshold: stop only when every output's frontier has a
        # satisfying member (src/SearchUtils.jl:109-141 returns false on
        # any output that doesn't)
        if early_stop is not None and all(
            c is not None
            and any(early_stop(m.loss, m.complexity) for m in c)
            for c in latest_cands
        ):
            break
    _stop_trace()

    for j in range(nout):
        states = live_states[j]
        total_evals += float(jnp.sum(states.num_evals))
        results.append(
            hof_to_candidates(live_hofs[j], options, variable_names)
        )
        out_states.append(
            SearchState(
                island_states=states,
                global_hof=live_hofs[j],
                iteration=its[j] + 1,
                # the host master key at this point: resuming from this
                # state continues the exact iteration key chain
                rng_key=out_keys[j],
            )
        )

    search_time_s = time.time() - t_start
    if recorder is not None:
        recorder.record_final(total_evals, search_time_s)
        recorder.save()

    cache_stats = None
    if use_cache:
        # this search's own work only: cumulative minus resume baseline,
        # so totals always equal the sum of the per_iteration rows
        tot = np.sum(np.stack(cache_prev), axis=0) - np.sum(
            np.stack(cache_base), axis=0
        )
        scored, unique, hits = (int(v) for v in tot)
        evaluated = unique - hits
        cache_stats = {
            "totals": {
                "scored": scored,
                "unique": unique,
                "memo_hits": hits,
                "evaluated": evaluated,
                # fraction of scored trees answered without evaluation
                # (intra-batch duplicates + memo hits)
                "hit_rate": (
                    (scored - evaluated) / scored if scored else 0.0
                ),
                "unique_ratio": unique / scored if scored else 0.0,
            },
            "per_iteration": cache_iter_rows,
            "banks": [b.stats if b is not None else None for b in banks],
        }

    if sink is not None:
        # ---- srprof modeled-vs-measured join (telemetry.profile):
        # model every stage's cost at this run's shapes and join it
        # with the measured span totals into per-stage `profile`
        # events — the roofline attribution the report CLI renders.
        # Trace-only + host math; a failure degrades to a probe_error
        # event, never into the search result. ----
        if spans_rec is not None:
            try:
                from .telemetry.profile import emit_profile_events

                emit_profile_events(
                    sink, spans_rec.stage_totals(), options,
                    nfeatures, int(X.shape[1]),
                    compile_totals=spans_rec.compile_s,
                )
            except Exception as e:  # pragma: no cover - defensive
                sink.emit(
                    "probe_error",
                    error=f"profile: {type(e).__name__}: "
                          f"{str(e)[:200]}",
                )
        if return_state:
            # in-memory serialization point (the caller may persist it
            # with utils.checkpoint.save_search_state, which emits its
            # own on-disk saved_state event)
            sink.emit(
                "saved_state", outputs=nout, path=None, in_memory=True,
                iteration=max((s.iteration for s in out_states),
                              default=0),
            )
        sink.emit(
            "run_end",
            num_evals=total_evals,
            search_time_s=search_time_s,
            hof=[
                [
                    {
                        "complexity": c.complexity,
                        "loss": c.loss,
                        "score": c.score,
                        "equation": c.equation,
                    }
                    for c in cands
                ]
                for cands in results
            ],
        )
        sink.close()

    return EquationSearchResult(
        candidates=results,
        options=options,
        variable_names=variable_names,
        state=out_states if return_state else None,
        num_evals=total_evals,
        search_time_s=search_time_s,
        cache_stats=cache_stats,
        dataset_diagnostics=data_diags.to_dict(),
    )


# introspection passthrough: help()/inspect.signature on the public
# wrapper surface the impl's full keyword signature and doc
equation_search.__wrapped__ = _equation_search_impl
equation_search.__doc__ = (
    (_equation_search_impl.__doc__ or "")
    + "\n\n"
    + (equation_search.__doc__ or "")
)
