"""Scikit-learn-style estimator facade over `equation_search`.

The reference is the search engine behind PySR's `PySRRegressor`; users
coming from that ecosystem expect a fit/predict estimator with
`(n_samples, n_features)` data layout. This wraps the functional API
(`api.equation_search`, which uses the reference's `(nfeatures, n)`
layout from src/Dataset.jl) in that convention. No sklearn dependency —
duck-typed `get_params`/`set_params` follow the estimator protocol.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from .api import EquationSearchResult, equation_search


def _valid_option_keys() -> set:
    """Every kwarg make_options accepts: Options fields, deprecated
    camelCase aliases, and the turbo mapping."""
    import dataclasses

    from .models.options import _DEPRECATED_KWARGS, Options

    keys = {f.name for f in dataclasses.fields(Options)}
    keys.update(_DEPRECATED_KWARGS)
    # make_options-level remaps (not Options fields themselves)
    keys.update(
        ("turbo", "elementwise_loss", "una_constraints", "bin_constraints")
    )
    return keys


class SymbolicRegressor:
    """Evolutionary symbolic regression estimator.

    Parameters are `equation_search` / `make_options` kwargs (e.g.
    binary_operators, unary_operators, npop, npopulations, maxsize,
    parsimony, ...) plus `niterations`. Data is `(n_samples, n_features)`
    like any sklearn estimator; it is transposed to the engine's
    `(nfeatures, n)` layout internally.

    After `fit`: `equations_` (per-output Pareto frontier),
    `best_equation_`, `result_` (the full EquationSearchResult);
    `predict`/`score` evaluate the chosen frontier member.
    """

    def __init__(self, niterations: int = 10, **options: Any):
        self.niterations = niterations
        self.options = options
        self.result_: Optional[EquationSearchResult] = None

    # -- sklearn estimator protocol ------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        # no nested estimators, so `deep` changes nothing (sklearn's deep
        # expansion only applies to sub-estimator params)
        return {"niterations": self.niterations, **self.options}

    def set_params(self, **params: Any) -> "SymbolicRegressor":
        """Set estimator parameters, raising on unknown names (the sklearn
        contract GridSearchCV/clone rely on — silent absorption would hide
        typos until fit, or forever)."""
        valid = _valid_option_keys()
        unknown = [
            k for k in params if k != "niterations" and k not in valid
        ]
        if unknown:
            raise ValueError(
                f"Invalid parameter(s) {sorted(unknown)} for "
                "SymbolicRegressor; valid parameters are 'niterations' "
                "plus make_options kwargs"
            )
        self.niterations = params.pop("niterations", self.niterations)
        self.options.update(params)
        return self

    # -- fitting -------------------------------------------------------
    def fit(
        self,
        X,
        y,
        *,
        weights=None,
        variable_names: Optional[Sequence[str]] = None,
    ) -> "SymbolicRegressor":
        X = np.asarray(X)
        if X.ndim != 2:
            raise ValueError("X must be (n_samples, n_features)")
        y = np.asarray(y)
        yt = y.T if y.ndim == 2 else y
        self.result_ = equation_search(
            X.T,
            yt,
            weights=weights,
            variable_names=variable_names,
            niterations=self.niterations,
            **self.options,
        )
        self.n_features_in_ = X.shape[1]
        return self

    def _fitted(self) -> EquationSearchResult:
        if self.result_ is None:
            raise RuntimeError("SymbolicRegressor is not fitted; call fit()")
        return self.result_

    # -- inference -----------------------------------------------------
    @property
    def equations_(self):
        return self._fitted().candidates

    @property
    def best_equation_(self) -> str:
        return self._fitted().best().equation

    def predict(self, X, output: int = 0, complexity: Optional[int] = None):
        result = self._fitted()
        X = np.asarray(X)
        if X.ndim != 2 or X.shape[1] != self.n_features_in_:
            raise ValueError(f"X must be (n_samples, {self.n_features_in_})")
        return result.predict(X.T, output=output, complexity=complexity)

    def score(self, X, y, output: int = 0) -> float:
        """R^2 of the best equation (sklearn regressor convention). For
        multi-output fits pass the full (n_samples, n_outputs) y and pick
        the column with `output`."""
        y = np.asarray(y)
        if y.ndim == 2:
            y = y[:, output]
        y = y.ravel()
        y_pred = np.asarray(self.predict(X, output=output)).ravel()
        if y.shape != y_pred.shape:
            raise ValueError(
                f"y has {y.shape[0]} samples, predictions have "
                f"{y_pred.shape[0]}"
            )
        ss_res = float(np.sum((y - y_pred) ** 2))
        ss_tot = float(np.sum((y - np.mean(y)) ** 2))
        if ss_tot == 0.0:
            # constant target: sklearn's r2_score convention
            return 1.0 if ss_res == 0.0 else 0.0
        return 1.0 - ss_res / ss_tot

    def sympy(self, output: int = 0, complexity: Optional[int] = None):
        return self._fitted().sympy(output=output, complexity=complexity)

    def latex(self, output: int = 0, complexity: Optional[int] = None) -> str:
        return self._fitted().latex(output=output, complexity=complexity)

    def __repr__(self) -> str:
        if self.result_ is None:
            opts = ", ".join(f"{k}={v!r}" for k, v in self.get_params().items())
            return f"SymbolicRegressor({opts})"
        return repr(self.result_)
