"""Expression complexity (reference src/Complexity.jl:13-40).

Default complexity = node count (`count_nodes`); with custom mappings it is a
weighted sum over nodes — on the flat encoding this is a masked gather+sum,
fully jittable (SURVEY.md §7 decision 1).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .options import Options
from .trees import BIN, CONST, UNA, VAR, TreeBatch

Array = jax.Array


def compute_complexity(trees: TreeBatch, options: Options) -> Array:
    """Complexity per tree; shape = batch shape of `trees`."""
    use, bin_c, una_c, var_c, const_c = options.complexity_arrays()
    idx = jnp.arange(trees.max_len, dtype=jnp.int32)
    valid = idx < trees.length[..., None]
    if not use:
        return trees.length
    bin_t = jnp.asarray(bin_c) if len(bin_c) else jnp.ones(1, jnp.int32)
    una_t = jnp.asarray(una_c) if len(una_c) else jnp.ones(1, jnp.int32)
    per_node = jnp.where(
        trees.kind == CONST,
        const_c,
        jnp.where(
            trees.kind == VAR,
            var_c,
            jnp.where(
                trees.kind == UNA,
                una_t[jnp.clip(trees.op, 0, una_t.shape[0] - 1)],
                bin_t[jnp.clip(trees.op, 0, bin_t.shape[0] - 1)],
            ),
        ),
    )
    return jnp.sum(jnp.where(valid, per_node, 0), axis=-1).astype(jnp.int32)
