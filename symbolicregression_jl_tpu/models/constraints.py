"""Constraint checking on flat postfix trees
(reference src/CheckConstraints.jl:9-170).

All checks are pure integer array ops, jittable and vmappable:
* global size cap (complexity <= curmaxsize) and depth cap;
* per-operator subtree-size caps (`constraints=...`, reference
  flag_bin/una_operator_complexity :9-65): for each flagged operator, every
  occurrence's child subtree sizes must be within the cap;
* nested-operator caps (`nested_constraints=...`, reference
  flag_illegal_nests / count_max_nestedness :68-139): for each (outer op ->
  inner op, max) rule, the count of inner ops strictly inside any outer-op
  subtree must be <= max. Subtree occurrence counts come from prefix sums
  over the postfix span.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .complexity import compute_complexity
from .options import Options
from .trees import BIN, UNA, TreeBatch, subtree_sizes, tree_depth

Array = jax.Array


def _op_occurrence_mask(tree: TreeBatch, kind: int, op_idx: int) -> Array:
    live = jnp.arange(tree.max_len, dtype=jnp.int32) < tree.length
    return (tree.kind == kind) & (tree.op == op_idx) & live


def check_constraints_single(
    tree: TreeBatch, options: Options, curmaxsize: Array
) -> Array:
    """Single tree (fields (L,)) -> bool. vmap for batches.

    Reference entry point: check_constraints(tree, options, maxsize)
    (src/CheckConstraints.jl:142-170)."""
    ops = options.operators
    ok = compute_complexity(tree, options) <= curmaxsize
    ok &= tree_depth(tree.kind, tree.length) <= options.maxdepth
    ok &= tree.length >= 1

    sizes = None
    need_sizes = bool(options.constraints) or bool(options.nested_constraints)
    if need_sizes:
        sizes = subtree_sizes(tree.kind, tree.length)

    # per-operator subtree-size caps
    for name, caps in options.constraints:
        from ..ops.operators import canonical_name

        cname = canonical_name(name)
        if cname in ops.binary_names:
            op_idx = ops.binary_names.index(cname)
            if isinstance(caps, int):
                caps = (caps, caps)
            l_cap, r_cap = caps
            mask = _op_occurrence_mask(tree, BIN, op_idx)
            idx = jnp.arange(tree.max_len, dtype=jnp.int32)
            r_size = sizes[jnp.maximum(idx - 1, 0)]
            l_root = idx - 1 - r_size
            l_size = sizes[jnp.clip(l_root, 0, tree.max_len - 1)]
            viol = jnp.zeros_like(mask)
            if l_cap is not None and l_cap >= 0:
                viol |= mask & (l_size > l_cap)
            if r_cap is not None and r_cap >= 0:
                viol |= mask & (r_size > r_cap)
            ok &= ~jnp.any(viol)
        elif cname in ops.unary_names:
            op_idx = ops.unary_names.index(cname)
            cap = caps if isinstance(caps, int) else caps[0]
            if cap is not None and cap >= 0:
                mask = _op_occurrence_mask(tree, UNA, op_idx)
                idx = jnp.arange(tree.max_len, dtype=jnp.int32)
                c_size = sizes[jnp.maximum(idx - 1, 0)]
                ok &= ~jnp.any(mask & (c_size > cap))

    # nested-operator caps
    for outer_name, inner_rules in options.nested_constraints:
        from ..ops.operators import canonical_name

        o_name = canonical_name(outer_name)
        if o_name in ops.binary_names:
            o_kind, o_idx = BIN, ops.binary_names.index(o_name)
        elif o_name in ops.unary_names:
            o_kind, o_idx = UNA, ops.unary_names.index(o_name)
        else:
            continue
        outer_mask = _op_occurrence_mask(tree, o_kind, o_idx)
        idx = jnp.arange(tree.max_len, dtype=jnp.int32)
        span_start = idx - sizes + 1
        for inner_name, max_count in inner_rules:
            i_name = canonical_name(inner_name)
            if i_name in ops.binary_names:
                i_kind, i_idx = BIN, ops.binary_names.index(i_name)
            elif i_name in ops.unary_names:
                i_kind, i_idx = UNA, ops.unary_names.index(i_name)
            else:
                continue
            inner_occ = _op_occurrence_mask(tree, i_kind, i_idx).astype(jnp.int32)
            prefix = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(inner_occ)])
            # strict inside: occurrences in [span_start, idx) (excl. root)
            count = prefix[idx] - prefix[jnp.clip(span_start, 0, tree.max_len)]
            ok &= ~jnp.any(outer_mask & (count > max_count))

    return ok


def check_constraints(
    trees: TreeBatch, options: Options, curmaxsize: Array
) -> Array:
    """Batched over leading dims."""
    batch_shape = trees.length.shape
    if batch_shape == ():
        return check_constraints_single(trees, options, curmaxsize)
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    out = jax.vmap(lambda t: check_constraints_single(t, options, curmaxsize))(flat)
    return out.reshape(batch_shape)
