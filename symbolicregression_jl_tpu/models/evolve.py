"""The evolution engine: batched regularized evolution + simulated annealing.

Analogs: next_generation / crossover_generation (reference src/Mutate.jl:25-341),
reg_evol_cycle (src/RegularizedEvolution.jl:14-159), s_r_cycle +
optimize_and_simplify_population (src/SingleIteration.jl:17-127).

TPU-first redesign (SURVEY.md §7 decision 3): instead of the reference's one
sequential steady-state step at a time, each cycle runs
B = options.n_parallel_tournaments tournaments *in parallel*, mutates/crosses
the B winners in parallel (vmapped device tree surgery), scores them in one
batched interpreter call, and replaces the B oldest members. The whole
s_r_cycle is a single `lax.scan` — one XLA computation per island iteration,
vmappable over islands and shardable over the mesh.

Algorithmic knobs preserved: tournament geometric rank sampling,
annealing acceptance exp(-Δscore/(alpha·T)) (src/Mutate.jl:226-245),
adaptive-parsimony frequency ratio acceptance, per-mutation weight
adjustment (src/Mutate.jl:51-62), ≤10 constraint retries (src/Mutate.jl:75-177),
replace-oldest aging, temperature schedule LinRange(1,0)
(src/SingleIteration.jl:27-32).

The `optimize` mutation (weight 0.0 by default in the reference) is handled
at population level by constant_opt.py rather than inside the mutation
switch; in the switch it falls through to do_nothing.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .complexity import compute_complexity
from .constant_opt import optimize_constants_islands
from .constraints import check_constraints_single
from .fitness import sample_batch_idx, score_trees, score_trees_cached
from .mutate_device import (
    append_random_op,
    combine_operators,
    crossover_trees,
    delete_random_op,
    gen_random_tree_fixed_size,
    insert_random_op,
    mutate_constant,
    mutate_operator,
    simplify_tree,
)
from .options import (
    ADD_NODE,
    DELETE_NODE,
    DO_NOTHING,
    INSERT_NODE,
    MUTATE_CONSTANT,
    MUTATE_OPERATOR,
    N_MUTATIONS,
    OPTIMIZE,
    RANDOMIZE,
    SIMPLIFY,
    Options,
)
from .parsimony import (
    RunningSearchStatistics,
    move_window,
    normalize,
    update_frequencies,
)
from .population import (
    HallOfFame,
    Population,
    init_hall_of_fame,
    tournament_winner,
    update_hall_of_fame,
)
from .trees import TreeBatch, count_constants, tree_depth

Array = jax.Array


# rows of IslandState.mut_counts: the 9 mutation kinds in MutationWeights
# order, plus crossover (the reference recorder logs per-event
# mutate/crossover accept/reject — here the batched engine keeps aggregate
# counters instead, src/Mutate.jl passim / src/RegularizedEvolution.jl:103-132)
MUTATION_NAMES = (
    "mutate_constant",
    "mutate_operator",
    "add_node",
    "insert_node",
    "delete_node",
    "simplify",
    "randomize",
    "do_nothing",
    "optimize",
    "crossover",
)


def mutation_counts_table(mut_counts) -> dict:
    """``IslandState.mut_counts`` (optionally with leading island/batch
    axes, which are summed away) as ``{kind_name: {"proposed", "accepted",
    "accept_rate"}}`` — the host-side view the telemetry ``metrics`` event
    and the run doctor publish. Counters are cumulative over the run
    (per-iteration rates come from differencing two snapshots).
    ``accept_rate`` is None until the kind has been proposed at least
    once."""
    import numpy as np

    counts = np.asarray(mut_counts, np.int64)
    counts = counts.reshape((-1,) + counts.shape[-2:]).sum(axis=0)
    out = {}
    for i, name in enumerate(MUTATION_NAMES):
        proposed, accepted = int(counts[i, 0]), int(counts[i, 1])
        out[name] = {
            "proposed": proposed,
            "accepted": accepted,
            "accept_rate": accepted / proposed if proposed else None,
        }
    return out


class IslandState(NamedTuple):
    """Everything one island owns. vmap/shard_map over a leading axis of
    these gives multi-island search."""

    pop: Population
    stats: RunningSearchStatistics
    hof: HallOfFame  # island-local best-seen (best_examples_seen analog)
    key: Array
    birth_counter: Array  # int32 scalar
    num_evals: Array  # float32 scalar
    mut_counts: Array  # (len(MUTATION_NAMES), 2) int32: proposed / accepted
    # evaluation memo-bank telemetry (options.cache_fitness; stays zero
    # otherwise): cumulative [trees scored, unique programs evaluated,
    # device-memo hits] — fused multi-island scoring spreads its global
    # counts evenly over islands (remainder on island 0), so per-island
    # values are bookkeeping shares and the cross-island SUM is exact
    cache_counts: Array  # (3,) int32


# ---------------------------------------------------------------------------
# Mutation of one member (vmapped over the B winners)
# ---------------------------------------------------------------------------


def _adjusted_mutation_logits(
    tree: TreeBatch, curmaxsize: Array, options: Options
) -> Array:
    """Per-member mutation weights with the reference's adjustments
    (src/Mutate.jl:51-62): mutate_constant scaled by min(8, #constants)/8
    (more constants -> proportionally likelier, saturating at 8; zero
    constants -> impossible); at the size OR depth cap -> no add/insert."""
    w = jnp.asarray(options.mutation_weights.as_tuple(), jnp.float32)
    idx = jnp.arange(tree.max_len, dtype=jnp.int32)
    n_const = count_constants(tree)
    n_ops = jnp.sum((tree.kind >= 3) & (idx < tree.length))
    complexity = compute_complexity(tree, options)
    depth = tree_depth(tree.kind, tree.length)
    at_cap = (complexity >= curmaxsize) | (depth >= options.maxdepth)
    sel = jnp.arange(N_MUTATIONS, dtype=jnp.int32)
    const_scale = jnp.minimum(n_const, 8).astype(jnp.float32) / 8.0
    w = jnp.where(sel == MUTATE_CONSTANT, w * const_scale, w)
    w = jnp.where((sel == MUTATE_OPERATOR) & (n_ops == 0), 0.0, w)
    w = jnp.where((sel == ADD_NODE) & at_cap, 0.0, w)
    w = jnp.where((sel == INSERT_NODE) & at_cap, 0.0, w)
    return jnp.where(w > 0, jnp.log(jnp.maximum(w, 1e-30)), -jnp.inf)


def _apply_mutation(
    key: Array,
    kind: Array,
    tree: TreeBatch,
    temperature: Array,
    curmaxsize: Array,
    nfeatures: int,
    options: Options,
) -> Tuple[TreeBatch, Array]:
    """One attempt of the sampled mutation kind. Returns (tree', ok) where
    ok includes the constraint check (reference retry body,
    src/Mutate.jl:75-177)."""
    ops = options.operators
    k1, k2 = jax.random.split(key)

    def b_mutate_constant(k):
        return mutate_constant(
            k, tree, temperature, options.perturbation_factor,
            options.probability_negate_constant,
        )

    def b_mutate_operator(k):
        return mutate_operator(k, tree, ops)

    def b_add_node(k):
        return append_random_op(k, tree, nfeatures, ops)

    def b_insert_node(k):
        ka, kb = jax.random.split(k)
        do_prepend = jax.random.bernoulli(ka)
        t_i, ok_i = insert_random_op(kb, tree, nfeatures, ops, at_root=False)
        t_p, ok_p = insert_random_op(kb, tree, nfeatures, ops, at_root=True)
        t = jax.tree_util.tree_map(
            lambda a, b: jnp.where(do_prepend, b, a), t_i, t_p
        )
        return t, jnp.where(do_prepend, ok_p, ok_i)

    def b_delete_node(k):
        return delete_random_op(k, tree, nfeatures, ops)

    def b_simplify(k):
        # constant folding only — the full operator-combining pass runs once
        # per iteration in simplify_population_islands; inlining its
        # while_loop here (inside vmap x retry-loop x cycle-scan) explodes
        # compile time for no search benefit
        t, _ = simplify_tree(tree, ops)
        return t, jnp.bool_(True)

    def b_randomize(k):
        ka, kb = jax.random.split(k)
        # size ~ U{1..curmaxsize} (reference src/Mutate.jl randomize path)
        hi = jnp.minimum(jnp.maximum(curmaxsize, 1), tree.max_len) + 1
        size = jax.random.randint(ka, (), 1, hi, dtype=jnp.int32)
        t = gen_random_tree_fixed_size(
            kb, size, nfeatures, ops, tree.max_len, tree.cval.dtype
        )
        return t, jnp.bool_(True)

    def b_nothing(k):
        return tree, jnp.bool_(True)

    branches = [
        b_mutate_constant,
        b_mutate_operator,
        b_add_node,
        b_insert_node,
        b_delete_node,
        b_simplify,
        b_randomize,
        b_nothing,
        b_nothing,  # OPTIMIZE handled at population level
    ]
    new_tree, ok = jax.lax.switch(kind, branches, k1)
    ok &= check_constraints_single(new_tree, options, curmaxsize)
    return new_tree, ok


_N_RETRIES = 10  # reference src/Mutate.jl:75


def _mutate_member(
    key: Array,
    tree: TreeBatch,
    score: Array,
    temperature: Array,
    frequencies: Array,
    curmaxsize: Array,
    nfeatures: int,
    options: Options,
) -> Tuple[TreeBatch, Array, Array, Array]:
    """Sample a mutation kind and apply it with <=10 constraint retries.
    Returns (tree', was_mutated, always_accept, kind); acceptance happens
    later (needs score), except always_accept (successful simplify) which
    skips the annealing gate.

    The retries run as ONE vmapped batch and the first success is taken —
    identical distribution to the reference's sequential retry loop
    (src/Mutate.jl:75-177; each attempt is i.i.d.) and identical total
    compute to a lax.fori_loop (which cannot exit early), but a 10x
    shorter sequential critical path per cycle."""
    k_kind, k_apply = jax.random.split(key)
    logits = _adjusted_mutation_logits(tree, curmaxsize, options)
    kind = jax.random.categorical(k_kind, logits)

    cands, oks = jax.vmap(
        lambda k: _apply_mutation(
            k, kind, tree, temperature, curmaxsize, nfeatures, options
        )
    )(jax.random.split(k_apply, _N_RETRIES))
    first = jnp.argmax(oks)  # index of the first successful attempt
    success = jnp.any(oks)
    # on total failure keep the parent (skip_mutation_failures=true behavior,
    # reference src/Mutate.jl:179-205)
    result = jax.tree_util.tree_map(
        lambda c, t: jnp.where(success, c[first], t), cands, tree
    )
    was_mutated = success & (kind != DO_NOTHING) & (kind != OPTIMIZE)
    always_accept = (kind == SIMPLIFY) & success
    return result, was_mutated, always_accept, kind


def _accept_mutation(
    key: Array,
    old_tree: TreeBatch,
    new_tree: TreeBatch,
    old_score: Array,
    new_score: Array,
    temperature: Array,
    frequencies: Array,
    options: Options,
) -> Array:
    """Annealing x adaptive-parsimony acceptance
    (reference src/Mutate.jl:207-245). Returns bool accept."""
    prob = jnp.float32(1.0)
    if options.annealing:
        delta = new_score - old_score
        prob = prob * jnp.exp(
            -delta / (options.alpha * jnp.maximum(temperature, 1e-6))
        )
    if options.use_frequency:
        # reference src/Mutate.jl:231-245: NORMALIZED frequency when
        # 0 < size <= maxsize, the constant 1e-6 otherwise (the
        # normalization matters exactly because the out-of-range
        # constant is in normalized units; in-range values carry a tiny
        # floor only to keep the ratio NaN-free when a bin decays to 0)
        S = frequencies.shape[0]
        norm = normalize(frequencies)

        def f_at(c):
            raw = norm[jnp.clip(c - 1, 0, S - 1)]
            in_range = (c > 0) & (c <= options.maxsize)
            return jnp.where(in_range, jnp.maximum(raw, 1e-30), 1e-6)

        f_old = f_at(compute_complexity(old_tree, options))
        f_new = f_at(compute_complexity(new_tree, options))
        prob = prob * f_old / f_new
    accept = jax.random.uniform(key) < prob
    accept &= jnp.isfinite(new_score)
    return accept


def _crossover_pair(
    key: Array,
    a: TreeBatch,
    b: TreeBatch,
    curmaxsize: Array,
    options: Options,
) -> Tuple[TreeBatch, TreeBatch, Array]:
    """Crossover with <=10 constraint retries
    (reference crossover_generation src/Mutate.jl:285-341). Retries run as
    one vmapped batch, first success taken (see _mutate_member)."""

    def attempt(k):
        ca, cb, ok = crossover_trees(k, a, b)
        ok &= check_constraints_single(ca, options, curmaxsize)
        ok &= check_constraints_single(cb, options, curmaxsize)
        return ca, cb, ok

    cas, cbs, oks = jax.vmap(attempt)(jax.random.split(key, _N_RETRIES))
    first = jnp.argmax(oks)
    success = jnp.any(oks)
    ra = jax.tree_util.tree_map(
        lambda c, t: jnp.where(success, c[first], t), cas, a
    )
    rb = jax.tree_util.tree_map(
        lambda c, t: jnp.where(success, c[first], t), cbs, b
    )
    return ra, rb, success


# ---------------------------------------------------------------------------
# One batched steady-state cycle, split into propose -> score -> integrate
# so multi-island callers can fuse ALL islands' scoring into ONE interpreter
# call (the Pallas kernel needs large flat batches to pay off).
# ---------------------------------------------------------------------------


class MutationEvents(NamedTuple):
    """Per-cycle device-side event record for the full-lineage recorder
    (the batched analog of the reference's per-event mutation log,
    src/Mutate.jl:207-281 accept/reject + src/RegularizedEvolution.jl:103-132).
    Host-side draining computes refs (tree_hash) and strings. Reason codes:
    0=accepted, 1=rejected (annealing/frequency gate), 2=constraint-failed
    (no valid mutation found, parent kept), 3=no-op slot (do_nothing /
    optimize placeholder)."""

    parent: TreeBatch  # (B, ...)
    child: TreeBatch  # (B, ...) the proposed child (pre-acceptance)
    kind: Array  # (B,) mutation kind; crossover = len(MUTATION_NAMES)-1
    accepted: Array  # (B,) bool
    reason: Array  # (B,) int32
    score: Array  # (B,) child score
    loss: Array  # (B,) child loss
    dead: TreeBatch  # (B, ...) the replaced-oldest members (death events)
    dead_loss: Array  # (B,)


REASON_NAMES = ("accept", "reject", "constraint_failed", "noop")


class _Proposed(NamedTuple):
    """Per-island child proposals awaiting scoring."""

    children: TreeBatch  # (B, ...)
    parents: TreeBatch  # (B, ...)
    parent_idx: Array  # (B,)
    parent_scores: Array  # (B,)
    was_mutated: Array  # (B,) bool
    always_accept: Array  # (B,) bool
    use_cross: Array  # (B,) bool
    kind: Array  # (B,) sampled mutation kind (ignored on crossover slots)
    accept_keys: Array  # (B, 2) PRNG keys
    next_key: Array


def _propose_children(
    state: IslandState,
    temperature: Array,
    curmaxsize: Array,
    nfeatures: int,
    options: Options,
) -> _Proposed:
    """Tournaments + mutation/crossover for one island
    (the pre-scoring half of reference src/RegularizedEvolution.jl:14-159)."""
    B = options.n_parallel_tournaments
    B += B % 2  # paired slots for crossover
    pop, stats = state.pop, state.stats

    key, k_tour, k_mut, k_acc, k_cross, k_coin = jax.random.split(state.key, 6)

    # tournaments
    tkeys = jax.random.split(k_tour, B)
    parent_idx = jax.vmap(
        lambda k: tournament_winner(k, pop, stats.frequencies, options)
    )(tkeys)
    parents = pop.trees[parent_idx]
    parent_scores = pop.scores[parent_idx]

    # mutation path
    mkeys = jax.random.split(k_mut, B)
    mut_trees, was_mutated, always_accept, kinds = jax.vmap(
        lambda k, t, s: _mutate_member(
            k, t, s, temperature, stats.frequencies, curmaxsize, nfeatures,
            options,
        )
    )(mkeys, parents, parent_scores)

    # crossover path on slot pairs (2j, 2j+1)
    ckeys = jax.random.split(k_cross, B // 2)
    pa = jax.tree_util.tree_map(lambda x: x[0::2], parents)
    pb = jax.tree_util.tree_map(lambda x: x[1::2], parents)
    ca, cb, cross_ok = jax.vmap(
        lambda k, a, b: _crossover_pair(k, a, b, curmaxsize, options)
    )(ckeys, pa, pb)
    cross_trees = jax.tree_util.tree_map(
        lambda a, b: jnp.stack([a, b], axis=1).reshape((B,) + a.shape[1:]),
        ca,
        cb,
    )

    use_cross_pair = (
        jax.random.bernoulli(k_coin, options.crossover_probability, (B // 2,))
        & cross_ok
    )
    use_cross = jnp.repeat(use_cross_pair, 2)

    children = jax.tree_util.tree_map(
        lambda c, m: jnp.where(
            jnp.reshape(use_cross, use_cross.shape + (1,) * (c.ndim - 1)), c, m
        ),
        cross_trees,
        mut_trees,
    )
    return _Proposed(
        children=children,
        parents=parents,
        parent_idx=parent_idx,
        parent_scores=parent_scores,
        was_mutated=was_mutated,
        always_accept=always_accept,
        use_cross=use_cross,
        kind=kinds,
        accept_keys=jax.random.split(k_acc, B),
        next_key=key,
    )


def _integrate_children(
    state: IslandState,
    prop: _Proposed,
    child_scores: Array,
    child_losses: Array,
    temperature: Array,
    n_rows: int,
    options: Options,
    collect_events: bool = False,
):
    """Acceptance + replace-oldest + statistics for one island
    (the post-scoring half of reference src/RegularizedEvolution.jl)."""
    pop, stats = state.pop, state.stats
    B = child_scores.shape[0]

    # acceptance (mutation slots only; crossover children always enter,
    # reference src/Mutate.jl:285-341 has no annealing gate for crossover)
    accept = jax.vmap(
        lambda k, ot, nt, os, ns: _accept_mutation(
            k, ot, nt, os, ns, temperature, stats.frequencies, options
        )
    )(prop.accept_keys, prop.parents, prop.children, prop.parent_scores,
      child_scores)
    # simplify is value-preserving: always accepted (reference early return,
    # src/Mutate.jl:107-140)
    accept = accept | prop.use_cross | (prop.always_accept & ~prop.use_cross)
    # slots whose child == parent (do_nothing / failed mutation) keep parent
    accept = jnp.where(prop.was_mutated | prop.use_cross, accept, False)

    final_trees = jax.tree_util.tree_map(
        lambda c, p: jnp.where(
            jnp.reshape(accept, accept.shape + (1,) * (c.ndim - 1)), c, p
        ),
        prop.children,
        prop.parents,
    )
    final_scores = jnp.where(accept, child_scores, prop.parent_scores)
    final_losses = jnp.where(accept, child_losses, pop.losses[prop.parent_idx])

    # replace the B oldest members (reference replace-oldest-by-birth,
    # src/RegularizedEvolution.jl:101,134)
    oldest = jnp.argsort(pop.birth)[:B]
    new_pop_trees = jax.tree_util.tree_map(
        lambda all_t, ch: all_t.at[oldest].set(ch), pop.trees, final_trees
    )
    new_birth = pop.birth.at[oldest].set(
        state.birth_counter + jnp.arange(B, dtype=jnp.int32)
    )
    new_pop = Population(
        trees=new_pop_trees,
        scores=pop.scores.at[oldest].set(final_scores),
        losses=pop.losses.at[oldest].set(final_losses),
        birth=new_birth,
    )

    # adaptive parsimony statistics fed by the new members
    # (reference src/RegularizedEvolution.jl:103-132)
    child_complexity = compute_complexity(final_trees, options)
    new_stats = update_frequencies(stats, child_complexity)

    # island-local hall of fame (best_examples_seen,
    # reference src/SingleIteration.jl:47-57)
    new_hof = update_hall_of_fame(
        state.hof, final_trees, final_scores, final_losses, options
    )

    eval_fraction = (
        options.batch_size / n_rows if options.batching else 1.0
    )

    # aggregate mutation telemetry: proposed/accepted per kind + crossover
    # (batched analog of the reference recorder's per-event mutation log)
    n_kinds = len(MUTATION_NAMES)
    cross_row = n_kinds - 1
    row = jnp.where(prop.use_cross, cross_row, prop.kind)
    ones = jnp.ones_like(row)
    # do_nothing slots keep the parent BY DESIGN — the reference logs them
    # as accepted (src/Mutate.jl early returns), so the counter does too.
    # OPTIMIZE slots are placeholders here (the actual optimization is the
    # iteration-level optimize_mutation pass, which records attempted/
    # improved in the OPTIMIZE row itself — optimize_island_constants), so
    # they are excluded from the counters entirely: accepted <= proposed
    # stays deterministic.
    noop = ~prop.use_cross & (prop.kind == DO_NOTHING)
    is_opt_slot = ~prop.use_cross & (prop.kind == OPTIMIZE)
    proposed = jnp.zeros((n_kinds,), jnp.int32).at[row].add(
        jnp.where(is_opt_slot, 0, 1)
    )
    accepted = jnp.zeros((n_kinds,), jnp.int32).at[row].add(
        jnp.where((accept | noop) & ~is_opt_slot, 1, 0)
    )
    new_counts = state.mut_counts + jnp.stack([proposed, accepted], axis=-1)

    new_state = IslandState(
        pop=new_pop,
        stats=new_stats,
        hof=new_hof,
        key=prop.next_key,
        birth_counter=state.birth_counter + B,
        num_evals=state.num_evals + B * eval_fraction,
        mut_counts=new_counts,
        cache_counts=state.cache_counts,
    )
    if not collect_events:
        return new_state
    mutated_or_cross = prop.was_mutated | prop.use_cross | prop.always_accept
    # no-op for event purposes includes the OPTIMIZE placeholder slots
    reason = jnp.where(
        accept,
        0,
        jnp.where(
            mutated_or_cross,
            1,
            jnp.where(noop | is_opt_slot, 3, 2),
        ),
    ).astype(jnp.int32)
    events = MutationEvents(
        parent=prop.parents,
        child=prop.children,
        kind=row.astype(jnp.int32),
        accepted=accept,
        reason=reason,
        score=child_scores,
        loss=child_losses,
        dead=jax.tree_util.tree_map(lambda x: x[oldest], pop.trees),
        dead_loss=pop.losses[oldest],
    )
    return new_state, events


def reg_evol_cycle(
    state: IslandState,
    temperature: Array,
    curmaxsize: Array,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    row_idx: Optional[Array] = None,
) -> IslandState:
    """B parallel tournaments -> mutate/crossover -> score -> accept ->
    replace B oldest (reference src/RegularizedEvolution.jl:14-159,
    batched). Single-island form; multi-island callers use
    reg_evol_cycle_islands for fused scoring."""
    nfeatures = X.shape[0]
    prop = _propose_children(state, temperature, curmaxsize, nfeatures,
                             options)
    child_scores, child_losses = score_trees(
        prop.children, X, y, weights, baseline, options, row_idx
    )
    return _integrate_children(
        state, prop, child_scores, child_losses, temperature, X.shape[1],
        options,
    )


# ---------------------------------------------------------------------------
# Multi-island fused cycle: all islands' children scored in ONE flat
# interpreter call. Tree surgery stays vmapped per island (cheap int ops);
# the expensive (trees x rows) evaluation gets the large flat batch the
# Pallas kernel needs. This is the TPU answer to the reference's
# one-task-per-island scheduling (SURVEY.md §2.3).
# ---------------------------------------------------------------------------


def _flatten2(tree_batch: TreeBatch) -> TreeBatch:
    return jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[2:]), tree_batch
    )


def _spread_stats(stats, I: int) -> Array:
    """DedupStats from one fused multi-island scoring call -> per-island
    (I, 3) int32 increments. Global counts are spread evenly with the
    remainder on island 0 so the cross-island sum stays exact."""
    vec = jnp.stack(
        [stats.total, stats.unique, stats.memo_hits]
    ).astype(jnp.int32)  # (3,)
    base = vec // I
    rem = vec - base * I
    return jnp.tile(base[None, :], (I, 1)).at[0].add(rem)


def _add_cache_counts(states: IslandState, add: Array) -> IslandState:
    return states._replace(cache_counts=states.cache_counts + add)


def reg_evol_cycle_islands(
    states: IslandState,  # leading (I,)
    temperature: Array,
    curmaxsize: Array,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    row_idx: Optional[Array] = None,
    collect_events: bool = False,
    memo=None,
):
    """row_idx: None (full data), (batch,) shared minibatch, or
    (I, batch) per-island independent minibatches (the reference's
    per-island score_func_batch draws, src/LossFunctions.jl:95-115).

    memo: optional cache.DeviceMemo consumed only with
    options.cache_fitness (and only on full-data scoring — the cached
    scorer drops it for minibatch rows). CAUTION: only pass a memo whose
    values were scored at THIS call's batch shape — with
    eval_backend='auto' the kernel choice is batch-size-dependent, and a
    value from another kernel can be ULP-different. The production
    driver (api.py) therefore feeds the bank only to the population
    rescore and leaves this memo None."""
    nfeatures = X.shape[0]
    I = states.birth_counter.shape[0]
    props = jax.vmap(
        lambda st: _propose_children(
            st, temperature, curmaxsize, nfeatures, options
        )
    )(states)
    B = props.parent_scores.shape[1]
    cache_add = None
    if row_idx is not None and row_idx.ndim == 2:
        # per-island draws: score each island's children against its own
        # minibatch (vmapped — forgoes the one fused flat call, so the
        # Pallas kernel does not engage on this path)
        if options.cache_fitness:
            s, l, stats = jax.vmap(
                lambda ch, ri: score_trees_cached(
                    ch, X, y, weights, baseline, options, ri
                )
            )(props.children, row_idx)
            cache_add = jnp.stack(
                [stats.total, stats.unique, stats.memo_hits], axis=-1
            ).astype(jnp.int32)  # (I, 3): per-island dedup within B
        else:
            s, l = jax.vmap(
                lambda ch, ri: score_trees(
                    ch, X, y, weights, baseline, options, ri
                )
            )(props.children, row_idx)
    else:
        flat_children = _flatten2(props.children)  # (I*B, ...)
        if options.cache_fitness:
            s, l, stats = score_trees_cached(
                flat_children, X, y, weights, baseline, options, row_idx,
                memo=memo,
            )
            cache_add = _spread_stats(stats, I)
        else:
            s, l = score_trees(
                flat_children, X, y, weights, baseline, options, row_idx
            )
        s, l = s.reshape(I, B), l.reshape(I, B)
    out = jax.vmap(
        lambda st, pr, cs, cl: _integrate_children(
            st, pr, cs, cl, temperature, X.shape[1], options,
            collect_events=collect_events,
        )
    )(states, props, s, l)
    if cache_add is None:
        return out
    if collect_events:
        new_states, events = out
        return _add_cache_counts(new_states, cache_add), events
    return _add_cache_counts(out, cache_add)


# ---------------------------------------------------------------------------
# s_r_cycle: the per-iteration hot loop as one lax.scan
# ---------------------------------------------------------------------------


def s_r_cycle_islands(
    states: IslandState,  # leading (I,)
    curmaxsize: Array,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    ncycles: Optional[int] = None,
    collect_events: bool = False,
    temperatures: Optional[Array] = None,
    apply_move_window: bool = True,
    memo=None,
):
    """ncycles fused evolution cycles over the annealing temperature
    schedule LinRange(1, 0) (reference src/SingleIteration.jl:17-61), all
    islands advancing together with one scoring call per cycle.

    With collect_events=True (recorder mode) additionally returns
    MutationEvents stacked (ncycles, I, B, ...) for host-side draining.

    Batching note: by default one minibatch per cycle is shared by all
    islands so the fused scoring call slices X once (each cycle still
    draws fresh rows). options.independent_island_batches=True matches
    the reference exactly — an independent draw per island per cycle
    (src/LossFunctions.jl:95-115) — at the cost of the fused flat
    scoring call (per-island vmapped scoring; no Pallas on that path).

    `temperatures` overrides the internally-built schedule and
    `apply_move_window=False` suppresses the end-of-iteration adaptive-
    parsimony window decay: both exist for the chunked-dispatch driver
    (api._make_iteration_driver), which splits one logical iteration's
    cycle scan across several shorter jit calls — each chunk receives its
    slice of the ONE iteration-wide LinRange(1,0) schedule, and only the
    last chunk applies the once-per-iteration stats decay
    (reference src/AdaptiveParsimony.jl move_window: once per cycle
    group, not per scan chunk)."""
    ncycles = ncycles or options.ncycles_per_iteration
    if temperatures is None:
        if options.annealing and ncycles > 1:
            temperatures = jnp.linspace(1.0, 0.0, ncycles, dtype=jnp.float32)
        else:
            temperatures = jnp.ones((ncycles,), jnp.float32)

    n_rows = X.shape[1]
    I = states.birth_counter.shape[0]

    def step(carry, inputs):
        sts, key = carry
        temperature = inputs
        if options.batching:
            kb, key = jax.random.split(key)
            if options.independent_island_batches:
                row_idx = jax.vmap(
                    lambda k: sample_batch_idx(
                        k, n_rows, options.batch_size
                    )
                )(jax.random.split(kb, I))
            else:
                row_idx = sample_batch_idx(kb, n_rows, options.batch_size)
        else:
            row_idx = None
        out = reg_evol_cycle_islands(
            sts, temperature, curmaxsize, X, y, weights, baseline, options,
            row_idx, collect_events=collect_events, memo=memo,
        )
        if collect_events:
            sts, events = out
        else:
            sts, events = out, None
        return (sts, key), events

    batch_key = jax.random.fold_in(states.key[0], 0x5F3759DF)
    (states, _), events = jax.lax.scan(step, (states, batch_key), temperatures)
    if apply_move_window:
        states = states._replace(stats=jax.vmap(move_window)(states.stats))
    if collect_events:
        return states, events
    return states


def s_r_cycle(
    state: IslandState,
    curmaxsize: Array,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    ncycles: Optional[int] = None,
) -> IslandState:
    """Single-island s_r_cycle (tests / simple drivers): the I=1 special
    case of s_r_cycle_islands."""
    states = jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], state)
    states = s_r_cycle_islands(
        states, curmaxsize, X, y, weights, baseline, options, ncycles
    )
    return jax.tree_util.tree_map(lambda x: x[0], states)


def simplify_population_islands(
    states: IslandState,  # leading (I,)
    curmaxsize: Array,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    memo=None,
) -> IslandState:
    """Simplify every member of every island then rescore on the full
    dataset in one fused call (the simplify + finalize_scores parts of
    optimize_and_simplify_population, reference src/SingleIteration.jl:63-127;
    constant optimization is applied separately by constant_opt.py).

    This full-data rescore is the cross-iteration memo bank's main
    customer (options.cache_fitness + memo): populations change by a few
    members per iteration, so most of the npop x I rescored programs were
    absorbed by the bank last iteration and skip evaluation."""
    I = states.birth_counter.shape[0]
    npop = states.pop.scores.shape[1]
    def _simp(t):
        t, _ = simplify_tree(t, options.operators)
        t, _ = combine_operators(t, options.operators)
        return t

    trees = jax.vmap(jax.vmap(_simp))(states.pop.trees)
    if options.cache_fitness:
        s, l, stats = score_trees_cached(
            _flatten2(trees), X, y, weights, baseline, options, memo=memo
        )
        states = _add_cache_counts(states, _spread_stats(stats, I))
    else:
        s, l = score_trees(
            _flatten2(trees), X, y, weights, baseline, options
        )
    scores, losses = s.reshape(I, npop), l.reshape(I, npop)
    new_pop = states.pop._replace(trees=trees, scores=scores, losses=losses)
    new_hofs = jax.vmap(
        lambda h, t, sc, lo: update_hall_of_fame(h, t, sc, lo, options)
    )(states.hof, trees, scores, losses)
    return states._replace(
        pop=new_pop,
        hof=new_hofs,
        num_evals=states.num_evals + npop,
    )


def simplify_population(
    state: IslandState,
    curmaxsize: Array,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
) -> IslandState:
    """Single-island form of simplify_population_islands."""
    states = jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], state)
    states = simplify_population_islands(
        states, curmaxsize, X, y, weights, baseline, options
    )
    return jax.tree_util.tree_map(lambda x: x[0], states)


def optimize_island_constants(
    key: Array,
    state: IslandState,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    probability: Optional[float] = None,
    count_optimize_telemetry: bool = False,
) -> IslandState:
    """Constant-optimize one island's population and fold the improved
    members into its hall of fame (the constant-opt leg of the reference's
    optimize_and_simplify_population, src/SingleIteration.jl:63-127).
    Single source for both the production iteration (api.py) and
    engine-level tests.

    With count_optimize_telemetry=True (the mutation_weights.optimize pass)
    the attempted/improved counts land in the OPTIMIZE row of mut_counts
    (the cycle switch's OPTIMIZE placeholder slots are excluded from the
    counters so accepted <= proposed holds deterministically).

    I=1 special case of optimize_islands_constants (same add/strip
    leading-axis shape as simplify_population over its islands form)."""
    states = jax.tree_util.tree_map(lambda x: x[None], state)
    states2 = optimize_islands_constants(
        key[None], states, X, y, weights, baseline, options, probability,
        count_optimize_telemetry,
    )
    return jax.tree_util.tree_map(lambda x: x[0], states2)


def optimize_islands_constants(
    keys: Array,
    states,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    probability: Optional[float] = None,
    count_optimize_telemetry: bool = False,
):
    """Multi-island sibling of optimize_island_constants — the production
    entry (api.py). Selection and write-back vmap per island, but the
    optimization itself goes through constant_opt.optimize_constants_islands
    so the fused-kernel BFGS can batch EVERY island's
    (restart x member) instances into one Pallas launch — a shape
    `jax.vmap(optimize_island_constants)` cannot express (pallas_call has
    no batching rule). The jnp fallback path is numerically identical to
    the vmapped form."""
    pops2, n_evals, n_attempted = optimize_constants_islands(
        keys, states.pop, X, y, weights, baseline, options, probability
    )

    def fold(state, pop2, n_ev, n_att):
        hof2 = update_hall_of_fame(
            state.hof, pop2.trees, pop2.scores, pop2.losses, options
        )
        counts = state.mut_counts
        if count_optimize_telemetry:
            n_improved = jnp.sum(
                pop2.losses < state.pop.losses
            ).astype(jnp.int32)
            counts = counts.at[OPTIMIZE, 0].add(n_att)
            counts = counts.at[OPTIMIZE, 1].add(n_improved)
        return state._replace(
            pop=pop2, hof=hof2, num_evals=state.num_evals + n_ev,
            mut_counts=counts,
        )

    return jax.vmap(fold)(states, pops2, n_evals, n_attempted)


def expected_optimize_count(options: Options) -> float:
    """Expected `optimize` mutation events per island per iteration.

    The reference runs constant optimization inline whenever the mutation
    switch samples :optimize (src/Mutate.jl:142-168). The batched engine
    instead sizes ONE iteration-level optimization pass to the same
    expected event count: cycles x mutation slots x P(kind == optimize).
    The kind probability uses the unadjusted weights (per-member weight
    adjustment only redistributes mass between the other kinds in edge
    cases), and crossover slots don't sample a kind."""
    w = options.mutation_weights.as_tuple()
    total = sum(w)
    if total <= 0 or w[OPTIMIZE] <= 0:
        return 0.0
    B = options.n_parallel_tournaments
    B += B % 2
    p_kind = w[OPTIMIZE] / total
    return (
        options.ncycles_per_iteration
        * B
        * (1.0 - options.crossover_probability)
        * p_kind
    )


def init_island_state(
    key: Array,
    options: Options,
    nfeatures: int,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    dtype=jnp.float32,
) -> IslandState:
    from .population import init_population

    k1, k2 = jax.random.split(key)
    pop = init_population(
        k1, options, nfeatures, X, y, weights, baseline, dtype=dtype
    )
    from .parsimony import init_search_statistics

    return IslandState(
        pop=pop,
        stats=init_search_statistics(options.actual_maxsize),
        hof=init_hall_of_fame(options, dtype),
        key=k2,
        birth_counter=jnp.int32(pop.npop),
        num_evals=jnp.float32(pop.npop),
        mut_counts=jnp.zeros((len(MUTATION_NAMES), 2), jnp.int32),
        cache_counts=jnp.zeros((3,), jnp.int32),
    )
