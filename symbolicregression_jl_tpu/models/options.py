"""Options / MutationWeights / ComplexityMapping — the single immutable config
object threaded through every call.

Analog of the reference's `Options{CT}` (src/OptionsStruct.jl:106-164) and its
~60-kwarg constructor (src/Options.jl:315-686). Knob names and defaults mirror
the reference (src/Options.jl:316-378: npop=33, npopulations=15,
ncycles_per_iteration=550, maxsize=20, parsimony=0.0032,
tournament_selection_n=12, tournament_selection_p=0.86, ...), plus TPU-native
knobs (mesh layout, eval backend, parallel tournament width) that replace the
reference's parallelism machinery.

Static (hashable) so an Options instance can close over jitted functions.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import numpy as np

from ..ops.losses import resolve_loss
from ..ops.operators import OperatorSet, make_operator_set

# Mutation kind indices (order matters: used by lax.switch in mutate_device)
MUTATE_CONSTANT = 0
MUTATE_OPERATOR = 1
ADD_NODE = 2
INSERT_NODE = 3
DELETE_NODE = 4
SIMPLIFY = 5
RANDOMIZE = 6
DO_NOTHING = 7
OPTIMIZE = 8
N_MUTATIONS = 9


@dataclasses.dataclass(frozen=True)
class MutationWeights:
    """Weighted mutation choice (reference src/OptionsStruct.jl:8-52).

    Defaults follow the reference's MutationWeights defaults."""

    mutate_constant: float = 0.048
    mutate_operator: float = 0.47
    add_node: float = 0.79
    insert_node: float = 5.1
    delete_node: float = 1.7
    simplify: float = 0.0020
    randomize: float = 0.00023
    do_nothing: float = 0.21
    optimize: float = 0.0

    def as_tuple(self) -> Tuple[float, ...]:
        return (
            self.mutate_constant,
            self.mutate_operator,
            self.add_node,
            self.insert_node,
            self.delete_node,
            self.simplify,
            self.randomize,
            self.do_nothing,
            self.optimize,
        )


@dataclasses.dataclass(frozen=True)
class ComplexityMapping:
    """Per-op/variable/constant complexity weights
    (reference src/OptionsStruct.jl:75-104). When `use` is False, complexity
    is simply the node count (`count_nodes`)."""

    use: bool = False
    binop_complexities: Tuple[int, ...] = ()
    unaop_complexities: Tuple[int, ...] = ()
    variable_complexity: int = 1
    constant_complexity: int = 1


# Deprecated camelCase kwargs accepted for parity with the reference's
# back-compat table (src/Options.jl:122-143,380-427).
_DEPRECATED_KWARGS = {
    "hofMigration": "hof_migration",
    "shouldOptimizeConstants": "should_optimize_constants",
    "perturbationFactor": "perturbation_factor",
    "batchSize": "batch_size",
    "crossoverProbability": "crossover_probability",
    "warmupMaxsizeBy": "warmup_maxsize_by",
    "useFrequency": "use_frequency",
    "useFrequencyInTournament": "use_frequency_in_tournament",
    "npop": "npop",
    "fractionReplaced": "fraction_replaced",
    "fractionReplacedHof": "fraction_replaced_hof",
    "ns": "tournament_selection_n",
    "probPickFirst": "tournament_selection_p",
    "earlyStopCondition": "early_stop_condition",
    "stateReturn": "return_state",
}


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


class TenantIsolationError(ValueError):
    """Options combination that cannot keep tenants isolated in a
    tenant-batched search (``Options.tenants > 1``, serving/batched.py).

    A tenant-batched search runs many independent jobs through ONE
    compiled program; any knob that funnels per-run host-side output
    into a single shared location (a snapshot file, a hall-of-fame CSV,
    the lineage recorder's one JSON document) would silently interleave
    tenants. The error is structured: ``.fields`` names the conflicting
    Options fields and ``.conflicts`` maps each to its reason, so a job
    server can report exactly which knobs to fix per rejected job."""

    def __init__(self, conflicts):
        self.conflicts = dict(conflicts)
        self.fields = tuple(self.conflicts)
        detail = "; ".join(
            f"{name}: {reason}" for name, reason in conflicts
        )
        super().__init__(
            f"tenants > 1 conflicts with field(s) "
            f"{', '.join(self.fields)} — {detail}"
        )


# Scalar knobs that shape the search but NOT the traced graph: they are
# excluded from Options._graph_key and enter jitted functions as traced
# arguments (Options.traced_scalars / bind_scalars), so sweeping them
# costs zero recompiles. Every field here must only ever be consumed as
# array math — never in Python control flow (audited use sites:
# fitness.loss_to_score, evolve mutate/anneal, population tournament,
# migration bernoulli draws).
TRACED_SCALAR_FIELDS = (
    "parsimony",
    "alpha",
    "perturbation_factor",
    "probability_negate_constant",
    "adaptive_parsimony_scaling",
    "tournament_selection_p",
    "fraction_replaced",
    "fraction_replaced_hof",
)

# --- the compile-identity contract (analysis/keys.py — srkey) ---------
# Every Options field is declared in EXACTLY ONE of GRAPH_FIELDS /
# TRACED_SCALAR_FIELDS / ORCHESTRATION_FIELDS; srkey fails the build on
# any unclassified or doubly-classified field, and differentially
# verifies each class against the traced programs:
#
#   GRAPH_FIELDS          compiled into the jitted search graph — part
#                         of _graph_key (and hash/eq), so perturbing one
#                         MUST change the key (new warm-compile bucket,
#                         new lru-cached factory closure).
#   TRACED_SCALAR_FIELDS  enter jit as traced f32 arguments
#                         (traced_scalars/bind_scalars) — absent from
#                         the key, absent from the traced graph.
#   ORCHESTRATION_FIELDS  host-side only — perturbing one must leave
#                         every traced program byte-identical AND the
#                         key unchanged (jit-reachable code must never
#                         read them: srlint SR010).
GRAPH_FIELDS = (
    "binary_operators",
    "unary_operators",
    "npopulations",
    "npop",
    "ncycles_per_iteration",
    "tournament_selection_n",
    "topn",
    "maxsize",
    "maxdepth",
    "max_len",
    "loss",
    "loss_function",
    "annealing",
    "use_frequency",
    "use_frequency_in_tournament",
    "mutation_weights",
    "crossover_probability",
    "migration",
    "hof_migration",
    "should_optimize_constants",
    "optimizer_algorithm",
    "optimizer_probability",
    "optimizer_nrestarts",
    "optimizer_iterations",
    "optimizer_backend",
    "batching",
    "batch_size",
    "independent_island_batches",
    "constraints",
    "nested_constraints",
    "complexity_of_operators",
    "complexity_of_constants",
    "complexity_of_variables",
    "recorder",
    "cache_fitness",
    "cache_device_slots",
    "n_parallel_tournaments",
    "eval_backend",
    "kernel_program",
    "kernel_leaf_skip",
    "eval_bucket_ladder",
    "eval_rows_per_tile",
    "max_cycles_per_dispatch",
    "row_shards",
    "precision",
    "tenants",
)

ORCHESTRATION_FIELDS = (
    "skip_mutation_failures",
    "fast_cycle",
    "warmup_maxsize_by",
    "early_stop_condition",
    "timeout_in_seconds",
    "max_evals",
    "seed",
    "deterministic",
    "verbosity",
    "progress",
    "output_file",
    "save_to_file",
    "terminal_width",
    "define_helper_functions",
    "recorder_file",
    "telemetry",
    "telemetry_dir",
    "telemetry_every",
    "telemetry_run_id",
    "telemetry_attempt",
    "profile_trace_dir",
    "snapshot_path",
    "snapshot_every_dispatches",
    "cache_capacity",
    "data_policy",
    "island_axis",
    "row_axis",
    "tenant_axis",
)


# --- process-lifetime identity tokens for callable config values ------
# `id()` is only unique among LIVE objects: after a callable is
# garbage-collected its id is reused, so two DISTINCT custom losses
# observed at different times could alias one warm-compile bucket or
# one memo-bank fingerprint (srlint SR011). The registry hands each
# callable a monotonically increasing token and keeps a STRONG
# reference, so the id that keys the lookup can never be reused within
# the process. Tokens are process-local, exactly like the ids they
# replace — never persist them.
_CALLABLE_TOKENS: Dict[int, int] = {}
_CALLABLE_REFS: list = []


def callable_token(fn: Callable) -> int:
    """Stable process-lifetime identity token for a callable config
    value (custom ``loss`` / ``loss_function``) — used by
    ``Options._graph_key`` and ``cache.memo.dataset_fingerprint``
    instead of ``id()``. The registered callable is pinned for the
    process lifetime (a handful of user losses, not a leak vector)."""
    tok = _CALLABLE_TOKENS.get(id(fn))
    if tok is None:
        tok = len(_CALLABLE_REFS)
        _CALLABLE_TOKENS[id(fn)] = tok
        # pin: if fn were collected, a new callable could reuse its id
        # and inherit its token
        _CALLABLE_REFS.append(fn)
    return tok


@dataclasses.dataclass(frozen=True, eq=False)
class Options:
    # --- operators ---
    binary_operators: Tuple[str, ...] = ("+", "-", "*", "/")
    unary_operators: Tuple[str, ...] = ()
    # --- population / search shape ---
    npopulations: int = 15
    npop: int = 33
    ncycles_per_iteration: int = 550
    tournament_selection_n: int = 12
    tournament_selection_p: float = 0.86
    topn: int = 12
    # --- size limits ---
    maxsize: int = 20
    maxdepth: Optional[int] = None
    # --- loss / scoring ---
    loss: Union[str, Callable] = "L2DistLoss"
    # Custom full-tree objective (reference `loss_function(tree, dataset,
    # options)`, src/LossFunctions.jl:60-67): a jax-traceable callable
    # (tree: TreeBatch, X, y, weights, options) -> scalar loss. Overrides
    # the elementwise `loss` path entirely.
    loss_function: Optional[Callable] = None
    parsimony: float = 0.0032
    alpha: float = 0.100000
    annealing: bool = False
    use_frequency: bool = True
    use_frequency_in_tournament: bool = True
    adaptive_parsimony_scaling: float = 20.0
    # --- mutation ---
    mutation_weights: MutationWeights = MutationWeights()
    crossover_probability: float = 0.066
    perturbation_factor: float = 0.076
    probability_negate_constant: float = 0.01
    skip_mutation_failures: bool = True
    # The reference's fast_cycle (src/Options.jl:247-249,
    # src/RegularizedEvolution.jl:32-79) threads tournament blocks within a
    # population. The TPU engine is ALWAYS batched that way (and further,
    # across islands), so this flag is accepted for compatibility and
    # ignored.
    fast_cycle: bool = False
    # --- migration ---
    migration: bool = True
    hof_migration: bool = True
    fraction_replaced: float = 0.00036
    fraction_replaced_hof: float = 0.035
    # --- constant optimization ---
    should_optimize_constants: bool = True
    optimizer_algorithm: str = "BFGS"
    optimizer_probability: float = 0.14
    optimizer_nrestarts: int = 2
    optimizer_iterations: int = 8
    # --- batching ---
    batching: bool = False
    batch_size: int = 50
    # True = an independent minibatch per island per cycle (the
    # reference's exact per-island score_func_batch semantics,
    # src/LossFunctions.jl:95-115) via per-island vmapped scoring; False
    # (default) = one fresh minibatch per cycle shared across islands so
    # scoring stays one fused flat call (the Pallas-kernel-sized batch).
    independent_island_batches: bool = False
    # --- constraints ---
    constraints: Tuple[Tuple[str, Any], ...] = ()
    nested_constraints: Tuple[Tuple[str, Tuple[Tuple[str, int], ...]], ...] = ()
    complexity_of_operators: Tuple[Tuple[str, int], ...] = ()
    complexity_of_constants: int = 1
    complexity_of_variables: int = 1
    # --- schedule / stopping ---
    warmup_maxsize_by: float = 0.0
    early_stop_condition: Optional[Union[float, Callable]] = None
    timeout_in_seconds: Optional[float] = None
    max_evals: Optional[int] = None
    # --- misc ---
    seed: int = 0
    deterministic: bool = True
    verbosity: int = 1
    progress: bool = True
    output_file: Optional[str] = None
    # Gate for the hall-of-fame CSV double-write (reference save_to_file,
    # src/Options.jl:285,353): False keeps output_file configured but
    # suppresses the writes.
    save_to_file: bool = True
    # Progress-bar width in characters (reference terminal_width,
    # src/Options.jl:359); None = the default width.
    terminal_width: Optional[int] = None
    # Reference define_helper_functions (src/Options.jl:312-376) `eval`s
    # operator helpers into Julia's Main for REPL tree-calling. Operators
    # here are plain Python callables already importable from
    # ops.operators, so the knob is accepted for drop-in migration and has
    # nothing to do.
    define_helper_functions: bool = True
    recorder: bool = False
    recorder_file: str = "pysr_recorder.json"
    # --- unified search telemetry (telemetry/ subsystem) ---
    # Opt-in per-stage span timers + metrics registry + JSONL event log
    # (docs/observability.md). Host-side orchestration only: no primitive
    # is added to any jitted search program and the hall of fame is
    # bit-identical with telemetry on or off. With telemetry enabled the
    # iteration dispatches through the phased driver (one phase program
    # per stage instead of one fused program) so each stage can be
    # fenced and timed — numerically identical, slightly more compile
    # and dispatch overhead. Orchestration-only knobs: absent from
    # _graph_key.
    telemetry: bool = False
    # Directory for the per-run events-<run>.jsonl file (created if
    # needed); None = current working directory.
    telemetry_dir: Optional[str] = None
    # Emit a metrics snapshot every k-th iteration (spans and lifecycle
    # events are always emitted); 1 = every iteration.
    telemetry_every: int = 1
    # Stable logical run id stamped into the run_start event (additive
    # `run_id` schema field) — the fleet layer's join key
    # (docs/observability.md "Fleet"). The resilience supervisor
    # threads ONE id through every attempt of a supervised run, so the
    # fleet index collapses the attempt trail into a single row instead
    # of inferring lineage from filenames. None (default) = the event
    # log's own id. Orchestration-only: absent from _graph_key.
    telemetry_run_id: Optional[str] = None
    # 1-based supervisor attempt index stamped into run_start (additive
    # `attempt` field). None = take SRTPU_RUN_ATTEMPT from the
    # environment (the TPU watcher exports it into retried steps),
    # defaulting to 1. Orchestration-only.
    telemetry_attempt: Optional[int] = None
    # Capture a jax.profiler (XLA/Perfetto) trace of the whole search
    # into this directory (view with `tensorboard --logdir DIR`). The
    # telemetry spans' `srtpu/<stage>` annotations appear on the traced
    # timeline, so the per-stage attribution and the op-level profile
    # line up (docs/observability.md "Profiling"). Orchestration-only:
    # absent from _graph_key, zero primitives added to any jitted
    # program, hall of fame bit-identical with tracing on or off.
    # Independent of `telemetry` (a trace can be captured without the
    # event log); single-controller, like every other capture knob.
    profile_trace_dir: Optional[str] = None
    # --- periodic search-state snapshots (resilience/ subsystem) ---
    # Serialize the compact per-output SearchState (populations, hall of
    # fame, host PRNG key) to this path every snapshot_every_dispatches
    # host-loop dispatches, crash-atomically through
    # utils.checkpoint.save_search_state (docs/resilience.md). Resume
    # via equation_search(saved_state=load_search_state(path)) — or the
    # resilience.supervisor retry loop — is a bit-identical continuation
    # of the interrupted run (same hall of fame, same key chain).
    # Orchestration-only knobs: host-side between dispatches, absent
    # from _graph_key, zero primitives added to any jitted program.
    snapshot_path: Optional[str] = None
    # Snapshot cadence in dispatches (one dispatch = one iteration of
    # one output through the production driver). A configured
    # snapshot_path always snapshots: leaving this 0 with a path set
    # normalizes to 1 (every dispatch) — a path that silently never
    # wrote would lose the whole run to the first preemption, the exact
    # failure the knob exists to prevent. Multi-output runs align
    # snapshots to round boundaries (after the last output's dispatch)
    # so every output's saved iteration counter agrees and the resume
    # math stays exact.
    snapshot_every_dispatches: int = 0
    # --- evaluation memo bank (cache/ subsystem) ---
    # Opt-in fitness caching, two tiers: intra-batch dedup of every fused
    # eval batch (duplicate programs evaluated once, losses scattered
    # back) + a cross-iteration host-side LRU keyed by tree content hash
    # x dataset fingerprint x loss config that pre-fills known full-data
    # fitnesses before dispatch. Guaranteed bit-identical trajectories vs.
    # the uncached path under a fixed seed (docs/memo_bank.md); hit-rate /
    # unique-ratio counters surface in the progress line, the recorder,
    # and EquationSearchResult.cache_stats. Elementwise-loss path only
    # (a custom loss_function bypasses the cache).
    cache_fitness: bool = False
    # Host LRU capacity (entries) of the cross-iteration memo bank.
    # Orchestration-only: not part of the compiled graph.
    cache_capacity: int = 65536
    # Device memo table slots shipped into each jitted iteration (static
    # shape -> compiled into the graph). 0 keeps intra-batch dedup but
    # disables the cross-iteration tier.
    cache_device_slots: int = 1024
    # --- TPU-native knobs (no reference analog; replace Distributed.jl) ---
    n_parallel_tournaments: int = 0  # 0 => npop // tournament_selection_n
    eval_backend: str = "auto"  # "jnp" | "pallas" | "auto"
    # Program shape for the Pallas kernel: "auto" uses the fixed default
    # in models/fitness.py (_DEFAULT_PROGRAM, set from kernel_tune A/B
    # measurements on hardware); "postfix" / "instr" / "instr_packed"
    # pin a shape (shapes documented in ops/pallas_eval.py). Ignored on
    # the jnp interpreter path, like eval_backend="jnp".
    kernel_program: str = "auto"
    # Slot-dispatch shape inside the postfix Pallas kernel: "auto" uses
    # the measured default in models/fitness.py (_DEFAULT_LEAF_SKIP, set
    # from the on-chip kernel_tune A/B of the skip variants); False pins
    # the single branchless candidate mux; True adds a scalar-predicated
    # 2-way branch that skips all operator candidates on leaf slots;
    # "class" a 3-way branch (leaf | unary | binary) where the binary arm
    # also skips the transcendental candidates. Applies to the postfix
    # program only (the instr programs have no leaf slots).
    kernel_leaf_skip: "str | bool" = "auto"
    # Length-bucketed jnp interpreter evaluation (docs/eval_pipeline.md).
    # Non-empty: a host-static ladder of cumulative batch fractions,
    # ascending and ending at 1.0 (e.g. (0.25, 0.5, 1.0)). Each scoring
    # batch is argsorted by program length, split at the ladder's
    # positional boundaries, and every bucket's slot loop truncates to
    # that bucket's longest program — exact (bit-identical to the flat
    # path: truncated slots are PAD no-ops) and faster whenever the
    # population skews short (early curmaxsize warm-up, post-simplify
    # populations). Applies only where the jnp interpreter runs (CPU,
    # small batches, f64/f16); batches routed to the Pallas kernel keep
    # the flat composition — the kernel already prices trees by length.
    # () (default) = flat evaluation, identical graphs to pre-ladder
    # builds.
    eval_bucket_ladder: Tuple[float, ...] = ()
    # Row-tiled streaming loss for the jnp interpreter path: > 0 streams
    # dataset rows through fixed-width tiles of this many rows inside the
    # fused per-tree reduction, bounding eval-stage memory at
    # O(batch x rows_per_tile) instead of O(batch x nrows). NOT
    # bit-identical to the flat reduction (tile-wise partial sums reduce
    # in a different order) — opt-in for large datasets, default off.
    eval_rows_per_tile: int = 0
    # Constant-optimization eval path: "auto" routes BFGS through the
    # fused Pallas loss/grad kernels (ops/pallas_grad.py) at population
    # scale on TPU; "jnp" pins the vmapped-interpreter path; "pallas"
    # forces the fused path (TPU-only; requires BFGS + elementwise loss).
    optimizer_backend: str = "auto"
    # Cap on evolution cycles fused into ONE jit dispatch. None (default)
    # keeps the whole iteration — cycle scan + simplify + constant opt +
    # migration — a single XLA program (minimum dispatch overhead; the
    # right setting everywhere a single call stays short). An int k
    # splits the iteration into phased dispatches with at most k cycles
    # per call (api._make_iteration_driver): with batching=False
    # (the default) results are bit-identical to the fused form (the
    # chunks share one iteration-wide annealing schedule and a single
    # end-of-iteration stats decay; tests/test_dispatch_chunking.py),
    # at the cost of ~60-70 ms dispatch overhead per extra call on a
    # tunneled device. With batching=True each chunk re-derives its
    # minibatch key chain from the evolved state key, so the chunked
    # path draws different (equally-distributed, deterministic)
    # minibatch rows than the fused scan would.
    # Exists because very-long-running single calls are where the
    # at-scale (64x1000) TPU `UNAVAILABLE` device fault lives — shorter
    # dispatches both bound per-call device time and localize a fault to
    # a phase instead of poisoning the whole iteration.
    max_cycles_per_dispatch: Optional[int] = None
    # Dataset-row sharding width of the device mesh: with row_shards=r the
    # mesh is (n_devices//r, r) (islands x rows) and X/y shard their row
    # dim. Since ISSUE 15, row_shards > 1 also switches every scoring /
    # constant-optimization row reduction to the fixed-order pairwise
    # tree (ops/losses.py::pairwise_sum), whose result is invariant to
    # row partitioning — a row-sharded search is bit-identical to the
    # single-device run of the same Options (docs/robustness_numeric.md;
    # the pre-15 psum reassociation exclusion in docs/multichip.md is
    # gone). Part of _graph_key: the two reduction graphs compile as
    # distinct programs.
    row_shards: int = 1
    # --- hostile-data front door (models/dataset.py, ISSUE 15) ---
    # What equation_search does with a dataset that fails validation
    # (NaN/Inf cells, constant y, degenerate feature columns, scale
    # hazards — docs/robustness_numeric.md):
    #   "reject" (default) — fail fast with a structured
    #     DatasetDiagnostics report (hard errors only; warnings like a
    #     constant target are reported, never fatal);
    #   "mask"   — rows with any non-finite cell are excluded from the
    #     loss through the existing weights path (weight 0) and their
    #     cells replaced by finite placeholders so the lockstep
    #     evaluation stays finite; a no-op on clean data (bit-identical
    #     to "reject");
    #   "repair" — non-finite X cells are imputed with their column's
    #     finite mean (the row stays live); non-finite y/weight rows
    #     fall back to masking (targets are never invented).
    # Orchestration-only: the policy transforms the data BEFORE any
    # jitted program sees it, so it is absent from _graph_key.
    data_policy: str = "reject"
    # Working dtype for X/y/constants/losses (the reference's Float16/32/64
    # type parameter T). "float64" flips on jax_enable_x64 at search start;
    # "bfloat16" is the TPU-native half precision — large bf16 batches on
    # TPU run the Pallas kernel's bf16-compute/f32-accumulate variant,
    # f64/f16 route to the jnp interpreter (dispatch_eval).
    precision: str = "float32"
    island_axis: str = "islands"
    row_axis: str = "rows"
    # --- multi-tenant batched serving (serving/batched.py) ---
    # tenants > 1 marks this Options as the per-tenant configuration of a
    # tenant-batched search: the serving engine stacks that many
    # same-shape datasets along a leading tenants axis and vmaps the
    # iteration programs over it. Part of _graph_key (the vmapped
    # program is a different compiled graph), and validated in
    # __post_init__ against knobs that break per-tenant isolation
    # (TenantIsolationError). The solo equation_search front door
    # rejects tenants > 1 — use serving.batched_equation_search.
    tenants: int = 1
    tenant_axis: str = "tenants"
    max_len: int = 0  # 0 => round_up(maxsize + 2, 8)

    # ------------------------------------------------------------------
    def __post_init__(self):
        if self.maxdepth is None:
            object.__setattr__(self, "maxdepth", self.maxsize)
        if self.max_len == 0:
            object.__setattr__(self, "max_len", _round_up(self.maxsize + 2, 8))
        if self.n_parallel_tournaments == 0:
            object.__setattr__(
                self,
                "n_parallel_tournaments",
                max(1, self.npop // self.tournament_selection_n),
            )
        # normalize tuple-ized dict-like kwargs
        for f in ("binary_operators", "unary_operators"):
            v = getattr(self, f)
            if not isinstance(v, tuple):
                object.__setattr__(self, f, tuple(v))
        for f in ("constraints", "nested_constraints", "complexity_of_operators"):
            v = getattr(self, f)
            if isinstance(v, dict):
                object.__setattr__(
                    self,
                    f,
                    tuple(
                        (k, tuple(sorted(val.items())) if isinstance(val, dict) else val)
                        for k, val in sorted(v.items())
                    ),
                )
        if self.precision not in ("float32", "float64", "bfloat16", "float16"):
            raise ValueError(
                "precision must be one of float32/float64/bfloat16/float16"
            )
        if not 0 < self.tournament_selection_p <= 1:
            raise ValueError("tournament_selection_p must be in (0, 1]")
        if self.kernel_program not in (
            "auto", "postfix", "instr", "instr_packed"
        ):
            raise ValueError(
                "kernel_program must be one of "
                "auto/postfix/instr/instr_packed"
            )
        if self.kernel_leaf_skip not in ("auto", False, True, "class"):
            raise ValueError(
                "kernel_leaf_skip must be one of auto/False/True/'class'"
            )
        if self.kernel_leaf_skip not in ("auto", False) and (
            self.kernel_program in ("instr", "instr_packed")
        ):
            raise ValueError(
                "kernel_leaf_skip applies to the postfix program only; "
                f"kernel_program={self.kernel_program!r} has no leaf slots"
            )
        if self.optimizer_backend not in ("auto", "jnp", "pallas"):
            raise ValueError(
                "optimizer_backend must be one of auto/jnp/pallas"
            )
        if not isinstance(self.eval_bucket_ladder, tuple):
            object.__setattr__(
                self, "eval_bucket_ladder",
                tuple(float(f) for f in self.eval_bucket_ladder),
            )
        ladder = self.eval_bucket_ladder
        if ladder:
            if any(
                not 0.0 < float(f) <= 1.0 for f in ladder
            ) or list(ladder) != sorted(ladder):
                raise ValueError(
                    "eval_bucket_ladder must be ascending cumulative "
                    f"batch fractions in (0, 1], got {ladder!r}"
                )
            if float(ladder[-1]) != 1.0:
                raise ValueError(
                    "eval_bucket_ladder must end at 1.0 (the last bucket "
                    f"covers the whole batch), got {ladder!r}"
                )
        if self.eval_rows_per_tile < 0:
            raise ValueError("eval_rows_per_tile must be >= 0")
        if self.row_shards < 1:
            raise ValueError("row_shards must be >= 1")
        if self.row_shards > 1 and self.eval_backend == "pallas":
            raise ValueError(
                "eval_backend='pallas' is incompatible with row_shards > 1:"
                " the kernel's row reduction is not the fixed-order "
                "pairwise tree the row-sharded bit-identity contract "
                "requires (docs/robustness_numeric.md) — use "
                "eval_backend='auto' or 'jnp'"
            )
        if self.row_shards > 1 and self.optimizer_backend == "pallas":
            raise ValueError(
                "optimizer_backend='pallas' is incompatible with "
                "row_shards > 1 (the fused grad kernel's row reduction "
                "is not partition-invariant; docs/robustness_numeric.md)"
                " — use optimizer_backend='auto' or 'jnp'"
            )
        if self.row_shards > 1 and self.loss_function is not None:
            raise ValueError(
                "a custom loss_function is incompatible with "
                "row_shards > 1: its internal row reductions (jnp.sum/"
                "jnp.mean over the sharded rows) reassociate under the "
                "row mesh, so the row-sharded bit-identity contract "
                "(docs/robustness_numeric.md) cannot be guaranteed for "
                "an arbitrary callable — use row_shards=1, or express "
                "the objective as an elementwise `loss` (whose "
                "aggregation the engine makes partition-invariant)"
            )
        if self.data_policy not in ("reject", "mask", "repair"):
            raise ValueError(
                "data_policy must be one of reject/mask/repair, got "
                f"{self.data_policy!r}"
            )
        if (
            self.max_cycles_per_dispatch is not None
            and self.max_cycles_per_dispatch < 1
        ):
            raise ValueError("max_cycles_per_dispatch must be >= 1 or None")
        if self.tournament_selection_n > self.npop:
            raise ValueError("tournament_selection_n must be <= npop")
        if self.cache_capacity < 1:
            raise ValueError("cache_capacity must be >= 1")
        if self.telemetry_every < 1:
            raise ValueError("telemetry_every must be >= 1")
        if self.telemetry_attempt is not None and self.telemetry_attempt < 1:
            raise ValueError("telemetry_attempt must be >= 1 (1-based)")
        if self.snapshot_every_dispatches < 0:
            raise ValueError("snapshot_every_dispatches must be >= 0")
        if self.snapshot_path and self.snapshot_every_dispatches == 0:
            # a configured path always snapshots (see the field doc)
            object.__setattr__(self, "snapshot_every_dispatches", 1)
        if self.snapshot_every_dispatches > 0 and not self.snapshot_path:
            raise ValueError(
                "snapshot_every_dispatches requires snapshot_path"
            )
        if self.cache_device_slots < 0:
            raise ValueError("cache_device_slots must be >= 0")
        if self.tenants < 1:
            raise ValueError("tenants must be >= 1")
        if self.tenants > 1:
            if self.row_shards > 1:
                raise ValueError(
                    "tenants > 1 is incompatible with row_shards > 1: "
                    "the device mesh is (tenants, islands) in batched "
                    "serving — shard rows in solo searches only"
                )
            # per-tenant isolation contract (docs/serving.md): every
            # host-side output channel must either be off or carry a
            # "{tenant}" placeholder the engine expands per tenant —
            # a shared file would interleave independent jobs
            conflicts = []
            if self.recorder:
                conflicts.append((
                    "recorder",
                    "the lineage recorder materializes ONE run's "
                    "populations into one JSON document; there is no "
                    "per-tenant recorder — run the job solo",
                ))
            if (
                self.snapshot_path is not None
                and "{tenant}" not in str(self.snapshot_path)
            ):
                conflicts.append((
                    "snapshot_path",
                    "a shared snapshot file would interleave tenants; "
                    "use a per-tenant template such as "
                    "'snaps/tenant{tenant}.npz'",
                ))
            if (
                self.output_file is not None
                and "{tenant}" not in str(self.output_file)
            ):
                conflicts.append((
                    "output_file",
                    "a shared hall-of-fame CSV would interleave "
                    "tenants; use a per-tenant template such as "
                    "'hof_tenant{tenant}.csv'",
                ))
            if conflicts:
                raise TenantIsolationError(conflicts)
        # build and cache derived structures
        object.__setattr__(self, "_operators", make_operator_set(
            self.binary_operators, self.unary_operators))
        resolve_loss(self.loss)  # validate early

    # ------------------------------------------------------------------
    @property
    def operators(self) -> OperatorSet:
        return self._operators  # type: ignore[attr-defined]

    @property
    def elementwise_loss(self) -> Callable:
        return resolve_loss(self.loss)

    @property
    def dtype(self):
        import jax.numpy as jnp

        return {
            "float32": jnp.float32,
            "float64": jnp.float64,
            "bfloat16": jnp.bfloat16,
            "float16": jnp.float16,
        }[self.precision]

    @property
    def actual_maxsize(self) -> int:
        # Reference: actualMaxsize = maxsize + MAX_DEGREE
        # (src/SymbolicRegression.jl:479); hall-of-fame slots 1..maxsize+2.
        return self.maxsize + 2

    def complexity_arrays(self):
        """Build integer complexity tables aligned with the operator set.

        Returns (use_custom, binop_c, unaop_c, var_c, const_c) with numpy
        arrays, for models/complexity.py."""
        from ..ops.operators import canonical_name

        ops = self.operators
        custom = {canonical_name(k): v for k, v in self.complexity_of_operators}
        use = bool(custom) or self.complexity_of_constants != 1 or self.complexity_of_variables != 1
        bin_c = np.array(
            [int(custom.get(n, 1)) for n in ops.binary_names], np.int32
        )
        una_c = np.array(
            [int(custom.get(n, 1)) for n in ops.unary_names], np.int32
        )
        return use, bin_c, una_c, int(self.complexity_of_variables), int(
            self.complexity_of_constants
        )

    def early_stop_fn(self) -> Optional[Callable]:
        """Scalar threshold -> closure (reference src/Options.jl:601-605)."""
        cond = self.early_stop_condition
        if cond is None:
            return None
        if callable(cond):
            return cond
        thresh = float(cond)
        return lambda loss, complexity: loss < thresh

    def _graph_key(self):
        """Fields that affect the compiled search graph. Hash/eq use only
        these so jit-compilation caches hit across Options that differ only
        in orchestration knobs (verbosity, output_file, stopping...).

        The TRACED_SCALAR_FIELDS knobs (parsimony, alpha, annealing and
        migration fractions, ...) are deliberately ABSENT: they enter the
        jitted iteration as traced arguments (`traced_scalars`), so a
        sweep over them re-uses one compiled graph instead of paying the
        20-40s TPU compile per variant. That also means the iteration
        factories' lru_caches can legitimately return one closure for
        Options differing only in those knobs — which is exactly why the
        jitted functions REQUIRE the scalars argument at every call: the
        caller's own Options supplies the values, never the closure."""
        return (
            self.binary_operators, self.unary_operators, self.npopulations,
            self.npop, self.ncycles_per_iteration, self.maxsize, self.max_len,
            self.maxdepth,
            self.tournament_selection_n,
            self.topn, self.batching, self.batch_size,
            self.independent_island_batches,
            self.n_parallel_tournaments, self.eval_backend,
            self.kernel_program, self.kernel_leaf_skip, self.precision,
            # bucketed / row-tiled eval graphs are compiled in; so is
            # the row_shards>1 deterministic pairwise reduction (two
            # Options differing only in row_shards trace DIFFERENT
            # scoring graphs — the lru-cached factories must not share
            # a closure across them)
            self.eval_bucket_ladder, self.eval_rows_per_tile,
            self.row_shards,
            self.constraints, self.nested_constraints,
            self.complexity_of_operators, self.complexity_of_constants,
            self.complexity_of_variables, self.mutation_weights.as_tuple(),
            self.crossover_probability, self.annealing,
            self.use_frequency, self.use_frequency_in_tournament,
            self.migration,
            self.hof_migration, self.should_optimize_constants,
            self.optimizer_probability, self.optimizer_nrestarts,
            self.optimizer_iterations, self.optimizer_algorithm,
            self.optimizer_backend,
            # callables are keyed by process-lifetime token, not id():
            # ids are reused after GC, so two distinct custom losses
            # could otherwise alias one warm-compile bucket (SR011)
            str(self.loss) if not callable(self.loss)
            else callable_token(self.loss),
            None if self.loss_function is None
            else callable_token(self.loss_function),
            # recorder mode adds the event-collection outputs to the graph
            self.recorder,
            # the dedup/memo scoring path and the device memo table shape
            # are compiled in (cache_capacity is host-side and absent)
            self.cache_fitness, self.cache_device_slots,
            # dispatch chunking changes which compiled programs exist
            # (fused single call vs phased sub-programs)
            self.max_cycles_per_dispatch,
            # the tenant-batched (vmapped) iteration is a different
            # compiled graph from the solo one (serving/batched.py)
            self.tenants,
        )

    def traced_scalars(self) -> Tuple:
        """The trace-irrelevant scalar knobs as jnp.float32 leaves, in
        TRACED_SCALAR_FIELDS order — passed as a traced argument to the
        jitted iteration/init functions so sweeping any of them re-uses
        the compiled graph (the reference pays compilation once per
        *method*, not per config — src/precompile.jl:34-79)."""
        import jax.numpy as jnp

        return tuple(
            jnp.float32(getattr(self, f)) for f in TRACED_SCALAR_FIELDS
        )

    def bind_scalars(self, scalars: Tuple) -> "Options":
        """Shallow copy with the TRACED_SCALAR_FIELDS replaced by `scalars`
        (typically tracers, inside jit). Downstream code reads
        options.parsimony etc. unchanged; every audited use site is pure
        array math (no Python control flow on these fields)."""
        import copy

        new = copy.copy(self)
        for f, v in zip(TRACED_SCALAR_FIELDS, scalars):
            object.__setattr__(new, f, v)
        return new

    def __hash__(self):
        return hash(self._graph_key())

    def __eq__(self, other):
        if not isinstance(other, Options):
            return NotImplemented
        return self._graph_key() == other._graph_key()


def make_options(**kwargs) -> Options:
    """Kwarg constructor accepting deprecated camelCase names
    (reference src/Options.jl:122-143,380-427)."""
    remapped = {}
    for k, v in kwargs.items():
        k2 = _DEPRECATED_KWARGS.get(k, k)
        if k2 in remapped:
            raise ValueError(f"Duplicate kwarg {k2!r}")
        remapped[k2] = v
    # The reference's SIMD knob (src/Options.jl:250-252): here the
    # accelerated eval path is the Pallas TPU kernel, so turbo=True maps to
    # eval_backend="auto" (kernel on TPU, interpreter elsewhere) and
    # turbo=False pins the portable interpreter.
    if "turbo" in remapped:
        turbo = remapped.pop("turbo")
        remapped.setdefault("eval_backend", "auto" if turbo else "jnp")
    # Recorder defaults from the environment like the reference
    # (src/Options.jl:597-599): unset kwarg + PYSR_RECORDER=1 turns it on.
    if "recorder" not in remapped and os.environ.get("PYSR_RECORDER") == "1":
        remapped["recorder"] = True
    # The reference renamed `loss` -> `elementwise_loss`
    # (src/Options.jl:142,319); both name the same elementwise-loss knob.
    if "elementwise_loss" in remapped:
        if "loss" in remapped:
            raise ValueError("Pass either loss= or elementwise_loss=, not both")
        remapped["loss"] = remapped.pop("elementwise_loss")
    # Split per-arity constraint kwargs (reference una_constraints /
    # bin_constraints, src/Options.jl:33-84) merge into the unified
    # `constraints` mapping. Dict form only — the reference's positional
    # list form is ordered by its operator tuple, which invites silent
    # misalignment; a dict says what it means.
    for k in ("una_constraints", "bin_constraints"):
        if k in remapped:
            extra = remapped.pop(k)
            if extra is None:
                continue
            if not isinstance(extra, dict):
                raise ValueError(
                    f"{k} must be a dict of operator-name -> constraint "
                    "(the reference's positional-list form is not supported;"
                    " name the operators)"
                )
            merged = dict(remapped.get("constraints") or {})
            for op, spec in extra.items():
                if op in merged:
                    raise ValueError(
                        f"operator {op!r} constrained in both constraints= "
                        f"and {k}="
                    )
                merged[op] = spec
            remapped["constraints"] = merged
    if isinstance(remapped.get("mutation_weights"), (list, tuple)):
        remapped["mutation_weights"] = MutationWeights(*remapped["mutation_weights"])
    elif isinstance(remapped.get("mutation_weights"), dict):
        remapped["mutation_weights"] = MutationWeights(**remapped["mutation_weights"])
    opts = Options(**remapped)
    if opts.eval_backend == "pallas" and opts.precision in (
        "float64", "float16"
    ):
        # fail at construction, not at the first evaluation: the kernel
        # computes in f32 (bf16 storage-only) and dispatch_eval rejects
        # other dtypes rather than silently downcasting
        raise ValueError(
            f"eval_backend='pallas' supports float32/bfloat16 only "
            f"(precision={opts.precision!r} has no native TPU kernel "
            "path); use eval_backend='jnp' or 'auto'"
        )
    if opts.precision == "float64" and opts.eval_backend != "jnp":
        # The reference's default dtype is Float64 with native-speed fused
        # eval (src/InterfaceDynamicExpressions.jl:50-52). Here the Pallas
        # kernel is f32/bf16-only — v5e has no native f64 vector path —
        # so float64 scoring routes to the lockstep jnp interpreter. Say
        # so up front rather than letting a user discover an order-of-
        # magnitude eval-throughput gap by profiling (BASELINE.md
        # 'float64' records the measured ratio).
        import warnings

        warnings.warn(
            "precision='float64': fitness evaluation uses the jnp "
            "lockstep interpreter — the Pallas TPU kernel supports only "
            "float32/bfloat16 (no native f64 on this TPU generation). "
            "Expect roughly interpreter-vs-kernel (O(100x) on TPU) lower "
            "eval throughput than float32; use precision='float32' unless "
            "you need f64 constants/losses. See BASELINE.md.",
            stacklevel=2,
        )
    return opts
