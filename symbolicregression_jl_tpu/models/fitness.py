"""Scoring: loss evaluation + baseline-normalized, parsimony-penalized score.

Analog of reference src/LossFunctions.jl: `_eval_loss` (eval_tree_array ->
Inf-on-incomplete -> weighted mean, :34-50), `loss_to_score`
(loss/baseline + size*parsimony, :70-83), `score_func` (:86-92) and
`score_func_batch` (random minibatch, :95-115). Here every function is
batched over whole populations: one XLA call scores thousands of trees.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.interpreter import eval_loss_trees_fused, eval_trees
from ..ops.losses import aggregate_loss, contain_nonfinite
from ..ops.operators import OperatorSet
from .complexity import compute_complexity
from .options import Options
from .trees import TreeBatch

Array = jax.Array


_PALLAS_MIN_BATCH = 512

# Minimum trees x rows work volume for the Pallas kernel ('auto' routing).
# The kernel lays rows out on (8, 128) float32 vregs — one full row tile
# is 1024 lanes — so the gate is calibrated as _PALLAS_MIN_BATCH trees at
# exactly one full tile of rows: a 512-tree batch at >=1024 rows routes to
# the kernel as before, while a large-batch/tiny-rows call (e.g. 8192
# trees x 50 minibatch rows, where every grid step pads 974 of 1024
# lanes) now stays on the jnp interpreter, which wastes nothing on rows.
_PALLAS_MIN_WORK = _PALLAS_MIN_BATCH * 1024


def _pallas_work_gate(n_trees: int, n_rows: int) -> bool:
    """True when an (n_trees x n_rows) eval is big enough that the Pallas
    kernel's tile padding is amortized. The static _PALLAS_MIN_WORK
    calibration is the default; a persistent kernel-tune cache entry for
    this device kind (tune/cache.py, written by kernel_tune.py
    --autotune) replaces it with the MEASURED crossover — and with no
    cache present `tuned_min_work()` is None, so routing is
    byte-identical to the static rule."""
    from ..tune.cache import tuned_min_work

    min_work = tuned_min_work()
    if min_work is None:
        min_work = _PALLAS_MIN_WORK
    return n_trees * n_rows >= min_work


def _tuned_kernel_kwargs(operators, max_len: int, dtype_name: str) -> dict:
    """eval_trees_pallas/eval_loss_trees_pallas keyword overrides from
    the persistent tune cache for (this device kind, opset, maxsize,
    dtype) — {} when no cache or no matching entry, so untuned dispatch
    reproduces the static defaults exactly. All values are host-static
    (they select a compiled kernel variant, like the defaults they
    replace)."""
    from ..tune.cache import lookup_kernel_config

    cfg = lookup_kernel_config(operators, max_len, dtype_name)
    if not cfg:
        return {}
    kw: dict = {}
    if isinstance(cfg.get("t_block"), int):
        kw["t_block"] = cfg["t_block"]
    if isinstance(cfg.get("r_block"), int):
        kw["r_block"] = cfg["r_block"]
    if cfg.get("dispatch") in ("mux", "chain"):
        kw["dispatch"] = cfg["dispatch"]
    if isinstance(cfg.get("tree_unroll"), int):
        kw["tree_unroll"] = cfg["tree_unroll"]
    if cfg.get("ladder"):
        kw["bucket_ladder"] = tuple(float(x) for x in cfg["ladder"])
    return kw

# Kernel program shape used when kernel_program="auto": the best measured
# variant on hardware (benchmark/kernel_tune.py A/B history in BASELINE.md).
_DEFAULT_PROGRAM = "postfix"

# Slot-dispatch shape used when kernel_leaf_skip="auto": False until the
# on-chip kernel_tune A/B of the skip variants shows a win (BASELINE.md
# round-3 sweep slot); flip here to adopt a winner globally.
_DEFAULT_LEAF_SKIP: "bool | str" = False


def dispatch_eval(
    trees: TreeBatch, X: Array, operators: OperatorSet,
    backend: str = "auto", program: str = "auto",
    leaf_skip: "str | bool" = "auto",
):
    """Choose the eval kernel. 'auto': the Pallas scalar-dispatch kernel for
    large float32/bfloat16 top-level batches on TPU (the bench /
    standalone-eval hot path); the portable jnp lockstep interpreter
    otherwise (small per-island batches inside the vmapped evolution step,
    CPU, f64/f16 dtypes). bfloat16 inputs run the kernel's bf16-storage /
    f32-compute variant (the TPU-native half precision; Mosaic cannot
    lower transcendentals on bf16 vectors, so bf16 is storage-only).

    The Pallas kernel has no VJP rule — differentiable callers (constant
    optimization) must force backend='jnp' or call eval_trees directly;
    'auto' never changes semantics or breaks grads only because the guards
    below route those cases to the jnp path."""
    if backend == "pallas" and X.dtype not in (jnp.float32, jnp.bfloat16):
        # never silently downcast: the kernel computes in f32 (bf16 is
        # storage-only), so an explicit pallas request for f64/f16 data
        # would quietly lose the precision the caller asked for
        raise ValueError(
            f"eval_backend='pallas' supports float32/bfloat16 only, got "
            f"{X.dtype} (float64 has no native TPU path — use "
            "eval_backend='jnp'; see BASELINE.md 'float64')"
        )
    if _routes_to_pallas(trees, X, backend):
        from ..ops.pallas_eval import eval_trees_pallas

        compute_dtype = (
            "bfloat16" if X.dtype == jnp.bfloat16 else "float32"
        )
        resolved_program = _DEFAULT_PROGRAM if program == "auto" else program
        resolved_skip = (
            _DEFAULT_LEAF_SKIP if leaf_skip == "auto" else leaf_skip
        )
        if resolved_program != "postfix":
            resolved_skip = False  # instr programs have no leaf slots
        tuned = _tuned_kernel_kwargs(
            operators, trees.kind.shape[-1], compute_dtype
        )
        if resolved_program != "postfix":
            tuned.pop("bucket_ladder", None)  # postfix-only parameter
        y, ok = eval_trees_pallas(
            trees, X, operators, compute_dtype=compute_dtype,
            program=resolved_program, leaf_skip=resolved_skip, **tuned,
        )
        # downstream scoring expects the working dtype; the kernel
        # accumulates/returns f32 (bf16-compute, f32-accumulate)
        return y.astype(X.dtype), ok
    return eval_trees(trees, X, operators)


def resolve_eval_backend_pallas(
    backend: str, dtype, n_trees: int, n_rows: int,
    deterministic: bool = False,
) -> bool:
    """THE kernel routing decision, in shape terms: True when evaluation
    runs the Pallas kernel. Single source of truth — dispatch_eval, the
    loss-path builder (_make_eval_loss_fn, via _routes_to_pallas), and
    the memo bank's fingerprint resolution (cache/memo.py, which must
    predict the backend the rescore will use or a served loss could be
    ULP-wrong) all call this one predicate. All inputs are trace-time
    constants, so the decision is host-static.

    deterministic (row_shards > 1): the Pallas kernel's row reduction
    is the kernel's own accumulation order, NOT the fixed-order
    pairwise tree that makes row-sharded scoring partition-invariant —
    so deterministic scoring NEVER routes to the kernel (Options
    rejects the explicit eval_backend='pallas' + row_shards>1 combo at
    construction; 'auto' quietly keeps the jnp pairwise graph). Without
    this gate the bit-identity contract of docs/robustness_numeric.md
    would silently not hold exactly on the TPU path it targets."""
    from ..ops.pallas_eval import pallas_available

    import jax.numpy as _jnp

    if deterministic:
        return False
    return backend == "pallas" or (
        backend == "auto"
        and pallas_available()
        and dtype in (_jnp.float32, _jnp.bfloat16)
        and _pallas_work_gate(n_trees, n_rows)
    )


def _routes_to_pallas(
    trees: TreeBatch, X: Array, backend: str, deterministic: bool = False
) -> bool:
    """resolve_eval_backend_pallas on an actual (trees, X) call shape."""
    return resolve_eval_backend_pallas(
        backend, X.dtype, int(np.prod(trees.length.shape)), X.shape[1],
        deterministic=deterministic,
    )


def _bucket_bounds(n: int, ladder: Tuple[float, ...]) -> Tuple[int, ...]:
    """Static positional boundaries [0, n1, ..., n] of a length-sorted
    batch of n trees under a cumulative-fraction ladder. Duplicate
    boundaries (empty buckets at small n) are kept — callers skip
    zero-width buckets."""
    bounds = [0]
    for frac in ladder:
        bounds.append(min(n, max(bounds[-1], int(round(frac * n)))))
    bounds[-1] = n  # the ladder's last rung is validated to be 1.0
    return tuple(bounds)


def eval_loss_trees_bucketed(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    operators: OperatorSet,
    loss_fn: Callable,
    ladder: Tuple[float, ...],
    rows_per_tile: int = 0,
    presorted: bool = False,
    deterministic: bool = False,
) -> Array:
    """Length-bucketed jnp evaluation: per-tree aggregated loss,
    bit-identical to the flat interpreter path (with rows_per_tile=0).

    GP populations are dominated by short programs (early iterations run
    under a small curmaxsize; mutation shrinks as often as it grows), but
    the lockstep interpreter scans all max_len slots for every tree. This
    driver argsorts the flat batch by program length, splits the sorted
    order at the ladder's host-static positional boundaries (cumulative
    batch fractions — `_bucket_bounds`), and evaluates each bucket with
    the slot loop truncated to THAT bucket's longest program (a traced
    bound: `jnp.max` over the bucket, so an all-short bucket stops at its
    actual need rather than a fixed rung). Losses scatter back to the
    original order. Exact by construction: every truncated slot is PAD,
    and PAD steps are identities in the interpreter (`_slot_step`), so
    per-tree results are invariant to bucket assignment — which is also
    why composing with the dedup sort below is safe.

    presorted=True skips the argsort: the caller guarantees the batch is
    already grouped so that ordering by position approximates ordering by
    length (the dedup pipeline's length-major sort — cache/dedup.py — so
    dedup and bucketing share ONE sort; its filler slots are length-1
    programs that never raise a bucket's bound). Correctness does NOT
    depend on the ordering, only the realized speedup does."""
    batch_shape = trees.length.shape
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    N = flat.length.shape[0]
    if presorted:
        order = None
        ordered = flat
    else:
        order = jnp.argsort(flat.length, stable=True)
        ordered = jax.tree_util.tree_map(lambda x: x[order], flat)
    bounds = _bucket_bounds(N, ladder)
    losses = []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        if lo == hi:
            continue
        bucket = ordered[lo:hi]
        n_steps = jnp.max(bucket.length)
        losses.append(
            eval_loss_trees_fused(
                bucket, X, y, weights, operators, loss_fn,
                rows_per_tile=rows_per_tile, n_steps=n_steps,
                deterministic=deterministic,
            )
        )
    if not losses:  # N == 0: every bucket zero-width, like the flat path
        return jnp.zeros(batch_shape, X.dtype)
    loss_sorted = losses[0] if len(losses) == 1 else jnp.concatenate(losses)
    if order is None:
        loss = loss_sorted
    else:
        loss = jnp.zeros((N,), loss_sorted.dtype).at[order].set(loss_sorted)
    return loss.reshape(batch_shape)


def _make_eval_loss_fn(
    X: Array,
    y: Array,
    weights: Optional[Array],
    operators: OperatorSet,
    loss_fn: Callable,
    backend: str,
    program: str,
    leaf_skip: "str | bool",
    bucket_ladder: Tuple[float, ...] = (),
    rows_per_tile: int = 0,
    length_sorted: bool = False,
    deterministic: bool = False,
) -> Callable:
    """TreeBatch -> per-tree aggregated loss (Inf on NaN/Inf evals,
    reference src/LossFunctions.jl:36-39). The ONE definition of the
    scoring composition: both the plain and the deduped/memoized paths
    call this exact closure, which is what makes the cache subsystem's
    bit-identity guarantee a structural property instead of a
    keep-two-copies-in-sync obligation. The inf-sentinel fold is the
    shared `contain_nonfinite` epilogue on every branch (the
    containment contract, docs/robustness_numeric.md), and
    deterministic=True (derived from Options.row_shards > 1 by the
    options-level callers) selects the fixed-order pairwise row
    reduction on every jnp branch so row-sharded scoring is
    bit-identical to single-device scoring.

    Dispatch decision tree (docs/eval_pipeline.md): batches that route
    to the Pallas kernel take the KERNEL-FUSED epilogue when the fused
    seam's restrictions hold (unweighted, float32, postfix program —
    the loss reduction + containment runs inside the kernel via
    eval_loss_trees_pallas, honoring `bucket_ladder` and any tuned
    kernel config), else the flat composition (the kernel already
    prices trees by length — ops/pallas_eval.py design note 3b); jnp
    batches take the length-bucketed graph when `bucket_ladder` is
    non-empty (bit-identical), else the row-tiled fused reduction when
    `rows_per_tile` > 0 (opt-in, NOT bit-identical), else the flat
    composition unchanged. length_sorted=True is the dedup pipeline's
    shared-sort hint (see eval_loss_trees_bucketed)."""

    def eval_fn(trees: TreeBatch) -> Array:
        if not _routes_to_pallas(trees, X, backend,
                                 deterministic=deterministic):
            if bucket_ladder:
                return eval_loss_trees_bucketed(
                    trees, X, y, weights, operators, loss_fn,
                    bucket_ladder, rows_per_tile=rows_per_tile,
                    presorted=length_sorted, deterministic=deterministic,
                )
            if rows_per_tile > 0 or deterministic:
                # deterministic scoring always takes the fused graph:
                # its pairwise row reduction is the partition-invariant
                # one (the flat composition below reduces with
                # jnp.mean, which reassociates under row sharding)
                return eval_loss_trees_fused(
                    trees, X, y, weights, operators, loss_fn,
                    rows_per_tile=rows_per_tile,
                    deterministic=deterministic,
                )
        else:
            resolved_program = (
                _DEFAULT_PROGRAM if program == "auto" else program
            )
            if (weights is None and resolved_program == "postfix"
                    and X.dtype == jnp.float32):
                # kernel-fused loss epilogue: the (B, nrows) prediction
                # matrix never reaches HBM. Weighted / bf16 / instr
                # batches fall through to the unfused composition below
                # (the PR 12 rules: deterministic never routes here at
                # all — _routes_to_pallas gates it above).
                from ..ops.pallas_eval import eval_loss_trees_pallas

                tuned = _tuned_kernel_kwargs(
                    operators, trees.kind.shape[-1], "float32"
                )
                tuned.setdefault("bucket_ladder", bucket_ladder)
                return eval_loss_trees_pallas(
                    trees, X, y, operators, loss_fn,
                    presorted=length_sorted, **tuned,
                )
        y_pred, ok = dispatch_eval(trees, X, operators, backend, program,
                                   leaf_skip)
        elem = loss_fn(y_pred, y)
        loss = aggregate_loss(elem, weights)
        return contain_nonfinite(loss, ok)

    return eval_fn


def eval_loss_trees(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    operators: OperatorSet,
    loss_fn: Callable,
    row_idx: Optional[Array] = None,
    backend: str = "auto",
    program: str = "auto",
    leaf_skip: "str | bool" = "auto",
    bucket_ladder: Tuple[float, ...] = (),
    rows_per_tile: int = 0,
    deterministic: bool = False,
) -> Array:
    """Per-tree aggregated loss over all rows (or the row_idx minibatch).

    Trees whose evaluation hit NaN/Inf get Inf loss
    (reference src/LossFunctions.jl:36-39). bucket_ladder / rows_per_tile
    / deterministic select the length-bucketed / row-tiled /
    fixed-order-reduction jnp graphs — see _make_eval_loss_fn for the
    dispatch decision tree and exactness guarantees per path."""
    if row_idx is not None:
        X = X[:, row_idx]
        y = y[row_idx]
        weights = None if weights is None else weights[row_idx]
    return _make_eval_loss_fn(
        X, y, weights, operators, loss_fn, backend, program, leaf_skip,
        bucket_ladder, rows_per_tile, deterministic=deterministic,
    )(trees)


def eval_loss_trees_deduped(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    operators: OperatorSet,
    loss_fn: Callable,
    row_idx: Optional[Array] = None,
    backend: str = "auto",
    program: str = "auto",
    leaf_skip: "str | bool" = "auto",
    bucket_ladder: Tuple[float, ...] = (),
    rows_per_tile: int = 0,
    deterministic: bool = False,
    memo=None,
):
    """eval_loss_trees through the cache subsystem: intra-batch dedup of
    identical programs + optional device-memo prefill (cache/dedup.py).
    Returns (loss, DedupStats) with loss bit-identical to eval_loss_trees.

    The memo holds FULL-data losses, so it is consulted only when
    row_idx is None — minibatch draws always evaluate (cache/memo.py
    keying rules).

    Bucketing composes with the dedup through ONE sort: dedup's
    length-major (length, hash) ordering leaves its compacted
    representative buffer grouped by length, so the closure is built with
    length_sorted=True and the bucketed path skips its own argsort
    (per-tree losses are invariant to bucket assignment, so the dedup's
    bit-identity contract — eval_fn(buffer) slot by slot — still holds)."""
    from ..cache.dedup import dedup_eval_losses

    if row_idx is not None:
        X = X[:, row_idx]
        y = y[row_idx]
        weights = None if weights is None else weights[row_idx]
        memo = None

    batch_shape = trees.length.shape
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    eval_fn = _make_eval_loss_fn(
        X, y, weights, operators, loss_fn, backend, program, leaf_skip,
        bucket_ladder, rows_per_tile, length_sorted=True,
        deterministic=deterministic,
    )
    loss, stats = dedup_eval_losses(flat, eval_fn, memo)
    return loss.reshape(batch_shape), stats


def score_trees_cached(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    row_idx: Optional[Array] = None,
    memo=None,
):
    """score_trees through the evaluation memo bank: (score, loss,
    DedupStats). Identical numerics to score_trees — dedup/memo hits
    substitute values the deterministic evaluator would produce for the
    same program on the same rows. The custom full-tree loss_function
    path bypasses the cache entirely (its objective may read the whole
    tree, so program identity is the wrong memo key granularity);
    stats report zero there."""
    from ..cache.dedup import DedupStats

    if options.loss_function is not None:
        score, loss = score_trees(
            trees, X, y, weights, baseline, options, row_idx
        )
        zero = jnp.int32(0)
        return score, loss, DedupStats(zero, zero, zero)
    loss, stats = eval_loss_trees_deduped(
        trees, X, y, weights, options.operators, options.elementwise_loss,
        row_idx, backend=options.eval_backend,
        program=options.kernel_program,
        leaf_skip=options.kernel_leaf_skip,
        bucket_ladder=options.eval_bucket_ladder,
        rows_per_tile=options.eval_rows_per_tile,
        deterministic=options.row_shards > 1,
        memo=memo,
    )
    complexity = compute_complexity(trees, options)
    score = loss_to_score(loss, baseline, complexity, options)
    score = contain_nonfinite(score, ref=loss)
    return score, loss, stats


def loss_to_score(
    loss: Array, baseline: float, complexity: Array, options: Options
) -> Array:
    """score = loss/baseline + complexity*parsimony
    (reference src/LossFunctions.jl:70-83)."""
    normalized = loss / baseline
    # parsimony may be an f32 tracer (TRACED_SCALAR_FIELDS): cast to the
    # working dtype so bf16/f16 scores don't get promoted to f32 (the
    # evolution scan carries scores at the search precision)
    par = jnp.asarray(options.parsimony, loss.dtype)
    return normalized + complexity.astype(loss.dtype) * par


def _custom_loss_trees(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    options: Options,
    row_idx: Optional[Array] = None,
) -> Array:
    """Custom full-tree objective, vmapped over the population (analog of the
    reference's eval_loss dispatch to a user loss_function,
    src/LossFunctions.jl:60-67)."""
    if row_idx is not None:
        X = X[:, row_idx]
        y = y[row_idx]
        weights = None if weights is None else weights[row_idx]
    batch_shape = trees.length.shape
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    fn = lambda t: options.loss_function(t, X, y, weights, options)
    loss = jax.vmap(fn)(flat)
    loss = contain_nonfinite(loss)
    return loss.reshape(batch_shape)


def score_trees(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    row_idx: Optional[Array] = None,
) -> Tuple[Array, Array]:
    """(score, loss) per tree — the batched `score_func`/`score_func_batch`."""
    if options.loss_function is not None:
        loss = _custom_loss_trees(trees, X, y, weights, options, row_idx)
    else:
        loss = eval_loss_trees(
            trees, X, y, weights, options.operators, options.elementwise_loss,
            row_idx, backend=options.eval_backend,
            program=options.kernel_program,
            leaf_skip=options.kernel_leaf_skip,
            bucket_ladder=options.eval_bucket_ladder,
            rows_per_tile=options.eval_rows_per_tile,
            deterministic=options.row_shards > 1,
        )
    complexity = compute_complexity(trees, options)
    score = loss_to_score(loss, baseline, complexity, options)
    score = contain_nonfinite(score, ref=loss)
    return score, loss


def sample_batch_idx(key: Array, n_rows: int, batch_size: int) -> Array:
    """Minibatch rows sampled with replacement
    (reference src/LossFunctions.jl:100-103)."""
    return jax.random.randint(key, (batch_size,), 0, n_rows, dtype=jnp.int32)
