"""Scoring: loss evaluation + baseline-normalized, parsimony-penalized score.

Analog of reference src/LossFunctions.jl: `_eval_loss` (eval_tree_array ->
Inf-on-incomplete -> weighted mean, :34-50), `loss_to_score`
(loss/baseline + size*parsimony, :70-83), `score_func` (:86-92) and
`score_func_batch` (random minibatch, :95-115). Here every function is
batched over whole populations: one XLA call scores thousands of trees.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.interpreter import eval_trees
from ..ops.losses import aggregate_loss
from ..ops.operators import OperatorSet
from .complexity import compute_complexity
from .options import Options
from .trees import TreeBatch

Array = jax.Array


_PALLAS_MIN_BATCH = 512

# Kernel program shape used when kernel_program="auto": the best measured
# variant on hardware (benchmark/kernel_tune.py A/B history in BASELINE.md).
_DEFAULT_PROGRAM = "postfix"

# Slot-dispatch shape used when kernel_leaf_skip="auto": False until the
# on-chip kernel_tune A/B of the skip variants shows a win (BASELINE.md
# round-3 sweep slot); flip here to adopt a winner globally.
_DEFAULT_LEAF_SKIP: "bool | str" = False


def dispatch_eval(
    trees: TreeBatch, X: Array, operators: OperatorSet,
    backend: str = "auto", program: str = "auto",
    leaf_skip: "str | bool" = "auto",
):
    """Choose the eval kernel. 'auto': the Pallas scalar-dispatch kernel for
    large float32/bfloat16 top-level batches on TPU (the bench /
    standalone-eval hot path); the portable jnp lockstep interpreter
    otherwise (small per-island batches inside the vmapped evolution step,
    CPU, f64/f16 dtypes). bfloat16 inputs run the kernel's bf16-storage /
    f32-compute variant (the TPU-native half precision; Mosaic cannot
    lower transcendentals on bf16 vectors, so bf16 is storage-only).

    The Pallas kernel has no VJP rule — differentiable callers (constant
    optimization) must force backend='jnp' or call eval_trees directly;
    'auto' never changes semantics or breaks grads only because the guards
    below route those cases to the jnp path."""
    from ..ops.pallas_eval import pallas_available

    if backend == "pallas" and X.dtype not in (jnp.float32, jnp.bfloat16):
        # never silently downcast: the kernel computes in f32 (bf16 is
        # storage-only), so an explicit pallas request for f64/f16 data
        # would quietly lose the precision the caller asked for
        raise ValueError(
            f"eval_backend='pallas' supports float32/bfloat16 only, got "
            f"{X.dtype} (float64 has no native TPU path — use "
            "eval_backend='jnp'; see BASELINE.md 'float64')"
        )
    if backend == "pallas" or (
        backend == "auto"
        and pallas_available()
        and X.dtype in (jnp.float32, jnp.bfloat16)
        and int(np.prod(trees.length.shape)) >= _PALLAS_MIN_BATCH
    ):
        from ..ops.pallas_eval import eval_trees_pallas

        compute_dtype = (
            "bfloat16" if X.dtype == jnp.bfloat16 else "float32"
        )
        resolved_program = _DEFAULT_PROGRAM if program == "auto" else program
        resolved_skip = (
            _DEFAULT_LEAF_SKIP if leaf_skip == "auto" else leaf_skip
        )
        if resolved_program != "postfix":
            resolved_skip = False  # instr programs have no leaf slots
        y, ok = eval_trees_pallas(
            trees, X, operators, compute_dtype=compute_dtype,
            program=resolved_program, leaf_skip=resolved_skip,
        )
        # downstream scoring expects the working dtype; the kernel
        # accumulates/returns f32 (bf16-compute, f32-accumulate)
        return y.astype(X.dtype), ok
    return eval_trees(trees, X, operators)


def _make_eval_loss_fn(
    X: Array,
    y: Array,
    weights: Optional[Array],
    operators: OperatorSet,
    loss_fn: Callable,
    backend: str,
    program: str,
    leaf_skip: "str | bool",
) -> Callable:
    """TreeBatch -> per-tree aggregated loss (Inf on NaN/Inf evals,
    reference src/LossFunctions.jl:36-39). The ONE definition of the
    scoring composition: both the plain and the deduped/memoized paths
    call this exact closure, which is what makes the cache subsystem's
    bit-identity guarantee a structural property instead of a
    keep-two-copies-in-sync obligation."""

    def eval_fn(trees: TreeBatch) -> Array:
        y_pred, ok = dispatch_eval(trees, X, operators, backend, program,
                                   leaf_skip)
        elem = loss_fn(y_pred, y)
        loss = aggregate_loss(elem, weights)
        return jnp.where(ok & jnp.isfinite(loss), loss, jnp.inf)

    return eval_fn


def eval_loss_trees(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    operators: OperatorSet,
    loss_fn: Callable,
    row_idx: Optional[Array] = None,
    backend: str = "auto",
    program: str = "auto",
    leaf_skip: "str | bool" = "auto",
) -> Array:
    """Per-tree aggregated loss over all rows (or the row_idx minibatch).

    Trees whose evaluation hit NaN/Inf get Inf loss
    (reference src/LossFunctions.jl:36-39)."""
    if row_idx is not None:
        X = X[:, row_idx]
        y = y[row_idx]
        weights = None if weights is None else weights[row_idx]
    return _make_eval_loss_fn(
        X, y, weights, operators, loss_fn, backend, program, leaf_skip
    )(trees)


def eval_loss_trees_deduped(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    operators: OperatorSet,
    loss_fn: Callable,
    row_idx: Optional[Array] = None,
    backend: str = "auto",
    program: str = "auto",
    leaf_skip: "str | bool" = "auto",
    memo=None,
):
    """eval_loss_trees through the cache subsystem: intra-batch dedup of
    identical programs + optional device-memo prefill (cache/dedup.py).
    Returns (loss, DedupStats) with loss bit-identical to eval_loss_trees.

    The memo holds FULL-data losses, so it is consulted only when
    row_idx is None — minibatch draws always evaluate (cache/memo.py
    keying rules)."""
    from ..cache.dedup import dedup_eval_losses

    if row_idx is not None:
        X = X[:, row_idx]
        y = y[row_idx]
        weights = None if weights is None else weights[row_idx]
        memo = None

    batch_shape = trees.length.shape
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    eval_fn = _make_eval_loss_fn(
        X, y, weights, operators, loss_fn, backend, program, leaf_skip
    )
    loss, stats = dedup_eval_losses(flat, eval_fn, memo)
    return loss.reshape(batch_shape), stats


def score_trees_cached(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    row_idx: Optional[Array] = None,
    memo=None,
):
    """score_trees through the evaluation memo bank: (score, loss,
    DedupStats). Identical numerics to score_trees — dedup/memo hits
    substitute values the deterministic evaluator would produce for the
    same program on the same rows. The custom full-tree loss_function
    path bypasses the cache entirely (its objective may read the whole
    tree, so program identity is the wrong memo key granularity);
    stats report zero there."""
    from ..cache.dedup import DedupStats

    if options.loss_function is not None:
        score, loss = score_trees(
            trees, X, y, weights, baseline, options, row_idx
        )
        zero = jnp.int32(0)
        return score, loss, DedupStats(zero, zero, zero)
    loss, stats = eval_loss_trees_deduped(
        trees, X, y, weights, options.operators, options.elementwise_loss,
        row_idx, backend=options.eval_backend,
        program=options.kernel_program,
        leaf_skip=options.kernel_leaf_skip,
        memo=memo,
    )
    complexity = compute_complexity(trees, options)
    score = loss_to_score(loss, baseline, complexity, options)
    score = jnp.where(jnp.isfinite(loss), score, jnp.inf)
    return score, loss, stats


def loss_to_score(
    loss: Array, baseline: float, complexity: Array, options: Options
) -> Array:
    """score = loss/baseline + complexity*parsimony
    (reference src/LossFunctions.jl:70-83)."""
    normalized = loss / baseline
    # parsimony may be an f32 tracer (TRACED_SCALAR_FIELDS): cast to the
    # working dtype so bf16/f16 scores don't get promoted to f32 (the
    # evolution scan carries scores at the search precision)
    par = jnp.asarray(options.parsimony, loss.dtype)
    return normalized + complexity.astype(loss.dtype) * par


def _custom_loss_trees(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    options: Options,
    row_idx: Optional[Array] = None,
) -> Array:
    """Custom full-tree objective, vmapped over the population (analog of the
    reference's eval_loss dispatch to a user loss_function,
    src/LossFunctions.jl:60-67)."""
    if row_idx is not None:
        X = X[:, row_idx]
        y = y[row_idx]
        weights = None if weights is None else weights[row_idx]
    batch_shape = trees.length.shape
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((-1,) + x.shape[len(batch_shape):]), trees
    )
    fn = lambda t: options.loss_function(t, X, y, weights, options)
    loss = jax.vmap(fn)(flat)
    loss = jnp.where(jnp.isfinite(loss), loss, jnp.inf)
    return loss.reshape(batch_shape)


def score_trees(
    trees: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    row_idx: Optional[Array] = None,
) -> Tuple[Array, Array]:
    """(score, loss) per tree — the batched `score_func`/`score_func_batch`."""
    if options.loss_function is not None:
        loss = _custom_loss_trees(trees, X, y, weights, options, row_idx)
    else:
        loss = eval_loss_trees(
            trees, X, y, weights, options.operators, options.elementwise_loss,
            row_idx, backend=options.eval_backend,
            program=options.kernel_program,
            leaf_skip=options.kernel_leaf_skip,
        )
    complexity = compute_complexity(trees, options)
    score = loss_to_score(loss, baseline, complexity, options)
    score = jnp.where(jnp.isfinite(loss), score, jnp.inf)
    return score, loss


def sample_batch_idx(key: Array, n_rows: int, batch_size: int) -> Array:
    """Minibatch rows sampled with replacement
    (reference src/LossFunctions.jl:100-103)."""
    return jax.random.randint(key, (batch_size,), 0, n_rows, dtype=jnp.int32)
