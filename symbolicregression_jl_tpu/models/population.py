"""Population & HallOfFame state + tournament selection.

Analogs: Population/PopMember (reference src/Population.jl:14-76,
src/PopMember.jl:9-67) and HallOfFame (src/HallOfFame.jl:11-88). State is a
struct-of-arrays NamedTuple so a whole island (and a whole mesh axis of
islands) is one pytree of rectangular arrays.

PopMember bookkeeping differences from the reference: `birth` is a
deterministic per-island counter instead of wall-clock time (reference
src/Utils.jl:18-30 uses time-of-day; the counter makes replace-oldest exact
and deterministic under jit), and lineage `ref` ids for the recorder are
assigned host-side when recording is enabled.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .complexity import compute_complexity
from .fitness import score_trees
from .mutate_device import gen_random_tree_fixed_size
from .options import Options
from .parsimony import RunningSearchStatistics, normalize
from .trees import TreeBatch

Array = jax.Array


class Population(NamedTuple):
    trees: TreeBatch  # fields (npop, L)
    scores: Array  # (npop,)
    losses: Array  # (npop,)
    birth: Array  # (npop,) int32

    @property
    def npop(self) -> int:
        return self.scores.shape[-1]


class HallOfFame(NamedTuple):
    """One slot per complexity 1..actual_maxsize
    (reference src/HallOfFame.jl:11-45)."""

    trees: TreeBatch  # fields (S, L)
    scores: Array  # (S,)
    losses: Array  # (S,)
    exists: Array  # (S,) bool


def init_hall_of_fame(options: Options, dtype=jnp.float32) -> HallOfFame:
    S = options.actual_maxsize
    L = options.max_len
    return HallOfFame(
        trees=TreeBatch(
            kind=jnp.zeros((S, L), jnp.int32),
            op=jnp.zeros((S, L), jnp.int32),
            feat=jnp.zeros((S, L), jnp.int32),
            cval=jnp.zeros((S, L), dtype),
            length=jnp.zeros((S,), jnp.int32),
        ),
        scores=jnp.full((S,), jnp.inf, dtype),
        losses=jnp.full((S,), jnp.inf, dtype),
        exists=jnp.zeros((S,), jnp.bool_),
    )


def init_population(
    key: Array,
    options: Options,
    nfeatures: int,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    npop: Optional[int] = None,
    nlength: int = 3,
    dtype=jnp.float32,
) -> Population:
    """Random initial population of small trees
    (reference src/Population.jl:31-46, npop x gen_random_tree(nlength))."""
    npop = npop or options.npop
    keys = jax.random.split(key, npop)
    trees = jax.vmap(
        lambda k: gen_random_tree_fixed_size(
            k, jnp.int32(nlength), nfeatures, options.operators,
            options.max_len, dtype,
        )
    )(keys)
    scores, losses = score_trees(trees, X, y, weights, baseline, options)
    return Population(
        trees=trees,
        scores=scores,
        losses=losses,
        birth=jnp.arange(npop, dtype=jnp.int32),
    )


# ---------------------------------------------------------------------------
# Tournament selection (reference src/Population.jl:72-132)
# ---------------------------------------------------------------------------


def tournament_winner(
    key: Array,
    pop: Population,
    stats_frequencies: Array,
    options: Options,
) -> Array:
    """One tournament: sample tournament_selection_n members without
    replacement, reweight scores by adaptive-parsimony frequency
    (score * exp(scaling * normalized_freq[complexity]), reference
    src/Population.jl:79-119), then pick the k-th best with the truncated
    geometric distribution p(1-p)^k (reference sample_tournament
    :122-132). Returns the population index of the winner."""
    n = options.tournament_selection_n
    k1, k2 = jax.random.split(key)
    idx = jax.random.choice(k1, pop.npop, (n,), replace=False)
    scores = pop.scores[idx]
    if options.use_frequency_in_tournament:
        complexity = compute_complexity(pop.trees[idx], options)
        freq = normalize(stats_frequencies)[
            jnp.clip(complexity - 1, 0, stats_frequencies.shape[0] - 1)
        ]
        # out-of-range sizes carry NO penalty in the reference
        # (frequency = 0 unless 0 < size <= maxsize — NOT actual_maxsize,
        # even though the histogram has maxsize+2 bins;
        # src/Population.jl:96-101) rather than the nearest bin's
        in_range = (complexity > 0) & (complexity <= options.maxsize)
        freq = jnp.where(in_range, freq, 0.0)
        scores = scores * jnp.exp(options.adaptive_parsimony_scaling * freq)
    order = jnp.argsort(scores)  # ascending: best first
    # tournament_selection_p may be a tracer (TRACED_SCALAR_FIELDS), so
    # clamp with jnp, not Python min
    p = jnp.minimum(options.tournament_selection_p, 1 - 1e-6)
    ranks = jnp.arange(n, dtype=jnp.int32)
    logits = ranks * jnp.log1p(-p) + jnp.log(p)
    pick = jax.random.categorical(k2, logits)
    return idx[order[pick]]


def best_sub_pop(pop: Population, topn: int) -> Tuple[TreeBatch, Array, Array]:
    """Top-n members by score (reference src/Population.jl:151-154).
    Returns (trees, scores, losses) of shape (topn, ...)."""
    order = jnp.argsort(pop.scores)[:topn]
    return pop.trees[order], pop.scores[order], pop.losses[order]


# ---------------------------------------------------------------------------
# Hall of fame updates & Pareto frontier
# ---------------------------------------------------------------------------


def update_hall_of_fame(
    hof: HallOfFame,
    trees: TreeBatch,
    scores: Array,
    losses: Array,
    options: Options,
) -> HallOfFame:
    """Merge a batch of candidates into the per-complexity best table
    (reference merge at src/SymbolicRegression.jl:722-744). For each
    complexity slot, keep the lowest-loss candidate if it beats the
    incumbent."""
    S = options.actual_maxsize
    complexity = compute_complexity(trees, options)  # (B,)
    slot = jnp.clip(complexity - 1, 0, S - 1)
    in_range = (complexity >= 1) & (complexity <= S) & jnp.isfinite(losses)

    # per-slot best candidate among the batch
    masked_loss = jnp.where(in_range[None, :] & (slot[None, :] == jnp.arange(S, dtype=jnp.int32)[:, None]),
                            losses[None, :], jnp.inf)  # (S, B)
    best_idx = jnp.argmin(masked_loss, axis=1)  # (S,)
    best_loss = jnp.take_along_axis(masked_loss, best_idx[:, None], axis=1)[:, 0]
    better = best_loss < hof.losses

    cand_trees = jax.tree_util.tree_map(lambda x: x[best_idx], trees)
    new_trees = jax.tree_util.tree_map(
        lambda c, h: jnp.where(
            jnp.reshape(better, better.shape + (1,) * (c.ndim - 1)), c, h
        ),
        cand_trees,
        hof.trees,
    )
    return HallOfFame(
        trees=new_trees,
        scores=jnp.where(better, scores[best_idx], hof.scores),
        losses=jnp.where(better, best_loss, hof.losses),
        exists=hof.exists | better,
    )


def merge_halls_of_fame(a: HallOfFame, b: HallOfFame) -> HallOfFame:
    """Elementwise per-slot min-loss merge (used for cross-island reduce)."""
    better = jnp.where(b.exists & ~a.exists, True, b.losses < a.losses)
    new_trees = jax.tree_util.tree_map(
        lambda x, y: jnp.where(
            jnp.reshape(better, better.shape + (1,) * (x.ndim - 1)), y, x
        ),
        a.trees,
        b.trees,
    )
    return HallOfFame(
        trees=new_trees,
        scores=jnp.where(better, b.scores, a.scores),
        losses=jnp.where(better, b.losses, a.losses),
        exists=a.exists | b.exists,
    )


def calculate_pareto_frontier(hof: HallOfFame) -> Array:
    """Boolean mask of hall-of-fame slots on the Pareto frontier: slots whose
    loss is strictly better than every smaller-complexity slot
    (reference src/HallOfFame.jl:58-88)."""
    S = hof.losses.shape[0]
    best_so_far = jax.lax.associative_scan(
        jnp.minimum, jnp.where(hof.exists, hof.losses, jnp.inf)
    )
    prev_best = jnp.concatenate(
        [jnp.full((1,), jnp.inf, best_so_far.dtype), best_so_far[:-1]]
    )
    return hof.exists & (jnp.where(hof.exists, hof.losses, jnp.inf) < prev_best)
