"""Flat postfix expression encoding — the TPU-native replacement for Node{T}.

The reference stores expressions as linked `Node{T}` binary trees
(DynamicExpressions.jl, imported at reference src/SymbolicRegression.jl:68-86)
and walks pointers. On TPU we need static shapes and gather/scan-friendly
layouts, so an expression is a fixed-width *postfix (RPN) program*:

    slot fields (all shape (L,)):
      kind : int32   PAD=0 | CONST=1 | VAR=2 | UNA=3 | BIN=4
      op   : int32   index into OperatorSet.unary_names / binary_names
      feat : int32   feature index for VAR nodes
      cval : float32 constant value for CONST nodes
    length : int32   number of valid slots; valid slots are [0, length)

Postfix order means children precede parents and every subtree is a
*contiguous span* [i - size(i) + 1, i], which makes crossover/mutation pure
gather arithmetic (see models/mutate_device.py) and evaluation a single
stack-machine scan (see ops/interpreter.py). A population is a stacked
TreeBatch with leading batch dims — `jax.vmap` / `shard_map` ready.

Host-side helpers here (Expr <-> arrays, parsing, printing) are the analog of
`string_tree` / `node_to_symbolic` (reference
src/InterfaceDynamicExpressions.jl:132-194) and are not on the hot path.
"""

from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.operators import INFIX, OperatorSet

Array = jax.Array

# Node kinds
PAD = 0
CONST = 1
VAR = 2
UNA = 3
BIN = 4

ARITY = np.array([0, 0, 0, 1, 2], dtype=np.int32)  # indexed by kind


class TreeBatch(NamedTuple):
    """A batch of postfix trees. All fields share leading batch dims.

    kind/op/feat: (..., L) int32; cval: (..., L) float; length: (...,) int32.
    """

    kind: Array
    op: Array
    feat: Array
    cval: Array
    length: Array

    @property
    def max_len(self) -> int:
        return self.kind.shape[-1]

    def __getitem__(self, idx) -> "TreeBatch":
        return TreeBatch(
            self.kind[idx], self.op[idx], self.feat[idx], self.cval[idx], self.length[idx]
        )


def empty_trees(batch_shape: Tuple[int, ...], max_len: int, dtype=jnp.float32) -> TreeBatch:
    shape = tuple(batch_shape) + (max_len,)
    return TreeBatch(
        kind=jnp.zeros(shape, jnp.int32),
        op=jnp.zeros(shape, jnp.int32),
        feat=jnp.zeros(shape, jnp.int32),
        cval=jnp.zeros(shape, dtype),
        length=jnp.zeros(batch_shape, jnp.int32),
    )


def stack_trees(trees: Sequence[TreeBatch]) -> TreeBatch:
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


# ---------------------------------------------------------------------------
# Host-side expression objects (for construction, printing, tests)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Expr:
    """Host-side expression node (test/UX only — never on the hot path)."""

    kind: int
    op: int = 0
    feat: int = 0
    cval: float = 0.0
    children: Tuple["Expr", ...] = ()

    @staticmethod
    def const(v: float) -> "Expr":
        return Expr(kind=CONST, cval=float(v))

    @staticmethod
    def var(i: int) -> "Expr":
        return Expr(kind=VAR, feat=int(i))

    @staticmethod
    def unary(op: int, child: "Expr") -> "Expr":
        return Expr(kind=UNA, op=int(op), children=(child,))

    @staticmethod
    def binary(op: int, left: "Expr", right: "Expr") -> "Expr":
        return Expr(kind=BIN, op=int(op), children=(left, right))

    def size(self) -> int:
        return 1 + sum(c.size() for c in self.children)

    def depth(self) -> int:
        if not self.children:
            return 1
        return 1 + max(c.depth() for c in self.children)

    def postfix(self) -> List["Expr"]:
        out: List[Expr] = []
        for c in self.children:
            out.extend(c.postfix())
        out.append(self)
        return out


def encode_tree(expr: Expr, max_len: int, dtype=np.float32) -> TreeBatch:
    """Expr -> single postfix TreeBatch (batch shape ())."""
    nodes = expr.postfix()
    n = len(nodes)
    if n > max_len:
        raise ValueError(f"Expression size {n} exceeds max_len {max_len}")
    kind = np.zeros(max_len, np.int32)
    op = np.zeros(max_len, np.int32)
    feat = np.zeros(max_len, np.int32)
    cval = np.zeros(max_len, dtype)
    for i, nd in enumerate(nodes):
        kind[i], op[i], feat[i], cval[i] = nd.kind, nd.op, nd.feat, nd.cval
    return TreeBatch(
        kind=jnp.asarray(kind),
        op=jnp.asarray(op),
        feat=jnp.asarray(feat),
        cval=jnp.asarray(cval),
        length=jnp.asarray(n, jnp.int32),
    )


def decode_tree(tree: TreeBatch) -> Expr:
    """Single postfix TreeBatch (batch shape ()) -> Expr. Validates arity."""
    kind = np.asarray(tree.kind)
    op = np.asarray(tree.op)
    feat = np.asarray(tree.feat)
    cval = np.asarray(tree.cval)
    n = int(tree.length)
    stack: List[Expr] = []
    for i in range(n):
        k = int(kind[i])
        if k == CONST:
            stack.append(Expr.const(float(cval[i])))
        elif k == VAR:
            stack.append(Expr.var(int(feat[i])))
        elif k == UNA:
            if not stack:
                raise ValueError(f"Invalid postfix: unary at {i} with empty stack")
            a = stack.pop()
            stack.append(Expr.unary(int(op[i]), a))
        elif k == BIN:
            if len(stack) < 2:
                raise ValueError(f"Invalid postfix: binary at {i} with stack<2")
            b = stack.pop()
            a = stack.pop()
            stack.append(Expr.binary(int(op[i]), a, b))
        elif k == PAD:
            raise ValueError(f"PAD inside valid region at slot {i}")
        else:
            raise ValueError(f"Bad kind {k} at slot {i}")
    if len(stack) != 1:
        raise ValueError(f"Invalid postfix: stack size {len(stack)} at end")
    return stack[0]


def is_valid_postfix(tree: TreeBatch) -> bool:
    """Host-side validity check used by tests."""
    try:
        decode_tree(tree)
        return True
    except ValueError:
        return False


# ---------------------------------------------------------------------------
# Printing / parsing (analog of string_tree, reference
# src/InterfaceDynamicExpressions.jl:132-153)
# ---------------------------------------------------------------------------


def _format_const(v: float) -> str:
    return f"{v:.6g}"


def expr_to_string(
    expr: Expr,
    operators: OperatorSet,
    variable_names: Optional[Sequence[str]] = None,
) -> str:
    def vname(i: int) -> str:
        if variable_names is not None:
            return variable_names[i]
        return f"x{i}"  # reference prints 1-indexed x1..; we use x0.. (Python)

    def rec(e: Expr) -> str:
        if e.kind == CONST:
            return _format_const(e.cval)
        if e.kind == VAR:
            return vname(e.feat)
        if e.kind == UNA:
            name = operators.unary_names[e.op]
            return f"{name}({rec(e.children[0])})"
        name = operators.binary_names[e.op]
        l, r = rec(e.children[0]), rec(e.children[1])
        if name in INFIX:
            return f"({l} {name} {r})"
        return f"{name}({l}, {r})"

    return rec(expr)


def tree_to_string(
    tree: TreeBatch,
    operators: OperatorSet,
    variable_names: Optional[Sequence[str]] = None,
) -> str:
    return expr_to_string(decode_tree(tree), operators, variable_names)


def parse_expression(
    s: str,
    operators: OperatorSet,
    variable_names: Optional[Sequence[str]] = None,
) -> Expr:
    """Parse an infix expression string back into an Expr.

    Supports the grammar produced by expr_to_string: infix + - * / ^ with
    standard precedence, function calls, unary minus, floats, and variable
    names (default x0, x1, ...).
    """
    import re

    tokens = re.findall(r"[A-Za-z_][A-Za-z_0-9]*|\d+\.?\d*(?:[eE][+-]?\d+)?|\S", s)
    pos = 0

    def peek() -> Optional[str]:
        return tokens[pos] if pos < len(tokens) else None

    def take() -> str:
        nonlocal pos
        t = tokens[pos]
        pos += 1
        return t

    def var_index(name: str) -> Optional[int]:
        if variable_names is not None and name in variable_names:
            return list(variable_names).index(name)
        m = re.fullmatch(r"x(\d+)", name)
        if m and variable_names is None:
            return int(m.group(1))
        return None

    def expect(tok: str) -> None:
        got = take() if pos < len(tokens) else "<eof>"
        if got != tok:
            raise ValueError(f"Expected {tok!r}, got {got!r} in {s!r}")

    def parse_primary() -> Expr:
        if pos >= len(tokens):
            raise ValueError(f"Unexpected end of expression in {s!r}")
        t = take()
        if t == "(":
            e = parse_sum()
            expect(")")
            return e
        if t == "-":
            child = parse_primary()
            if child.kind == CONST:
                return Expr.const(-child.cval)
            try:
                return Expr.unary(operators.unary_index("neg"), child)
            except ValueError:
                return Expr.binary(
                    operators.binary_index("-"), Expr.const(0.0), child
                )
        if re.fullmatch(r"\d+\.?\d*(?:[eE][+-]?\d+)?", t):
            return Expr.const(float(t))
        # identifier: function call or variable
        if peek() == "(":
            take()
            args = [parse_sum()]
            while peek() == ",":
                take()
                args.append(parse_sum())
            expect(")")
            if len(args) == 1:
                return Expr.unary(operators.unary_index(t), args[0])
            return Expr.binary(operators.binary_index(t), args[0], args[1])
        vi = var_index(t)
        if vi is None:
            raise ValueError(f"Unknown identifier {t!r}")
        return Expr.var(vi)

    def parse_power() -> Expr:
        base = parse_primary()
        if peek() == "^":
            take()
            exp = parse_power()  # right-assoc
            return Expr.binary(operators.binary_index("^"), base, exp)
        return base

    def parse_product() -> Expr:
        e = parse_power()
        while peek() in ("*", "/"):
            t = take()
            rhs = parse_power()
            e = Expr.binary(operators.binary_index(t), e, rhs)
        return e

    def parse_sum() -> Expr:
        e = parse_product()
        while peek() in ("+", "-"):
            t = take()
            rhs = parse_product()
            e = Expr.binary(operators.binary_index(t), e, rhs)
        return e

    out = parse_sum()
    if pos != len(tokens):
        raise ValueError(f"Trailing tokens: {tokens[pos:]}")
    return out


# ---------------------------------------------------------------------------
# Device-side structural queries (jittable; used by mutation + constraints)
# ---------------------------------------------------------------------------


def subtree_sizes(kind: Array, length: Array) -> Array:
    """Per-slot subtree sizes via a stack scan. Shape (L,) int32.

    For slot i holding a node of arity a, size[i] = 1 + sum of sizes of its
    a children (which are the top a completed subtrees before i). PAD slots
    get size 0. Jittable; vmap over batch dims.
    """
    L = kind.shape[-1]
    arity = jnp.asarray(ARITY)[kind]

    def step(carry, x):
        stack, sp = carry  # stack of subtree sizes, stack pointer
        a, valid = x
        top1 = stack[jnp.maximum(sp - 1, 0)]
        top2 = stack[jnp.maximum(sp - 2, 0)]
        size = 1 + jnp.where(a >= 1, top1, 0) + jnp.where(a == 2, top2, 0)
        new_sp = jnp.where(valid, sp - a + 1, sp)
        write_at = jnp.maximum(new_sp - 1, 0)
        new_stack = jnp.where(valid, stack.at[write_at].set(size), stack)
        out = jnp.where(valid, size, 0)
        return (new_stack, new_sp), out

    init_stack = jnp.zeros(L // 2 + 2, jnp.int32)
    idx = jnp.arange(L, dtype=jnp.int32)
    valid = idx < length
    (_, _), sizes = jax.lax.scan(step, (init_stack, jnp.int32(0)), (arity, valid))
    return sizes


def node_depths(kind: Array, length: Array) -> Array:
    """Per-slot subtree *depth* (height) via the same stack scan."""
    L = kind.shape[-1]
    arity = jnp.asarray(ARITY)[kind]

    def step(carry, x):
        stack, sp = carry
        a, valid = x
        top1 = stack[jnp.maximum(sp - 1, 0)]
        top2 = stack[jnp.maximum(sp - 2, 0)]
        d = 1 + jnp.maximum(jnp.where(a >= 1, top1, 0), jnp.where(a == 2, top2, 0))
        new_sp = jnp.where(valid, sp - a + 1, sp)
        write_at = jnp.maximum(new_sp - 1, 0)
        new_stack = jnp.where(valid, stack.at[write_at].set(d), stack)
        return (new_stack, new_sp), jnp.where(valid, d, 0)

    init_stack = jnp.zeros(L // 2 + 2, jnp.int32)
    idx = jnp.arange(L, dtype=jnp.int32)
    valid = idx < length
    (_, _), depths = jax.lax.scan(step, (init_stack, jnp.int32(0)), (arity, valid))
    return depths


def tree_depth(kind: Array, length: Array) -> Array:
    """Depth of the whole tree (root = slot length-1)."""
    depths = node_depths(kind, length)
    return depths[jnp.maximum(length - 1, 0)]


def count_constants(tree: TreeBatch) -> Array:
    idx = jnp.arange(tree.max_len, dtype=jnp.int32)
    valid = idx < tree.length[..., None]
    return jnp.sum((tree.kind == CONST) & valid, axis=-1)


def get_constants(tree: TreeBatch) -> Tuple[Array, Array]:
    """Return (cval, is_const_mask) — the analog of get_constants/set_constants
    (reference DynamicExpressions API, imported at src/SymbolicRegression.jl:68-86).
    Constants stay in-place in the cval field; mask selects them."""
    idx = jnp.arange(tree.max_len, dtype=jnp.int32)
    valid = idx < tree.length[..., None]
    mask = (tree.kind == CONST) & valid
    return tree.cval, mask


def set_constants(tree: TreeBatch, cval: Array) -> TreeBatch:
    _, mask = get_constants(tree)
    return tree._replace(cval=jnp.where(mask, cval, tree.cval))


def tree_hash(tree: TreeBatch) -> "np.ndarray":
    """Content hash of the program(s) — the analog of Node hashing in the
    reference's expression engine (exercised by its test/test_hash.jl).

    Only the `length` live slots (plus length itself) feed the digest, so
    two encodings of the same program hash equal regardless of padded-tail
    garbage AND of the encoding's max_len (the flat encoding's version of
    pointer-identity-free structural hashing). Works on a single tree
    (returns a 0-d uint64 array) or any batch shape. Host-side (numpy);
    not jittable.

    The evaluation memo bank needs the same canonicalization contract but
    a digest computable INSIDE jitted graphs: cache/hashing.py implements
    a two-lane FNV fold as `tree_hash_device` (jnp) with a bit-identical
    numpy twin `tree_hash_host`. This blake2b digest stays the recorder's
    lineage-ref format; the FNV pair is the cache key format — both honor
    the dead-field/padded-tail rules asserted by tests/test_hash.py."""
    kind = np.ascontiguousarray(tree.kind, dtype=np.int32)
    op = np.ascontiguousarray(tree.op, dtype=np.int32)
    feat = np.ascontiguousarray(tree.feat, dtype=np.int32)
    cval = np.asarray(tree.cval, dtype=np.float64)
    length = np.asarray(tree.length, dtype=np.int32)

    # leaf/unary slots: op/feat fields that the node kind ignores are noise
    op = np.where(kind >= UNA, op, 0).astype(np.int32)
    feat = np.where(kind == VAR, feat, 0).astype(np.int32)
    cval = np.where(kind == CONST, cval, 0.0)

    import hashlib

    flat_shape = kind.shape[:-1]
    out = np.empty(flat_shape, dtype=np.uint64)
    for i in np.ndindex(flat_shape):
        n = int(length[i])
        h = hashlib.blake2b(digest_size=8)
        h.update(np.int32(n).tobytes())
        h.update(kind[i][:n].tobytes())
        h.update(op[i][:n].tobytes())
        h.update(feat[i][:n].tobytes())
        h.update(cval[i][:n].tobytes())
        out[i] = np.frombuffer(h.digest(), dtype=np.uint64)[0]
    return out[()] if flat_shape == () else out
