"""Adaptive parsimony running statistics
(reference src/AdaptiveParsimony.jl:20-95).

A per-complexity frequency histogram over recently-seen expressions, used to
(a) scale tournament fitness by `exp(scaling * normalized_freq)` and (b) bias
mutation acceptance by the old/new size frequency ratio. Pure-array, jittable:
state is a float vector of length actual_maxsize, updated by scatter-add and
decayed toward a fixed window mass.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

Array = jax.Array

WINDOW_SIZE = 100000.0  # reference src/AdaptiveParsimony.jl:29


def normalize(frequencies: Array) -> Array:
    """Frequency vector normalized to sum 1 (the reference's
    normalized_frequencies, src/AdaptiveParsimony.jl:91-95) — the single
    owner of the 1e-9 clamp for every consumer (tournament rescale,
    acceptance-gate ratio, stats property)."""
    return frequencies / jnp.maximum(jnp.sum(frequencies), 1e-9)


class RunningSearchStatistics(NamedTuple):
    frequencies: Array  # (actual_maxsize,) float32
    window_size: float = WINDOW_SIZE

    @property
    def normalized(self) -> Array:
        return normalize(self.frequencies)


def init_search_statistics(actual_maxsize: int) -> RunningSearchStatistics:
    # Reference initializes all-ones (src/AdaptiveParsimony.jl:26-33).
    return RunningSearchStatistics(
        frequencies=jnp.ones(actual_maxsize, jnp.float32)
    )


def update_frequencies(
    stats: RunningSearchStatistics, complexities: Array
) -> RunningSearchStatistics:
    """Scatter-add 1 at each observed complexity
    (reference src/AdaptiveParsimony.jl:42-49). complexities is any-shape
    int array; out-of-range sizes are dropped."""
    size = stats.frequencies.shape[0]
    c = complexities.reshape(-1) - 1  # complexity 1 -> slot 0
    valid = (c >= 0) & (c < size)
    c = jnp.clip(c, 0, size - 1)
    freqs = stats.frequencies.at[c].add(jnp.where(valid, 1.0, 0.0))
    return stats._replace(frequencies=freqs)


def move_window(stats: RunningSearchStatistics) -> RunningSearchStatistics:
    """Decay total mass back to window_size, preferring to shrink the
    largest bins — approximated here by proportional scaling (the reference
    uses an iterative per-bin shave, src/AdaptiveParsimony.jl:57-89; the
    fixed point of both is the same proportional cap)."""
    tot = jnp.sum(stats.frequencies)
    # SR009 form: clamp the divisor — an empty stats table (tot = 0)
    # would compute 0/0 = NaN in the untaken branch. Bit-identical:
    # the selected lanes require tot > window_size >= the clamp floor.
    scale = jnp.where(
        tot > stats.window_size,
        stats.window_size / jnp.maximum(tot, 1e-9),
        1.0,
    )
    return stats._replace(frequencies=stats.frequencies * scale)


def normalize_frequencies(stats: RunningSearchStatistics) -> Array:
    """(reference src/AdaptiveParsimony.jl:91-95)"""
    return stats.normalized
