"""Dataset container (reference src/Dataset.jl:24-64).

Holds X (nfeatures, n), y (n,), optional weights, variable names, the
weighted mean of y (`avg_y`) and the baseline loss of the constant
predictor avg_y (reference src/LossFunctions.jl:122-126), which normalizes
all scores.

Arrays live as jnp device arrays; on the TPU build the rows dimension may be
sharded over the mesh's row axis (the analog of the reference's `batching`
advice for >10k rows, src/Configure.jl:63-70).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@dataclasses.dataclass
class Dataset:
    X: Array  # (nfeatures, n)
    y: Array  # (n,)
    weights: Optional[Array] = None  # (n,)
    variable_names: Optional[Tuple[str, ...]] = None
    avg_y: float = 0.0
    baseline_loss: float = 1.0

    @property
    def n(self) -> int:
        return self.X.shape[1]

    @property
    def nfeatures(self) -> int:
        return self.X.shape[0]


def make_dataset(
    X,
    y,
    weights=None,
    variable_names: Optional[Sequence[str]] = None,
    dtype=jnp.float32,
) -> Dataset:
    X = jnp.asarray(X, dtype)
    y = jnp.asarray(y, dtype)
    if X.ndim != 2:
        raise ValueError("X must be (nfeatures, n)")
    if y.shape != (X.shape[1],):
        raise ValueError(f"y shape {y.shape} != (n,) = ({X.shape[1]},)")
    w = None
    if weights is not None:
        w = jnp.asarray(weights, dtype)
        if w.shape != y.shape:
            raise ValueError("weights must match y shape")
    if w is None:
        avg_y = float(jnp.mean(y))
    else:
        avg_y = float(jnp.sum(y * w) / jnp.sum(w))
    names = tuple(variable_names) if variable_names is not None else None
    if names is not None and len(names) != X.shape[0]:
        raise ValueError("variable_names length must equal nfeatures")
    return Dataset(X=X, y=y, weights=w, variable_names=names, avg_y=avg_y)


def update_baseline_loss(dataset: Dataset, options_or_loss) -> Dataset:
    """Score the constant predictor avg_y
    (reference src/LossFunctions.jl:122-126).

    Accepts either an elementwise loss callable or an Options; with an
    Options whose loss_function is set, the baseline goes through the
    custom full-tree objective on an encoded constant tree (the reference
    dispatches eval_loss -> loss_function for the baseline member too,
    src/LossFunctions.jl:60-67)."""
    loss_function = getattr(options_or_loss, "loss_function", None)
    if loss_function is not None:
        from .trees import Expr, encode_tree

        options = options_or_loss
        const_tree = jax.tree_util.tree_map(
            jnp.asarray,
            encode_tree(Expr.const(float(dataset.avg_y)), options.max_len),
        )
        base = float(
            loss_function(
                const_tree, dataset.X, dataset.y, dataset.weights, options
            )
        )
    else:
        elementwise_loss = (
            options_or_loss.elementwise_loss
            if hasattr(options_or_loss, "elementwise_loss")
            else options_or_loss
        )
        pred = jnp.full_like(dataset.y, dataset.avg_y)
        elem = elementwise_loss(pred, dataset.y)
        if dataset.weights is None:
            base = float(jnp.mean(elem))
        else:
            base = float(
                jnp.sum(elem * dataset.weights) / jnp.sum(dataset.weights)
            )
    dataset.baseline_loss = base if np.isfinite(base) and base > 0 else 1.0
    return dataset
