"""Dataset container (reference src/Dataset.jl:24-64).

Holds X (nfeatures, n), y (n,), optional weights, variable names, the
weighted mean of y (`avg_y`) and the baseline loss of the constant
predictor avg_y (reference src/LossFunctions.jl:122-126), which normalizes
all scores.

Arrays live as jnp device arrays; on the TPU build the rows dimension may be
sharded over the mesh's row axis (the analog of the reference's `batching`
advice for >10k rows, src/Configure.jl:63-70).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

# |value| above this in a float32 search is a scale hazard: one squaring
# (the single most common GP sub-expression) overflows to inf, so every
# tree touching the column scores the inf sentinel. sqrt(f32 max) ~ 1.8e19.
SCALE_HAZARD_ABS = float(np.sqrt(np.finfo(np.float32).max))


class HostileDatasetError(ValueError):
    """Raised by ``sanitize_dataset`` under ``data_policy='reject'`` when
    validation finds hard errors. Carries the full structured report in
    ``.diagnostics`` so a job server can return it to the tenant instead
    of a stringified traceback."""

    def __init__(self, message: str, diagnostics: "DatasetDiagnostics"):
        super().__init__(message)
        self.diagnostics = diagnostics


@dataclasses.dataclass
class DatasetDiagnostics:
    """Structured result of :func:`validate_dataset` — the machine-readable
    half of the hostile-data front door (docs/robustness_numeric.md).

    ``errors`` are findings that poison a search outright (non-finite
    cells, no usable rows, degenerate weights): fatal under
    ``data_policy='reject'``, repaired/masked under the other policies.
    ``warnings`` are findings a search survives but an operator should
    see (constant target, degenerate feature columns, scale hazards):
    reported under every policy, never fatal."""

    n_rows: int = 0
    n_features: int = 0
    n_outputs: int = 1
    # non-finite census
    nonfinite_x_cells: int = 0
    nonfinite_y_cells: int = 0
    nonfinite_weight_cells: int = 0
    bad_rows: int = 0              # rows with ANY non-finite cell
    bad_row_fraction: float = 0.0
    # degeneracy
    constant_y_outputs: List[int] = dataclasses.field(default_factory=list)
    degenerate_features: List[int] = dataclasses.field(default_factory=list)
    duplicate_rows: int = 0
    # dtype/scale hazards
    scale_hazard_features: List[int] = dataclasses.field(
        default_factory=list
    )
    scale_hazard_y: bool = False
    # finite input values that became non-finite in the working dtype
    # (e.g. float64 1e40 cast to float32): stamped by equation_search's
    # front door so the report names the cast, not phantom NaN/Inf in
    # the caller's data
    cast_overflow_cells: int = 0
    nonpositive_weights: int = 0
    # verdicts
    errors: List[str] = dataclasses.field(default_factory=list)
    warnings: List[str] = dataclasses.field(default_factory=list)
    # what sanitize_dataset actually did (policy provenance)
    policy: Optional[str] = None
    masked_rows: int = 0
    repaired_cells: int = 0

    @property
    def ok(self) -> bool:
        return not self.errors

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


def validate_dataset(X, ys, weights=None) -> DatasetDiagnostics:
    """Host-side (numpy) validation of a search dataset: the front door
    every ``equation_search`` call passes through BEFORE any jitted
    program sees the data. X is (nfeatures, n); ys is (n,) or (nout, n);
    weights optional (n,). Read-only — returns the census, never
    modifies (``sanitize_dataset`` acts on it)."""
    X = np.asarray(X)
    ys = np.asarray(ys)
    if ys.ndim == 1:
        ys = ys[None, :]
    w = None if weights is None else np.asarray(weights)
    d = DatasetDiagnostics(
        n_rows=int(X.shape[1]), n_features=int(X.shape[0]),
        n_outputs=int(ys.shape[0]),
    )

    if w is not None and w.shape != (d.n_rows,):
        # a malformed weights vector is exactly the class of hostile
        # tenant input the front door exists to diagnose — report it
        # structurally instead of letting the census crash on a raw
        # numpy broadcast error
        d.errors.append(
            f"weights shape {tuple(w.shape)} must be (n,) = "
            f"({d.n_rows},)"
        )
        w = None  # weight-dependent census skipped

    fin_x = np.isfinite(X)
    fin_y = np.isfinite(ys)
    d.nonfinite_x_cells = int((~fin_x).sum())
    d.nonfinite_y_cells = int((~fin_y).sum())
    bad_row = ~fin_x.all(axis=0) | ~fin_y.all(axis=0)
    if w is not None:
        fin_w = np.isfinite(w)
        d.nonfinite_weight_cells = int((~fin_w).sum())
        d.nonpositive_weights = int((w[fin_w] < 0).sum())
        bad_row = bad_row | ~fin_w
    d.bad_rows = int(bad_row.sum())
    d.bad_row_fraction = (
        d.bad_rows / d.n_rows if d.n_rows else 0.0
    )

    # --- hard errors: data that poisons the lockstep evaluation ---
    if d.n_rows == 0:
        d.errors.append("dataset has zero rows")
    if d.nonfinite_x_cells:
        d.errors.append(
            f"{d.nonfinite_x_cells} non-finite cell(s) in X "
            f"({d.bad_rows} row(s) affected): every tree touching them "
            "evaluates non-finite and scores the inf sentinel"
        )
    if d.nonfinite_y_cells:
        d.errors.append(
            f"{d.nonfinite_y_cells} non-finite target value(s): the "
            "elementwise loss is non-finite on those rows for every tree"
        )
    if d.nonfinite_weight_cells:
        d.errors.append(
            f"{d.nonfinite_weight_cells} non-finite weight(s)"
        )
    if d.nonpositive_weights:
        d.errors.append(
            f"{d.nonpositive_weights} negative weight(s): weighted-mean "
            "aggregation is undefined for them"
        )
    if d.n_rows and d.bad_rows == d.n_rows:
        d.errors.append("every row has a non-finite cell — no usable rows")
    if w is not None and d.n_rows:
        finite_w = w[np.isfinite(w)]
        if finite_w.size and not (finite_w > 0).any():
            d.errors.append(
                "weights sum to zero: no row carries loss weight"
            )

    # --- warnings: survivable but worth an operator's attention ---
    good = ~bad_row
    for j in range(d.n_outputs):
        yj = ys[j][good]
        yj = yj[np.isfinite(yj)]
        if yj.size and float(yj.max() - yj.min()) == 0.0:
            d.constant_y_outputs.append(j)
    if d.constant_y_outputs:
        outs = d.constant_y_outputs
        d.warnings.append(
            f"constant target (zero variance) on output(s) {outs}: the "
            "baseline predictor is already exact; baseline loss falls "
            "back to 1.0 and scores are uninformative"
        )
    for i in range(d.n_features):
        col = X[i][good] if d.n_rows else X[i]
        col = col[np.isfinite(col)]
        if col.size == 0 or float(col.max() - col.min()) == 0.0:
            d.degenerate_features.append(i)
    if d.degenerate_features:
        d.warnings.append(
            f"degenerate feature column(s) {d.degenerate_features} "
            "(constant or no finite values over the usable rows): they "
            "carry no signal and enlarge the search space"
        )
    for i in range(d.n_features):
        col = X[i][np.isfinite(X[i])]
        if col.size and float(np.abs(col).max()) > SCALE_HAZARD_ABS:
            d.scale_hazard_features.append(i)
    fin_y_vals = ys[np.isfinite(ys)]
    d.scale_hazard_y = bool(
        fin_y_vals.size
        and float(np.abs(fin_y_vals).max()) > SCALE_HAZARD_ABS
    )
    if d.scale_hazard_features or d.scale_hazard_y:
        where = []
        if d.scale_hazard_features:
            where.append(f"feature(s) {d.scale_hazard_features}")
        if d.scale_hazard_y:
            where.append("the target")
        d.warnings.append(
            f"|values| above {SCALE_HAZARD_ABS:.2g} in {' and '.join(where)}:"
            " a single squaring overflows float32 — most trees touching "
            "them will score the inf sentinel (consider rescaling)"
        )
    if 0 < d.n_rows <= 100_000 and d.n_features:
        # duplicate-row census (cheap hash over the usable rows); a
        # heavily duplicated dataset wastes eval rows and biases the loss
        rows = np.ascontiguousarray(X.T)
        uniq = np.unique(
            rows[good] if d.n_rows else rows, axis=0
        ).shape[0]
        d.duplicate_rows = int(max(0, good.sum() - uniq))
        if d.duplicate_rows > good.sum() // 2:
            d.warnings.append(
                f"{d.duplicate_rows} duplicate row(s) among "
                f"{int(good.sum())} usable rows"
            )
    return d


def sanitize_dataset(
    X,
    ys,
    weights,
    policy: str,
    diagnostics: Optional[DatasetDiagnostics] = None,
):
    """Apply ``Options.data_policy`` to a validated dataset. Returns
    ``(X, ys, weights, diagnostics)`` with numpy arrays (dtype preserved).
    A clean dataset passes through UNTOUCHED under every policy — same
    objects, no weights invented — so the clean-data search is
    bit-identical across policies (asserted in tests).

    reject — raise :class:`HostileDatasetError` when validation found
    hard errors (warnings never raise).

    mask — rows with any non-finite cell leave the loss through the
    existing weights path (weight 0) and their cells are replaced with
    finite placeholders (feature-column finite mean; per-output finite
    target mean) so the lockstep evaluation of EVERY tree stays finite
    on them; a zero-weight row then contributes exactly 0 to the
    weighted loss sum. Raises only when masking cannot produce a usable
    dataset (all rows bad).

    repair — non-finite X cells are imputed cell-wise with the column's
    finite mean and the row STAYS live (full weight); rows whose target
    or weight is non-finite fall back to masking — a target is never
    invented. Scale hazards are reported, never clamped (legitimate
    wide-range data must not be silently rewritten)."""
    d = diagnostics or validate_dataset(X, ys, weights)
    d.policy = policy
    if policy == "reject":
        if d.errors:
            raise HostileDatasetError(
                "hostile dataset rejected (data_policy='reject'): "
                + "; ".join(d.errors)
                + " — use data_policy='mask' or 'repair' to search "
                "anyway (docs/robustness_numeric.md)",
                d,
            )
        return X, ys, weights, d

    X_in, ys_orig, w_in = X, ys, weights
    X = np.asarray(X)
    ys_in = np.asarray(ys)
    multi = ys_in.ndim == 2
    ys2 = ys_in if multi else ys_in[None, :]
    w = None if weights is None else np.asarray(weights)
    changed = False

    structural = [
        e for e in d.errors
        if "zero rows" in e or "sum to zero" in e
        or "negative weight" in e or "weights shape" in e
        # "no usable rows" is structural for MASK (masking every row
        # leaves nothing) but NOT for repair: cell-wise imputation can
        # bring X-only-bad rows back alive, and the genuinely-unusable
        # outcome (every row still masked after repair) is caught by
        # the no-positively-weighted-rows guard below
        or (policy == "mask" and "no usable rows" in e)
    ]
    if structural:
        raise HostileDatasetError(
            f"dataset unusable under data_policy={policy!r}: "
            + "; ".join(structural),
            d,
        )

    fin_x = np.isfinite(X)
    fin_y = np.isfinite(ys2)
    bad_w = np.zeros(X.shape[1], bool) if w is None else ~np.isfinite(w)

    def _col_fill(row_vals, fin):
        vals = row_vals[fin]
        return vals.mean() if vals.size else np.asarray(0.0, X.dtype)

    if policy == "repair" and not fin_x.all():
        # cell-wise imputation: the row stays live unless y/w is bad
        # too. Only columns that HAVE finite values are imputed (a mean
        # exists to impute FROM); a column with no finite values would
        # be invented data wholesale — its cells stay non-finite and
        # the rows fall through to masking below.
        X = X.copy()
        changed = True
        repaired = 0
        for i in np.where(~fin_x.all(axis=1))[0]:
            if fin_x[i].any():
                repaired += int((~fin_x[i]).sum())
                X[i, ~fin_x[i]] = _col_fill(X[i], fin_x[i])
        d.repaired_cells = repaired
        fin_x = np.isfinite(X)

    # rows that must leave the loss: any remaining non-finite cell
    mask_rows = ~fin_x.all(axis=0) | ~fin_y.all(axis=0) | bad_w
    if mask_rows.any():
        changed = True
        X = X.copy()
        ys2 = ys2.copy()
        for i in range(X.shape[0]):
            col_bad = mask_rows & ~np.isfinite(X[i])
            if col_bad.any():
                X[i, col_bad] = _col_fill(X[i], np.isfinite(X[i]))
        for j in range(ys2.shape[0]):
            row_bad = mask_rows & ~np.isfinite(ys2[j])
            if row_bad.any():
                ys2[j, row_bad] = _col_fill(ys2[j], np.isfinite(ys2[j]))
        if w is None:
            w = np.ones(X.shape[1], X.dtype)
        else:
            w = w.copy()
        w[mask_rows] = 0
        d.masked_rows = int(mask_rows.sum())
        if not (np.asarray(w)[~mask_rows] > 0).any():
            raise HostileDatasetError(
                f"data_policy={policy!r} left no positively-weighted "
                "usable rows",
                d,
            )
    if not changed:
        # clean data passes through UNTOUCHED (the very objects the
        # caller handed in): bit-identity across policies by identity
        return X_in, ys_orig, w_in, d
    return X, (ys2 if multi else ys2[0]), w, d


@dataclasses.dataclass
class Dataset:
    X: Array  # (nfeatures, n)
    y: Array  # (n,)
    weights: Optional[Array] = None  # (n,)
    variable_names: Optional[Tuple[str, ...]] = None
    avg_y: float = 0.0
    baseline_loss: float = 1.0

    @property
    def n(self) -> int:
        return self.X.shape[1]

    @property
    def nfeatures(self) -> int:
        return self.X.shape[0]


def make_dataset(
    X,
    y,
    weights=None,
    variable_names: Optional[Sequence[str]] = None,
    dtype=jnp.float32,
) -> Dataset:
    X = jnp.asarray(X, dtype)
    y = jnp.asarray(y, dtype)
    if X.ndim != 2:
        raise ValueError("X must be (nfeatures, n)")
    if y.shape != (X.shape[1],):
        raise ValueError(f"y shape {y.shape} != (n,) = ({X.shape[1]},)")
    w = None
    if weights is not None:
        w = jnp.asarray(weights, dtype)
        if w.shape != y.shape:
            raise ValueError("weights must match y shape")
    if w is None:
        avg_y = float(jnp.mean(y))
    else:
        avg_y = float(jnp.sum(y * w) / jnp.sum(w))
    names = tuple(variable_names) if variable_names is not None else None
    if names is not None and len(names) != X.shape[0]:
        raise ValueError("variable_names length must equal nfeatures")
    return Dataset(X=X, y=y, weights=w, variable_names=names, avg_y=avg_y)


def update_baseline_loss(dataset: Dataset, options_or_loss) -> Dataset:
    """Score the constant predictor avg_y
    (reference src/LossFunctions.jl:122-126).

    Accepts either an elementwise loss callable or an Options; with an
    Options whose loss_function is set, the baseline goes through the
    custom full-tree objective on an encoded constant tree (the reference
    dispatches eval_loss -> loss_function for the baseline member too,
    src/LossFunctions.jl:60-67)."""
    loss_function = getattr(options_or_loss, "loss_function", None)
    if loss_function is not None:
        from .trees import Expr, encode_tree

        options = options_or_loss
        const_tree = jax.tree_util.tree_map(
            jnp.asarray,
            encode_tree(Expr.const(float(dataset.avg_y)), options.max_len),
        )
        base = float(
            loss_function(
                const_tree, dataset.X, dataset.y, dataset.weights, options
            )
        )
    else:
        elementwise_loss = (
            options_or_loss.elementwise_loss
            if hasattr(options_or_loss, "elementwise_loss")
            else options_or_loss
        )
        pred = jnp.full_like(dataset.y, dataset.avg_y)
        elem = elementwise_loss(pred, dataset.y)
        if dataset.weights is None:
            base = float(jnp.mean(elem))
        else:
            base = float(
                jnp.sum(elem * dataset.weights) / jnp.sum(dataset.weights)
            )
    dataset.baseline_loss = base if np.isfinite(base) and base > 0 else 1.0
    return dataset


def load_csv_dataset(
    path: str,
    target: "str | int" = -1,
    delimiter: Optional[str] = None,
    weights_column: "Optional[str | int]" = None,
    dtype=jnp.float32,
) -> Dataset:
    """Load a Dataset from a numeric CSV/TSV file.

    Rows are samples, columns are features; `target` picks the y column by
    header name or index (default: last column). Parsing goes through the
    C++ host runtime (native/srtpu_native.cpp srt_csv_*) when built, with a
    numpy fallback. Column names become variable_names.
    """
    from .. import native

    data = None
    names = None
    loaded = native.load_csv(path, delimiter) if native.native_available() else None
    if loaded is not None:
        data, names = loaded
    else:
        # numpy fallback: sniff a header line
        with open(path) as f:
            first = f.readline()
        delim = delimiter
        if delim is None:
            # space is a last resort: header names may contain spaces
            delim = max(",;\t", key=first.count) if first else ","
            if first.count(delim) == 0:
                delim = " "
        fields = [c.strip() for c in first.strip().split(delim)]

        def _is_num(s):
            try:
                float(s)
                return True
            except ValueError:
                return False

        has_header = any(not _is_num(c) for c in fields if c)
        if has_header:
            # keep positional alignment with data columns; name blanks
            names = [c if c else f"col{i}" for i, c in enumerate(fields)]
        data = np.loadtxt(
            path, delimiter=None if delim == " " else delim,
            skiprows=1 if has_header else 0, ndmin=2,
        )

    ncols = data.shape[1]

    def _col_index(sel, what: str) -> int:
        if isinstance(sel, str):
            if names is None or sel not in names:
                raise ValueError(f"No column named {sel!r} in {path!r}")
            return names.index(sel)
        if not -ncols <= sel < ncols:
            raise ValueError(
                f"{what} index {sel} out of range for {ncols} columns"
            )
        return sel % ncols

    t_idx = _col_index(target, "target")
    w_idx = (
        _col_index(weights_column, "weights_column")
        if weights_column is not None
        else None
    )
    feat_idx = [i for i in range(ncols) if i != t_idx and i != w_idx]
    X = data[:, feat_idx].T
    y = data[:, t_idx]
    w = data[:, w_idx] if w_idx is not None else None
    var_names = [names[i] for i in feat_idx] if names is not None else None
    return make_dataset(X, y, w, var_names, dtype=dtype)
