"""Dataset container (reference src/Dataset.jl:24-64).

Holds X (nfeatures, n), y (n,), optional weights, variable names, the
weighted mean of y (`avg_y`) and the baseline loss of the constant
predictor avg_y (reference src/LossFunctions.jl:122-126), which normalizes
all scores.

Arrays live as jnp device arrays; on the TPU build the rows dimension may be
sharded over the mesh's row axis (the analog of the reference's `batching`
advice for >10k rows, src/Configure.jl:63-70).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@dataclasses.dataclass
class Dataset:
    X: Array  # (nfeatures, n)
    y: Array  # (n,)
    weights: Optional[Array] = None  # (n,)
    variable_names: Optional[Tuple[str, ...]] = None
    avg_y: float = 0.0
    baseline_loss: float = 1.0

    @property
    def n(self) -> int:
        return self.X.shape[1]

    @property
    def nfeatures(self) -> int:
        return self.X.shape[0]


def make_dataset(
    X,
    y,
    weights=None,
    variable_names: Optional[Sequence[str]] = None,
    dtype=jnp.float32,
) -> Dataset:
    X = jnp.asarray(X, dtype)
    y = jnp.asarray(y, dtype)
    if X.ndim != 2:
        raise ValueError("X must be (nfeatures, n)")
    if y.shape != (X.shape[1],):
        raise ValueError(f"y shape {y.shape} != (n,) = ({X.shape[1]},)")
    w = None
    if weights is not None:
        w = jnp.asarray(weights, dtype)
        if w.shape != y.shape:
            raise ValueError("weights must match y shape")
    if w is None:
        avg_y = float(jnp.mean(y))
    else:
        avg_y = float(jnp.sum(y * w) / jnp.sum(w))
    names = tuple(variable_names) if variable_names is not None else None
    if names is not None and len(names) != X.shape[0]:
        raise ValueError("variable_names length must equal nfeatures")
    return Dataset(X=X, y=y, weights=w, variable_names=names, avg_y=avg_y)


def update_baseline_loss(dataset: Dataset, options_or_loss) -> Dataset:
    """Score the constant predictor avg_y
    (reference src/LossFunctions.jl:122-126).

    Accepts either an elementwise loss callable or an Options; with an
    Options whose loss_function is set, the baseline goes through the
    custom full-tree objective on an encoded constant tree (the reference
    dispatches eval_loss -> loss_function for the baseline member too,
    src/LossFunctions.jl:60-67)."""
    loss_function = getattr(options_or_loss, "loss_function", None)
    if loss_function is not None:
        from .trees import Expr, encode_tree

        options = options_or_loss
        const_tree = jax.tree_util.tree_map(
            jnp.asarray,
            encode_tree(Expr.const(float(dataset.avg_y)), options.max_len),
        )
        base = float(
            loss_function(
                const_tree, dataset.X, dataset.y, dataset.weights, options
            )
        )
    else:
        elementwise_loss = (
            options_or_loss.elementwise_loss
            if hasattr(options_or_loss, "elementwise_loss")
            else options_or_loss
        )
        pred = jnp.full_like(dataset.y, dataset.avg_y)
        elem = elementwise_loss(pred, dataset.y)
        if dataset.weights is None:
            base = float(jnp.mean(elem))
        else:
            base = float(
                jnp.sum(elem * dataset.weights) / jnp.sum(dataset.weights)
            )
    dataset.baseline_loss = base if np.isfinite(base) and base > 0 else 1.0
    return dataset


def load_csv_dataset(
    path: str,
    target: "str | int" = -1,
    delimiter: Optional[str] = None,
    weights_column: "Optional[str | int]" = None,
    dtype=jnp.float32,
) -> Dataset:
    """Load a Dataset from a numeric CSV/TSV file.

    Rows are samples, columns are features; `target` picks the y column by
    header name or index (default: last column). Parsing goes through the
    C++ host runtime (native/srtpu_native.cpp srt_csv_*) when built, with a
    numpy fallback. Column names become variable_names.
    """
    from .. import native

    data = None
    names = None
    loaded = native.load_csv(path, delimiter) if native.native_available() else None
    if loaded is not None:
        data, names = loaded
    else:
        # numpy fallback: sniff a header line
        with open(path) as f:
            first = f.readline()
        delim = delimiter
        if delim is None:
            # space is a last resort: header names may contain spaces
            delim = max(",;\t", key=first.count) if first else ","
            if first.count(delim) == 0:
                delim = " "
        fields = [c.strip() for c in first.strip().split(delim)]

        def _is_num(s):
            try:
                float(s)
                return True
            except ValueError:
                return False

        has_header = any(not _is_num(c) for c in fields if c)
        if has_header:
            # keep positional alignment with data columns; name blanks
            names = [c if c else f"col{i}" for i, c in enumerate(fields)]
        data = np.loadtxt(
            path, delimiter=None if delim == " " else delim,
            skiprows=1 if has_header else 0, ndmin=2,
        )

    ncols = data.shape[1]

    def _col_index(sel, what: str) -> int:
        if isinstance(sel, str):
            if names is None or sel not in names:
                raise ValueError(f"No column named {sel!r} in {path!r}")
            return names.index(sel)
        if not -ncols <= sel < ncols:
            raise ValueError(
                f"{what} index {sel} out of range for {ncols} columns"
            )
        return sel % ncols

    t_idx = _col_index(target, "target")
    w_idx = (
        _col_index(weights_column, "weights_column")
        if weights_column is not None
        else None
    )
    feat_idx = [i for i in range(ncols) if i != t_idx and i != w_idx]
    X = data[:, feat_idx].T
    y = data[:, t_idx]
    w = data[:, w_idx] if w_idx is not None else None
    var_names = [names[i] for i in feat_idx] if names is not None else None
    return make_dataset(X, y, w, var_names, dtype=dtype)
