"""On-device batched constant optimization.

Analog of the reference's optimize_constants
(src/ConstantOptimization.jl:22-65): members are selected with probability
optimizer_probability, their constants fitted by BFGS with backtracking line
search and `optimizer_nrestarts` random restarts, and results written back
only when improved.

TPU-first design (SURVEY.md §7 build step 5): instead of Optim.jl's host
loop per member, every (member x restart) is an independent BFGS instance
run in lockstep under vmap — gradients come from jax.grad through the tree
interpreter, the line search evaluates all K candidate steps in one batched
call, and per-instance convergence is handled by masking. One XLA call
optimizes the whole population.

The optimization variable is the full cval vector (L,) with gradients masked
to constant slots — non-constant slots stay exactly zero-gradient so H stays
block-structured automatically.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.interpreter import _eval_single
from ..ops.losses import aggregate_loss, contain_nonfinite
from .fitness import loss_to_score
from .complexity import compute_complexity
from .options import Options
from .population import Population
from .trees import CONST, TreeBatch

Array = jax.Array

_LS_STEPS = 8  # candidate step sizes per line search: 2^0 .. 2^-7


def _member_loss_fn(
    tree: TreeBatch,
    X: Array,
    y: Array,
    weights: Optional[Array],
    options: Options,
):
    """loss(cval) for one member over the full dataset
    (reference opt objective src/ConstantOptimization.jl:11-19). Dispatches
    to options.loss_function when set, like every other scoring path —
    constants must be fitted to the same objective selection uses.
    Both forms contain non-finite objectives through the shared
    `contain_nonfinite` epilogue, and with row_shards > 1 the row
    reduction goes through the same fixed-order pairwise tree as the
    scoring path (the optimizer's f_best is written into pop.losses, so
    its reduction must be partition-invariant too or a row-sharded run
    would diverge from the single-device one at the first write-back)."""
    if options.loss_function is not None:

        def f_custom(cval: Array) -> Array:
            loss = options.loss_function(
                tree._replace(cval=cval), X, y, weights, options
            )
            return contain_nonfinite(loss)

        return f_custom

    loss_fn = options.elementwise_loss
    deterministic = options.row_shards > 1

    def f(cval: Array) -> Array:
        y_pred, ok = _eval_single(
            tree.kind, tree.op, tree.feat, cval, tree.length, X,
            options.operators,
        )
        elem = loss_fn(y_pred, y)
        loss = aggregate_loss(elem, weights, deterministic=deterministic)
        return contain_nonfinite(loss, ok)

    return f


def _bfgs_single(
    loss_f, x0: Array, cmask: Array, n_iters: int
) -> Tuple[Array, Array]:
    """Fixed-iteration BFGS with parallel backtracking line search.

    Runs on one (member, restart) instance; vmapped by the caller. Returns
    (x_final, loss_final). Gradient is masked to constant slots."""
    L = x0.shape[0]
    grad_f = jax.grad(loss_f)

    def masked_grad(x):
        g = grad_f(x) * cmask
        return jnp.where(jnp.isfinite(g), g, 0.0)

    def body(i, carry):
        x, f, g, H = carry
        d = -(H @ g)
        # safeguard: if d is not a descent direction, fall back to -g
        descent = jnp.dot(d, g) < 0
        d = jnp.where(descent, d, -g)
        ts = 2.0 ** -jnp.arange(_LS_STEPS, dtype=x.dtype)
        cand = x[None, :] + ts[:, None] * d[None, :]
        fs = jax.vmap(loss_f)(cand)
        k = jnp.argmin(fs)
        f_new = fs[k]
        # non-finite step rejection (the containment contract): the
        # objective is inf-contained so f_new < f already excludes
        # non-finite candidates whenever f is finite; the explicit
        # isfinite makes the reject-step rule hold from a NON-finite
        # initial point too (f0 = inf must never accept an inf step)
        improved = (f_new < f) & jnp.isfinite(f_new)
        t = ts[k]
        x_new = jnp.where(improved, x + t * d, x)
        g_new = jnp.where(improved, masked_grad(x_new), g)
        s = x_new - x
        yv = g_new - g
        sy = jnp.dot(s, yv)
        # SR009 form: divide the clamped input, then select — 1/sy on
        # the near-zero lanes would manufacture inf in the untaken
        # branch (bit-identical: selected lanes see the true sy)
        ok_sy = jnp.abs(sy) > 1e-10
        rho = jnp.where(ok_sy, 1.0 / jnp.where(ok_sy, sy, 1.0), 0.0)
        I = jnp.eye(L, dtype=x.dtype)
        V = I - rho * jnp.outer(s, yv)
        H_new = V @ H @ V.T + rho * jnp.outer(s, s)
        ok_H = improved & (rho > 0) & jnp.all(jnp.isfinite(H_new))
        H = jnp.where(ok_H, H_new, H)
        f = jnp.where(improved, f_new, f)
        return x_new, f, g_new, H

    f0 = loss_f(x0)
    g0 = masked_grad(x0)
    H0 = jnp.eye(L, dtype=x0.dtype)
    x, f, _, _ = jax.lax.fori_loop(0, n_iters, body, (x0, f0, g0, H0))
    # restored-constants fallback: an instance whose objective never
    # reached a finite value hands back its ORIGINAL constants with the
    # inf objective — the caller's write-back then restores the member
    # untouched instead of adopting line-search wreckage
    return jnp.where(jnp.isfinite(f), x, x0), f


def _nelder_mead_single(
    loss_f, x0: Array, cmask: Array, n_iters: int
) -> Tuple[Array, Array]:
    """Fixed-iteration Nelder-Mead on the masked constant subspace
    (reference Optim.NelderMead branch, src/ConstantOptimization.jl:33-43).

    Batched-TPU variant: the simplex has L+1 vertices (offsets only on
    constant slots; the duplicate vertices of non-constant dims are inert),
    and the rare full-simplex shrink is replaced by pulling the worst vertex
    toward the best (one eval instead of L+1, keeps every vmapped instance
    in lockstep)."""
    L = x0.shape[0]
    # Initial simplex: x0 plus L offset vertices. Active (constant) dims get
    # the classic per-coordinate offset; rows belonging to inactive dims
    # would be duplicates of x0 (the offset is masked away), which stalls
    # NM — give them deterministic pseudo-random offsets across the ACTIVE
    # dims instead, so every vertex is distinct within the active subspace
    # (NM only ever moves inside the simplex's affine hull, so the search
    # stays in that subspace automatically).
    # relative + absolute spread, like Optim.jl's AffineSimplexer
    # (x*(1+0.025) + 0.5): pure-relative offsets stall from near-zero starts
    base = 0.05 * x0 + 0.5
    i_idx = jnp.arange(L, dtype=jnp.int32)[:, None]
    j_idx = jnp.arange(L, dtype=jnp.int32)[None, :]
    pattern = (((i_idx * 31 + j_idx * 17) % 7) - 3).astype(x0.dtype) / 3.0
    offs = jnp.where(
        jnp.eye(L, dtype=bool), jnp.diag(base), pattern * base[None, :]
    ) * cmask[None, :]
    verts = jnp.concatenate([x0[None, :], x0[None, :] + offs])
    fs = jax.vmap(loss_f)(verts)

    def body(i, carry):
        verts, fs = carry
        order = jnp.argsort(fs)
        verts = verts[order]
        fs = fs[order]
        best, worst = verts[0], verts[-1]
        f_best, f_second, f_worst = fs[0], fs[-2], fs[-1]
        centroid = jnp.mean(verts[:-1], axis=0)
        xr = centroid + (centroid - worst)      # reflection
        xe = centroid + 2.0 * (centroid - worst)  # expansion
        xc = centroid + 0.5 * (worst - centroid)  # contraction
        xs = best + 0.5 * (worst - best)          # worst -> best pull
        cand = jnp.stack([xr, xe, xc, xs])
        fr, fe, fc, fsh = jax.vmap(loss_f)(cand)
        # standard NM acceptance, vectorized over the 4 candidates
        new_x = jnp.where(
            (fr < f_best) & (fe < fr), xe,
            jnp.where(
                fr < f_second, xr,
                jnp.where(fc < f_worst, xc, xs),
            ),
        )
        new_f = jnp.where(
            (fr < f_best) & (fe < fr), fe,
            jnp.where(
                fr < f_second, fr,
                jnp.where(fc < f_worst, fc, fsh),
            ),
        )
        # reject non-finite steps explicitly (containment contract):
        # with an all-inf simplex (hostile data / poisoned x0) the inf
        # candidates must never displace a vertex
        accept = (new_f < f_worst) & jnp.isfinite(new_f)
        verts = verts.at[-1].set(jnp.where(accept, new_x, worst))
        fs = fs.at[-1].set(jnp.where(accept, new_f, f_worst))
        return verts, fs

    verts, fs = jax.lax.fori_loop(0, n_iters * 3, body, (verts, fs))
    k = jnp.argmin(fs)
    # restored-constants fallback (see _bfgs_single): never hand back a
    # vertex whose objective is non-finite
    return jnp.where(jnp.isfinite(fs[k]), verts[k], x0), fs[k]


def _newton_single(
    loss_f, x0: Array, cmask: Array, n_iters: int
) -> Tuple[Array, Array]:
    """Per-coordinate Newton with gradient fallback (reference uses
    Optim.Newton when a tree has a single constant,
    src/ConstantOptimization.jl:33-37). Steps along diag(H)^-1 grad with a
    backtracking line search; with one active constant that IS the Newton
    step, with several it is Jacobi-preconditioned gradient descent."""
    grad_f = jax.grad(loss_f)

    def masked_grad(x):
        g = grad_f(x) * cmask
        return jnp.where(jnp.isfinite(g), g, 0.0)

    def hdiag(x):
        h = jnp.diagonal(jax.jacfwd(masked_grad)(x))
        return jnp.where(jnp.isfinite(h), h, 0.0)

    def body(i, carry):
        x, f = carry
        g = masked_grad(x)
        h = hdiag(x)
        step = jnp.where(jnp.abs(h) > 1e-8, g / jnp.abs(h), g)
        ts = 2.0 ** -jnp.arange(_LS_STEPS, dtype=x.dtype)
        cand = x[None, :] - ts[:, None] * step[None, :]
        fs = jax.vmap(loss_f)(cand)
        k = jnp.argmin(fs)
        # non-finite step rejection, like _bfgs_single
        improved = (fs[k] < f) & jnp.isfinite(fs[k])
        x = jnp.where(improved, cand[k], x)
        f = jnp.where(improved, fs[k], f)
        return x, f

    x, f = jax.lax.fori_loop(0, n_iters, body, (x0, loss_f(x0)))
    # restored-constants fallback (see _bfgs_single)
    return jnp.where(jnp.isfinite(f), x, x0), f


_FORCE_INTERPRET = False  # tests only: run the fused kernels in interpret
# mode so the batched path is exercisable off-TPU


def _use_fused_kernels(options: Options, n_instances: int, X: Array) -> bool:
    """Route constant optimization through the fused Pallas loss/grad
    kernels (optimizer_backend knob): 'auto' engages them for BFGS at
    population scale on TPU with a standard elementwise loss in f32 —
    the same conditions under which fitness.dispatch_eval picks the eval
    kernel — and only when the packed word's address space fits; 'jnp'
    pins the vmapped interpreter path; 'pallas' forces the fused path
    (TPU-only, no custom loss_function, BFGS; layout overflows raise
    from the kernel)."""
    from ..ops.pallas_eval import _SLOT_UNROLL, _round_up, pallas_available
    from .fitness import _pallas_work_gate

    backend = options.optimizer_backend
    if backend == "jnp":
        return False
    if options.row_shards > 1:
        # deterministic (row-sharded) optimization must reduce rows
        # with the same fixed-order pairwise tree as the scoring path —
        # the fused kernel's row reduction is the kernel's own
        # accumulation order, which would break the row-sharded
        # bit-identity contract at the first f_best write-back
        # (docs/robustness_numeric.md; Options rejects the explicit
        # optimizer_backend='pallas' + row_shards>1 combo)
        return False
    if options.optimizer_algorithm != "BFGS" or (
        options.loss_function is not None
    ):
        if backend == "pallas":
            raise ValueError(
                "optimizer_backend='pallas' requires "
                "optimizer_algorithm='BFGS' and no custom loss_function"
            )
        return False
    if backend == "pallas":
        return True
    # packed-word limits (mirrors make_loss_kernel's check): 'auto' must
    # quietly keep the jnp path where the fused kernel would raise
    ops = options.operators
    n_codes = 2 + ops.n_unary + ops.n_binary
    ML = options.max_len
    L_pad = _round_up(ML, _SLOT_UNROLL)
    fits = n_codes <= 255 and X.shape[0] + L_pad + ML + 1 <= 2048
    return (
        fits
        and pallas_available()
        and X.dtype == jnp.float32
        # instances x rows work volume, like the eval kernel's gate: the
        # grad kernel tiles rows onto the same (8, 128) vregs, so a
        # many-instances/tiny-rows launch would mostly pad row lanes
        and _pallas_work_gate(n_instances, X.shape[1])
    )


def _bfgs_batched(
    trees_flat: TreeBatch,
    x0: Array,
    cmask: Array,
    X: Array,
    y: Array,
    weights: Optional[Array],
    options: Options,
    n_iters: int,
) -> Tuple[Array, Array]:
    """BFGS over M = (restarts x members) instances with losses and
    gradients from the fused Pallas kernels (ops/pallas_grad.py) — one
    kernel launch per step for the WHOLE batch, instead of vmapping
    per-member `jax.grad` closures through the lockstep interpreter.
    Same update rule as _bfgs_single (descent safeguard, parallel
    backtracking, curvature-gated H update); used at population scale
    where the per-closure path would materialize (instances x rows)
    prediction intermediates in HBM."""
    from ..ops.pallas_grad import make_loss_kernel

    M, L = x0.shape
    loss_fn = options.elementwise_loss
    ops = options.operators

    # structure-dependent staging (instruction schedule, sort, packing)
    # happens ONCE here; the BFGS loop below only swaps constants in
    grad_fn = make_loss_kernel(
        trees_flat, X, y, weights, ops, loss_fn=loss_fn, with_grad=True,
        interpret=_FORCE_INTERPRET,
    )
    trees_ls = jax.tree_util.tree_map(
        lambda a: jnp.repeat(a, _LS_STEPS, axis=0), trees_flat
    )
    ls_fn = make_loss_kernel(
        trees_ls, X, y, weights, ops, loss_fn=loss_fn, with_grad=False,
        interpret=_FORCE_INTERPRET,
    )

    def loss_grad(x):
        loss, grad, ok = grad_fn(x)
        f = contain_nonfinite(loss, ok)
        # the grad-side containment twin: a non-finite gradient
        # component is zeroed (reject the direction, keep the instance)
        g = jnp.where(jnp.isfinite(grad), grad, 0.0) * cmask
        return f, g

    def loss_batch(xs):  # (M, _LS_STEPS, L) -> (M, _LS_STEPS)
        loss, _, ok = ls_fn(xs.reshape(M * _LS_STEPS, L))
        return contain_nonfinite(loss, ok).reshape(M, _LS_STEPS)

    I = jnp.eye(L, dtype=x0.dtype)

    def body(i, carry):
        x, f, g, H = carry
        d = -jnp.einsum("mij,mj->mi", H, g)
        descent = jnp.einsum("mi,mi->m", d, g) < 0
        d = jnp.where(descent[:, None], d, -g)
        ts = 2.0 ** -jnp.arange(_LS_STEPS, dtype=x.dtype)
        cand = x[:, None, :] + ts[None, :, None] * d[:, None, :]
        fs = loss_batch(cand)
        k = jnp.argmin(fs, axis=1)
        f_new = jnp.take_along_axis(fs, k[:, None], axis=1)[:, 0]
        # non-finite step rejection, like _bfgs_single
        improved = (f_new < f) & jnp.isfinite(f_new)
        # select, don't scale: 0 * inf direction would poison x with NaN
        # (matching _bfgs_single's where-form)
        x_new = jnp.where(
            improved[:, None], x + ts[k][:, None] * d, x
        )
        _, g_cand = loss_grad(x_new)
        g_new = jnp.where(improved[:, None], g_cand, g)
        s = x_new - x
        yv = g_new - g
        sy = jnp.einsum("mi,mi->m", s, yv)
        # SR009 form: clamp the divisor, then select (see _bfgs_single)
        ok_sy = jnp.abs(sy) > 1e-10
        rho = jnp.where(ok_sy, 1.0 / jnp.where(ok_sy, sy, 1.0), 0.0)
        V = I[None] - rho[:, None, None] * s[:, :, None] * yv[:, None, :]
        H_new = (
            jnp.einsum("mij,mjk,mlk->mil", V, H, V)
            + rho[:, None, None] * s[:, :, None] * s[:, None, :]
        )
        ok_H = (
            improved & (rho > 0)
            & jnp.all(jnp.isfinite(H_new), axis=(1, 2))
        )
        H = jnp.where(ok_H[:, None, None], H_new, H)
        f = jnp.where(improved, f_new, f)
        return x_new, f, g_new, H

    f0, g0 = loss_grad(x0)
    H0 = jnp.broadcast_to(I, (M, L, L))  # srlint: disable=SR007 -- fori_loop carry: per-instance Hessians must be materialized once
    x, f, _, _ = jax.lax.fori_loop(0, n_iters, body, (x0, f0, g0, H0))
    # restored-constants fallback (see _bfgs_single)
    return jnp.where(jnp.isfinite(f)[:, None], x, x0), f


# name -> (fn, evals_per_member(L, n_iters)) for num_evals accounting
_OPTIMIZERS = {
    "BFGS": (
        _bfgs_single,
        lambda L, it: 1 + it * (_LS_STEPS + 1),
    ),
    "NelderMead": (
        _nelder_mead_single,
        lambda L, it: (L + 1) + 3 * it * 4,
    ),
    "Newton": (
        _newton_single,
        lambda L, it: 1 + it * (_LS_STEPS + 2),
    ),
}


def _static_shapes(pop: Population, options: Options,
                   probability: Optional[float]):
    """(K, n_starts, L) — the static sizes of one island's optimization."""
    if probability is None:
        probability = options.optimizer_probability
    K = max(1, int(round(pop.npop * probability)))
    return K, 1 + options.optimizer_nrestarts, pop.trees.max_len


def _select_and_starts(key, pop, options, K, n_starts):
    """Pick the K members to optimize and build their restart starting
    points; pure jnp so it vmaps over islands. Fixed-size random subset
    K ~= npop * p (static shape; the reference's per-member Bernoulli
    draw has the same mean); members without constants are deprioritized
    and masked out via `eligible`."""
    L = pop.trees.max_len
    n_restarts = n_starts - 1
    k_sel, k_perturb = jax.random.split(key)
    idx = jnp.arange(L, dtype=jnp.int32)
    has_consts = jnp.sum(
        (pop.trees.kind == CONST) & (idx < pop.trees.length[:, None]), axis=-1
    ) > 0
    priority = jax.random.uniform(
        k_sel, (pop.npop,)
    ) + has_consts.astype(jnp.float32)
    _, sel_idx = jax.lax.top_k(priority, K)  # (K,)
    sub_trees = jax.tree_util.tree_map(lambda x: x[sel_idx], pop.trees)
    sub_losses = pop.losses[sel_idx]
    eligible = has_consts[sel_idx]

    # starts: x0 plus perturbed restarts x0 * (1 + 0.5*randn)
    # (reference src/ConstantOptimization.jl:46-54)
    eps = jax.random.normal(k_perturb, (n_starts, K, L), pop.trees.cval.dtype)
    scale = jnp.concatenate(
        [
            jnp.zeros((1, K, L), pop.trees.cval.dtype),
            0.5 * jnp.ones((n_restarts, K, L), pop.trees.cval.dtype),
        ]
    )
    starts = sub_trees.cval[None] * (1.0 + scale * eps)

    cmask = (
        (sub_trees.kind == CONST) & (idx < sub_trees.length[:, None])
    ).astype(pop.trees.cval.dtype)
    return sel_idx, sub_trees, sub_losses, eligible, starts, cmask


# Portable-path memory bound: `jax.grad` through the lockstep interpreter
# saves the per-slot candidate stacks as residuals — O(L x n_ops x rows)
# per instance, ~0.8MB at maxsize 18 x 9 ops x 1000 rows — so one flat
# vmap over every (island x restart x member) instance peaks at 11.7GB of
# XLA temp at 64 islands x npop 256 (measured 2026-08-02; v5e HBM is
# 16GB, and the resulting on-chip OOM surfaces through the axon tunnel as
# an opaque UNAVAILABLE device error). Chunking with lax.map bounds the
# live residual set to `chunk` instances; the chunks run sequentially,
# which costs nothing here — each instance is already a serial fori_loop,
# and the chunk width keeps the device saturated.
_PORTABLE_OPT_CHUNK = 2048


def _flatten_island_instances(sub_trees, starts, cmask, I, K, n_starts, L):
    """(I, K, ...) member arrays + (I, n_starts, K, L) starts ->
    restart-major flat instances of length n_starts*I*K (the layout both
    the fused-kernel launch and the chunked portable path consume)."""
    flat_sub = jax.tree_util.tree_map(
        lambda a: a.reshape((I * K,) + a.shape[2:]), sub_trees
    )
    tiled = jax.tree_util.tree_map(
        lambda a: jnp.tile(a, (n_starts,) + (1,) * (a.ndim - 1)), flat_sub
    )
    starts_flat = jnp.moveaxis(starts, 1, 0).reshape(n_starts * I * K, L)
    cmask_flat = jnp.tile(cmask.reshape(I * K, L), (n_starts, 1))
    return tiled, starts_flat, cmask_flat


def _run_vmapped_chunked(trees_flat, starts_flat, cmask_flat, X, y,
                         weights, options, optimizer,
                         chunk=_PORTABLE_OPT_CHUNK):
    """The portable path over flat instances: one `jax.grad`/loss closure
    per instance, vmapped within fixed-size chunks, lax.map over chunks
    (see _PORTABLE_OPT_CHUNK). Returns (xs (N, L), fs (N,))."""

    def run_one(tree, x0, cm):
        f = _member_loss_fn(tree, X, y, weights, options)
        return optimizer(f, x0, cm, options.optimizer_iterations)

    N, L = starts_flat.shape
    if N <= chunk:
        return jax.vmap(run_one)(trees_flat, starts_flat, cmask_flat)
    # whole chunks through lax.map, the remainder as one smaller vmap —
    # padding the remainder up to a whole chunk would burn up to chunk-1
    # full dummy optimizer runs (~16% of the work at the 64x256 default)
    n_chunks, rem = divmod(N, chunk)
    head = lambda a: a[: n_chunks * chunk].reshape(
        (n_chunks, chunk) + a.shape[1:]
    )
    xs, fs = jax.lax.map(
        lambda ch: jax.vmap(run_one)(*ch),
        (
            jax.tree_util.tree_map(head, trees_flat),
            head(starts_flat),
            head(cmask_flat),
        ),
    )
    xs, fs = xs.reshape(-1, L), fs.reshape(-1)
    if rem:
        tail = lambda a: a[n_chunks * chunk:]
        xs_t, fs_t = jax.vmap(run_one)(
            jax.tree_util.tree_map(tail, trees_flat),
            tail(starts_flat), tail(cmask_flat),
        )
        xs = jnp.concatenate([xs, xs_t])
        fs = jnp.concatenate([fs, fs_t])
    return xs, fs


def _write_back(pop, sel_idx, sub_trees, sub_losses, eligible, xs, fs,
                baseline, options, n_starts, evals_per_member):
    """Fold optimized constants back where improved; pure jnp so it vmaps
    over islands. Returns (Population, n_evals, n_attempted)."""
    L = pop.trees.max_len
    # best restart per member
    best_r = jnp.argmin(fs, axis=0)  # (K,)
    x_best = jnp.take_along_axis(xs, best_r[None, :, None], axis=0)[0]
    f_best = jnp.take_along_axis(fs, best_r[None, :], axis=0)[0]

    # containment contract: never write a non-finite constant back into
    # the population, even behind a finite objective — exp(c) with
    # c -> -inf evaluates finite, but an inf/NaN cval poisons every
    # later mutation/perturbation and the export path. A member whose
    # best restart carries a non-finite constant keeps its pre-opt
    # constants (restored, not adopted).
    improved = (
        eligible & (f_best < sub_losses) & jnp.isfinite(f_best)
        & jnp.all(jnp.isfinite(x_best), axis=-1)
    )
    new_sub_cval = jnp.where(improved[:, None], x_best, sub_trees.cval)
    sub_complexity = compute_complexity(
        sub_trees._replace(cval=new_sub_cval), options
    )
    new_sub_losses = jnp.where(improved, f_best, sub_losses)
    new_sub_scores = jnp.where(
        improved,
        loss_to_score(new_sub_losses, baseline, sub_complexity, options),
        pop.scores[sel_idx],
    )

    new_cval = pop.trees.cval.at[sel_idx].set(new_sub_cval)
    new_trees = pop.trees._replace(cval=new_cval)
    n_attempted = jnp.sum(eligible.astype(jnp.int32))
    n_evals = (
        n_attempted.astype(jnp.float32)
        * n_starts
        * evals_per_member(L, options.optimizer_iterations)
    )
    return (
        Population(
            trees=new_trees,
            scores=pop.scores.at[sel_idx].set(new_sub_scores),
            losses=pop.losses.at[sel_idx].set(new_sub_losses),
            birth=pop.birth,
        ),
        n_evals,
        n_attempted,
    )


def _get_optimizer(options: Options):
    if options.optimizer_algorithm not in _OPTIMIZERS:
        raise ValueError(
            f"optimizer_algorithm {options.optimizer_algorithm!r} not in "
            f"{sorted(_OPTIMIZERS)}"
        )
    return _OPTIMIZERS[options.optimizer_algorithm]


def optimize_constants_population(
    key: Array,
    pop: Population,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    probability: Optional[float] = None,
) -> Tuple[Population, Array, Array]:
    """Select members w.p. optimizer_probability (or `probability` when
    given — the `optimize` mutation pass uses its own rate), fit their
    constants, write back where improved (reference
    src/SingleIteration.jl:75-79 + src/ConstantOptimization.jl:22-65).
    Returns (population', n_extra_evals, n_attempted) — n_attempted is
    the number of constant-bearing members actually optimized (bounds
    the telemetry's accepted count).

    NOTE: must not be called under `jax.vmap` with the fused path
    engaged (the Pallas launch has no batching rule); the production
    multi-island entry is `optimize_constants_islands`, which batches
    islands into the kernel launch itself — this function is its I=1
    special case.
    """
    pops = jax.tree_util.tree_map(lambda x: x[None], pop)
    pops2, n_evals, n_attempted = optimize_constants_islands(
        key[None], pops, X, y, weights, baseline, options, probability
    )
    return (
        jax.tree_util.tree_map(lambda x: x[0], pops2),
        n_evals[0],
        n_attempted[0],
    )


def optimize_constants_islands(
    keys: Array,
    pops: Population,
    X: Array,
    y: Array,
    weights: Optional[Array],
    baseline: float,
    options: Options,
    probability: Optional[float] = None,
) -> Tuple[Population, Array, Array]:
    """Multi-island constant optimization: `pops` carries a leading
    islands axis on every field, `keys` is (I, key). Selection and
    write-back vmap per island; the OPTIMIZATION itself routes either
    through one global fused-kernel BFGS over every
    (island x restart x member) instance — the path jax.vmap cannot
    express, since the Pallas launch has no batching rule — or through
    the per-member vmapped closures (identical results to vmapping
    `optimize_constants_population`). Returns (pops', n_evals (I,),
    n_attempted (I,))."""
    I = pops.losses.shape[0]
    one = jax.tree_util.tree_map(lambda x: x[0], pops)
    K, n_starts, L = _static_shapes(one, options, probability)
    optimizer, evals_per_member = _get_optimizer(options)

    sel_idx, sub_trees, sub_losses, eligible, starts, cmask = jax.vmap(
        lambda k, p: _select_and_starts(k, p, options, K, n_starts)
    )(keys, pops)
    # shapes: sel_idx (I, K), sub_trees (I, K, ...), starts
    # (I, n_starts, K, L), cmask (I, K, L)

    # both paths consume the same restart-major flat instance layout
    tiled, starts_flat, cmask_flat = _flatten_island_instances(
        sub_trees, starts, cmask, I, K, n_starts, L
    )
    if _use_fused_kernels(options, I * n_starts * K, X):
        x_flat, f_flat = _bfgs_batched(
            tiled, starts_flat, cmask_flat, X, y, weights, options,
            options.optimizer_iterations,
        )
    else:
        x_flat, f_flat = _run_vmapped_chunked(
            tiled, starts_flat, cmask_flat, X, y, weights, options,
            optimizer,
        )
    xs = jnp.moveaxis(
        x_flat.reshape(n_starts, I, K, L), 0, 1
    )  # (I, n_starts, K, L)
    fs = jnp.moveaxis(f_flat.reshape(n_starts, I, K), 0, 1)

    return jax.vmap(
        lambda p, si, st, sl, el, x, f: _write_back(
            p, si, st, sl, el, x, f, baseline, options, n_starts,
            evals_per_member,
        )
    )(pops, sel_idx, sub_trees, sub_losses, eligible, xs, fs)
