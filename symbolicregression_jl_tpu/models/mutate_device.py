"""On-device tree surgery: mutations, crossover, random generation.

The reference mutates linked Node trees with pointer surgery on the host
(src/MutationFunctions.jl). Here every genetic operator is pure array
arithmetic on the flat postfix encoding (SURVEY.md §7 decision 3), so the
entire evolution step jits and shards:

* every subtree is a contiguous postfix span [i-size(i)+1, i];
* all edits reduce to one primitive, `splice` (replace a span with a donor
  span) implemented as a piecewise index-mapped gather;
* node choice is masked categorical sampling with jax.random.

All functions operate on a SINGLE tree (fields shape (L,)) and are designed
to be `jax.vmap`-ed over the mutation batch. Each returns (tree', ok) where
ok=False means the edit could not be applied (no eligible node / result too
long) and tree' is the unchanged input.

Reference parity targets cited per-function.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..ops.operators import OperatorSet
from .trees import (
    ARITY,
    BIN,
    CONST,
    PAD,
    UNA,
    VAR,
    TreeBatch,
    subtree_sizes,
)

Array = jax.Array

_NEG_INF = -1e9


# ---------------------------------------------------------------------------
# Sampling helpers
# ---------------------------------------------------------------------------


def select_node(key: Array, mask: Array) -> Tuple[Array, Array]:
    """Uniformly sample an index where mask is True.

    Analog of `random_node` with a predicate (reference
    src/MutationFunctions.jl:8-29). Returns (index, any_valid)."""
    logits = jnp.where(mask, 0.0, _NEG_INF)
    idx = jax.random.categorical(key, logits)
    return idx, jnp.any(mask)


def valid_mask(tree: TreeBatch) -> Array:
    return jnp.arange(tree.max_len, dtype=jnp.int32) < tree.length


def make_random_leaf(
    key: Array, nfeatures: int, dtype=jnp.float32
) -> Tuple[Array, Array, Array, Array]:
    """50/50 constant (randn) / feature leaf
    (reference src/MutationFunctions.jl:151-157). Returns scalar fields."""
    k1, k2, k3 = jax.random.split(key, 3)
    is_const = jax.random.bernoulli(k1)
    kind = jnp.where(is_const, CONST, VAR)
    feat = jax.random.randint(k2, (), 0, nfeatures, dtype=jnp.int32)
    cval = jax.random.normal(k3, (), jnp.float32).astype(dtype)
    return kind.astype(jnp.int32), jnp.int32(0), jnp.where(is_const, feat * 0, feat), cval


# ---------------------------------------------------------------------------
# The splice primitive
# ---------------------------------------------------------------------------


def splice(
    tree: TreeBatch,
    start: Array,
    end: Array,
    donor_kind: Array,
    donor_op: Array,
    donor_feat: Array,
    donor_cval: Array,
    d_start: Array,
    d_len: Array,
) -> Tuple[TreeBatch, Array]:
    """Replace tree[start:end) with donor[d_start : d_start+d_len).

    Pure gather: for each output slot pick from the prefix, the donor span,
    or the shifted suffix. Returns (tree', ok) with ok=False (and tree
    unchanged) if the result would exceed max_len."""
    L = tree.max_len
    DL = donor_kind.shape[0]
    new_len = tree.length - (end - start) + d_len
    ok = (new_len <= L) & (new_len >= 1)

    i = jnp.arange(L, dtype=jnp.int32)
    in_pre = i < start
    in_donor = (i >= start) & (i < start + d_len)
    src_suffix = jnp.clip(i - (start + d_len) + end, 0, L - 1)
    src_tree = jnp.where(in_pre, i, src_suffix)
    src_donor = jnp.clip(d_start + i - start, 0, DL - 1)
    live = i < new_len

    def pick(tf, df, pad_val):
        out = jnp.where(in_donor, df[src_donor], tf[src_tree])
        return jnp.where(live, out, pad_val)

    new = TreeBatch(
        kind=pick(tree.kind, donor_kind, PAD),
        op=pick(tree.op, donor_op, 0),
        feat=pick(tree.feat, donor_feat, 0),
        cval=pick(tree.cval, donor_cval, jnp.zeros((), tree.cval.dtype)),
        length=jnp.where(ok, new_len, tree.length).astype(jnp.int32),
    )
    new = jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new, tree
    )
    return new, ok


def splice_tree_donor(
    tree: TreeBatch, start, end, donor: TreeBatch, d_start, d_len
) -> Tuple[TreeBatch, Array]:
    return splice(
        tree, start, end, donor.kind, donor.op, donor.feat, donor.cval, d_start, d_len
    )


def _donor4(kinds, ops, feats, cvals, dtype):
    """Pack up to 4 scalar nodes into fixed donor arrays."""
    return (
        jnp.stack(kinds).astype(jnp.int32),
        jnp.stack(ops).astype(jnp.int32),
        jnp.stack(feats).astype(jnp.int32),
        jnp.stack(cvals).astype(dtype),
    )


# ---------------------------------------------------------------------------
# Structural spans
# ---------------------------------------------------------------------------


def node_span(tree: TreeBatch, idx: Array, sizes: Array) -> Tuple[Array, Array]:
    """Postfix span [start, end) of the subtree rooted at slot idx."""
    size = sizes[idx]
    return idx - size + 1, idx + 1


def child_spans(tree: TreeBatch, idx: Array, sizes: Array):
    """For an op node at idx: (left_start, left_end, right_start, right_end).
    For unary nodes the 'right' span is the child and left is empty."""
    r_size = sizes[jnp.maximum(idx - 1, 0)]
    r_start = idx - r_size
    l_root = idx - 1 - r_size
    l_size = sizes[jnp.maximum(l_root, 0)]
    l_start = l_root - l_size + 1
    return l_start, l_root + 1, r_start, idx


# ---------------------------------------------------------------------------
# Mutations (reference src/MutationFunctions.jl)
# ---------------------------------------------------------------------------


def mutate_constant(
    key: Array, tree: TreeBatch, temperature: Array, perturbation_factor: float,
    probability_negate: float,
) -> Tuple[TreeBatch, Array]:
    """Multiplicative perturbation + occasional negation of one constant
    (reference src/MutationFunctions.jl:50-79)."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    mask = (tree.kind == CONST) & valid_mask(tree)
    idx, ok = select_node(k1, mask)
    max_change = perturbation_factor * temperature + 1.1
    factor = max_change ** jax.random.uniform(k2)
    bigger = jax.random.bernoulli(k3)
    factor = jnp.where(bigger, factor, 1.0 / factor)  # srlint: disable=SR009 -- factor = max_change**u with max_change >= 1.1, u in [0,1): strictly positive, division is total here
    negate = jax.random.bernoulli(k4, probability_negate)
    new_val = tree.cval[idx] * factor * jnp.where(negate, -1.0, 1.0)
    new_cval = tree.cval.at[idx].set(new_val.astype(tree.cval.dtype))
    new = tree._replace(cval=jnp.where(ok, new_cval, tree.cval))
    return new, ok


def mutate_operator(
    key: Array, tree: TreeBatch, operators: OperatorSet
) -> Tuple[TreeBatch, Array]:
    """Swap one operator for a random same-arity operator
    (reference src/MutationFunctions.jl:33-47)."""
    k1, k2 = jax.random.split(key)
    mask = ((tree.kind == UNA) | (tree.kind == BIN)) & valid_mask(tree)
    idx, ok = select_node(k1, mask)
    is_una = tree.kind[idx] == UNA
    n_una = max(operators.n_unary, 1)
    n_bin = max(operators.n_binary, 1)
    new_op = jnp.where(
        is_una,
        jax.random.randint(k2, (), 0, n_una, dtype=jnp.int32),
        jax.random.randint(k2, (), 0, n_bin, dtype=jnp.int32),
    )
    new = tree._replace(op=jnp.where(ok, tree.op.at[idx].set(new_op), tree.op))
    return new, ok


def _random_op_donor(key: Array, use_unary: Array, nfeatures: int,
                     operators: OperatorSet, dtype):
    """Donor [leaf, op] (unary, d_len=2) or [leaf, leaf, op] (binary,
    d_len=3) with fresh random leaves."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    lk1, lo1, lf1, lc1 = make_random_leaf(k1, nfeatures, dtype)
    lk2, lo2, lf2, lc2 = make_random_leaf(k2, nfeatures, dtype)
    op_u = jax.random.randint(k3, (), 0, max(operators.n_unary, 1), dtype=jnp.int32)
    op_b = jax.random.randint(k4, (), 0, max(operators.n_binary, 1), dtype=jnp.int32)
    zero = jnp.int32(0)
    zf = jnp.zeros((), dtype)
    # unary layout: [leaf1, OP, -, -]; binary layout: [leaf1, leaf2, OP, -]
    dk = jnp.where(
        use_unary,
        jnp.stack([lk1, jnp.int32(UNA), zero, zero]),
        jnp.stack([lk1, lk2, jnp.int32(BIN), zero]),
    )
    do = jnp.where(
        use_unary,
        jnp.stack([zero, op_u, zero, zero]),
        jnp.stack([zero, zero, op_b, zero]),
    )
    df = jnp.where(
        use_unary,
        jnp.stack([lf1, zero, zero, zero]),
        jnp.stack([lf1, lf2, zero, zero]),
    )
    dc = jnp.where(
        use_unary,
        jnp.stack([lc1, zf, zf, zf]),
        jnp.stack([lc1, lc2, zf, zf]),
    )
    d_len = jnp.where(use_unary, 2, 3)
    return dk, do, df, dc, d_len


def _choose_unary(key: Array, operators: OperatorSet) -> Array:
    """Coin-flip unary vs binary, degenerate when one family is absent."""
    if operators.n_unary == 0:
        return jnp.bool_(False)
    if operators.n_binary == 0:
        return jnp.bool_(True)
    return jax.random.bernoulli(key)


def append_random_op(
    key: Array, tree: TreeBatch, nfeatures: int, operators: OperatorSet
) -> Tuple[TreeBatch, Array]:
    """Replace a random leaf with a random operator over fresh leaves
    (reference src/MutationFunctions.jl:82-111)."""
    k1, k2, k3 = jax.random.split(key, 3)
    mask = ((tree.kind == CONST) | (tree.kind == VAR)) & valid_mask(tree)
    idx, any_leaf = select_node(k1, mask)
    use_unary = _choose_unary(k2, operators)
    dk, do, df, dc, d_len = _random_op_donor(
        k3, use_unary, nfeatures, operators, tree.cval.dtype
    )
    new, fit = splice(tree, idx, idx + 1, dk, do, df, dc, 0, d_len)
    ok = any_leaf & fit
    new = jax.tree_util.tree_map(lambda n, o: jnp.where(ok, n, o), new, tree)
    return new, ok


def insert_random_op(
    key: Array, tree: TreeBatch, nfeatures: int, operators: OperatorSet,
    at_root: bool = False,
) -> Tuple[TreeBatch, Array]:
    """Make a random node the child of a new random operator; binary gets a
    fresh leaf as the other child, side chosen at random
    (reference insert_random_op src/MutationFunctions.jl:114-130; with
    at_root=True this is prepend_random_op, :133-149)."""
    k1, k2, k3, k4, k5, k6 = jax.random.split(key, 6)
    sizes = subtree_sizes(tree.kind, tree.length)
    if at_root:
        idx = tree.length - 1
        any_node = tree.length > 0
    else:
        idx, any_node = select_node(k1, valid_mask(tree))
    s, e = node_span(tree, idx, sizes)

    use_unary = _choose_unary(k2, operators)
    as_left = jax.random.bernoulli(k3)
    op_u = jax.random.randint(k4, (), 0, max(operators.n_unary, 1), dtype=jnp.int32)
    op_b = jax.random.randint(k5, (), 0, max(operators.n_binary, 1), dtype=jnp.int32)
    lk, lo, lf, lc = make_random_leaf(k6, nfeatures, tree.cval.dtype)
    zero = jnp.int32(0)
    zf = jnp.zeros((), tree.cval.dtype)
    dtype = tree.cval.dtype

    # Case 1 (unary): insert [OP] at e.
    # Case 2 (binary, subtree as left): insert [leaf, OP] at e.
    # Case 3 (binary, subtree as right): insert [OP] at e then [leaf] at s.
    op_kind = jnp.where(use_unary, UNA, BIN).astype(jnp.int32)
    op_idx = jnp.where(use_unary, op_u, op_b)

    dk1 = jnp.stack([lk, jnp.int32(0), zero, zero])
    do1 = jnp.stack([zero, zero, zero, zero])
    df1 = jnp.stack([lf, zero, zero, zero])
    dc1 = jnp.stack([lc, zf, zf, zf])

    tail_is_leaf_op = (~use_unary) & as_left
    dk_tail = jnp.where(
        tail_is_leaf_op,
        jnp.stack([lk, op_kind, zero, zero]),
        jnp.stack([op_kind, zero, zero, zero]),
    )
    do_tail = jnp.where(
        tail_is_leaf_op,
        jnp.stack([zero, op_idx, zero, zero]),
        jnp.stack([op_idx, zero, zero, zero]),
    )
    df_tail = jnp.where(
        tail_is_leaf_op,
        jnp.stack([lf, zero, zero, zero]),
        jnp.stack([zero, zero, zero, zero]),
    )
    dc_tail = jnp.where(
        tail_is_leaf_op,
        jnp.stack([lc, zf, zf, zf]),
        jnp.stack([zf, zf, zf, zf]),
    )
    tail_len = jnp.where(tail_is_leaf_op, 2, 1)
    new, ok1 = splice(tree, e, e, dk_tail, do_tail, df_tail, dc_tail, 0, tail_len)

    need_front_leaf = (~use_unary) & (~as_left)
    front_len = jnp.where(need_front_leaf, 1, 0)
    new2, ok2 = splice(new, s, s, dk1, do1, df1, dc1, 0, front_len)

    ok = any_node & ok1 & ok2
    out = jax.tree_util.tree_map(lambda n, o: jnp.where(ok, n, o), new2, tree)
    return out, ok


def prepend_random_op(key, tree, nfeatures, operators):
    return insert_random_op(key, tree, nfeatures, operators, at_root=True)


def delete_random_op(
    key: Array, tree: TreeBatch, nfeatures: int, operators: OperatorSet
) -> Tuple[TreeBatch, Array]:
    """Replace a random operator node by one of its children
    (reference delete_random_op src/MutationFunctions.jl:193-233). If the
    tree is a single leaf, regenerates a fresh random leaf."""
    k1, k2, k3 = jax.random.split(key, 3)
    sizes = subtree_sizes(tree.kind, tree.length)
    mask = ((tree.kind == UNA) | (tree.kind == BIN)) & valid_mask(tree)
    idx, any_op = select_node(k1, mask)
    s, e = node_span(tree, idx, sizes)
    l_start, l_end, r_start, r_end = child_spans(tree, idx, sizes)
    is_una = tree.kind[idx] == UNA
    keep_right = jax.random.bernoulli(k2) | is_una
    c_start = jnp.where(keep_right, r_start, l_start)
    c_end = jnp.where(keep_right, r_end, l_end)
    new, fit = splice_tree_donor(tree, s, e, tree, c_start, c_end - c_start)
    ok = any_op & fit

    # single-leaf fallback: fresh random leaf (reference :198-205)
    lk, lo, lf, lc = make_random_leaf(k3, nfeatures, tree.cval.dtype)
    leaf_tree = TreeBatch(
        kind=jnp.zeros_like(tree.kind).at[0].set(lk),
        op=jnp.zeros_like(tree.op),
        feat=jnp.zeros_like(tree.feat).at[0].set(lf),
        cval=jnp.zeros_like(tree.cval).at[0].set(lc),
        length=jnp.int32(1),
    )
    is_leaf_only = tree.length == 1
    out = jax.tree_util.tree_map(
        lambda n, o, l: jnp.where(
            is_leaf_only, l, jnp.where(ok, n, o)
        ),
        new,
        tree,
        leaf_tree,
    )
    return out, ok | is_leaf_only


def gen_random_tree_fixed_size(
    key: Array,
    target_size: Array,
    nfeatures: int,
    operators: OperatorSet,
    max_len: int,
    dtype=jnp.float32,
) -> TreeBatch:
    """Grow a random tree to ~target_size nodes by repeatedly replacing a
    random leaf with a random operator
    (reference gen_random_tree_fixed_size src/MutationFunctions.jl:248-263).
    Fully on-device: a fori_loop of masked append_random_op steps."""
    k0, kloop = jax.random.split(key)
    lk, lo, lf, lc = make_random_leaf(k0, nfeatures, dtype)
    tree = TreeBatch(
        kind=jnp.zeros(max_len, jnp.int32).at[0].set(lk),
        op=jnp.zeros(max_len, jnp.int32),
        feat=jnp.zeros(max_len, jnp.int32).at[0].set(lf),
        cval=jnp.zeros(max_len, dtype).at[0].set(lc),
        length=jnp.int32(1),
    )
    target = jnp.minimum(target_size, max_len)

    def body(i, carry):
        tree, key = carry
        key, k1, k2, k3 = jax.random.split(key, 4)
        remaining = target - tree.length
        # choose arity so we don't overshoot when exactly 1 slot remains
        if operators.n_unary > 0 and operators.n_binary > 0:
            use_unary = (remaining == 1) | jax.random.bernoulli(k1)
        elif operators.n_unary > 0:
            use_unary = jnp.bool_(True)
        else:
            use_unary = jnp.bool_(False)
        mask = ((tree.kind == CONST) | (tree.kind == VAR)) & valid_mask(tree)
        idx, any_leaf = select_node(k2, mask)
        dk, do, df, dc, d_len = _random_op_donor(
            k3, use_unary, nfeatures, operators, dtype
        )
        new, fit = splice(tree, idx, idx + 1, dk, do, df, dc, 0, d_len)
        grow = (tree.length < target) & any_leaf & fit
        tree = jax.tree_util.tree_map(
            lambda n, o: jnp.where(grow, n, o), new, tree
        )
        return tree, key

    steps = max_len // 2 + 1
    tree, _ = jax.lax.fori_loop(0, steps, body, (tree, kloop))
    return tree


def crossover_trees(
    key: Array, a: TreeBatch, b: TreeBatch
) -> Tuple[TreeBatch, TreeBatch, Array]:
    """Swap random subtrees between two trees
    (reference crossover_trees src/MutationFunctions.jl:266-294).
    Returns (a', b', ok); ok=False if either result would overflow."""
    k1, k2 = jax.random.split(key)
    sizes_a = subtree_sizes(a.kind, a.length)
    sizes_b = subtree_sizes(b.kind, b.length)
    ia, ok_a = select_node(k1, valid_mask(a))
    ib, ok_b = select_node(k2, valid_mask(b))
    sa, ea = node_span(a, ia, sizes_a)
    sb, eb = node_span(b, ib, sizes_b)
    a2, fit_a = splice_tree_donor(a, sa, ea, b, sb, eb - sb)
    b2, fit_b = splice_tree_donor(b, sb, eb, a, sa, ea - sa)
    ok = ok_a & ok_b & fit_a & fit_b
    a_out = jax.tree_util.tree_map(lambda n, o: jnp.where(ok, n, o), a2, a)
    b_out = jax.tree_util.tree_map(lambda n, o: jnp.where(ok, n, o), b2, b)
    return a_out, b_out, ok


# ---------------------------------------------------------------------------
# Simplification: constant folding (analog of simplify_tree, reference
# src/SingleIteration.jl:73-74 via DynamicExpressions.simplify_tree)
# ---------------------------------------------------------------------------


def _const_fold_scan(tree: TreeBatch, operators: OperatorSet):
    """Per-node (is_const, folded_value, parent_index) via one stack scan.

    folded_value is meaningful only where is_const. Parent of the root is -1.
    Operator values are computed on scalars with the same jnp semantics as
    the interpreter, so folding is bit-compatible with evaluation."""
    L = tree.max_len
    arity_table = jnp.asarray(ARITY)
    unary_fns = operators.unary_fns
    binary_fns = operators.binary_fns

    def step(carry, x):
        (cstack, vstack, istack, sp, parent) = carry
        i, k, o, c = x
        a_c = cstack[jnp.maximum(sp - 1, 0)]
        b_c = cstack[jnp.maximum(sp - 2, 0)]
        a_v = vstack[jnp.maximum(sp - 1, 0)]
        b_v = vstack[jnp.maximum(sp - 2, 0)]
        a_i = istack[jnp.maximum(sp - 1, 0)]
        b_i = istack[jnp.maximum(sp - 2, 0)]

        if unary_fns:
            una_all = jnp.stack([fn(a_v) for fn in unary_fns])
            una = una_all[jnp.clip(o, 0, len(unary_fns) - 1)]
        else:
            una = a_v
        if binary_fns:
            bin_all = jnp.stack([fn(b_v, a_v) for fn in binary_fns])
            binv = bin_all[jnp.clip(o, 0, len(binary_fns) - 1)]
        else:
            binv = a_v

        is_leaf_const = k == CONST
        node_const = jnp.where(
            k <= VAR,
            is_leaf_const,
            jnp.where(k == UNA, a_c, a_c & b_c),
        )
        node_val = jnp.where(
            k <= VAR, c, jnp.where(k == UNA, una, binv)
        )
        # only fold finite values (don't bake NaN/Inf constants in)
        node_const = node_const & jnp.isfinite(node_val)

        # record parents of consumed children
        arity = arity_table[k]
        parent = jnp.where(
            arity >= 1, parent.at[jnp.maximum(a_i, 0)].set(i), parent
        )
        parent = jnp.where(
            arity == 2, parent.at[jnp.maximum(b_i, 0)].set(i), parent
        )

        new_sp = jnp.where(k == PAD, sp, sp - arity + 1)
        w = jnp.maximum(new_sp - 1, 0)
        valid = k != PAD
        cstack = jnp.where(valid, cstack.at[w].set(node_const), cstack)
        vstack = jnp.where(valid, vstack.at[w].set(node_val), vstack)
        istack = jnp.where(valid, istack.at[w].set(i), istack)
        return (cstack, vstack, istack, new_sp, parent), (node_const, node_val)

    D = L // 2 + 2
    init = (
        jnp.zeros(D, jnp.bool_),
        jnp.zeros(D, tree.cval.dtype),
        jnp.full(D, -1, jnp.int32),
        jnp.int32(0),
        jnp.full(L, -1, jnp.int32),
    )
    xs = (jnp.arange(L, dtype=jnp.int32), tree.kind, tree.op, tree.cval)
    (c_, v_, i_, sp_, parent), (is_const, fold_val) = jax.lax.scan(step, init, xs)
    live = valid_mask(tree)
    return is_const & live, fold_val, parent


def simplify_tree(
    tree: TreeBatch, operators: OperatorSet
) -> Tuple[TreeBatch, Array]:
    """Fold maximal constant subtrees into single CONST leaves.

    Keeps nodes that are not inside any constant subtree; replaces each
    fold-root by a CONST leaf; compacts the survivors preserving postfix
    order (scatter by cumulative index). Returns (tree', changed)."""
    is_const, fold_val, parent = _const_fold_scan(tree, operators)
    live = valid_mask(tree)
    parent_const = jnp.where(
        parent >= 0, is_const[jnp.clip(parent, 0, tree.max_len - 1)], False
    )
    fold_root = is_const & ~parent_const
    keep = live & (~is_const | fold_root)
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    n_new = jnp.sum(keep.astype(jnp.int32))
    L = tree.max_len
    tgt = jnp.where(keep, pos, L)  # L = dropped

    new_kind_src = jnp.where(fold_root, CONST, tree.kind)
    new_op_src = jnp.where(fold_root, 0, tree.op)
    new_feat_src = jnp.where(fold_root, 0, tree.feat)
    new_cval_src = jnp.where(fold_root, fold_val, tree.cval)

    def scatter(src, fill):
        out = jnp.full((L,), fill, src.dtype)
        return out.at[tgt].set(src, mode="drop")

    new = TreeBatch(
        kind=scatter(new_kind_src, PAD),
        op=scatter(new_op_src, 0),
        feat=scatter(new_feat_src, 0),
        cval=scatter(new_cval_src, jnp.zeros((), tree.cval.dtype)),
        length=n_new.astype(jnp.int32),
    )
    changed = n_new < tree.length
    out = jax.tree_util.tree_map(lambda n, o: jnp.where(changed, n, o), new, tree)
    return out, changed


# ---------------------------------------------------------------------------
# Operator combining (reference `combine_operators` from DynamicExpressions,
# applied at src/SingleIteration.jl:73-74): rebalance constant chains so
# constant folding can collapse them — (x + c1) + c2 -> x + (c1+c2),
# (x*c1)/c2 -> x*(c1/c2), etc.
# ---------------------------------------------------------------------------


def _binop_idx(operators: OperatorSet, name: str) -> int:
    try:
        return operators.binary_names.index(name)
    except ValueError:
        return -1


def _combine_fold_table(operators: OperatorSet):
    """Static (inner_op, outer_op) -> (fold, result_op) rules for the
    postfix window [c1, inner, c2, outer]: (L inner c1) outer c2."""
    p = _binop_idx(operators, "+")
    m = _binop_idx(operators, "-")
    t = _binop_idx(operators, "*")
    d = _binop_idx(operators, "/")
    add = lambda a, b: a + b
    sub_ = lambda a, b: a - b
    mul = lambda a, b: a * b
    div_ = lambda a, b: a / b
    rules = []
    if p >= 0:
        rules.append((p, p, add, p))  # (L+c1)+c2 = L+(c1+c2)
    if p >= 0 and m >= 0:
        rules.append((p, m, sub_, p))  # (L+c1)-c2 = L+(c1-c2)
        rules.append((m, p, sub_, m))  # (L-c1)+c2 = L-(c1-c2)
    if m >= 0:
        rules.append((m, m, add, m))  # (L-c1)-c2 = L-(c1+c2)
    if t >= 0:
        rules.append((t, t, mul, t))  # (L*c1)*c2 = L*(c1*c2)
    if t >= 0 and d >= 0:
        rules.append((t, d, div_, t))  # (L*c1)/c2 = L*(c1/c2)
        rules.append((d, t, div_, d))  # (L/c1)*c2 = L/(c1/c2)
    if d >= 0:
        rules.append((d, d, mul, d))  # (L/c1)/c2 = L/(c1*c2)
    return rules


def _combine_pass(tree: TreeBatch, operators: OperatorSet):
    """One combining step: apply at most one constant-chain fold and one
    commutative rotation (constant left child moved to the right) — lowest
    slot first. Returns (tree', changed)."""
    L = tree.max_len
    i = jnp.arange(L, dtype=jnp.int32)
    live = valid_mask(tree)
    kind, op, cval = tree.kind, tree.op, tree.cval
    rules = _combine_fold_table(operators)

    # ---- fold: window [u-3]=CONST c1, [u-2]=BIN inner, [u-1]=CONST c2,
    #      [u]=BIN outer  (by postfix layout u-1 is outer's right child,
    #      u-2 its left child, u-3 inner's right child)
    changed = jnp.bool_(False)
    if rules:
        sh = lambda a, k: jnp.roll(a, k)  # sh(a,1)[u] = a[u-1]
        win = (
            live
            & (kind == BIN)
            & (sh(kind, 1) == CONST)
            & (sh(kind, 2) == BIN)
            & (sh(kind, 3) == CONST)
            & (i >= 3)
        )
        c1 = sh(cval, 3)
        c2 = sh(cval, 1)
        inner = sh(op, 2)
        fold_ok = jnp.zeros(L, jnp.bool_)
        fold_val = jnp.zeros(L, cval.dtype)
        fold_op = jnp.zeros(L, jnp.int32)
        for (op_in, op_out, fold, res_op) in rules:
            match = win & (inner == op_in) & (op == op_out)
            v = fold(c1, c2)
            match = match & jnp.isfinite(v)
            fold_ok = fold_ok | match
            fold_val = jnp.where(match, v, fold_val)
            fold_op = jnp.where(match, res_op, fold_op)
        u = jnp.argmax(fold_ok)  # first applicable window
        do_fold = jnp.any(fold_ok)
        # rewrite: cval[u-3] = fold_val[u]; op[u-2] = fold_op[u];
        # delete slots u-1 and u
        cval = jnp.where(
            do_fold & (i == u - 3), fold_val[u], cval
        )
        op = jnp.where(do_fold & (i == u - 2), fold_op[u], op)
        keep = ~(do_fold & ((i == u - 1) | (i == u))) & live
        pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
        tgt = jnp.where(keep, pos, L)

        def scatter(src, fill):
            out = jnp.full((L,), fill, src.dtype)
            return out.at[tgt].set(src, mode="drop")

        n_new = jnp.sum(keep.astype(jnp.int32))
        folded = TreeBatch(
            kind=scatter(kind, PAD),
            op=scatter(op, 0),
            feat=scatter(tree.feat, 0),
            cval=scatter(cval, jnp.zeros((), cval.dtype)),
            length=n_new.astype(jnp.int32),
        )
        tree = jax.tree_util.tree_map(
            lambda n, o: jnp.where(do_fold, n, o),
            folded,
            tree._replace(op=op, cval=cval),
        )
        tree = tree._replace(
            length=jnp.where(do_fold, n_new, tree.length).astype(jnp.int32)
        )
        changed = changed | do_fold

    # ---- canonicalize: commutative op with CONST left child and
    #      non-const right child -> rotate [c, R..., op] to [R..., c, op]
    comm = [x for x in (_binop_idx(operators, "+"), _binop_idx(operators, "*"))
            if x >= 0]
    if comm:
        live = valid_mask(tree)
        sizes = subtree_sizes(tree.kind, tree.length)
        is_comm = jnp.zeros(L, jnp.bool_)
        for cidx in comm:
            is_comm = is_comm | (tree.op == cidx)
        r_root = jnp.clip(i - 1, 0, L - 1)
        size_r = sizes[r_root]
        l_root = jnp.clip(i - 1 - size_r, 0, L - 1)
        rot = (
            live
            & (tree.kind == BIN)
            & is_comm
            & (tree.kind[l_root] == CONST)
            & (tree.kind[r_root] != CONST)
            & (i >= 2)
        )
        u = jnp.argmax(rot)
        do_rot = jnp.any(rot)
        p = jnp.clip(u - 1 - sizes[jnp.clip(u - 1, 0, L - 1)], 0, L - 1)
        # src index for cyclic rotate of span [p, u-1] by one
        src = jnp.where(
            (i >= p) & (i < u - 1), i + 1, jnp.where(i == u - 1, p, i)
        )
        src = jnp.clip(src, 0, L - 1)

        def rotate(a):
            return jnp.where(do_rot, a[src], a)

        tree = tree._replace(
            kind=rotate(tree.kind),
            op=rotate(tree.op),
            feat=rotate(tree.feat),
            cval=rotate(tree.cval),
        )
        changed = changed | do_rot

    return tree, changed


def combine_operators(
    tree: TreeBatch, operators: OperatorSet
) -> Tuple[TreeBatch, Array]:
    """Iterated constant-chain combining to a fixed point (bounded passes).

    Covers the reference's (x op c1) op c2 family over +,-,*,/ plus
    commutative canonicalization of constant left children; constant
    subtree folding itself is simplify_tree's job."""
    def body(carry):
        t, _, any_ch, n = carry
        t2, ch = _combine_pass(t, operators)
        return t2, ch, any_ch | ch, n + 1

    def cond(carry):
        _, ch, _, n = carry
        return ch & (n < tree.max_len)

    t0, ch0 = _combine_pass(tree, operators)
    t, _, changed, _ = jax.lax.while_loop(
        cond, body, (t0, ch0, ch0, jnp.int32(1))
    )
    return t, changed
