"""Deterministic fault injection — outages by construction, not by luck.

The recurring operational failure of the long-lived search (ROADMAP #3,
BENCH_r02–r05 ``tunnel_state`` down/half-open) is the device or tunnel
dying mid-run. Recovery paths that are only exercised by real outages
rot; this module makes every failure mode a reproducible test input:

* ``raise`` / ``tunnel_down`` — raise :class:`FaultInjected` at exactly
  dispatch N of the host loop (``tunnel_down`` spells its message like
  the runtime's ``UNAVAILABLE`` tunnel fault, so classification paths
  see what they would see in production);
* ``kill`` — SIGKILL this process at dispatch N (no atexit, no finally:
  the honest simulation of a preempted VM or an OOM kill);
* ``tear_checkpoint`` — truncate checkpoint write N mid-byte and die,
  proving the crash-atomic write discipline of
  ``utils/checkpoint.py`` (a torn ``.tmp`` must never shadow a good
  snapshot).

A :class:`FaultPlan` is **one-shot**: once tripped it is spent, so the
supervisor's resumed attempt (or a restarted process, via the fuse
file) runs clean instead of re-dying at the same dispatch. Plans come
from :func:`set_fault_plan` (in-process tests) or the environment
(``SRTPU_FAULT_PLAN="kill@2"``, crossing the process boundary for
subprocess kill tests; ``SRTPU_FAULT_FUSE=/path`` persists the spent
mark across the restart).

Pure host-side stdlib — no jax import; safe to import from anywhere.
"""

from __future__ import annotations

import dataclasses
import os
import signal
from typing import Optional, Tuple

#: recognized plan kinds (the fault-plan vocabulary, docs/resilience.md)
FAULT_KINDS = ("raise", "kill", "tunnel_down", "tear_checkpoint")

ENV_PLAN = "SRTPU_FAULT_PLAN"
ENV_FUSE = "SRTPU_FAULT_FUSE"


class FaultInjected(RuntimeError):
    """The exception every non-kill injected fault raises. A RuntimeError
    so production handlers (the api loop's dispatch_fault emission, the
    supervisor's classify-and-resume) treat it exactly like a real
    device fault — nothing may special-case injected failures, or the
    test would prove the special case, not the recovery path."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One deterministic failure: ``kind`` at index ``at``.

    ``at`` counts the unit the kind targets: the host loop's 0-based
    dispatch index for ``raise``/``kill``/``tunnel_down``, the 0-based
    checkpoint file-write index for ``tear_checkpoint`` (each
    ``save_search_state`` call performs two file writes — target then
    ``.bkup`` — so ``at=1`` tears the run's very first backup write)."""

    kind: str
    at: int = 0

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; one of {FAULT_KINDS}"
            )
        if self.at < 0:
            raise ValueError("fault index must be >= 0")

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """``"kind@N"`` (the SRTPU_FAULT_PLAN spelling) -> FaultPlan."""
        kind, sep, at = spec.strip().partition("@")
        if not sep:
            raise ValueError(
                f"fault plan {spec!r} is not of the form 'kind@N'"
            )
        try:
            n = int(at)
        except ValueError:
            raise ValueError(f"fault plan index {at!r} is not an integer")
        return cls(kind=kind, at=n)

    def spec(self) -> str:
        return f"{self.kind}@{self.at}"


# module state: the active plan (explicit set wins over env), spent plan
# specs (in-process one-shot), and the checkpoint write counter
_PLAN: Optional[FaultPlan] = None
_PLAN_EXPLICIT = False
_SPENT: set = set()
_WRITE_COUNT = 0


def set_fault_plan(plan: Optional[FaultPlan]) -> None:
    """Install (or with None, clear) the in-process fault plan. Resets
    the spent set and the checkpoint write counter: a test installing a
    plan starts a fresh failure scenario."""
    global _PLAN, _PLAN_EXPLICIT, _WRITE_COUNT
    _PLAN = plan
    _PLAN_EXPLICIT = plan is not None
    _SPENT.clear()
    _WRITE_COUNT = 0


def clear_fault_plan() -> None:
    set_fault_plan(None)


def get_fault_plan() -> Optional[FaultPlan]:
    """The active plan: an explicitly set one, else SRTPU_FAULT_PLAN
    from the environment (re-read every call — the supervisor's retry
    and a watcher-restarted process both see the current value)."""
    if _PLAN_EXPLICIT:
        return _PLAN
    spec = os.environ.get(ENV_PLAN)
    if not spec:
        return None
    return FaultPlan.parse(spec)


def _fuse_path() -> Optional[str]:
    return os.environ.get(ENV_FUSE) or None


def _is_spent(plan: FaultPlan) -> bool:
    if plan.spec() in _SPENT:
        return True
    fuse = _fuse_path()
    if not fuse or not os.path.exists(fuse):
        return False
    # the fuse stores the spec of the plan that blew it: only THAT plan
    # is spent — a stale fuse from a previous scenario must not silently
    # disarm a different plan (an unreadable fuse fails safe as spent,
    # never double-firing a kill)
    try:
        with open(fuse) as f:
            return f.readline().strip() == plan.spec()
    except OSError:
        return True


def _trip(plan: FaultPlan) -> None:
    """Mark the plan spent BEFORE the failure fires: for 'kill' there is
    no after, and the restarted process must find the fuse blown."""
    _SPENT.add(plan.spec())
    fuse = _fuse_path()
    if fuse:
        try:
            with open(fuse, "w") as f:
                f.write(plan.spec() + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            pass  # a fuse that cannot persist still spends in-process


def on_dispatch(index: int) -> None:
    """Hook called by the search host loop immediately before issuing
    dispatch `index` (0-based, counted across outputs). Raises or kills
    per the active plan; a no-op with no plan, a spent plan, or a
    non-matching index."""
    plan = get_fault_plan()
    if (
        plan is None
        or plan.kind not in ("raise", "kill", "tunnel_down")
        or index != plan.at
        or _is_spent(plan)
    ):
        return
    _trip(plan)
    if plan.kind == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    if plan.kind == "tunnel_down":
        raise FaultInjected(
            f"UNAVAILABLE: simulated tunnel down at dispatch {index} "
            "(fault-injected)"
        )
    raise FaultInjected(
        f"injected dispatch fault at dispatch {index}"
    )


def on_checkpoint_write(payload: bytes) -> Tuple[bytes, bool]:
    """Hook called by ``utils.checkpoint`` once per checkpoint FILE
    write with the full payload about to be written. Returns
    ``(bytes_to_write, torn)``: with an active ``tear_checkpoint`` plan
    at this write index, the payload comes back truncated mid-byte and
    ``torn`` is True — the writer must write the torn bytes (the
    process "died" part-way through) and then raise
    :class:`FaultInjected` WITHOUT completing the atomic rename."""
    global _WRITE_COUNT
    plan = get_fault_plan()
    if plan is None or plan.kind != "tear_checkpoint" or _is_spent(plan):
        return payload, False
    index = _WRITE_COUNT
    _WRITE_COUNT += 1
    if index != plan.at:
        return payload, False
    _trip(plan)
    return payload[: max(1, len(payload) // 2)], True
