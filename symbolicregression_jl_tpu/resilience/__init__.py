"""Preemption-tolerant search (docs/resilience.md, ROADMAP #3).

Three pieces that together make every search survivable:

* periodic snapshots — ``Options.snapshot_path`` /
  ``snapshot_every_dispatches`` serialize the compact per-output
  ``SearchState`` (populations + hall of fame + host PRNG key) through
  ``utils.checkpoint`` every k dispatches, crash-atomically;
* :mod:`~symbolicregression_jl_tpu.resilience.faults` — deterministic
  fault injection (raise / SIGKILL / tunnel-down at dispatch N, torn
  checkpoint writes), so recovery paths are tested by construction;
* :mod:`~symbolicregression_jl_tpu.resilience.supervisor` —
  :func:`supervised_search`, the retry/backoff loop that resumes from
  the newest valid snapshot instead of restarting, bit-identically.
"""

from . import faults
from .faults import FaultInjected, FaultPlan, clear_fault_plan, set_fault_plan
from .supervisor import SupervisedResult, backoff_s, supervised_search

__all__ = [
    "FaultInjected",
    "FaultPlan",
    "SupervisedResult",
    "backoff_s",
    "clear_fault_plan",
    "faults",
    "set_fault_plan",
    "supervised_search",
]
