"""Auto-resume supervisor: retry/backoff around ``equation_search`` that
resumes from the newest valid snapshot instead of restarting.

PR 7 made the sharded search a compiled contract, PR 8 taught the
telemetry doctor to call a fault-with-``saved_state`` *resumable*, and
the snapshot plumbing (Options ``snapshot_path`` /
``snapshot_every_dispatches``) makes mid-run state durable — this module
closes the loop (ROADMAP #3): a dispatch fault, a tunnel drop, or an
injected failure costs at most ``snapshot_every_dispatches`` dispatches
of work, never the run.

Policy (docs/resilience.md):

* **capped attempts** — at most ``max_attempts`` ``equation_search``
  calls, then the last exception re-raises (a deterministically failing
  config must not loop forever);
* **exponential backoff with jitter** — attempt k sleeps
  ``min(cap, base * 2**(k-1)) * (1 + jitter*u)`` before retrying, so a
  flapping tunnel is not hammered in lockstep;
* **resume, not restart** — every attempt first loads the newest valid
  snapshot at ``snapshot_path`` (``load_search_state`` falls back to
  ``.bkup`` on a torn main file) and runs only the REMAINING
  iterations; the snapshot's Options fingerprint is checked at load, so
  a stale file from a different config restarts cleanly instead of
  resuming garbage;
* **classified failures** — with telemetry enabled, each failed
  attempt's event log goes through ``telemetry.analyze.analyze_run``
  and the verdict (``faulted``/``resumable``) is recorded in the
  returned :class:`SupervisedResult.history` — the machine-readable
  story of what died and what was recovered.

Resumes are bit-identical continuations: the snapshot carries each
output's host PRNG key, so a supervised run that faulted and resumed
produces the same hall of fame as the uninterrupted run (asserted in
tests/test_ad_resilience.py on fused and chunked drivers, donation on
and off).
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional

from .faults import FaultInjected  # noqa: F401  (re-exported convenience)

#: equation_search kwargs that are NOT Options kwargs (the same split
#: equation_search itself performs); everything else in **search_kwargs
#: constructs the Options.
_SEARCH_ONLY_KWARGS = frozenset((
    "weights", "variable_names", "saved_state", "warm_start_file",
    "return_state", "runtests", "on_iteration", "parallelism",
    "numprocs", "procs", "addprocs_function",
))


@dataclasses.dataclass
class SupervisedResult:
    """`equation_search` result plus the supervision record."""

    result: Any  # EquationSearchResult
    attempts: int = 1
    resumes: int = 0
    #: one entry per FAILED attempt: {"attempt", "error_type", "error",
    #: "verdict", "resumable", "resumed_from_iteration", "backoff_s"}
    history: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    #: the stable logical run id threaded through every attempt's
    #: run_start (the fleet index's join key — docs/observability.md
    #: "Fleet"); None only on pre-fleet results
    run_id: Optional[str] = None


def backoff_s(
    attempt: int,
    base_s: float,
    cap_s: float,
    jitter: float,
    rng: random.Random,
) -> float:
    """Delay before the retry following failed attempt `attempt`
    (1-based): exponential in the attempt index, capped, with
    multiplicative jitter in [0, jitter]."""
    d = min(cap_s, base_s * (2.0 ** max(0, attempt - 1)))
    if jitter > 0:
        d *= 1.0 + jitter * rng.random()
    return d


def _newest_event_log(telemetry_dir: str, since_ts: float) -> Optional[str]:
    try:
        cands = [
            os.path.join(telemetry_dir, f)
            for f in os.listdir(telemetry_dir)
            if f.startswith("events-") and f.endswith(".jsonl")
        ]
        cands = [p for p in cands if os.path.getmtime(p) >= since_ts]
        return max(cands, key=os.path.getmtime) if cands else None
    except OSError:
        return None


def _classify(telemetry_dir: Optional[str], since_ts: float) -> Dict[str, Any]:
    """The doctor's view of the attempt that just failed: verdict +
    resumable flag from the newest event log the attempt wrote, or
    {} when there is no telemetry to read."""
    if not telemetry_dir:
        return {}
    path = _newest_event_log(telemetry_dir, since_ts)
    if path is None:
        return {}
    from ..telemetry.analyze import analyze_run

    try:
        report = analyze_run(path)
    except OSError:
        return {}
    return {
        "verdict": report.get("verdict"),
        "resumable": bool(report.get("resumable")),
        "event_log": path,
    }


def supervised_search(
    X,
    y,
    *,
    snapshot_path: str,
    snapshot_every_dispatches: int = 1,
    niterations: int = 10,
    max_attempts: int = 3,
    backoff_base_s: float = 1.0,
    backoff_cap_s: float = 60.0,
    backoff_jitter: float = 0.25,
    sleep_fn: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
    fleet_root: Optional[str] = None,
    **search_kwargs,
) -> SupervisedResult:
    """Run ``equation_search(X, y, niterations=..., **search_kwargs)``
    under supervision: snapshots every ``snapshot_every_dispatches``
    dispatches to ``snapshot_path``, and on failure retries (backoff,
    capped attempts) resuming from the newest valid snapshot — including
    a snapshot left by a previous PROCESS (a supervised run restarted
    after SIGKILL picks up exactly where the dead one's last snapshot
    stopped).

    Accepts the same kwargs as ``equation_search`` (``options=`` or
    option kwargs, plus ``return_state``/``weights``/...). The snapshot
    knobs are forced into the Options; ``saved_state`` is owned by the
    supervisor and may not be passed. Raises the last failure when
    ``max_attempts`` is exhausted.

    Fleet provenance (docs/observability.md "Fleet"): one stable
    ``run_id`` is generated per supervised run and threaded — with the
    1-based attempt index — through every attempt's Options, so each
    attempt's ``run_start`` event carries it and the fleet index
    collapses the whole resumable->resumed trail into ONE row. With a
    ``fleet_root`` (or ``SRTPU_FLEET_ROOT`` in the environment) the run
    is also registered into ``<fleet_root>/fleet_registry.jsonl`` before
    the first attempt, so the fleet sees it even before any event log
    exists. Purely host-side file writes: the hall of fame is
    bit-identical with registration on or off."""
    if "saved_state" in search_kwargs:
        raise ValueError(
            "supervised_search owns saved_state (it resumes from "
            "snapshot_path); pass a fresh snapshot_path instead"
        )
    from ..api import equation_search
    from ..models.options import make_options
    from ..utils.checkpoint import CheckpointIncompatible, load_search_state

    options = search_kwargs.pop("options", None)
    search_only = {
        k: v for k, v in search_kwargs.items() if k in _SEARCH_ONLY_KWARGS
    }
    option_kwargs = {
        k: v for k, v in search_kwargs.items()
        if k not in _SEARCH_ONLY_KWARGS
    }
    if options is None:
        options = make_options(**option_kwargs)
    elif option_kwargs:
        raise ValueError("Pass either options= or option kwargs, not both")
    options = dataclasses.replace(
        options,
        snapshot_path=snapshot_path,
        snapshot_every_dispatches=snapshot_every_dispatches,
    )
    rng = rng or random.Random(options.seed)
    telemetry_dir = (
        (options.telemetry_dir or ".") if options.telemetry else None
    )

    # one stable logical run id for ALL attempts: the fleet join key
    # (each attempt's run_start carries run_id + its attempt index)
    import uuid

    run_id = options.telemetry_run_id or uuid.uuid4().hex[:16]
    fleet_root = fleet_root or os.environ.get("SRTPU_FLEET_ROOT") or None
    if fleet_root:
        from ..telemetry.fleet import register_run

        register_run(
            fleet_root,
            source="supervisor",
            run_id=run_id,
            telemetry_dir=telemetry_dir,
            snapshot_path=snapshot_path,
            niterations=niterations,
            max_attempts=max_attempts,
        )

    history: List[Dict[str, Any]] = []
    resumes = 0
    attempt = 0
    while True:
        attempt += 1
        # newest valid snapshot (main, else .bkup) decides resume vs
        # fresh start. A fingerprint mismatch is a RESTART (the file is
        # from another config), recorded in history immediately — the
        # decision must be visible even when the fresh attempt then
        # succeeds. Generic corruption (both twins unreadable)
        # PROPAGATES: load's contract says a destroyed checkpoint is
        # never silently a fresh start, and the supervisor must not
        # convert hours of banked progress into a quiet rerun.
        saved = None
        if os.path.exists(snapshot_path) or os.path.exists(
            snapshot_path + ".bkup"
        ):
            try:
                saved = load_search_state(snapshot_path, options=options)
            except CheckpointIncompatible as e:
                history.append({
                    "attempt": attempt,
                    "snapshot_error": f"{type(e).__name__}: {e}",
                })
            except FileNotFoundError:
                saved = None  # raced away between exists() and load
        done = min((s.iteration for s in saved), default=0) if saved else 0
        remaining = max(0, niterations - done)
        if saved is not None:
            # attempt 1 can already be a resume: a supervised run
            # restarted after SIGKILL starts from the dead run's snapshot
            resumes += 1
        t_attempt = time.time()
        try:
            result = equation_search(
                X, y,
                # the fleet provenance rides the Options (orchestration
                # only — _graph_key unchanged, no recompiles): this
                # attempt's run_start carries (run_id, attempt)
                options=dataclasses.replace(
                    options,
                    telemetry_run_id=run_id,
                    telemetry_attempt=attempt,
                ),
                niterations=remaining,
                saved_state=saved, **search_only,
            )
            return SupervisedResult(
                result=result,
                attempts=attempt,
                resumes=resumes,
                history=history,
                run_id=run_id,
            )
        except Exception as e:
            entry: Dict[str, Any] = {
                "attempt": attempt,
                "error_type": type(e).__name__,
                "error": str(e)[:500],
                "resumed_from_iteration": done if saved else None,
            }
            entry.update(_classify(telemetry_dir, t_attempt))
            if attempt >= max_attempts:
                entry["gave_up"] = True
                history.append(entry)
                raise
            delay = backoff_s(
                attempt, backoff_base_s, backoff_cap_s, backoff_jitter,
                rng,
            )
            entry["backoff_s"] = round(delay, 3)
            history.append(entry)
            sleep_fn(delay)
