"""Symbolic export/import — the L6 interop layer.

Analog of the reference's SymbolicUtils bridge (reference
src/InterfaceDynamicExpressions.jl:160-194: `node_to_symbolic` /
`symbolic_to_node` and the `convert(::Type{Node}, x, options)` pair, tested
for eval-equivalence round-trips in test/test_simplification.jl:66-83).
Here the symbolic backend is sympy (host-side, never on the hot path):

    to_sympy(tree, options)        TreeBatch/Expr -> sympy expression
    from_sympy(expr, options)      sympy expression -> Expr (encodable)
    sympy_simplify_tree(tree, ...) round-trip through sympy.simplify
    to_latex(tree, options)        LaTeX string
    to_callable(tree, options)     jitted X -> y inference function
                                   (the reference's `tree(X)` callable,
                                   DynamicExpressions' functional form)
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Union

import jax
import numpy as np

from ..models.options import Options
from ..models.trees import (
    BIN,
    CONST,
    UNA,
    VAR,
    Expr,
    TreeBatch,
    decode_tree,
    encode_tree,
)
from ..ops.operators import OperatorSet

try:  # sympy is host-side UX only; everything degrades without it
    import sympy
except ImportError:  # pragma: no cover
    sympy = None


def _require_sympy():
    if sympy is None:  # pragma: no cover
        raise ImportError("sympy is required for symbolic export")


def _operators(opts: Union[Options, OperatorSet]) -> OperatorSet:
    return opts.operators if isinstance(opts, Options) else opts


# ---------------------------------------------------------------------------
# name -> sympy constructor (built lazily so import works without sympy)
# ---------------------------------------------------------------------------


def _sympy_tables():
    s = sympy
    unary = {
        "cos": s.cos,
        "sin": s.sin,
        "tan": s.tan,
        "exp": s.exp,
        "log": s.log,
        "log2": lambda x: s.log(x, 2),
        "log10": lambda x: s.log(x, 10),
        "log1p": lambda x: s.log(x + 1),
        "sqrt": s.sqrt,
        "abs": s.Abs,
        "square": lambda x: x**2,
        "cube": lambda x: x**3,
        "neg": lambda x: -x,
        "relu": lambda x: s.Max(x, 0),
        "sinh": s.sinh,
        "cosh": s.cosh,
        "tanh": s.tanh,
        "asin": s.asin,
        "acos": s.acos,
        "atan": s.atan,
        "asinh": s.asinh,
        "acosh": s.acosh,
        "atanh": s.atanh,
        "erf": s.erf,
        "erfc": s.erfc,
        "gamma": s.gamma,
        "sigmoid": lambda x: 1 / (1 + s.exp(-x)),
        "gauss": lambda x: s.exp(-(x**2)),
        "inv": lambda x: 1 / x,
        "sign": s.sign,
        "identity": lambda x: x,
    }
    binary = {
        "+": lambda a, b: a + b,
        "-": lambda a, b: a - b,
        "*": lambda a, b: a * b,
        "/": lambda a, b: a / b,
        "^": lambda a, b: a**b,
        "pow": lambda a, b: a**b,
        "mod": s.Mod,
        "max": s.Max,
        "min": s.Min,
        "greater": lambda a, b: s.Piecewise((1.0, a > b), (0.0, True)),
        "logical_or": lambda a, b: s.Piecewise(
            (1.0, sympy.Or(a > 0, b > 0)), (0.0, True)
        ),
        "logical_and": lambda a, b: s.Piecewise(
            (1.0, sympy.And(a > 0, b > 0)), (0.0, True)
        ),
        "atan2": s.atan2,
    }
    return unary, binary


def _var_symbols(
    nfeatures: int, variable_names: Optional[Sequence[str]]
) -> list:
    if variable_names is not None:
        return [sympy.Symbol(n, real=True) for n in variable_names]
    return [sympy.Symbol(f"x{i}", real=True) for i in range(nfeatures)]


def to_sympy(
    tree: Union[TreeBatch, Expr],
    options: Union[Options, OperatorSet],
    variable_names: Optional[Sequence[str]] = None,
):
    """Convert an expression to a sympy expression (analog of
    `node_to_symbolic`, reference src/InterfaceDynamicExpressions.jl:160-176).
    """
    _require_sympy()
    ops = _operators(options)
    expr = tree if isinstance(tree, Expr) else decode_tree(tree)
    una_tab, bin_tab = _sympy_tables()

    max_feat = _max_feature(expr)
    syms = _var_symbols(
        max_feat + 1
        if variable_names is None
        else len(variable_names),
        variable_names,
    )

    def rec(e: Expr):
        if e.kind == CONST:
            return sympy.Float(e.cval)
        if e.kind == VAR:
            return syms[e.feat]
        if e.kind == UNA:
            name = ops.unary_names[e.op]
            fn = una_tab.get(name)
            if fn is None:
                fn = sympy.Function(name)
            return fn(rec(e.children[0]))
        name = ops.binary_names[e.op]
        fn = bin_tab.get(name)
        if fn is None:
            fn = sympy.Function(name)
        return fn(rec(e.children[0]), rec(e.children[1]))

    return rec(expr)


def _max_feature(expr: Expr) -> int:
    m = expr.feat if expr.kind == VAR else 0
    for c in expr.children:
        m = max(m, _max_feature(c))
    return m


def from_sympy(
    sexpr,
    options: Union[Options, OperatorSet],
    variable_names: Optional[Sequence[str]] = None,
) -> Expr:
    """Convert a sympy expression back to an Expr using only the operators
    in the active OperatorSet (analog of `convert(::Type{Node}, x, options)`,
    reference src/InterfaceDynamicExpressions.jl:178-194). Raises ValueError
    if the expression needs an operator outside the set."""
    _require_sympy()
    ops = _operators(options)

    def var_index(name: str) -> int:
        if variable_names is not None and name in variable_names:
            return list(variable_names).index(name)
        if name.startswith("x") and name[1:].isdigit():
            return int(name[1:])
        raise ValueError(f"Unknown variable {name!r}")

    def bin_idx(name: str) -> int:
        try:
            return ops.binary_index(name)
        except ValueError:
            raise ValueError(
                f"Expression requires binary operator {name!r} "
                f"not in operator set {ops.binary_names}"
            )

    def una_idx(name: str) -> int:
        try:
            return ops.unary_index(name)
        except ValueError:
            raise ValueError(
                f"Expression requires unary operator {name!r} "
                f"not in operator set {ops.unary_names}"
            )

    def fold_assoc(name: str, args) -> Expr:
        out = rec(args[0])
        for a in args[1:]:
            out = Expr.binary(bin_idx(name), out, rec(a))
        return out

    def negated(a):
        """If `a` is a negative term, return its positive counterpart."""
        if a.is_Number:
            return -a if a < 0 else None
        if a.is_Mul:
            coeff, rest = a.as_coeff_Mul()
            if coeff < 0:
                return (-coeff) * rest
        return None

    def negate(inner: Expr) -> Expr:
        if "neg" in ops.unary_names:
            return Expr.unary(una_idx("neg"), inner)
        if "-" in ops.binary_names:
            return Expr.binary(bin_idx("-"), Expr.const(0.0), inner)
        if "*" in ops.binary_names:
            return Expr.binary(bin_idx("*"), Expr.const(-1.0), inner)
        raise ValueError("Cannot express negation with operator set")

    def rec(e) -> Expr:
        if e.is_Number:
            return Expr.const(float(e))
        if e.is_Symbol:
            return Expr.var(var_index(str(e)))
        if e.func == sympy.Add:
            # Render negative terms as `a - b` when "-" is available, so
            # Add(x0, Mul(-1, x1)) doesn't require "*" in the set.
            pos, neg = [], []
            for a in e.args:
                nb = negated(a)
                if nb is not None and "-" in ops.binary_names:
                    neg.append(nb)
                else:
                    pos.append(a)
            out = negate(rec(neg.pop(0))) if not pos else fold_assoc("+", pos)
            for b in neg:
                out = Expr.binary(bin_idx("-"), out, rec(b))
            return out
        if e.func == sympy.Mul:
            coeff, rest = e.as_coeff_Mul()
            if coeff == -1 and "*" not in ops.binary_names:
                return negate(rec(rest))
            return fold_assoc("*", e.args)
        if e.func == sympy.Pow:
            base, expo = e.args
            # x^-1 -> inv or 1/x; x^0.5 -> sqrt; small int powers -> mults
            if expo == -1:
                if "inv" in ops.unary_names:
                    return Expr.unary(una_idx("inv"), rec(base))
                if "/" in ops.binary_names:
                    return Expr.binary(
                        bin_idx("/"), Expr.const(1.0), rec(base)
                    )
            if expo == sympy.Rational(1, 2):
                if "sqrt" in ops.unary_names:
                    return Expr.unary(una_idx("sqrt"), rec(base))
            if "^" in ops.binary_names:
                return Expr.binary(bin_idx("^"), rec(base), rec(expo))
            if (
                expo.is_Integer
                and 2 <= int(expo) <= 4
                and "*" in ops.binary_names
            ):
                out = rec(base)
                b = rec(base)
                for _ in range(int(expo) - 1):
                    out = Expr.binary(bin_idx("*"), out, b)
                return out
            if expo.is_Integer and int(expo) < 0 and "/" in ops.binary_names:
                inner = rec(base**(-expo))
                return Expr.binary(bin_idx("/"), Expr.const(1.0), inner)
            raise ValueError(f"Cannot express power {e} with operator set")
        name = e.func.__name__.lower()
        remap = {"abs": "abs", "max": "max", "min": "min"}
        name = remap.get(name, name)
        if len(e.args) == 1:
            # Rewrite fallbacks for operators absent from the set.
            if name == "abs" and "abs" not in ops.unary_names:
                if "sqrt" in ops.unary_names and "*" in ops.binary_names:
                    inner = rec(e.args[0])
                    return Expr.unary(
                        una_idx("sqrt"),
                        Expr.binary(bin_idx("*"), inner, inner),
                    )
            return Expr.unary(una_idx(name), rec(e.args[0]))
        if len(e.args) == 2:
            if name in ("max", "min"):
                return Expr.binary(bin_idx(name), rec(e.args[0]), rec(e.args[1]))
            return Expr.binary(bin_idx(name), rec(e.args[0]), rec(e.args[1]))
        if len(e.args) > 2 and name in ("max", "min"):
            return fold_assoc(name, e.args)
        raise ValueError(f"Cannot convert sympy node {e!r} (func={e.func})")

    return rec(sympy.sympify(sexpr))


def sympy_simplify_tree(
    tree: Union[TreeBatch, Expr],
    options: Union[Options, OperatorSet],
    variable_names: Optional[Sequence[str]] = None,
    max_len: Optional[int] = None,
) -> TreeBatch:
    """Round-trip tree -> sympy.simplify -> tree. Falls back to the original
    tree if the simplified form needs operators outside the set (the
    reference's round-trip tests allow the same, test_simplification.jl).
    """
    _require_sympy()
    ops = _operators(options)
    if max_len is None:
        max_len = (
            options.max_len
            if isinstance(options, Options)
            else (tree.max_len if isinstance(tree, TreeBatch) else 64)
        )
    orig = tree if isinstance(tree, Expr) else decode_tree(tree)
    try:
        simplified = sympy.simplify(to_sympy(orig, ops, variable_names))
        expr = from_sympy(simplified, ops, variable_names)
        if expr.size() > max_len:
            expr = orig
    except (ValueError, TypeError, OverflowError):
        expr = orig
    return encode_tree(expr, max_len)


def to_latex(
    tree: Union[TreeBatch, Expr],
    options: Union[Options, OperatorSet],
    variable_names: Optional[Sequence[str]] = None,
) -> str:
    """LaTeX form of an expression (via sympy printing)."""
    _require_sympy()
    return sympy.latex(to_sympy(tree, options, variable_names))


def to_callable(
    tree: TreeBatch,
    options: Union[Options, OperatorSet],
) -> Callable:
    """Jitted inference function X (nfeat, n) -> y (n,) for a discovered
    equation — the analog of DynamicExpressions' `tree(X)` callable form
    (reference README.md:67-74 uses eval_tree_array directly)."""
    from ..ops.interpreter import eval_tree

    ops = _operators(options)

    @jax.jit
    def f(X):
        y, ok = eval_tree(tree, X, ops)
        return y

    return f
