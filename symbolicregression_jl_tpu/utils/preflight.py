"""Preflight configuration checks before a search starts.

Analog of the reference's Configure.jl battery
(test_option_configuration :3-50, test_dataset_configuration :53-83): verify
operators are NaN-safe over a probe grid (they must return NaN, not raise),
shapes line up, and batching is suggested for very large datasets. The
worker-shipping half of Configure.jl (:86-285) has no analog — SPMD programs
are identical on every host by construction.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from ..models.options import Options


class PreflightError(ValueError):
    pass


def test_entire_pipeline(options: Options, X, ys, weights=None) -> None:
    """Mini end-to-end probe: a 4-member population evolved for a handful
    of cycles on a 20-row slice (analog of Configure.jl's
    test_entire_pipeline :249-285, which runs a tiny s_r_cycle on every
    worker before the real search). Raises PreflightError on failure."""
    import jax.numpy as jnp

    from ..models.evolve import init_island_state, s_r_cycle

    try:
        probe = make_probe_options(options)
        # probe the first up-to-20 USABLE rows: with a weights vector,
        # rows carrying zero weight are excluded from the loss (the
        # data_policy="mask" front door parks bad rows there —
        # docs/robustness_numeric.md), and a probe slice of only
        # zero-weight rows would aggregate 0/0 -> all-inf scores and
        # fail a perfectly healthy configuration
        X_h, ys_h = np.asarray(X), np.asarray(ys)
        w_h = None if weights is None else np.asarray(weights)
        if w_h is not None and np.any(w_h > 0):
            idx = np.where(w_h > 0)[0][:20]
        else:
            idx = np.arange(min(20, X_h.shape[1]))
        Xp = jnp.asarray(X_h[:, idx], jnp.float32)
        yp = jnp.asarray(ys_h[0, idx], jnp.float32)
        wp = (
            None if w_h is None
            else jnp.asarray(w_h[idx], jnp.float32)
        )
        st = init_island_state(
            jax.random.PRNGKey(0), probe, X.shape[0], Xp, yp, wp, 1.0
        )
        st = s_r_cycle(st, jnp.int32(probe.maxsize), Xp, yp, wp, 1.0, probe)
        if not bool(jnp.any(jnp.isfinite(st.pop.scores))):
            raise PreflightError(
                "pipeline probe produced no finite scores — check the "
                "operator set and loss against your data ranges"
            )
    except PreflightError:
        raise
    except Exception as e:
        raise PreflightError(f"pipeline probe failed: {e}") from e


def make_probe_options(options: Options) -> Options:
    """Tiny-budget copy of the user's Options for the pipeline probe."""
    import dataclasses

    return dataclasses.replace(
        options,
        npop=4,
        npopulations=1,
        ncycles_per_iteration=3,
        tournament_selection_n=2,
        n_parallel_tournaments=2,
        maxsize=min(options.maxsize, 8),
        max_len=0,
        should_optimize_constants=False,
        batching=False,
        verbosity=0,
        progress=False,
    )


def preflight_checks(
    options: Options, X, ys, weights, pipeline: bool = False
) -> None:
    ops = options.operators
    # binary and unary operator names must not collide
    # (reference src/Configure.jl:44-50: binop ∩ unaop = ∅)
    overlap = set(ops.binary_names) & set(ops.unary_names)
    if overlap:
        raise PreflightError(
            f"Operators {sorted(overlap)} appear as both binary and unary"
        )
    # probe grid +-100 like the reference (src/Configure.jl:29-43)
    grid = jnp.asarray(
        np.concatenate([np.linspace(-100, 100, 41), [0.0, -0.0, 1e-9]]),
        jnp.float32,
    )
    with jax.disable_jit():  # tiny arrays; avoid 2*n_ops compilations
        for name, fn in zip(ops.unary_names, ops.unary_fns):
            try:
                out = fn(grid)
            except Exception as e:  # pragma: no cover
                raise PreflightError(
                    f"Unary operator {name!r} raised on the probe grid: {e}"
                ) from e
            if out.shape != grid.shape:
                raise PreflightError(
                    f"Unary operator {name!r} is not elementwise"
                )
        for name, fn in zip(ops.binary_names, ops.binary_fns):
            try:
                out = fn(grid[:, None], grid[None, :])
            except Exception as e:  # pragma: no cover
                raise PreflightError(
                    f"Binary operator {name!r} raised on the probe grid: {e}"
                ) from e

    if weights is not None:
        w = np.asarray(weights)
        if w.shape != (X.shape[1],):
            raise PreflightError(
                f"weights shape {w.shape} must be (n,) = ({X.shape[1]},)"
            )
    if not np.all(np.isfinite(np.asarray(X))):
        raise PreflightError("X contains non-finite values")
    if not np.all(np.isfinite(np.asarray(ys))):
        raise PreflightError("y contains non-finite values")
    if X.shape[1] > 10000 and not options.batching:
        # reference src/Configure.jl:63-70
        warnings.warn(
            "Dataset has >10k rows; consider Options(batching=True) "
            "(or shard rows over the mesh) for faster evolution",
            stacklevel=3,
        )
    if pipeline:
        test_entire_pipeline(options, X, ys, weights)
