"""Preflight configuration checks before a search starts.

Analog of the reference's Configure.jl battery
(test_option_configuration :3-50, test_dataset_configuration :53-83): verify
operators are NaN-safe over a probe grid (they must return NaN, not raise),
shapes line up, and batching is suggested for very large datasets. The
worker-shipping half of Configure.jl (:86-285) has no analog — SPMD programs
are identical on every host by construction.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from ..models.options import Options


class PreflightError(ValueError):
    pass


def preflight_checks(options: Options, X, ys, weights) -> None:
    ops = options.operators
    # probe grid +-100 like the reference (src/Configure.jl:29-43)
    grid = jnp.asarray(
        np.concatenate([np.linspace(-100, 100, 41), [0.0, -0.0, 1e-9]]),
        jnp.float32,
    )
    with jax.disable_jit():  # tiny arrays; avoid 2*n_ops compilations
        for name, fn in zip(ops.unary_names, ops.unary_fns):
            try:
                out = fn(grid)
            except Exception as e:  # pragma: no cover
                raise PreflightError(
                    f"Unary operator {name!r} raised on the probe grid: {e}"
                ) from e
            if out.shape != grid.shape:
                raise PreflightError(
                    f"Unary operator {name!r} is not elementwise"
                )
        for name, fn in zip(ops.binary_names, ops.binary_fns):
            try:
                out = fn(grid[:, None], grid[None, :])
            except Exception as e:  # pragma: no cover
                raise PreflightError(
                    f"Binary operator {name!r} raised on the probe grid: {e}"
                ) from e

    if weights is not None:
        w = np.asarray(weights)
        if w.shape != (X.shape[1],):
            raise PreflightError(
                f"weights shape {w.shape} must be (n,) = ({X.shape[1]},)"
            )
    if not np.all(np.isfinite(np.asarray(X))):
        raise PreflightError("X contains non-finite values")
    if not np.all(np.isfinite(np.asarray(ys))):
        raise PreflightError("y contains non-finite values")
    if X.shape[1] > 10000 and not options.batching:
        # reference src/Configure.jl:63-70
        warnings.warn(
            "Dataset has >10k rows; consider Options(batching=True) "
            "(or shard rows over the mesh) for faster evolution",
            stacklevel=3,
        )
