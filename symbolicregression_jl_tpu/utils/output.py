"""Hall-of-fame rendering, CSV checkpointing, resume loading.

Analogs: string_dominating_pareto_curve (reference src/HallOfFame.jl:112-152,
score column = -Δlog(loss)/Δcomplexity), the double-write CSV checkpoint
(src/SymbolicRegression.jl:747-767: file + .bkup each update to survive a
mid-write kill), and load_saved_hall_of_fame (src/SearchUtils.jl:275-301).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, List, Optional, Sequence

import jax
import numpy as np

from ..models.options import Options
from ..models.population import HallOfFame, calculate_pareto_frontier
from ..models.trees import TreeBatch, decode_tree, expr_to_string

Array = jax.Array


@dataclasses.dataclass
class Candidate:
    """One hall-of-fame entry, host-side."""

    complexity: int
    loss: float
    score: float  # -Δlog(loss)/Δcomplexity vs previous frontier point
    equation: str
    tree: TreeBatch  # single tree (batch shape ())

    def __repr__(self):
        return (
            f"Candidate(complexity={self.complexity}, loss={self.loss:.6g}, "
            f"equation={self.equation!r})"
        )


def hof_to_candidates(
    hof: HallOfFame,
    options: Options,
    variable_names: Optional[Sequence[str]] = None,
    pareto_only: bool = True,
) -> List[Candidate]:
    """Decode the device HoF into sorted host-side candidates with the
    reference's Pareto score column (src/HallOfFame.jl:136-139)."""
    exists = np.asarray(hof.exists)
    losses = np.asarray(hof.losses)
    front = np.asarray(calculate_pareto_frontier(hof))
    pick = front if pareto_only else exists
    out: List[Candidate] = []
    prev_loss, prev_c = None, None
    for i in np.where(pick)[0]:
        tree = jax.tree_util.tree_map(lambda x: np.asarray(x[i]), hof.trees)
        eq = expr_to_string(decode_tree(tree), options.operators, variable_names)
        c = i + 1
        loss = float(losses[i])
        if prev_loss is None or prev_loss <= 0 or loss <= 0:
            score = 0.0 if prev_loss is None else np.inf
        else:
            score = -(np.log(loss) - np.log(prev_loss)) / max(c - prev_c, 1)
        out.append(
            Candidate(
                complexity=int(c),
                loss=loss,
                score=float(max(score, 0.0)),
                equation=eq,
                tree=tree,
            )
        )
        prev_loss, prev_c = loss, c
    return out


def pareto_table(
    candidates: List[Candidate], title: str = "Hall of Fame"
) -> str:
    """Render the frontier like the reference's progress table."""
    lines = [
        "-" * 78,
        f"{title}",
        "-" * 78,
        f"{'Complexity':<12}{'Loss':<16}{'Score':<12}Equation",
    ]
    for c in candidates:
        lines.append(
            f"{c.complexity:<12}{c.loss:<16.8g}{c.score:<12.4g}{c.equation}"
        )
    lines.append("-" * 78)
    return "\n".join(lines)


def save_hof_csv(
    candidates: List[Candidate], path: str
) -> None:
    """Double-write checkpoint: path then path.bkup
    (reference src/SymbolicRegression.jl:749-767)."""
    body = "Complexity;Loss;Equation\n" + "".join(
        f"{c.complexity};{c.loss:.12g};{c.equation}\n" for c in candidates
    )
    for p in (path, path + ".bkup"):
        with open(p, "w") as f:
            f.write(body)


def _parse_hof_csv(path, options, variable_names):
    """Parse one checkpoint file. Returns (candidates, clean) — clean is
    False when any line failed to parse (a torn file from a mid-write
    kill)."""
    from ..models.trees import encode_tree, parse_expression

    out: List[Candidate] = []
    clean = True
    with open(path) as f:
        f.readline()  # header
        for line in f:
            parts = line.rstrip("\n").split(";", 2)
            try:
                if len(parts) != 3:
                    raise ValueError("short line")
                c, loss, eq = parts
                expr = parse_expression(
                    eq, options.operators, variable_names
                )
                out.append(
                    Candidate(
                        complexity=int(c),
                        loss=float(loss),
                        score=0.0,
                        equation=eq,
                        tree=encode_tree(expr, options.max_len),
                    )
                )
            except (ValueError, KeyError):
                clean = False
    return out, clean


def load_hof_csv(
    path: str, options: Options, variable_names=None
) -> List[Candidate]:
    """Re-parse a checkpoint CSV back into candidates (equations re-parsed
    through parse_expression; analog of load_saved_hall_of_fame,
    reference src/SearchUtils.jl:275-301).

    The double-write (`save_hof_csv`) guarantees at least one intact copy
    survives a mid-write kill: a missing OR torn main file falls back to
    `.bkup` when the backup parses clean (prefer main on ties — it is the
    newer write)."""
    bkup = path + ".bkup"
    cands, clean = (
        _parse_hof_csv(path, options, variable_names)
        if os.path.exists(path)
        else ([], False)
    )
    if not clean and os.path.exists(bkup):
        bcands, bclean = _parse_hof_csv(bkup, options, variable_names)
        if bclean or len(bcands) > len(cands):
            return bcands
    return cands
