"""Host-side random expression generation (tests / fuzzing only).

Mirrors gen_random_tree_fixed_size semantics (reference
src/MutationFunctions.jl:248-263): grow a tree to an exact node count by
repeatedly replacing a random leaf with a random operator node. The on-device
generator lives in models/mutate_device.py; this host version is its test
oracle.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..models.trees import CONST, VAR, Expr
from ..ops.operators import OperatorSet


def make_random_leaf(rng: np.random.Generator, nfeatures: int) -> Expr:
    # 50/50 const/feature (reference src/MutationFunctions.jl:151-157)
    if rng.random() < 0.5:
        return Expr.const(float(rng.standard_normal()))
    return Expr.var(int(rng.integers(nfeatures)))


def _leaves(e: Expr, out: List[Expr]) -> None:
    if not e.children:
        out.append(e)
    for c in e.children:
        _leaves(c, out)


def random_expr_fixed_size(
    rng: np.random.Generator,
    operators: OperatorSet,
    nfeatures: int,
    target_size: int,
) -> Expr:
    """Grow to exactly target_size nodes (unary adds 1, binary adds 2; may
    overshoot by 1 with unary ops present, like the reference)."""
    root = make_random_leaf(rng, nfeatures)
    while root.size() < target_size:
        leaves: List[Expr] = []
        _leaves(root, leaves)
        leaf = leaves[rng.integers(len(leaves))]
        remaining = target_size - root.size()
        use_unary = operators.n_unary > 0 and (
            operators.n_binary == 0 or (remaining == 1 or rng.random() < 0.5)
        )
        if use_unary:
            op = int(rng.integers(operators.n_unary))
            new = Expr.unary(op, make_random_leaf(rng, nfeatures))
        else:
            op = int(rng.integers(operators.n_binary))
            new = Expr.binary(
                op,
                make_random_leaf(rng, nfeatures),
                make_random_leaf(rng, nfeatures),
            )
        # replace leaf in place
        leaf.kind, leaf.op, leaf.feat, leaf.cval, leaf.children = (
            new.kind,
            new.op,
            new.feat,
            new.cval,
            new.children,
        )
    return root
