"""Profiling helpers — the TPU-native analog of the reference's profiling
story (it ships `benchmark/analyze.py` to digest Julia `Profile` text
dumps; here the profiler of record is XLA's, viewed in
TensorBoard/Perfetto).

`trace(...)` wraps `jax.profiler.trace` for capturing a search's device
timeline; `annotate(...)` names host-side regions inside a capture;
`device_memory_stats()` snapshots per-device live-buffer usage (the HBM
analog of the reference's host ResourceMonitor).
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Capture an XLA profiler trace of the enclosed block.

    View with TensorBoard (`tensorboard --logdir <log_dir>`) or the
    Perfetto UI. Typical use wraps a few warm search iterations:

        with profiling.trace("/tmp/sr_trace"):
            equation_search(X, y, niterations=2, ...)
    """
    with jax.profiler.trace(
        log_dir, create_perfetto_link=create_perfetto_link
    ):
        yield


def annotate(name: str):
    """Named host-side region inside an active trace (shows up on the
    timeline alongside device ops)."""
    return jax.profiler.TraceAnnotation(name)


def device_memory_stats() -> Dict[str, Optional[Dict[str, int]]]:
    """Per-device memory statistics (bytes_in_use, peak_bytes_in_use, ...)
    keyed by device string; value None where the backend doesn't report
    (CPU usually doesn't)."""
    out: Dict[str, Optional[Dict[str, int]]] = {}
    for d in jax.devices():
        try:
            out[str(d)] = d.memory_stats()
        except Exception:  # pragma: no cover
            out[str(d)] = None
    return out
