"""Search telemetry recorder — the analog of the reference's `@recorder`
subsystem (reference src/Recorder.jl:6-20; enabled via `options.recorder`,
default from env var PYSR_RECORDER, src/Options.jl:597-599).

What the reference records (SURVEY.md §5): the options string, per-(output,
island) per-iteration population snapshots (record_population,
src/Population.jl:156-171), a mutation-lineage graph keyed by member `ref`
ids, and the final hall of fame; merged head-side via recursive_merge
(src/Utils.jl:41-51) and serialized to JSON with allow_inf at exit
(src/SymbolicRegression.jl:923-927).

TPU-native design: members live in device arrays without per-member ref
ids (the hot loop is one fused XLA computation), so refs are structural
content hashes (tree_hash). Two granularities are recorded:

* population snapshots per iteration (record_population), with
  survived/new lineage inferred from hash membership;
* the FULL per-event mutation log (record_mutation_events): in recorder
  mode the cycle scan stacks a fixed-shape MutationEvents record per
  cycle on device — parent/child trees, kind, accept/reject reason,
  replaced-member deaths — drained here once per iteration into the
  reference's ref-keyed `mutations` schema. One host transfer per
  iteration, zero cost when the recorder is off.
"""

from __future__ import annotations

import json
import math
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..models.options import Options
from ..models.trees import TreeBatch, decode_tree, expr_to_string

RecordType = Dict[str, Any]


def recursive_merge(*dicts: RecordType) -> RecordType:
    """Nested dict merge, later values win on conflicts at leaves
    (reference src/Utils.jl:41-51)."""
    out: RecordType = {}
    for d in dicts:
        for k, v in d.items():
            if k in out and isinstance(out[k], dict) and isinstance(v, dict):
                out[k] = recursive_merge(out[k], v)
            else:
                out[k] = v
    return out




class Recorder:
    """Accumulates a RecordType dict; `save()` writes JSON (Infinity allowed,
    mirroring the reference's allow_inf serialization)."""

    def __init__(self, options: Options,
                 variable_names: Optional[Sequence[str]] = None,
                 sink=None):
        self.options = options
        self.variable_names = variable_names
        # telemetry event sink (telemetry/events.py): save() announces the
        # written artifact there, so one JSONL trail names every output
        # channel of a run
        self.sink = sink
        self.record: RecordType = {
            "options": repr_options(options),
            "start_time": time.time(),
        }
        # previous snapshot hashes per (output, island) for lineage inference
        self._prev_hashes: Dict[str, set] = {}

    # -- population snapshots ------------------------------------------------
    def record_population(
        self,
        output: int,
        island: int,
        iteration: int,
        trees: TreeBatch,
        scores,
        losses,
        birth,
        mut_counts=None,
    ) -> None:
        """Analog of record_population (reference src/Population.jl:156-171),
        plus snapshot-level lineage (survived / new) and, when given,
        cumulative proposed/accepted counters per mutation kind (the
        batched engine's aggregate stand-in for the reference recorder's
        per-event mutation log)."""
        key = f"out{output + 1}_pop{island + 1}"
        # one device->host transfer for the whole island, sliced on host
        trees_np = jax.tree_util.tree_map(np.asarray, trees)
        scores = np.asarray(scores)
        losses = np.asarray(losses)
        birth = np.asarray(birth)
        npop = int(scores.shape[0])
        prev = self._prev_hashes.get(key, set())
        # whole-island stringification through the native batch printer when
        # available (C++ host runtime); per-member Python decode otherwise.
        # The printer renders by operator NAME, so custom Python-registered
        # operators work here too — only library presence gates the path.
        from .. import native

        eqs = None
        if native.native_available():
            eqs = native.trees_to_strings(
                trees_np.kind, trees_np.op, trees_np.feat, trees_np.cval,
                trees_np.length, self.options.operators, self.variable_names,
            )
        from ..models.trees import tree_hash

        refs = [f"{int(h):016x}" for h in np.atleast_1d(tree_hash(trees_np))]
        members: List[RecordType] = []
        cur: set = set()
        for m in range(npop):
            ref = refs[m]
            eq = eqs[m] if eqs is not None else expr_to_string(
                decode_tree(jax.tree_util.tree_map(lambda x: x[m], trees_np)),
                self.options.operators, self.variable_names,
            )
            members.append(
                {
                    "ref": ref,
                    "tree": eq,
                    "score": float(scores[m]),
                    "loss": float(losses[m]),
                    "birth": int(birth[m]),
                    # survivor of the previous snapshot keeps its ref;
                    # otherwise an accepted mutation/crossover/migrant
                    "parent": ref if ref in prev else "new",
                }
            )
            cur.add(ref)
        self._prev_hashes[key] = cur
        entry: RecordType = {
            "population": members,
            "time": time.time(),
        }
        if mut_counts is not None:
            from ..models.evolve import MUTATION_NAMES

            counts = np.asarray(mut_counts)
            entry["mutation_counts"] = {
                name: {
                    "proposed": int(counts[i, 0]),
                    "accepted": int(counts[i, 1]),
                }
                for i, name in enumerate(MUTATION_NAMES)
            }
        self.record.setdefault(key, {})[f"iteration{iteration + 1}"] = entry

    # -- full mutation lineage -----------------------------------------------
    def record_mutation_events(self, output: int, iteration: int,
                               events) -> None:
        """Drain one iteration's device-side MutationEvents ring into the
        reference recorder's `mutations` schema: every proposed child keyed
        by content-hash ref with tree/score/loss/parent and an event list
        carrying mutation kind + accept/reject reason
        (reference src/Recorder.jl:6-22, schema asserted by
        test/test_recorder.jl:24-46)."""
        from .. import native
        from ..models.evolve import MUTATION_NAMES, REASON_NAMES
        from ..models.trees import tree_hash

        ev = jax.tree_util.tree_map(np.asarray, events)
        # (ncycles, I, B, ...) -> flat N
        ncycles, I, B = ev.kind.shape
        flat = jax.tree_util.tree_map(
            lambda x: x.reshape((-1,) + x.shape[3:]), ev
        )
        n = flat.kind.shape[0]
        child_refs = [f"{int(h):016x}" for h in tree_hash(flat.child)]
        parent_refs = [f"{int(h):016x}" for h in tree_hash(flat.parent)]

        eqs = None
        if native.native_available():
            eqs = native.trees_to_strings(
                flat.child.kind, flat.child.op, flat.child.feat,
                flat.child.cval, flat.child.length,
                self.options.operators, self.variable_names,
            )

        dead_refs = [f"{int(h):016x}" for h in tree_hash(flat.dead)]

        mutations: RecordType = self.record.setdefault("mutations", {})
        cross_row = len(MUTATION_NAMES) - 1
        for e in range(n):
            ref = child_refs[e]
            entry = mutations.get(ref)
            entry_was_new = entry is None
            if entry is None:
                if eqs is not None:
                    eq = eqs[e]
                else:
                    eq = expr_to_string(
                        decode_tree(
                            jax.tree_util.tree_map(
                                lambda x: x[e], flat.child
                            )
                        ),
                        self.options.operators, self.variable_names,
                    )
                entry = mutations[ref] = {
                    "tree": eq,
                    "score": float(flat.score[e]),
                    "loss": float(flat.loss[e]),
                    "parent": parent_refs[e],
                    "events": [],
                }
            kind = int(flat.kind[e])
            cycle = e // (I * B)
            island = (e // B) % I
            entry["events"].append(
                {
                    "type": "crossover" if kind == cross_row else "mutate",
                    "mutation": MUTATION_NAMES[kind],
                    "accepted": bool(flat.accepted[e]),
                    "reason": REASON_NAMES[int(flat.reason[e])],
                    "output": output + 1,
                    "island": island + 1,
                    "iteration": iteration + 1,
                    "cycle": cycle + 1,
                }
            )
            # death of the replaced-oldest member in the same slot
            # (reference src/RegularizedEvolution.jl:103-132 death events).
            # Only the self-death of an entry first created by THIS event
            # is suppressed; a pre-existing entry with the same content
            # hash as the child legitimately records its member's death.
            dref = dead_refs[e]
            dentry = mutations.get(dref)
            if dentry is not None and not (dref == ref and entry_was_new):
                dentry["events"].append(
                    {
                        "type": "death",
                        "loss": float(flat.dead_loss[e]),
                        "output": output + 1,
                        "island": island + 1,
                        "iteration": iteration + 1,
                        "cycle": cycle + 1,
                    }
                )

    # -- evaluation memo-bank telemetry --------------------------------------
    def record_cache(self, output: int, iteration: int,
                     row: RecordType) -> None:
        """One iteration's memo-bank counters (options.cache_fitness):
        scored / unique / memo_hits / evaluated plus the derived
        unique-ratio, memo-hit-rate and eval-batch-fill fractions (the
        observable savings of the cache subsystem — no reference analog;
        the reference never deduplicates its evals)."""
        key = f"out{output + 1}_cache"
        self.record.setdefault(key, {})[f"iteration{iteration + 1}"] = {
            k: v for k, v in row.items() if k not in ("output", "iteration")
        }

    # -- hall of fame timeline ----------------------------------------------
    def record_hall_of_fame(self, output: int, iteration: int,
                            candidates) -> None:
        key = f"out{output + 1}_hall_of_fame"
        self.record.setdefault(key, {})[f"iteration{iteration + 1}"] = [
            {
                "complexity": c.complexity,
                "loss": c.loss,
                "score": c.score,
                "equation": c.equation,
            }
            for c in candidates
        ]

    def record_final(self, num_evals: float, search_time_s: float) -> None:
        self.record["num_evals"] = float(num_evals)
        self.record["search_time_s"] = float(search_time_s)

    def merge(self, other: RecordType) -> None:
        self.record = recursive_merge(self.record, other)

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.options.recorder_file
        with open(path, "w") as f:
            # json.dump emits bare Infinity/NaN tokens by default — the same
            # non-strict JSON the reference writes with allow_inf
            # (src/SymbolicRegression.jl:923-927).
            json.dump(self.record, f)
        if self.sink is not None:
            self.sink.emit(
                "recorder_saved", path=path, keys=len(self.record)
            )
        return path


def repr_options(options: Options) -> str:
    """Stable single-line options string for the record header
    (reference stores `"$(options)"`)."""
    fields = []
    for f in options.__dataclass_fields__:
        v = getattr(options, f, None)
        if callable(v):
            v = getattr(v, "__name__", "<callable>")
        fields.append(f"{f}={v!r}")
    return "Options(" + ", ".join(fields) + ")"


def find_iteration_from_record(key: str, record: RecordType) -> int:
    """Highest recorded iteration index for a population key
    (reference src/Recorder.jl:14-20)."""
    i = 0
    while f"iteration{i + 1}" in record.get(key, {}):
        i += 1
    return i
