"""Progress reporting + runtime self-measurement.

Analogs: the reference's 5-second state print with a 50-second moving
average of "cycles per second" (src/SymbolicRegression.jl:869-897;
src/SearchUtils.jl:233-268), the WrappedProgressBar (src/ProgressBars.jl,
silenced when SYMBOLIC_REGRESSION_TEST=true), and the ResourceMonitor that
estimates head-node occupation and warns above 20%
(src/SearchUtils.jl:143-213).

In the SPMD design there is no head node; the analog of "head occupation"
is the fraction of wall time the host spends *outside* the jitted iteration
(decoding, printing, checkpointing) while the device sits idle — measured
here and warned about at the same 20% threshold.
"""

from __future__ import annotations

import os
import sys
import time
from collections import deque
from typing import Deque, Optional, Tuple


def _quiet() -> bool:
    return os.environ.get("SYMBOLIC_REGRESSION_TEST", "") == "true"


def debug(verbosity: int, *args, **kwargs) -> None:
    """Verbosity-gated print (reference src/Utils.jl:6-16)."""
    if verbosity > 0 and not _quiet():
        print(*args, **kwargs)


class ResourceMonitor:
    """Host-occupation estimator (ResourceMonitor analog,
    reference src/SearchUtils.jl:143-213).

    The warning routes through the telemetry event sink when one is
    attached (a machine-readable ``resource_warning`` event, emitted even
    in quiet mode — the trail must survive silenced consoles) and prints
    to stderr only when the run is not quiet (verbosity > 0 and not
    SYMBOLIC_REGRESSION_TEST)."""

    def __init__(self, warn_fraction: float = 0.2, max_samples: int = 100,
                 sink=None, verbosity: int = 1):
        self.warn_fraction = warn_fraction
        self.sink = sink
        self.verbosity = verbosity
        self.device_s = 0.0
        self.host_s = 0.0
        self._samples: Deque[Tuple[float, float]] = deque(maxlen=max_samples)
        self._warned = False

    def note(self, device_s: float, host_s: float) -> None:
        self.device_s += device_s
        self.host_s += host_s
        self._samples.append((device_s, host_s))

    @property
    def host_occupation(self) -> float:
        tot = self.device_s + self.host_s
        return self.host_s / tot if tot > 0 else 0.0

    def maybe_warn(self) -> None:
        if (
            self._warned
            or len(self._samples) < 5
            or self.host_occupation <= self.warn_fraction
        ):
            return
        self._warned = True
        message = (
            f"the host spends {100 * self.host_occupation:.1f}% "
            "of wall time on orchestration (decoding/printing/"
            "checkpointing) while the device is idle. Consider "
            "verbosity=0, progress=False, or a larger "
            "ncycles_per_iteration."
        )
        if self.sink is not None:
            self.sink.emit(
                "resource_warning",
                host_occupation=self.host_occupation,
                message=message,
            )
        if self.verbosity > 0 and not _quiet():
            print("Warning: " + message, file=sys.stderr)


class SearchProgress:
    """Cycles/sec moving average + progress percentage.

    The reference counts `num_equations += ncycles_per_iteration * npop / 10`
    per finished island-iteration and averages over a 50 s window sampled
    every 5 s (src/SymbolicRegression.jl:851,869-896). Here one sample is
    recorded per host-loop iteration (= npopulations island-iterations)."""

    WINDOW_S = 50.0

    def __init__(self, total_iterations: int, options, sink=None) -> None:
        self.total = max(total_iterations, 1)
        self.options = options
        self.sink = sink
        self.t0 = time.time()
        self._samples: Deque[Tuple[float, float]] = deque()
        self._equations = 0.0

    def note_iteration(self, n_islands: int = 1) -> None:
        self._equations += (
            self.options.ncycles_per_iteration * self.options.npop / 10.0
        ) * n_islands
        now = time.time()
        self._samples.append((now, self._equations))
        while self._samples and now - self._samples[0][0] > self.WINDOW_S:
            self._samples.popleft()

    @property
    def cycles_per_second(self) -> float:
        if len(self._samples) < 2:
            return 0.0
        (t_a, e_a), (t_b, e_b) = self._samples[0], self._samples[-1]
        return (e_b - e_a) / max(t_b - t_a, 1e-9)

    def status_line(self, iteration: int, best_loss: float,
                    num_evals: float,
                    cache_counts: Optional[Tuple[int, int, int]] = None,
                    ) -> str:
        """cache_counts: cumulative (scored, unique, memo_hits) from the
        evaluation memo bank (options.cache_fitness) — rendered as the
        fraction of scored trees answered without evaluation, split into
        intra-batch dedup and cross-iteration memo hits."""
        pct = 100.0 * (iteration + 1) / self.total
        line = (
            f"Cycles/second: {self.cycles_per_second:.3e}. "
            f"Progress: {iteration + 1}/{self.total} ({pct:.0f}%). "
            f"Best loss: {best_loss:.6g}. Evals: {num_evals:.3g}. "
            f"Elapsed: {time.time() - self.t0:.1f}s."
        )
        if cache_counts is not None:
            scored, unique, hits = (int(v) for v in cache_counts)
            if scored > 0:
                saved = scored - (unique - hits)
                line += (
                    f" Cache: {100.0 * saved / scored:.0f}% hits "
                    f"(dedup {100.0 * (scored - unique) / scored:.0f}%, "
                    f"memo {100.0 * hits / scored:.0f}%)."
                )
        return line

    def report(self, iteration: int, best_loss: float, num_evals: float,
               cache_counts: Optional[Tuple[int, int, int]] = None,
               prefix: str = "", console: bool = True,
               output: Optional[int] = None,
               search_iteration: Optional[int] = None) -> str:
        """One iteration's status, through every attached channel: a
        ``progress`` event on the telemetry sink (always, when one is
        set — quiet consoles must not silence the machine-readable
        trail) and the classic status line on stdout (``console=True``
        and not quiet). Returns the rendered line."""
        import math

        if self.sink is not None:
            cache = None
            if cache_counts is not None:
                scored, unique, hits = (int(v) for v in cache_counts)
                cache = {"scored": scored, "unique": unique,
                         "memo_hits": hits}
            self.sink.emit(
                "progress",
                iteration=search_iteration,
                output=output,
                best_loss=(
                    float(best_loss)
                    if best_loss is not None and math.isfinite(best_loss)
                    else None
                ),
                num_evals=float(num_evals),
                cycles_per_second=self.cycles_per_second,
                elapsed_s=time.time() - self.t0,
                cache=cache,
            )
        line = prefix + self.status_line(
            iteration, best_loss, num_evals, cache_counts=cache_counts
        )
        if console and not _quiet():
            print(line)
        return line


class ProgressBar:
    """In-terminal bar with a multiline postfix (WrappedProgressBar analog,
    reference src/ProgressBars.jl:11-37). Rewinds and overwrites its
    previous output on TTYs; appends plainly when piped. Writes nothing
    when SYMBOLIC_REGRESSION_TEST=true."""

    def __init__(self, total: int, width: int = 40):
        self.total = max(total, 1)
        self.width = width
        self._last_lines = 0

    def update(self, done: int, postfix: str = "") -> None:
        if _quiet():
            return
        frac = min(done / self.total, 1.0)
        filled = int(frac * self.width)
        bar = "#" * filled + "-" * (self.width - filled)
        text = f"[{bar}] {done}/{self.total} ({100 * frac:.0f}%)"
        if postfix:
            text += "\n" + postfix
        if self._last_lines and sys.stdout.isatty():
            # move up over the previous render and clear each line
            sys.stdout.write(f"\x1b[{self._last_lines}F\x1b[0J")
        sys.stdout.write(text + "\n")
        sys.stdout.flush()
        self._last_lines = text.count("\n") + 1


class QuitWatcher:
    """'q'<enter> stops the search between iterations (stdin watcher analog,
    reference src/SearchUtils.jl:59-107). Polls stdin non-blockingly from
    the host loop — no thread, no raw-mode terminal changes. Inactive when
    stdin is not a TTY (pipes, CI) or under SYMBOLIC_REGRESSION_TEST."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled and not _quiet()
        try:
            self.enabled = self.enabled and sys.stdin.isatty()
        except Exception:  # pragma: no cover
            self.enabled = False
        if self.enabled and not _quiet():
            print("Press 'q' then <enter> to stop early.", file=sys.stderr)

    def should_quit(self) -> bool:
        if not self.enabled:
            return False
        import select

        try:
            ready, _, _ = select.select([sys.stdin], [], [], 0)
        except Exception:  # pragma: no cover
            return False
        if not ready:
            return False
        line = sys.stdin.readline()
        return line.strip().lower().startswith("q")
