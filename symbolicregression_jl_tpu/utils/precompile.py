"""Compilation warm-up — the analog of the reference's SnoopPrecompile
workload (reference src/precompile.jl:34-79, which runs a full 3-iteration
search for Float32 + Float64 at module load so user searches start hot).

XLA's equivalent of Julia's precompile cache is the persistent compilation
cache: `do_precompilation()` enables it (if not already configured) and
traces + compiles the search's hot programs — the fused iteration function
and the fitness kernel — on tiny shapes, so the first real
`equation_search` of a matching Options reuses the cached executables.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

import numpy as np


def probe_compilation_cache(
    cache_dir: str, timeout: float = 600.0
) -> bool:
    """Check in a throwaway subprocess whether this image's XLA executable
    serializer survives writing the persistent cache.

    Some jaxlib builds segfault inside `executable.serialize()` for certain
    CPU executables (observed on the batching-mode evolution step), killing
    the whole process from inside the cache write — so the probe compiles
    exactly that known-crashy shape with the cache enabled. A crash takes
    the subprocess, not the caller. Returns True when the cache is safe;
    the probe's own cache writes then pre-warm `cache_dir` for the caller.

    The probe always runs pinned to CPU: the serialize bug is CPU-only,
    and an accelerator held exclusively by the parent (TPU) must not be
    contended for. Callers skip the probe entirely on non-CPU backends
    (enable_compilation_cache does this)."""
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from symbolicregression_jl_tpu.utils.precompile import ("
        "do_precompilation)\n"
        f"do_precompilation(mode='compile', cache_dir={cache_dir!r}, "
        "probe_cache=False, batching=True, batch_size=8)\n"
        "print('CACHE_PROBE_OK')\n"
    )
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("PYTHONPATH", "")
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = pkg_root + os.pathsep + env["PYTHONPATH"]
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            timeout=timeout,
            text=True,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "CACHE_PROBE_OK" in r.stdout


def enable_compilation_cache(
    cache_dir: Optional[str] = None, probe: bool = False
) -> Optional[str]:
    """Point JAX's persistent compilation cache at `cache_dir`.

    An explicit `cache_dir` always wins; otherwise an already-configured
    cache (jax.config / JAX_COMPILATION_CACHE_DIR) is left untouched, and
    only a fully-unconfigured process gets the package default
    (~/.cache/symbolicregression_jl_tpu).

    With probe=True the cache is only enabled after
    probe_compilation_cache() demonstrates in a subprocess that the
    serializer survives on this backend; returns None (cache left
    disabled) when the probe fails.

    Two process-global caveats: (1) once any compile has used the cache,
    JAX keeps the initialized cache singleton even if the config is later
    pointed elsewhere — call jax._src.compilation_cache.reset_cache() to
    truly detach; (2) on some jaxlib builds `executable.serialize()` can
    crash for certain large CPU executables, killing the process from
    inside the cache write — that is exactly what the probe screens for
    (TPU executables are unaffected)."""
    import jax

    existing = jax.config.jax_compilation_cache_dir
    if cache_dir is None:
        if existing is not None:
            return existing
        cache_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "symbolicregression_jl_tpu"
        )
    os.makedirs(cache_dir, exist_ok=True)
    # the serializer bug is CPU-only: accelerator backends enable the
    # cache without probing (and the probe must never contend for an
    # exclusively-held chip)
    if probe and jax.default_backend() == "cpu":
        if not probe_compilation_cache(cache_dir):
            import warnings

            warnings.warn(
                "persistent compilation cache disabled: the executable "
                "serializer crashed in the probe subprocess (known jaxlib "
                "issue on some CPU executables)"
            )
            return None
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return cache_dir


def do_precompilation(
    mode: str = "compile",
    cache_dir: Optional[str] = None,
    nfeatures: int = 5,
    n_rows: int = 32,
    probe_cache: bool = True,
    **option_kwargs,
) -> None:
    """Warm the compile caches like the reference's precompile workload
    (src/precompile.jl:34-79; `mode=:compile` variant used by its tests).

    mode="compile": trace + compile the iteration program (no real search).
    mode="search": additionally run a real 3-iteration search, matching the
    reference's full workload.

    XLA executables are keyed on BOTH the Options and the data shapes, so
    warm with the `nfeatures`/`n_rows` of the dataset you will search and
    pass the same option kwargs (operators, npop, ...) — a warm-up on
    different shapes or options compiles different programs and the real
    search will still compile cold.

    probe_cache=True (default) screens the persistent cache through a
    subprocess serializer probe first; when the probe fails, the warm-up
    still runs but only fills this process's in-memory jit cache."""
    if mode not in ("compile", "search"):
        raise ValueError("mode must be 'compile' or 'search'")
    for reserved in ("niterations", "runtests"):
        if reserved in option_kwargs:
            raise ValueError(
                f"{reserved!r} is fixed by do_precompilation; only Options "
                "kwargs can be forwarded"
            )
    enable_compilation_cache(cache_dir, probe=probe_cache)

    from ..api import equation_search

    rng = np.random.default_rng(0)
    X = rng.standard_normal((nfeatures, n_rows)).astype(np.float32)
    y = np.cos(X[nfeatures - 1]) + X[0] ** 2 - 2.0
    kwargs = dict(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        npop=8,
        npopulations=2,
        tournament_selection_n=4,
        ncycles_per_iteration=3,
        maxsize=10,
        verbosity=0,
        progress=False,
    )
    kwargs.update(option_kwargs)
    niterations = 3 if mode == "search" else 1
    equation_search(
        X, y, niterations=niterations, runtests=False, **kwargs
    )
