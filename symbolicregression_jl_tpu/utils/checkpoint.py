"""Full search-state disk checkpointing.

The reference's exact-resume state lives only in the Julia session
(`return_state=true` → pass the tuple back to EquationSearch,
src/SearchUtils.jl:270-273); its only on-disk artifact is the hall-of-fame
CSV. Here the complete `SearchState` (per-island populations, statistics,
PRNG keys — device-side per-island keys AND the host-loop master key —
hall of fame, iteration counter) round-trips through a file, so an exact
resume survives a process restart:

    res = equation_search(X, y, return_state=True, ...)
    save_search_state("run.ckpt", res.state, options=options)
    # ... new process ...
    state = load_search_state("run.ckpt", options=options)
    res2 = equation_search(X, y, saved_state=state, ...)

Arrays are stored as host numpy inside a pickle (the state is small —
populations, not datasets). The payload is stamped with the schema magic
version and an Options fingerprint (the `_saved_state_compatible`-adjacent
shape fields), so an incompatible resume fails HERE with a clear message
instead of deep inside `equation_search`'s shape validation.

Every file write is **crash-atomic**: the payload goes to a `.tmp`
sibling, is fsync'd, then `os.replace`d over the target — first the main
file, then the `.bkup` twin. A kill at ANY byte leaves both the main and
backup files either absent or wholly intact (never torn), and a kill
between the two replaces leaves the main file new and the backup one
snapshot behind — both loadable. `resilience.faults` can tear a write
mid-byte on purpose (`tear_checkpoint@N`) to prove exactly this.

Under multi-host SPMD, shards spanning other processes are all-gathered
first, so every process can materialize the global state; writing is the
caller's to gate (process 0).
"""

from __future__ import annotations

import os
import pickle
from typing import List, Optional

import jax
import numpy as np

# v2 adds the Options fingerprint + per-output host PRNG key; v1
# payloads (no stamp, no rng_key) still load — fingerprint checking is
# simply skipped for them.
_MAGIC = "srtpu-search-state-v2"
_MAGIC_V1 = "srtpu-search-state-v1"

#: the Options fields a checkpoint must agree on to resume into the same
#: compiled shapes — the `_saved_state_compatible`-adjacent set, plus
#: precision (a dtype change passes shape checks but poisons the math).
_FINGERPRINT_FIELDS = (
    "npopulations", "npop", "maxsize", "max_len", "precision",
)


def options_fingerprint(options) -> dict:
    """The shape-compatibility stamp written into every checkpoint."""
    return {f: getattr(options, f) for f in _FINGERPRINT_FIELDS}


def _to_host(x) -> np.ndarray:
    """Fetch an array to host, all-gathering shards that live on other
    processes (multi-host sharded state)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        x = multihost_utils.process_allgather(x, tiled=True)
    return np.asarray(x)


def _write_atomic(path: str, payload: bytes) -> None:
    """One crash-atomic file write: `.tmp` sibling, fsync, os.replace.
    The resilience fault hook may hand back a truncated payload
    (`tear_checkpoint`): the torn bytes are written — the simulated
    death happened mid-write — and FaultInjected raises BEFORE the
    rename, so a torn `.tmp` can never shadow a good checkpoint."""
    from ..resilience import faults

    to_write, torn = faults.on_checkpoint_write(payload)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(to_write)
        f.flush()
        os.fsync(f.fileno())
    if torn:
        raise faults.FaultInjected(
            f"injected torn checkpoint write at {path!r} "
            f"({len(to_write)}/{len(payload)} bytes)"
        )
    os.replace(tmp, path)


def save_search_state(path: str, state: List["SearchState"],
                      sink=None, options=None, dispatch: Optional[int] = None,
                      cause: Optional[str] = None) -> str:
    """Write the list of per-output SearchStates (from
    `equation_search(..., return_state=True).state`) to `path` and its
    `.bkup` twin, each write crash-atomic (see module doc). `options`
    stamps the payload with the shape fingerprint `load_search_state`
    checks on resume. `sink` (a telemetry EventLog) records the
    serialization point as a ``saved_state`` event — with the snapshot
    cadence provenance (`dispatch`, `cause`) when the periodic-snapshot
    plumbing is the caller — the resume-not-restart trail the watcher
    and supervisor key off."""
    if state is None:
        raise ValueError(
            "state is None — run equation_search with return_state=True"
        )
    host = [
        {
            "island_states": jax.tree_util.tree_map(
                _to_host, s.island_states
            ),
            "global_hof": jax.tree_util.tree_map(_to_host, s.global_hof),
            "iteration": int(s.iteration),
            "rng_key": (
                None if getattr(s, "rng_key", None) is None
                else np.asarray(s.rng_key)
            ),
        }
        for s in state
    ]
    record = {"magic": _MAGIC, "outputs": host}
    if options is not None:
        record["options_fingerprint"] = options_fingerprint(options)
    payload = pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL)
    for p in (path, path + ".bkup"):
        _write_atomic(p, payload)
    if sink is not None:
        fields = dict(
            path=path,
            outputs=len(host),
            iteration=max((d["iteration"] for d in host), default=0),
        )
        if dispatch is not None:
            fields["dispatch"] = int(dispatch)
        if cause is not None:
            fields["cause"] = cause
        sink.emit("saved_state", **fields)
    return path


class CheckpointIncompatible(ValueError):
    """The checkpoint loaded structurally but was written under
    incompatible Options (shape fingerprint mismatch). Raised
    immediately — the `.bkup` twin carries the same fingerprint, so
    falling back to it could only mask the mismatch."""


def _parse_payload(p: str, options) -> List["SearchState"]:
    from ..api import SearchState

    with open(p, "rb") as f:
        data = pickle.load(f)
    magic = data.get("magic") if isinstance(data, dict) else None
    if magic not in (_MAGIC, _MAGIC_V1):
        raise ValueError(f"{p!r} is not a search-state checkpoint")
    stamp = data.get("options_fingerprint")
    if options is not None and stamp is not None:
        want = options_fingerprint(options)
        mismatched = {
            k: (stamp.get(k), want[k])
            for k in want if stamp.get(k) != want[k]
        }
        if mismatched:
            detail = ", ".join(
                f"{k}: checkpoint={a!r} vs options={b!r}"
                for k, (a, b) in sorted(mismatched.items())
            )
            raise CheckpointIncompatible(
                f"checkpoint {p!r} was written under incompatible "
                f"Options ({detail}); resume with the original "
                "configuration or start fresh"
            )
    states = [
        SearchState(
            island_states=d["island_states"],
            global_hof=d["global_hof"],
            iteration=d["iteration"],
            rng_key=d.get("rng_key"),
        )
        for d in data["outputs"]
    ]
    for s in states:
        # provenance for the telemetry run_start `resume_from` field:
        # which file this resumed state actually came from (the .bkup
        # when the main file was torn)
        s._source_path = p
    return states


def load_search_state(path: str,
                      options=None) -> List["SearchState"]:
    """Load a checkpoint written by save_search_state; falls back to the
    .bkup copy if the main file is missing or torn.

    With `options`, the payload's fingerprint stamp is checked and an
    incompatible checkpoint raises :class:`CheckpointIncompatible` (a
    ValueError) with the mismatched fields named — failing HERE beats
    failing deep inside `equation_search`'s shape validation.

    Raises FileNotFoundError only when NO checkpoint file exists (the
    resume-if-present pattern); corrupt-but-present checkpoints raise
    ValueError so a destroyed checkpoint is never silently mistaken for
    a fresh start."""
    last_err: Exception | None = None
    existed = False
    for p in (path, path + ".bkup"):
        if not os.path.exists(p):
            continue
        existed = True
        try:
            return _parse_payload(p, options)
        except CheckpointIncompatible:
            # both twins carry the same stamp: fail loud, never fall
            # through to an equally incompatible .bkup
            raise
        # corrupt pickles raise a zoo of types (AttributeError,
        # ImportError, struct.error, ...): any failure means "try bkup"
        except Exception as e:
            last_err = e
            continue
    if existed:
        raise ValueError(
            f"checkpoint at {path!r} exists but is unreadable "
            f"({last_err}); refusing to treat it as a fresh start"
        )
    raise FileNotFoundError(f"no search-state checkpoint at {path!r}")
