"""Full search-state disk checkpointing.

The reference's exact-resume state lives only in the Julia session
(`return_state=true` → pass the tuple back to EquationSearch,
src/SearchUtils.jl:270-273); its only on-disk artifact is the hall-of-fame
CSV. Here the complete `SearchState` (per-island populations, statistics,
PRNG keys, hall of fame, iteration counter) round-trips through a file, so
an exact resume survives a process restart:

    res = equation_search(X, y, return_state=True, ...)
    save_search_state("run.ckpt", res.state)
    # ... new process ...
    state = load_search_state("run.ckpt")
    res2 = equation_search(X, y, saved_state=state, ...)

Arrays are stored as host numpy inside a pickle (the state is small —
populations, not datasets); `equation_search` feeds them straight back to
jit, and its shape validation (`_saved_state_compatible`) still guards a
changed Options. Under multi-host SPMD, shards spanning other processes
are all-gathered first, so every process can materialize the global
state; writing is the caller's to gate (process 0).
"""

from __future__ import annotations

import pickle
from typing import List

import jax
import numpy as np

_MAGIC = "srtpu-search-state-v1"


def _to_host(x) -> np.ndarray:
    """Fetch an array to host, all-gathering shards that live on other
    processes (multi-host sharded state)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        x = multihost_utils.process_allgather(x, tiled=True)
    return np.asarray(x)


def save_search_state(path: str, state: List["SearchState"],
                      sink=None) -> str:
    """Write the list of per-output SearchStates (from
    `equation_search(..., return_state=True).state`) to `path`. Uses the
    same double-write discipline as the CSV checkpoint (file + .bkup).
    `sink` (a telemetry EventLog) records the serialization point as a
    ``saved_state`` event — the resume-not-restart trail of ROADMAP
    item 4 keys off these."""
    if state is None:
        raise ValueError(
            "state is None — run equation_search with return_state=True"
        )
    host = [
        {
            "island_states": jax.tree_util.tree_map(
                _to_host, s.island_states
            ),
            "global_hof": jax.tree_util.tree_map(_to_host, s.global_hof),
            "iteration": int(s.iteration),
        }
        for s in state
    ]
    payload = pickle.dumps({"magic": _MAGIC, "outputs": host},
                           protocol=pickle.HIGHEST_PROTOCOL)
    for p in (path, path + ".bkup"):
        with open(p, "wb") as f:
            f.write(payload)
    if sink is not None:
        sink.emit(
            "saved_state",
            path=path,
            outputs=len(host),
            iteration=max((d["iteration"] for d in host), default=0),
        )
    return path


def load_search_state(path: str) -> List["SearchState"]:
    """Load a checkpoint written by save_search_state; falls back to the
    .bkup copy if the main file is missing or torn.

    Raises FileNotFoundError only when NO checkpoint file exists (the
    resume-if-present pattern); corrupt-but-present checkpoints raise
    ValueError so a destroyed checkpoint is never silently mistaken for
    a fresh start."""
    import os

    from ..api import SearchState

    last_err: Exception | None = None
    existed = False
    for p in (path, path + ".bkup"):
        if not os.path.exists(p):
            continue
        existed = True
        try:
            with open(p, "rb") as f:
                data = pickle.load(f)
            if data.get("magic") != _MAGIC:
                raise ValueError(f"{p!r} is not a search-state checkpoint")
            return [
                SearchState(
                    island_states=d["island_states"],
                    global_hof=d["global_hof"],
                    iteration=d["iteration"],
                )
                for d in data["outputs"]
            ]
        # corrupt pickles raise a zoo of types (AttributeError,
        # ImportError, struct.error, ...): any failure means "try bkup"
        except Exception as e:
            last_err = e
            continue
    if existed:
        raise ValueError(
            f"checkpoint at {path!r} exists but is unreadable "
            f"({last_err}); refusing to treat it as a fresh start"
        )
    raise FileNotFoundError(f"no search-state checkpoint at {path!r}")
