"""Inter-island migration as collective-friendly array ops.

The reference migrates through the head node: it pools topn members of every
island (`bestSubPops`, src/SymbolicRegression.jl:709-779) and replaces
fraction_replaced of each returning island with pool samples plus
fraction_replaced_hof with hall-of-fame members (src/Migration.jl:15-35).

Here migration is SPMD (SURVEY.md §2.3 "TPU-native equivalent"): all arrays
carry a leading islands axis I; building the pool is a reshape across that
axis, which under a sharded `jit` lowers to an all-gather over the ICI mesh —
no head node, no channels. Each island then does masked scatter-replace
locally.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.evolve import IslandState
from ..models.options import Options
from ..models.population import (
    HallOfFame,
    Population,
    calculate_pareto_frontier,
)
from ..models.trees import TreeBatch

Array = jax.Array

#: The replicated-pin sites of the fused iteration, by name — the
#: ``sharding_constraint`` primitives srshard's constraint census counts
#: in the solo compiled program (and asserts absent from the
#: tenant-batched body, where ``inner_mesh=None`` / lint rule SR012
#: forbid constraints entirely). analysis/shard.py introspects this.
REPLICATED_PINS = ("topn_pool", "merged_hof")


def pin_replicated(tree, mesh: Mesh):
    """Pin every leaf of ``tree`` fully replicated over ``mesh`` with
    ``with_sharding_constraint`` — the one place the fused iteration
    constrains GSPMD by hand (see :data:`REPLICATED_PINS`). Callers must
    hold a real mesh; inside a tenant-vmapped body there is no mesh to
    name (api.py passes ``inner_mesh=None``) and this is never reached."""
    repl = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda x: jax.lax.with_sharding_constraint(x, repl), tree
    )


def _topn_pool(states: IslandState, topn: int):
    """(I, topn) best members of every island -> flattened pool (I*topn,)."""

    def one(pop: Population):
        order = jnp.argsort(pop.scores)[:topn]
        return (
            jax.tree_util.tree_map(lambda x: x[order], pop.trees),
            pop.scores[order],
            pop.losses[order],
        )

    trees, scores, losses = jax.vmap(one)(states.pop)
    flat = lambda x: x.reshape((-1,) + x.shape[2:])
    return (
        jax.tree_util.tree_map(flat, trees),
        scores.reshape(-1),
        losses.reshape(-1),
    )


def migrate(
    key: Array,
    states: IslandState,
    global_hof: HallOfFame,
    options: Options,
    mesh: Optional[Mesh] = None,
) -> IslandState:
    """Replace random slots of every island with pool / hall-of-fame members
    (reference src/Migration.jl:15-35; fractions
    fraction_replaced=3.6e-4, fraction_replaced_hof=0.035 per member).

    mesh: when the caller's jit is sharded over the island axis, the topn
    pool is pinned fully replicated here — the pool build then lowers to
    ONE all-gather of the (I*topn,) winner slices over the mesh, and the
    masked scatter-replace below stays device-local (GSPMD left free
    would otherwise gather whole populations for the `pool_field[choice]`
    cross-island indexing). None keeps the unsharded program unchanged."""
    if not options.migration:
        return states
    I = states.pop.scores.shape[0]
    npop = states.pop.scores.shape[1]
    topn = min(options.topn, npop)

    pool_trees, pool_scores, pool_losses = _topn_pool(states, topn)
    if mesh is not None:
        pool_trees, pool_scores, pool_losses = pin_replicated(
            (pool_trees, pool_scores, pool_losses), mesh
        )
    pool_size = I * topn

    k1, k2, k3, k4 = jax.random.split(key, 4)
    # pool migration
    replace_pool = jax.random.bernoulli(
        k1, options.fraction_replaced, (I, npop)
    )
    choice_pool = jax.random.randint(k2, (I, npop), 0, pool_size, dtype=jnp.int32)

    # hall-of-fame migration: sample only from existing Pareto-front slots
    # (reference hofMigration uses the dominating curve,
    # src/SymbolicRegression.jl:770-779)
    front = calculate_pareto_frontier(global_hof)
    any_front = jnp.any(front)
    logits = jnp.where(front, 0.0, -1e9)
    choice_hof = jax.random.categorical(
        k3, logits[None, :], shape=(I, npop)
    )
    replace_hof = (
        jax.random.bernoulli(k4, options.fraction_replaced_hof, (I, npop))
        & any_front
        & options.hof_migration
    )

    def blend(member_field, pool_field, hof_field):
        pool_pick = pool_field[choice_pool]  # (I, npop, ...)
        hof_pick = hof_field[choice_hof]
        extra = (1,) * (member_field.ndim - 2)
        rp = replace_pool.reshape(replace_pool.shape + extra)
        rh = replace_hof.reshape(replace_hof.shape + extra)
        out = jnp.where(rp, pool_pick, member_field)
        return jnp.where(rh, hof_pick, out)

    new_trees = jax.tree_util.tree_map(
        blend, states.pop.trees, pool_trees, global_hof.trees
    )
    new_scores = blend(states.pop.scores, pool_scores, global_hof.scores)
    new_losses = blend(states.pop.losses, pool_losses, global_hof.losses)

    # migrated members get fresh birth (reference src/Migration.jl:28-33)
    migrated = replace_pool | replace_hof
    new_birth = jnp.where(
        migrated,
        states.birth_counter[:, None] + jnp.arange(npop, dtype=jnp.int32)[None, :],
        states.pop.birth,
    )
    new_counter = states.birth_counter + npop

    return states._replace(
        pop=Population(
            trees=new_trees,
            scores=new_scores,
            losses=new_losses,
            birth=new_birth,
        ),
        birth_counter=new_counter,
    )


def merge_hofs_across_islands(
    hofs: HallOfFame, mesh: Optional[Mesh] = None
) -> HallOfFame:
    """Per-slot argmin-loss across the islands axis. Under a sharded jit the
    argmin lowers to a cross-island reduction over ICI (the analog of the
    head-node HoF merge, reference src/SymbolicRegression.jl:722-744).

    mesh: pins the merged result fully replicated — every device holds
    the whole global hall of fame, so the migrate() HoF sampling that
    consumes it stays device-local and the host-side candidate
    extraction reads a replicated array instead of triggering a
    per-iteration cross-device gather."""
    masked = jnp.where(hofs.exists, hofs.losses, jnp.inf)  # (I, S)
    best_i = jnp.argmin(masked, axis=0)  # (S,)
    S = best_i.shape[0]

    def pick(x):  # x: (I, S, ...)
        return jnp.take_along_axis(
            x, best_i.reshape((1, S) + (1,) * (x.ndim - 2)), axis=0
        )[0]

    merged = HallOfFame(
        trees=jax.tree_util.tree_map(pick, hofs.trees),
        scores=pick(hofs.scores),
        losses=pick(hofs.losses),
        exists=jnp.any(hofs.exists, axis=0),
    )
    if mesh is not None:
        merged = pin_replicated(merged, mesh)
    return merged
