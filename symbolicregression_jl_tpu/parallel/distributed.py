"""Multi-host runtime initialization.

The reference reaches multiple nodes through Distributed.jl `addprocs` with
pluggable cluster managers (src/SymbolicRegression.jl:258-265,500-528,
e.g. addprocs_slurm). The JAX-native equivalent is
`jax.distributed.initialize`: every host starts the same SPMD program, the
global mesh spans all hosts' devices, collectives ride ICI within a pod and
DCN across pods. No code or closures are shipped (the program is identical
on every host), which subsumes the reference's move_functions_to_workers
machinery (src/Configure.jl:86-189).
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> bool:
    """Initialize the JAX distributed runtime if configured.

    Arguments default from the standard env vars / cluster auto-detection
    (SLURM, GKE, ...). Returns True if multi-process mode is active.
    Safe to call on a single host (no-op) and under a single-task SLURM
    allocation (SLURM_NTASKS=1 is not a cluster). When a cluster IS
    configured, failures are loud: silently continuing single-process
    would split-brain the job (N independent searches racing on shared
    checkpoints while the joined hosts hang)."""
    already = getattr(jax.distributed, "is_initialized", None)
    if callable(already) and jax.distributed.is_initialized():
        return jax.process_count() > 1
    try:
        slurm_n = int(os.environ.get("SLURM_NTASKS") or 1)
    except ValueError:
        slurm_n = 1
    if (
        coordinator_address is None
        and "JAX_COORDINATOR_ADDRESS" not in os.environ
        and num_processes is None
        and slurm_n <= 1
    ):
        return False  # single-host
    try:
        from jax._src import xla_bridge

        backends_up = xla_bridge.backends_are_initialized()
    except Exception:  # pragma: no cover
        backends_up = False
    if backends_up:
        raise RuntimeError(
            "multi-host environment detected but this process already ran "
            "JAX computations, so the distributed runtime cannot be "
            "joined (jax.distributed.initialize must precede any JAX "
            "use). Call initialize_multihost() / equation_search before "
            "touching JAX, or unset the cluster env vars "
            "(JAX_COORDINATOR_ADDRESS / SLURM_NTASKS) for a deliberate "
            "single-process run."
        )
    # no try/except: a failed join of a configured cluster must crash the
    # job, not quietly run this host's own single-process search
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    return jax.process_count() > 1


def is_primary_host() -> bool:
    """Only the primary host does printing/checkpoint IO."""
    return jax.process_index() == 0
