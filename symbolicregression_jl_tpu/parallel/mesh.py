"""Device mesh construction and island-state sharding.

Replaces the reference's Distributed.jl head/worker runtime (§2.3 of
SURVEY.md: @spawnat/RemoteChannel/addprocs, src/SymbolicRegression.jl:500-528)
with SPMD over a `jax.sharding.Mesh`:

* axis `islands` — population parallelism (the island model): island state
  arrays carry a leading I dim sharded over this axis;
* axis `rows` — dataset-row parallelism (the analog of the reference's
  batching advice for big datasets, src/Configure.jl:63-70): X/y shard their
  row dim; loss reductions become cross-axis psums inserted by XLA.

Multi-host: `jax.distributed.initialize()` + the same mesh spanning all
processes' devices (DCN between hosts, ICI within) — see
parallel/distributed.py.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.options import Options


def make_mesh(
    options: Options,
    n_islands: int,
    devices=None,
    row_shards: int = 1,
) -> Optional[Mesh]:
    """Build a (islands, rows) mesh from available devices.

    Uses the largest device count d <= len(devices) such that d divides
    n_islands * row_shards layouts cleanly; returns None for a single
    device (plain jit, no sharding needed)."""
    devices = devices if devices is not None else jax.devices()
    n_dev = len(devices)
    if n_dev <= 1:
        return None
    row_shards = max(1, min(row_shards, n_dev))
    island_shards = n_dev // row_shards
    while island_shards > 1 and n_islands % island_shards != 0:
        island_shards -= 1
    use = island_shards * row_shards
    dev_array = np.array(devices[:use]).reshape(island_shards, row_shards)
    return Mesh(dev_array, (options.island_axis, options.row_axis))


def island_sharding(mesh: Optional[Mesh], options: Options):
    """NamedSharding putting the leading islands dim on the islands axis
    (None => fully replicated single-device)."""
    if mesh is None:
        return None
    return NamedSharding(mesh, P(options.island_axis))


def data_sharding(mesh: Optional[Mesh], options: Options, rows_dim: int = 1):
    """Shard dataset rows over the rows axis (features replicated)."""
    if mesh is None:
        return None
    spec = [None, None]
    spec[rows_dim] = options.row_axis
    return NamedSharding(mesh, P(*spec))


def put_global(x, sharding):
    """Place an array with `sharding`, working under multi-process SPMD.

    Single process: plain device_put. Multi-process: every process holds
    the same host value (the program is deterministic and identical on all
    hosts — the reason nothing needs shipping, see distributed.py), so
    each process contributes its addressable shards via
    make_array_from_callback."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    x_np = np.asarray(x)
    return jax.make_array_from_callback(
        x_np.shape, sharding, lambda idx: x_np[idx]
    )


def shard_island_states(states, mesh: Optional[Mesh], options: Options):
    if mesh is None:
        return states
    sh = island_sharding(mesh, options)
    return jax.tree_util.tree_map(lambda x: put_global(x, sh), states)


def shard_dataset(X, y, weights, mesh: Optional[Mesh], options: Options):
    if mesh is None:
        return X, y, weights
    xsh = data_sharding(mesh, options, rows_dim=1)
    vsh = NamedSharding(mesh, P(options.row_axis))
    X = put_global(X, xsh)
    y = put_global(y, vsh)
    if weights is not None:
        weights = put_global(weights, vsh)
    return X, y, weights
