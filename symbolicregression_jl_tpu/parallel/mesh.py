"""Device mesh construction and island-state sharding.

Replaces the reference's Distributed.jl head/worker runtime (§2.3 of
SURVEY.md: @spawnat/RemoteChannel/addprocs, src/SymbolicRegression.jl:500-528)
with SPMD over a `jax.sharding.Mesh`:

* axis `islands` — population parallelism (the island model): island state
  arrays carry a leading I dim sharded over this axis;
* axis `rows` — dataset-row parallelism (the analog of the reference's
  batching advice for big datasets, src/Configure.jl:63-70): X/y shard their
  row dim; loss reductions become cross-axis psums inserted by XLA.

Multi-host: `jax.distributed.initialize()` + the same mesh spanning all
processes' devices (DCN between hosts, ICI within) — see
parallel/distributed.py.
"""

from __future__ import annotations

import warnings
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.options import Options


def make_mesh(
    options: Options,
    n_islands: int,
    devices=None,
    row_shards: int = 1,
    tenants: int = 1,
) -> Optional[Mesh]:
    """Build a (islands, rows) mesh from available devices.

    Uses the largest device count d <= len(devices) such that d divides
    n_islands * row_shards layouts cleanly; returns None for a single
    device (plain jit, no sharding needed). When the division forces
    devices to sit idle (e.g. 8 devices, 6 islands -> a 6x1 mesh), the
    choice is loud: a warning names the mesh and the idle devices, so a
    quietly-degraded production run is visible in the log (and in the
    telemetry ``run_start`` event via :func:`describe_mesh`).

    tenants > 1 (serving/batched.py) builds a ``(tenants, islands)``
    mesh instead — the tenant batch dim composes with island
    parallelism as ``P('tenants', 'islands')`` on every state leaf.
    Row sharding is mutually exclusive with tenant batching (Options
    rejects the combination), so the rows axis never appears here."""
    devices = devices if devices is not None else jax.devices()
    n_dev = len(devices)
    if n_dev <= 1:
        return None
    if tenants > 1:
        t_shards = min(tenants, n_dev)
        while t_shards > 1 and tenants % t_shards != 0:
            t_shards -= 1
        island_shards = n_dev // t_shards
        while island_shards > 1 and n_islands % island_shards != 0:
            island_shards -= 1
        use = t_shards * island_shards
        if use < n_dev:
            warnings.warn(
                f"make_mesh: tenants={tenants} x npopulations="
                f"{n_islands} does not tile {n_dev} devices — using a "
                f"({t_shards}, {island_shards}) ({options.tenant_axis}, "
                f"{options.island_axis}) mesh on {use} device(s) and "
                f"leaving {n_dev - use} idle "
                f"({', '.join(str(d) for d in devices[use:])}). Pick "
                f"tenants/npopulations whose product's divisors tile "
                f"{n_dev} to use every device.",
                stacklevel=2,
            )
        dev_array = np.array(devices[:use]).reshape(t_shards, island_shards)
        return Mesh(dev_array, (options.tenant_axis, options.island_axis))
    row_shards = max(1, min(row_shards, n_dev))
    island_shards = n_dev // row_shards
    while island_shards > 1 and n_islands % island_shards != 0:
        island_shards -= 1
    use = island_shards * row_shards
    if use < n_dev:
        # name the knob actually responsible: a row_shards that does not
        # divide the device count wastes the remainder even when the
        # island count tiles perfectly
        if n_dev % row_shards != 0:
            remedy = (
                f"Pick row_shards dividing {n_dev} (and npopulations "
                f"divisible by the islands axis) to use every device."
            )
        else:
            remedy = (
                f"Pick npopulations divisible by {n_dev // row_shards} "
                "(or adjust row_shards) to use every device."
            )
        warnings.warn(
            f"make_mesh: npopulations={n_islands} with row_shards="
            f"{row_shards} does not tile {n_dev} devices — using a "
            f"({island_shards}, {row_shards}) ({options.island_axis}, "
            f"{options.row_axis}) mesh on {use} device(s) and leaving "
            f"{n_dev - use} idle ({', '.join(str(d) for d in devices[use:])}). "
            + remedy,
            stacklevel=2,
        )
    dev_array = np.array(devices[:use]).reshape(island_shards, row_shards)
    return Mesh(dev_array, (options.island_axis, options.row_axis))


def describe_mesh(mesh: Optional[Mesh], devices=None) -> Dict:
    """Machine-readable mesh facts for telemetry/bench records:
    ``mesh_shape`` ({axis: size}, None when unsharded), ``n_devices``
    (devices the mesh actually uses; 1 when unsharded), ``idle_devices``
    (available-but-unused device count), ``device_kind``."""
    devices = devices if devices is not None else jax.devices()
    if mesh is None:
        return {
            "mesh_shape": None,
            "n_devices": 1,
            "idle_devices": max(0, len(devices) - 1),
            "device_kind": devices[0].device_kind if devices else None,
        }
    use = int(mesh.devices.size)
    return {
        "mesh_shape": {
            str(name): int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)
        },
        "n_devices": use,
        "idle_devices": max(0, len(devices) - use),
        "device_kind": mesh.devices.ravel()[0].device_kind,
    }


def island_sharding(mesh: Optional[Mesh], options: Options):
    """NamedSharding putting the leading islands dim on the islands axis
    (None => fully replicated single-device)."""
    if mesh is None:
        return None
    return NamedSharding(mesh, P(options.island_axis))


def data_sharding(mesh: Optional[Mesh], options: Options, rows_dim: int = 1):
    """Shard dataset rows over the rows axis (features replicated)."""
    if mesh is None:
        return None
    spec = [None, None]
    spec[rows_dim] = options.row_axis
    return NamedSharding(mesh, P(*spec))


def replicated_sharding(mesh: Optional[Mesh]):
    """Fully-replicated NamedSharding over the mesh (scalars, PRNG keys,
    the merged hall of fame — everything every device must hold whole)."""
    if mesh is None:
        return None
    return NamedSharding(mesh, P())


def search_shardings(mesh: Optional[Mesh], options: Options):
    """The sharding vocabulary of one search iteration, as a dict the
    api.py jit factories thread into ``in_shardings``/``out_shardings``
    (the compiled contract of the production drivers —
    docs/multichip.md):

    - ``island``: leading-axis island parallelism — every IslandState
      leaf, the per-island PRNG key batches, and the memo-absorb
      snapshot;
    - ``replicated``: scalars, iteration keys, the merged HallOfFame;
    - ``x`` / ``rows``: dataset sharding over the rows axis (features
      replicated);
    - ``events``: recorder MutationEvents — cycle-scan outputs stack the
      scan axis in front, so the island axis is dim 1;
    - ``tenant``: per-tenant leaves (keys, baselines, merged HoFs). On a
      solo (islands, rows) mesh this aliases ``replicated`` so the
      factories can thread ONE vocabulary through both modes without
      changing the solo compiled contract.

    On a tenant mesh (``make_mesh(..., tenants>1)`` — axis names
    (tenants, islands)) the vocabulary composes with the leading tenant
    batch dim instead: ``island`` becomes ``P('tenants', 'islands')``
    (every IslandState leaf is (T, I, ...)), ``tenant`` is
    ``P('tenants')``, and the dataset specs shard the leading tenants
    dim of the stacked (T, nfeat, n) / (T, n) arrays (rows are never
    sharded in tenant mode — Options rejects tenants x row_shards).

    None mesh -> None (plain jit, no sharding arguments)."""
    if mesh is None:
        return None
    if options.tenant_axis in mesh.axis_names:
        ten = NamedSharding(mesh, P(options.tenant_axis))
        return {
            "island": NamedSharding(
                mesh, P(options.tenant_axis, options.island_axis)
            ),
            "tenant": ten,
            "replicated": NamedSharding(mesh, P()),
            "x": NamedSharding(mesh, P(options.tenant_axis, None, None)),
            "rows": ten,
        }
    return {
        "island": NamedSharding(mesh, P(options.island_axis)),
        "tenant": NamedSharding(mesh, P()),
        "replicated": NamedSharding(mesh, P()),
        "x": NamedSharding(mesh, P(None, options.row_axis)),
        "rows": NamedSharding(mesh, P(options.row_axis)),
        "events": NamedSharding(mesh, P(None, options.island_axis)),
    }


def spec_table(mesh: Optional[Mesh], options: Options) -> Optional[Dict]:
    """JSON-able view of :func:`search_shardings` — ``{name:
    [axis-or-null, ...]}`` — the introspection hook srshard records per
    mesh config (analysis/shard.py) and docs/multichip.md's
    PartitionSpec table is generated against. None mesh -> None."""
    sh = search_shardings(mesh, options)
    if sh is None:
        return None
    return {
        name: [None if axis is None else str(axis) for axis in ns.spec]
        for name, ns in sh.items()
    }


def put_global(x, sharding):
    """Place an array with `sharding`, working under multi-process SPMD.

    Single process: plain device_put. Multi-process: every process holds
    the same host value (the program is deterministic and identical on all
    hosts — the reason nothing needs shipping, see distributed.py), so
    each process contributes its addressable shards via
    make_array_from_callback."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    x_np = np.asarray(x)
    return jax.make_array_from_callback(
        x_np.shape, sharding, lambda idx: x_np[idx]
    )


def shard_island_states(states, mesh: Optional[Mesh], options: Options):
    if mesh is None:
        return states
    if options.tenant_axis in mesh.axis_names:
        sh = NamedSharding(
            mesh, P(options.tenant_axis, options.island_axis)
        )
    else:
        sh = island_sharding(mesh, options)
    return jax.tree_util.tree_map(lambda x: put_global(x, sh), states)


def shard_dataset(X, y, weights, mesh: Optional[Mesh], options: Options):
    """Place the dataset on the mesh. Solo mesh: rows over the rows
    axis. Tenant mesh: the stacked (T, nfeat, n) / (T, n) arrays shard
    their leading tenants dim (rows replicated within a tenant)."""
    if mesh is None:
        return X, y, weights
    if options.tenant_axis in mesh.axis_names:
        xsh = NamedSharding(mesh, P(options.tenant_axis, None, None))
        vsh = NamedSharding(mesh, P(options.tenant_axis))
    else:
        xsh = data_sharding(mesh, options, rows_dim=1)
        vsh = NamedSharding(mesh, P(options.row_axis))
    X = put_global(X, xsh)
    y = put_global(y, vsh)
    if weights is not None:
        weights = put_global(weights, vsh)
    return X, y, weights
