"""ctypes bindings for the C++ host runtime (native/srtpu_native.cpp).

The TPU compute path is JAX/XLA/Pallas; this module exposes the native host
runtime around it — batched tree printing, infix parsing, host-side
simplification (constant folding + operator combining), a multithreaded CPU
evaluator (the analog of the reference's DynamicExpressions CPU eval path),
and a CSV dataset loader.

Every entry point has a pure-Python fallback in the package (trees.py /
mutate_device.py / interpreter.py), so the framework works without the
shared library; when `libsrtpu_native.so` is present (built by
`make -C native`, attempted automatically once per process) the fast paths
are used. Custom Python-registered operators are never routed here —
`op_maps()` returns None for unknown names and callers fall back.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .ops.operators import INFIX, OperatorSet

_LIB_PATH = os.path.join(os.path.dirname(__file__), "_lib", "libsrtpu_native.so")
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_f32p = ctypes.POINTER(ctypes.c_float)
_f64p = ctypes.POINTER(ctypes.c_double)


def _try_build() -> None:
    """Build the .so from source if missing/stale and a toolchain exists."""
    src = os.path.join(_SRC_DIR, "srtpu_native.cpp")
    if not os.path.exists(src):
        return
    if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(src):
        return
    try:
        subprocess.run(
            ["make", "-C", _SRC_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except Exception:
        pass


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        _try_build()
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        if lib.srt_abi_version() != 2:
            return None

        lib.srt_op_id.argtypes = [ctypes.c_char_p, ctypes.c_int32]
        lib.srt_op_id.restype = ctypes.c_int32
        lib.srt_print_batch.argtypes = [
            ctypes.c_int64, ctypes.c_int32,
            _i32p, _i32p, _i32p, _f32p, _i32p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_char_p, ctypes.c_int64, _i64p,
        ]
        lib.srt_print_batch.restype = ctypes.c_int64
        lib.srt_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int32,
            _i32p, _i32p, _i32p, _f32p,
            ctypes.c_char_p, ctypes.c_int32,
        ]
        lib.srt_parse.restype = ctypes.c_int32
        lib.srt_simplify_batch.argtypes = [
            ctypes.c_int64, ctypes.c_int32,
            _i32p, _i32p, _i32p, _f32p, _i32p,
            _i32p, ctypes.c_int32, _i32p, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32,
        ]
        lib.srt_simplify_batch.restype = ctypes.c_int64
        lib.srt_eval_batch.argtypes = [
            ctypes.c_int64, ctypes.c_int32,
            _i32p, _i32p, _i32p, _f32p, _i32p,
            _f32p, ctypes.c_int32, ctypes.c_int64,
            _i32p, ctypes.c_int32, _i32p, ctypes.c_int32,
            _f32p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32,
            _f32p, _f32p,  # optional y_target, loss_out
        ]
        lib.srt_eval_batch.restype = ctypes.c_int32
        lib.srt_csv_probe.argtypes = [
            ctypes.c_char_p, ctypes.c_char, _i64p, _i64p, _i32p,
            ctypes.c_char_p, ctypes.c_int64,
        ]
        lib.srt_csv_probe.restype = ctypes.c_int32
        lib.srt_csv_read.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_int32, _f64p,
            ctypes.c_int64, ctypes.c_int64,
        ]
        lib.srt_csv_read.restype = ctypes.c_int32
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def op_maps(operators: OperatorSet) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(una_map, bin_map): operator-set index -> native opcode, or None if
    any operator has no native implementation (custom Python op)."""
    lib = _load()
    if lib is None:
        return None
    una = np.array(
        [lib.srt_op_id(n.encode(), 0) for n in operators.unary_names],
        np.int32,
    )
    bina = np.array(
        [lib.srt_op_id(n.encode(), 1) for n in operators.binary_names],
        np.int32,
    )
    if (len(una) and una.min() < 0) or (len(bina) and bina.min() < 0):
        return None
    return una, bina


def _as_c(tree_field, dtype) -> np.ndarray:
    arr = np.ascontiguousarray(np.asarray(tree_field), dtype)
    return arr


def _names_blob(names: Sequence[str]) -> bytes:
    return "\n".join(names).encode()


def trees_to_strings(
    kind, op, feat, cval, length,
    operators: OperatorSet,
    variable_names: Optional[Sequence[str]] = None,
) -> Optional[List[str]]:
    """Batched postfix -> infix strings; None if native path unavailable.

    Output is identical to models.trees.tree_to_string (same %.6g constant
    formatting, same infix/call forms)."""
    lib = _load()
    if lib is None:
        return None
    kind = _as_c(kind, np.int32)
    T = int(np.prod(kind.shape[:-1])) if kind.ndim > 1 else 1
    L = kind.shape[-1]
    kind = kind.reshape(T, L)
    op = _as_c(op, np.int32).reshape(T, L)
    feat = _as_c(feat, np.int32).reshape(T, L)
    cval = _as_c(cval, np.float32).reshape(T, L)
    length = _as_c(length, np.int32).reshape(T)
    infix = np.array(
        [1 if n in INFIX else 0 for n in operators.binary_names], np.uint8
    )
    offsets = np.zeros(T, np.int64)
    cap = 64 * T + 1024
    for _ in range(3):
        out = ctypes.create_string_buffer(cap)
        used = lib.srt_print_batch(
            T, L,
            kind.ctypes.data_as(_i32p), op.ctypes.data_as(_i32p),
            feat.ctypes.data_as(_i32p), cval.ctypes.data_as(_f32p),
            length.ctypes.data_as(_i32p),
            _names_blob(operators.unary_names),
            _names_blob(operators.binary_names),
            _names_blob(variable_names or ()),
            infix.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out, cap, offsets.ctypes.data_as(_i64p),
        )
        if used >= 0:
            raw = out.raw[:used]
            return [
                raw[offsets[t]: raw.index(b"\0", offsets[t])].decode()
                for t in range(T)
            ]
        cap = int(-used) + 1024
    return None


def parse_to_arrays(
    s: str,
    operators: OperatorSet,
    max_len: int,
    variable_names: Optional[Sequence[str]] = None,
):
    """Parse infix -> (kind, op, feat, cval, length) numpy arrays.

    Returns None if the native library is unavailable; raises ValueError on
    a parse error (same contract as models.trees.parse_expression)."""
    lib = _load()
    if lib is None:
        return None
    kind = np.zeros(max_len, np.int32)
    op = np.zeros(max_len, np.int32)
    feat = np.zeros(max_len, np.int32)
    cval = np.zeros(max_len, np.float32)
    err = ctypes.create_string_buffer(256)
    n = lib.srt_parse(
        s.encode(),
        _names_blob(operators.unary_names),
        _names_blob(operators.binary_names),
        _names_blob(variable_names or ()),
        max_len,
        kind.ctypes.data_as(_i32p), op.ctypes.data_as(_i32p),
        feat.ctypes.data_as(_i32p), cval.ctypes.data_as(_f32p),
        err, 256,
    )
    if n < 0:
        raise ValueError(f"parse error in {s!r}: {err.value.decode()}")
    return kind, op, feat, cval, np.int32(n)


def simplify_arrays(
    kind, op, feat, cval, length,
    operators: OperatorSet,
    fold: bool = True,
    combine: bool = True,
):
    """Host-side simplify (fold + combine) on postfix arrays.

    Returns (kind, op, feat, cval, length, n_changed) or None if native
    unavailable / custom operators present."""
    maps = op_maps(operators)
    if maps is None:
        return None
    una_map, bin_map = maps
    lib = _load()
    kind = _as_c(kind, np.int32).copy()
    shape = kind.shape
    T = int(np.prod(shape[:-1])) if kind.ndim > 1 else 1
    L = shape[-1]
    kind = kind.reshape(T, L)
    op = _as_c(op, np.int32).copy().reshape(T, L)
    feat = _as_c(feat, np.int32).copy().reshape(T, L)
    cval = _as_c(cval, np.float32).copy().reshape(T, L)
    length = _as_c(length, np.int32).copy().reshape(T)
    n_changed = lib.srt_simplify_batch(
        T, L,
        kind.ctypes.data_as(_i32p), op.ctypes.data_as(_i32p),
        feat.ctypes.data_as(_i32p), cval.ctypes.data_as(_f32p),
        length.ctypes.data_as(_i32p),
        una_map.ctypes.data_as(_i32p), len(una_map),
        bin_map.ctypes.data_as(_i32p), len(bin_map),
        int(fold), int(combine),
    )
    batch = shape[:-1]
    return (
        kind.reshape(shape), op.reshape(shape), feat.reshape(shape),
        cval.reshape(shape), length.reshape(batch), int(n_changed),
    )


def eval_batch(
    kind, op, feat, cval, length,
    X,
    operators: OperatorSet,
    n_threads: int = 0,
    y_target=None,
):
    """Multithreaded CPU evaluation of T trees over X (nfeat, n).

    Returns (y (T, n) float32, ok (T,) bool) — plus per-tree MSE losses
    against `y_target` when given (the reference's score_func = eval + loss
    reduction) — or None if unavailable. The reference's CPU hot path
    (DynamicExpressions eval_tree_array) — used as the honest CPU anchor in
    benchmarks and as a host-side oracle."""
    maps = op_maps(operators)
    if maps is None:
        return None
    una_map, bin_map = maps
    lib = _load()
    kind = _as_c(kind, np.int32)
    shape = kind.shape
    T = int(np.prod(shape[:-1])) if kind.ndim > 1 else 1
    L = shape[-1]
    kind = kind.reshape(T, L)
    op = _as_c(op, np.int32).reshape(T, L)
    feat = _as_c(feat, np.int32).reshape(T, L)
    cval = _as_c(cval, np.float32).reshape(T, L)
    length = _as_c(length, np.int32).reshape(T)
    X = np.ascontiguousarray(np.asarray(X), np.float32)
    nfeat, n = X.shape
    y = np.empty((T, n), np.float32)
    ok = np.empty(T, np.uint8)
    yt = None
    losses = None
    if y_target is not None:
        yt = np.ascontiguousarray(np.asarray(y_target), np.float32)
        if yt.shape != (n,):
            raise ValueError(f"y_target must be ({n},), got {yt.shape}")
        losses = np.empty(T, np.float32)
    rc = lib.srt_eval_batch(
        T, L,
        kind.ctypes.data_as(_i32p), op.ctypes.data_as(_i32p),
        feat.ctypes.data_as(_i32p), cval.ctypes.data_as(_f32p),
        length.ctypes.data_as(_i32p),
        X.ctypes.data_as(_f32p), nfeat, n,
        una_map.ctypes.data_as(_i32p), len(una_map),
        bin_map.ctypes.data_as(_i32p), len(bin_map),
        y.ctypes.data_as(_f32p),
        ok.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n_threads,
        yt.ctypes.data_as(_f32p) if yt is not None else None,
        losses.ctypes.data_as(_f32p) if losses is not None else None,
    )
    if rc != 0:
        return None
    batch = shape[:-1]
    out_y = y.reshape(batch + (n,))
    out_ok = ok.astype(bool).reshape(batch)
    if losses is not None:
        return out_y, out_ok, losses.reshape(batch)
    return out_y, out_ok


def load_csv(path: str, delimiter: Optional[str] = None):
    """Load a numeric CSV (optional header) -> (data (rows, cols) float64,
    column_names or None). None if native unavailable; raises OSError /
    ValueError on IO or format errors."""
    lib = _load()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    has_header = ctypes.c_int32()
    header = ctypes.create_string_buffer(1 << 16)
    d = (delimiter or "\0").encode()[:1]
    rc = lib.srt_csv_probe(
        path.encode(), d, ctypes.byref(rows), ctypes.byref(cols),
        ctypes.byref(has_header), header, len(header),
    )
    if rc != 0:
        raise OSError(f"Cannot read CSV {path!r}")
    if rows.value <= 0 or cols.value <= 0:
        raise ValueError(f"Empty CSV {path!r}")
    data = np.empty((rows.value, cols.value), np.float64)
    rc = lib.srt_csv_read(
        path.encode(), d, int(has_header.value),
        data.ctypes.data_as(_f64p), rows.value, cols.value,
    )
    if rc != 0:
        raise ValueError(f"Malformed CSV {path!r} (code {rc})")
    names = None
    if has_header.value:
        # positional alignment with data columns; name blank fields col<i>
        names = [
            c if c else f"col{i}"
            for i, c in enumerate(header.value.decode().split("\n"))
        ]
    return data, names
