"""srshard — static SPMD sharding-contract checker with a communication
cost model and a replication-blowup gate (the sixth analysis engine).

The island model became a compiled mesh contract in the multi-chip work
(``P('islands')`` over every IslandState carry leaf; ``P('tenants',
'islands')`` once serving batched tenants in front) — but until this
engine the only static guard was compile_surface's flat collective
census on one 8-device mesh, and nothing modeled what those collectives
COST or noticed a carry leaf silently falling back to full replication.
srshard AOT-lowers the production stage programs
(``analysis.memory.build_stage_programs``) and the fused iteration over
a matrix of forced-host device meshes and checks three things, all
trace/compile-only (nothing executes):

- **sharding contract, structurally**: the compiled output/input
  shardings are walked leaf-by-leaf — every IslandState carry leaf must
  carry the island (and tenant) axis end-to-end, the merged HallOfFame
  comes back replicated (per-tenant on a tenant mesh), the memo
  snapshot slot is replicated in the shard vocabulary, and the jaxpr
  constraint census is mode-correct (the solo fused program carries the
  migration/HoF-merge replicated pins; the tenant-batched program
  carries ZERO ``sharding_constraint`` primitives — the ``inner_mesh =
  None`` rule SR012 enforces statically);
- **replication blowups**: any compiled output leaf whose per-device
  footprint exceeds a threshold multiple of what the contract's
  expected sharding would give is flagged BY NAME — the "GSPMD gave up
  and all-gathered the population" failure srmem cannot see because it
  models one device;
- **tenant isolation + communication pricing**: tenants are
  embarrassingly parallel, so a collective whose replica groups mix
  tenant coordinates AND can combine tenant values (any data
  all-reduce / reduce-scatter / all-to-all / collective-permute) is a
  correctness leak — decoded from the optimized HLO's replica groups
  (iota and brace forms) and bisected to the culprit output leaf by
  group-halving, srkey-style. Two GSPMD artifacts are exempt as
  structurally value-preserving (``cross_tenant_collectives``
  docstring): cross-tenant all-gathers (replication data movement,
  still priced + census-gated + bounded by the replication gate) and
  the 1-byte ``pred[]`` all-reduce of SPMD while-loop condition
  convergence. Every
  collective is additionally priced (payload bytes x a ring-model
  factor over a tabled ICI bandwidth) and joined with srcost's
  per-stage compute numbers into a modeled comms-vs-compute fraction
  per stage, gated against the checked-in ``shard_baseline.json``
  (census drift or >10% comm-byte growth fails; same writer/refresh
  workflow as the other baselines).

Mesh matrix (8 forced-host devices, ``analysis.pin_platform``):
``mesh1x8`` / ``mesh2x4`` / ``mesh4x2`` (islands x rows) and
``tenants2x4`` (tenants x islands). Compile cost is the budget here —
the fused iteration costs ~1 min per mesh on the CI host and the cycle/
mutate stage programs ~40s each — so coverage is tiered EXPLICITLY
(never silently): the canonical ``mesh4x2`` compiles every stage plus
the fused iteration; the other island meshes compile the cheap
comm-bearing stage set; ``tenants2x4`` compiles the fused tenant
program (the zero-cross-tenant gate) plus the cheap stages vmapped over
the tenant axis. The skipped stages are recorded in each config's
``stage_set`` and called out as notes.

Hosts with fewer than 8 devices skip every config (skipped != missing:
skipped entries are never written into the baseline and never fail the
diff — the same discipline as compile_surface's ``sharded`` config).

CLI: ``python -m symbolicregression_jl_tpu.analysis --only shard
[--update-baseline]`` (docs/static_analysis.md, docs/multichip.md).
"""

from __future__ import annotations

import json
import os
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .compile_surface import (
    _BASE_KWARGS,
    _NFEAT,
    _NROWS,
    _abstract_inputs,
    count_primitives,
)

BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "shard_baseline.json"
)

#: Comm-byte growth beyond this fraction of the baseline fails CI
#: (shrinks only note a refresh) — same tolerance as srmem/srcost.
REGRESSION_TOLERANCE = 0.10

#: A compiled output leaf holding more than this multiple of its
#: contract-expected per-device bytes is a replication blowup.
REPLICATION_BLOWUP_FACTOR = 1.5

#: Leaves below this global size are exempt from replication accounting
#: (scalars and tiny counters are replicated by design).
_REPLICATION_MIN_BYTES = 1024

#: The device kind the comms/compute fractions are modeled against.
#: Fixed — NOT the host's own kind — so the modeled numbers are
#: deterministic across CI hosts (a CPU host "models a v5e pod slice").
MODEL_DEVICE_KIND = "v5e"

#: One-way aggregate inter-chip-interconnect bandwidth per chip,
#: bytes/s — coarse public anchors, the same scale-anchor convention as
#: telemetry/profile.py's TPU_PEAKS (substring-matched, longest key
#: first). These price the collectives' wire time in the modeled
#: comms-vs-compute fraction; they are scale anchors, not promises.
ICI_BANDWIDTH: Dict[str, float] = {
    "v5 lite": 2.0e11,
    "v5e": 2.0e11,
    "v5p": 6.0e11,
    "v6 lite": 4.5e11,
    "v6e": 4.5e11,
    "v4": 3.0e11,
    "v3": 8.2e10,
    "v2": 6.2e10,
}

#: Fallback for host interconnect (multi-host DCN / forced-host CPU
#: devices): a 100Gb NIC — pessimistic on purpose, so a collective that
#: would ride DCN instead of ICI prices loudly.
HOST_INTERCONNECT_BYTES_PER_S = 1.25e10

#: Ring-model wire factors per collective: the fraction of the payload
#: each participant moves over the interconnect for a group of size g.
_RING_FACTORS: Dict[str, Callable[[int], float]] = {
    "all-gather": lambda g: (g - 1) / g,
    "all-reduce": lambda g: 2.0 * (g - 1) / g,
    "reduce-scatter": lambda g: (g - 1) / g,
    "all-to-all": lambda g: (g - 1) / g,
    "collective-permute": lambda g: 1.0,
}

_COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
    "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z]+\d*)\[([0-9,]*)\]")

#: srshard's Options base: compile_surface's matrix kwargs at 8 islands,
#: so every mesh in the matrix tiles 8 devices exactly.
_SHARD_KWARGS = dict(_BASE_KWARGS, npopulations=8)

#: The cheap comm-bearing stage subset (each compiles in seconds on the
#: CI host; cycle/mutate cost ~40s each and ride the canonical mesh).
_CHEAP_STAGES = ("init", "eval", "simplify", "optimize", "merge_migrate")
_ALL_STAGES = (
    "init", "cycle", "mutate", "eval", "simplify", "optimize",
    "merge_migrate",
)

#: The canonical config: full stage set + the fused iteration, and the
#: per-stage comms fractions srprof's report joins against.
CANONICAL_CONFIG = "mesh4x2"

#: (name, extra Options kwargs, stage subset, compile the fused jit?).
#: Mesh shape falls out of make_mesh: 8 islands with row_shards r give
#: an (8/r, r) (islands, rows) mesh; tenants=2 gives (2, 4)
#: (tenants, islands).
_MESH_MATRIX: Tuple[Tuple[str, dict, Tuple[str, ...], bool], ...] = (
    ("mesh1x8", dict(row_shards=8), _CHEAP_STAGES, False),
    ("mesh2x4", dict(row_shards=4), _CHEAP_STAGES, False),
    ("mesh4x2", dict(row_shards=2), _ALL_STAGES, True),
    ("tenants2x4", dict(tenants=2), _CHEAP_STAGES, True),
)

#: Per-stage in_shardings, written in the search_shardings vocabulary
#: (parallel/mesh.py) so ONE table serves both mesh modes: on a solo
#: (islands, rows) mesh ``tenant`` aliases ``replicated`` and these are
#: exactly the production specs; on a (tenants, islands) mesh every
#: name composes with the leading tenant axis. Keyed by the
#: build_stage_programs argument order.
_STAGE_ARG_SPECS: Dict[str, Tuple[str, ...]] = {
    "init": ("island", "x", "rows", "tenant", "replicated"),
    "cycle": ("island", "replicated", "x", "rows", "tenant", "replicated"),
    "mutate": ("island", "replicated", "replicated"),
    "eval": ("island", "x", "rows", "tenant", "replicated"),
    "simplify": (
        "island", "replicated", "x", "rows", "tenant", "replicated"
    ),
    "optimize": ("island", "island", "x", "rows", "tenant", "replicated"),
    "merge_migrate": ("tenant", "island", "replicated"),
}

#: vmap in_axes per stage for the tenant-batched variants (the leading
#: tenants dim rides on everything per-tenant; curmaxsize and the
#: traced-scalar knobs are shared across the bucket).
_TENANT_STAGE_AXES: Dict[str, Tuple] = {
    "init": (0, 0, 0, 0, None),
    "cycle": (0, None, 0, 0, 0, None),
    "mutate": (0, None, None),
    "eval": (0, 0, 0, 0, None),
    "simplify": (0, None, 0, 0, 0, None),
    "optimize": (0, 0, 0, 0, 0, None),
    "merge_migrate": (0, 0, None),
}


# ---------------------------------------------------------------------------
# HLO collective parsing
# ---------------------------------------------------------------------------


def _decode_iota_groups(
    ngroups: int, gsize: int, dims: Sequence[int],
    perm: Optional[Sequence[int]],
) -> List[List[int]]:
    """Decode HLO's iota replica-group form
    ``[ngroups,gsize]<=[dims]T(perm)``: iota over ``dims``, transpose by
    ``perm``, flatten, reshape to (ngroups, gsize). Example:
    ``[4,2]<=[2,4]T(1,0)`` -> ``[[0,4],[1,5],[2,6],[3,7]]``."""
    import numpy as np

    n = 1
    for d in dims:
        n *= int(d)
    arr = np.arange(n).reshape(tuple(int(d) for d in dims))
    if perm is not None:
        arr = np.transpose(arr, tuple(int(p) for p in perm))
    return arr.reshape(ngroups, gsize).tolist()


_BRACE_GROUPS_RE = re.compile(r"replica_groups=\{((?:\{[^}]*\},?)*)\}")
_IOTA_GROUPS_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?"
)
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{[^}]*\},?)*)\}")


def _participant_groups(attrs: str, n_devices: int) -> List[List[int]]:
    """Participant groups of one collective instruction's attribute
    text. ``replica_groups={}`` (and an absent attribute) mean one group
    of all participants; collective-permute's source_target_pairs count
    as 2-participant groups."""
    m = _IOTA_GROUPS_RE.search(attrs)
    if m:
        dims = [int(x) for x in m.group(3).split(",")]
        perm = (
            [int(x) for x in m.group(4).split(",")] if m.group(4) else None
        )
        return _decode_iota_groups(int(m.group(1)), int(m.group(2)),
                                   dims, perm)
    m = _BRACE_GROUPS_RE.search(attrs)
    if m:
        groups = [
            [int(x) for x in g.split(",") if x.strip()]
            for g in re.findall(r"\{([^}]*)\}", m.group(1))
        ]
        groups = [g for g in groups if g]
        if groups:
            return groups
        return [list(range(n_devices))]
    m = _PAIRS_RE.search(attrs)
    if m:
        pairs = [
            [int(x) for x in g.split(",") if x.strip()]
            for g in re.findall(r"\{([^}]*)\}", m.group(1))
        ]
        return [p for p in pairs if p]
    return [list(range(n_devices))]


def _result_bytes(result_text: str) -> int:
    """Payload bytes of one collective: the largest shape in the result
    portion (async ``-start`` results are (operand, output) tuples — the
    output is never smaller than what moves on the wire per rank)."""
    best = 0
    for dtype, dims in _SHAPE_RE.findall(result_text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        best = max(best, n * _DTYPE_BYTES.get(dtype, 4))
    return best


def parse_collectives(hlo_text: str, n_devices: int) -> List[dict]:
    """Structured census of the cross-device collectives in optimized
    HLO text: ``[{"op", "bytes", "groups"}, ...]``. Counts each async
    pair once (by its ``-start`` half) — the compile_surface
    collective_census convention, with payloads and decoded participant
    groups on top."""
    out: List[dict] = []
    for line in hlo_text.splitlines():
        eq = line.find(" = ")
        if eq < 0:
            continue
        for op in _COLLECTIVE_OPS:
            idx = -1
            for tok in (f" {op}(", f" {op}-start("):
                idx = line.find(tok, eq)
                if idx >= 0:
                    break
            if idx < 0:
                continue
            out.append({
                "op": op,
                "bytes": _result_bytes(line[eq + 3:idx]),
                "groups": _participant_groups(line[idx:], n_devices),
            })
            break
    return out


def census_of(collectives: List[dict]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for c in collectives:
        counts[c["op"]] = counts.get(c["op"], 0) + 1
    return dict(sorted(counts.items()))


# ---------------------------------------------------------------------------
# communication cost model
# ---------------------------------------------------------------------------


def interconnect_bandwidth(device_kind: str) -> float:
    """ICI bytes/s for a device kind (substring match, longest key
    first), or the host-interconnect fallback."""
    low = (device_kind or "").lower()
    for key in sorted(ICI_BANDWIDTH, key=len, reverse=True):
        if key in low:
            return ICI_BANDWIDTH[key]
    return HOST_INTERCONNECT_BYTES_PER_S


def price_comms(
    collectives: List[dict], device_kind: str = MODEL_DEVICE_KIND
) -> dict:
    """Ring-model wire time of a collective census:
    ``{"comm_bytes", "modeled_s"}``. comm_bytes is the raw payload sum
    (the deterministic, table-independent quantity the baseline gates);
    modeled_s prices each payload by its ring factor at the group size
    over the tabled bandwidth."""
    bw = interconnect_bandwidth(device_kind)
    total = 0
    seconds = 0.0
    for c in collectives:
        g = max((len(grp) for grp in c["groups"]), default=1)
        total += int(c["bytes"])
        seconds += c["bytes"] * _RING_FACTORS[c["op"]](max(g, 1)) / bw
    return {"comm_bytes": int(total), "modeled_s": seconds}


def comms_fraction(modeled_comms_s: float, flops: float) -> float:
    """Modeled comms share of one program's wall time against
    MODEL_DEVICE_KIND's compute rate: comms_s / (comms_s + compute_s)."""
    from ..telemetry.profile import TPU_PEAKS

    compute_s = flops / TPU_PEAKS[MODEL_DEVICE_KIND]["flops_per_s"]
    denom = modeled_comms_s + compute_s
    return (modeled_comms_s / denom) if denom > 0 else 0.0


# ---------------------------------------------------------------------------
# tenant isolation
# ---------------------------------------------------------------------------


def cross_tenant_collectives(
    collectives: List[dict], n_island_shards: int
) -> List[dict]:
    """The collectives whose participant groups mix tenant coordinates
    AND can leak one tenant's values into another's results.
    Participant k of a compiled (tenants, islands)-mesh program is
    ``mesh.devices.ravel()[k]`` (C order), so its tenant coordinate is
    ``k // n_island_shards``.

    Two GSPMD artifacts are structurally benign and exempt (both appear
    in the real tenant-batched iteration, whose per-tenant bit-identity
    to solo runs is pinned by tests/test_serving.py):

    - **all-gather** — pure data movement: every participant's shard is
      preserved verbatim, never arithmetically combined, so a tenant's
      math can only consume its own slices back. GSPMD emits one when
      it replicates an intermediate it declines to partition (e.g. the
      constant-optimizer ``top_k`` operand); the payload still rides
      the priced census and the replication gate bounds the blowup.
    - **scalar-predicate all-reduce** (1-byte payload: ``pred[]``) —
      SPMD ``while``-loop condition convergence: every device on the
      mesh must agree on the loop predicate, so XLA and-reduces it
      across ALL devices by construction. Control flow, not data.

    Everything else crossing the tenant axis — any all-reduce of real
    data (the injected-``psum`` defect class), reduce-scatter,
    all-to-all, collective-permute — is a correctness leak."""
    bad = []
    for c in collectives:
        if c["op"] == "all-gather":
            continue
        if c["op"] == "all-reduce" and c["bytes"] <= 1:
            continue
        for g in c["groups"]:
            if len({p // n_island_shards for p in g}) > 1:
                bad.append(c)
                break
    return bad


def _bisect_tenant_culprits(
    compile_hlo: Callable[[Tuple[int, ...]], str],
    n_leaves: int,
    n_island_shards: int,
    n_devices: int,
) -> List[int]:
    """Group-halving bisection (the srkey pattern) over output-leaf
    indices: ``compile_hlo(idxs)`` compiles the program restricted to
    those output leaves; any subset still emitting a cross-tenant
    collective recurses into its halves until single leaves are named.
    O(c log n) compiles for c culprits."""
    culprits: List[int] = []

    def bad(idxs: Tuple[int, ...]) -> bool:
        colls = parse_collectives(compile_hlo(idxs), n_devices)
        return bool(cross_tenant_collectives(colls, n_island_shards))

    def rec(idxs: Tuple[int, ...]) -> None:
        if not bad(idxs):
            return
        if len(idxs) == 1:
            culprits.append(idxs[0])
            return
        mid = len(idxs) // 2
        rec(idxs[:mid])
        rec(idxs[mid:])

    rec(tuple(range(n_leaves)))
    return culprits


# ---------------------------------------------------------------------------
# structural contract + replication accounting
# ---------------------------------------------------------------------------


def _aval_bytes(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n * int(aval.dtype.itemsize)


def _shard_bytes(sharding, aval) -> int:
    n = 1
    for d in sharding.shard_shape(tuple(aval.shape)):
        n *= int(d)
    return n * int(aval.dtype.itemsize)


def _replication_stats(
    name: str,
    out_avals,
    out_shardings,
    expected_shardings,
    n_devices: int,
    factor: float = REPLICATION_BLOWUP_FACTOR,
) -> Tuple[List[str], float]:
    """(problems, max_replication_factor) of a compiled program's
    outputs. A leaf whose actual per-device bytes exceed ``factor`` x
    the contract-expected per-device bytes is flagged by its pytree
    path; the returned max factor is ``n_devices * shard_bytes /
    global_bytes`` over all non-tiny leaves (1.0 = fully sharded,
    n_devices = fully replicated)."""
    import jax

    problems: List[str] = []
    max_factor = 0.0
    aval_leaves = jax.tree_util.tree_flatten_with_path(out_avals)[0]
    sh_leaves = jax.tree_util.tree_leaves(
        out_shardings, is_leaf=lambda x: hasattr(x, "shard_shape")
    )
    exp_leaves = jax.tree_util.tree_leaves(
        expected_shardings, is_leaf=lambda x: hasattr(x, "shard_shape")
    )
    if not (len(aval_leaves) == len(sh_leaves) == len(exp_leaves)):
        return (
            [f"{name}: output sharding tree has {len(sh_leaves)} leaves "
             f"vs {len(aval_leaves)} avals / {len(exp_leaves)} expected "
             "— the replication gate no longer covers the outputs"],
            0.0,
        )
    for (path, aval), sh, exp in zip(aval_leaves, sh_leaves, exp_leaves):
        g = _aval_bytes(aval)
        if g < _REPLICATION_MIN_BYTES:
            continue
        got_b = _shard_bytes(sh, aval)
        max_factor = max(max_factor, n_devices * got_b / g)
        want_b = _shard_bytes(exp, aval)
        if want_b > 0 and got_b > factor * want_b:
            problems.append(
                f"{name}: replication blowup on output leaf"
                f"{jax.tree_util.keystr(path)} — {got_b} bytes/device "
                f"where the contract shards it to {want_b} "
                f"(x{got_b / want_b:.1f}; sharding {sh.spec} vs expected "
                f"{exp.spec}) — GSPMD fell back toward replication"
            )
    return problems, max_factor


def _fused_contract_problems(
    name: str, options, compiled, states_aval, tenant_mode: bool
) -> List[str]:
    """Walk the compiled fused iteration's output AND input shardings:
    carry leaves island-sharded (tenant+island on a tenant mesh) in and
    out, the merged HoF replicated (per-tenant on a tenant mesh)."""
    import jax

    problems: List[str] = []
    try:
        out_sh = compiled.output_shardings
        in_sh = compiled.input_shardings[0]
    except Exception as e:  # pragma: no cover - jax API variance
        return [f"{name}: could not read compiled shardings: {e}"]
    st_sh, ghof_sh = out_sh[0], out_sh[1]
    n_sh = len(jax.tree_util.tree_leaves(st_sh))
    n_aval = len(jax.tree_util.tree_leaves(states_aval))
    if n_sh != n_aval:
        problems.append(
            f"{name}: compiled output-sharding tree has {n_sh} leaves "
            f"but the IslandState aval has {n_aval} — the contract "
            "check no longer covers the carry"
        )

    def check_carry(tag: str, tree) -> None:
        for path, sh in jax.tree_util.tree_flatten_with_path(tree)[0]:
            spec = tuple(getattr(sh, "spec", ()) or ())
            ok = (
                spec[:2] == (options.tenant_axis, options.island_axis)
                if tenant_mode else
                bool(spec) and spec[0] == options.island_axis
            )
            if not ok:
                problems.append(
                    f"{name}: {tag} IslandState leaf"
                    f"{jax.tree_util.keystr(path)} has sharding {sh} "
                    "instead of island-axis sharding — a replicated "
                    "carry serializes every later iteration on one "
                    "device"
                )

    check_carry("carried", st_sh)
    check_carry("input", in_sh[0])
    for path, sh in jax.tree_util.tree_flatten_with_path(ghof_sh)[0]:
        spec = tuple(getattr(sh, "spec", ()) or ())
        ok = (
            spec[:1] == (options.tenant_axis,) if tenant_mode
            else sh.is_fully_replicated
        )
        if not ok:
            problems.append(
                f"{name}: merged HoF leaf{jax.tree_util.keystr(path)} "
                f"is not {'tenant-sharded' if tenant_mode else 'replicated'}"
                f" ({sh}) — host-side candidate extraction would gather "
                "per-iteration"
            )
    return problems


def _memo_vocabulary_problems(name: str, mesh, options_kwargs: dict
                              ) -> List[str]:
    """The memo snapshot's place in the shard vocabulary, checked
    without compiling: the cache-enabled iteration signature must take
    the memo replicated (every device serves hits locally) and emit the
    absorb snapshot island-sharded."""
    from ..api import _iteration_shard_kw
    from ..models.options import make_options

    cache_opts = make_options(
        **{**options_kwargs, "cache_fitness": True,
           "cache_device_slots": 8}
    )
    kw = _iteration_shard_kw(cache_opts, mesh, False)
    problems: List[str] = []
    memo_in = kw["in_shardings"][-1]
    absorb_out = kw["out_shardings"][-1]
    if not memo_in.is_fully_replicated:
        problems.append(
            f"{name}: memo snapshot input spec is {memo_in.spec} — the "
            "contract replicates it (every device serves memo hits "
            "locally)"
        )
    spec = tuple(absorb_out.spec or ())
    if not spec or spec[0] != cache_opts.island_axis:
        problems.append(
            f"{name}: absorb snapshot output spec is {spec} — the "
            "contract shards it over the island axis"
        )
    return problems


# ---------------------------------------------------------------------------
# program compilation
# ---------------------------------------------------------------------------


def _stage_in_shardings(stage: str, sh: dict):
    return tuple(sh[k] for k in _STAGE_ARG_SPECS[stage])


def _solo_stage_programs(options, stage_set: Sequence[str]) -> Dict:
    from .memory import build_stage_programs

    progs = build_stage_programs(options)
    return {s: progs[s] for s in stage_set}


def _tenant_stage_programs(options, stage_set: Sequence[str]) -> Dict:
    """The tenant-batched stage variants: each solo stage program
    vmapped over the leading tenants axis with its per-argument in_axes,
    traced at (T, ...) avals — the stage decomposition of the serving
    fused program."""
    import dataclasses

    import jax

    from .memory import build_stage_programs

    T = options.tenants
    solo = dataclasses.replace(options, tenants=1)
    progs = build_stage_programs(solo)
    out: Dict = {}
    for stage in stage_set:
        fn, args = progs[stage]
        axes = _TENANT_STAGE_AXES[stage]
        targs = tuple(
            jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct((T,) + l.shape, l.dtype),
                a,
            ) if ax == 0 else a
            for a, ax in zip(args, axes)
        )
        out[stage] = (jax.vmap(fn, in_axes=axes), targs)
    return out


def _check_stage(
    name: str,
    stage: str,
    fn,
    args,
    mesh,
    options,
    stage_flops: float,
    tenant_mode: bool,
) -> Tuple[dict, List[str]]:
    """AOT-compile one stage program under its contract in_shardings and
    return its entry (census, priced comms, replication report) plus any
    problems (cross-tenant collectives on a tenant mesh)."""
    import jax

    from ..parallel.mesh import search_shardings

    sh = search_shardings(mesh, options)
    in_sh = _stage_in_shardings(stage, sh)
    compiled = (
        jax.jit(fn, in_shardings=in_sh).lower(*args).compile()
    )
    n_dev = int(mesh.devices.size)
    colls = parse_collectives(compiled.as_text(), n_dev)
    priced = price_comms(colls)
    outs = jax.eval_shape(fn, *args)
    # report-only replication factor over the stage outputs (GSPMD
    # chooses them freely; the fused program is where the contract pins)
    _, max_factor = _replication_stats(
        f"{name}.{stage}", outs, compiled.output_shardings,
        compiled.output_shardings, n_dev,
    )
    problems: List[str] = []
    if tenant_mode:
        n_islands = int(mesh.devices.shape[1])
        for c in cross_tenant_collectives(colls, n_islands):
            problems.append(
                f"{name}.{stage}: CROSS-TENANT {c['op']} "
                f"({c['bytes']} bytes, groups {c['groups']}) — tenants "
                "are embarrassingly parallel; a collective crossing the "
                "tenant axis is a correctness leak"
            )
    entry = {
        "collectives": census_of(colls),
        "comm_bytes": priced["comm_bytes"],
        "modeled_comms_s": priced["modeled_s"],
        "comms_fraction": round(
            comms_fraction(priced["modeled_s"], stage_flops), 6
        ),
        "max_replication_factor": round(max_factor, 3),
    }
    return entry, problems


def _check_fused(
    name: str, options, mesh, tenant_mode: bool, compute_flops: float
) -> Tuple[dict, List[str]]:
    """The fused production iteration on this mesh: structural sharding
    contract, replication-blowup gate against the contract's expected
    out shardings, constraint-primitive census, collective census +
    pricing, and (tenant mesh) the zero-cross-tenant gate with
    leaf-level bisection on failure."""
    import jax

    from ..api import _iteration_shard_kw, _make_iteration_fn

    problems: List[str] = []
    I = options.npopulations
    states, key, cm, X, y, bl, scalars, memo, _ = _abstract_inputs(
        options, I
    )
    it_fn = _make_iteration_fn(options, False, mesh=mesh)
    args = (states, key, cm, X, y, bl, scalars)
    outs = jax.eval_shape(it_fn, *args)
    compiled = it_fn.lower(*args).compile()
    n_dev = int(mesh.devices.size)
    colls = parse_collectives(compiled.as_text(), n_dev)
    priced = price_comms(colls)
    if not colls:
        problems.append(
            f"{name}: the partitioned fused iteration compiled to ZERO "
            "cross-device collectives — the islands axis was "
            "partitioned away (migration/HoF-merge no longer "
            "communicate)"
        )

    problems += _fused_contract_problems(
        name, options, compiled, states, tenant_mode
    )
    shard_kw = _iteration_shard_kw(options, mesh, False)
    isl, ten = shard_kw["out_shardings"][0], shard_kw["out_shardings"][1]
    expected = (
        jax.tree_util.tree_map(lambda _: isl, outs[0]),
        jax.tree_util.tree_map(lambda _: ten, outs[1]),
    )
    rep_problems, max_factor = _replication_stats(
        name, (outs[0], outs[1]),
        (compiled.output_shardings[0], compiled.output_shardings[1]),
        expected, n_dev,
    )
    problems += rep_problems

    # constraint census: the solo fused program must carry the
    # migration/HoF-merge replicated pins; the tenant-batched body must
    # carry NONE (the inner_mesh=None rule — SR012's runtime complement)
    n_constraints = count_primitives(
        jax.make_jaxpr(it_fn)(*args)
    ).get("sharding_constraint", 0)
    if tenant_mode and n_constraints:
        problems.append(
            f"{name}: {n_constraints} sharding_constraint primitive(s) "
            "inside the tenant-batched iteration — constraints inside "
            "the vmapped body name axes the tenant program cannot see "
            "(the inner_mesh=None rule; lint rule SR012)"
        )
    elif not tenant_mode and not n_constraints:
        problems.append(
            f"{name}: the solo fused iteration carries no "
            "sharding_constraint primitives — the migration topn-pool / "
            "merged-HoF replicated pins vanished (parallel/migration.py)"
        )

    cross_tenant = 0
    if tenant_mode:
        n_islands = int(mesh.devices.shape[1])
        bad = cross_tenant_collectives(colls, n_islands)
        cross_tenant = len(bad)
        if bad:
            flat_out_sh = jax.tree_util.tree_leaves(
                compiled.output_shardings,
                is_leaf=lambda x: hasattr(x, "shard_shape"),
            )
            leaf_paths = [
                jax.tree_util.keystr(p)
                for p, _ in jax.tree_util.tree_flatten_with_path(outs)[0]
            ]

            def compile_hlo(idxs: Tuple[int, ...]) -> str:
                f = lambda *a: tuple(  # noqa: E731
                    jax.tree_util.tree_leaves(it_fn(*a))[i] for i in idxs
                )
                return (
                    jax.jit(
                        f,
                        out_shardings=tuple(flat_out_sh[i] for i in idxs),
                    )
                    .lower(*args).compile().as_text()
                )

            culprits = _bisect_tenant_culprits(
                compile_hlo, len(leaf_paths), n_islands, n_dev
            )
            ops = ", ".join(
                f"{c['op']} ({c['bytes']} bytes)" for c in bad
            )
            problems.append(
                f"{name}: {len(bad)} CROSS-TENANT collective(s) in the "
                f"fused iteration — {ops}; bisected culprit leaf(s): "
                + ", ".join(leaf_paths[i] for i in culprits)
            )

    entry = {
        "collectives": census_of(colls),
        "comm_bytes": priced["comm_bytes"],
        "modeled_comms_s": priced["modeled_s"],
        "comms_fraction": round(
            comms_fraction(priced["modeled_s"], compute_flops), 6
        ),
        "max_replication_factor": round(max_factor, 3),
        "sharding_constraints": int(n_constraints),
        "cross_tenant_collectives": int(cross_tenant),
    }
    return entry, problems


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def _baseline_entry(entry: dict) -> dict:
    def section(sec: dict) -> dict:
        return {
            "collectives": sec["collectives"],
            "comm_bytes": sec["comm_bytes"],
            # derived (bandwidth table + srcost join) — recorded for
            # srprof's report join, never diffed
            "comms_fraction": sec["comms_fraction"],
        }

    out = {
        "mesh_shape": entry["mesh_shape"],
        "n_devices": entry["n_devices"],
        "stage_set": entry["stage_set"],
        "stages": {s: section(se) for s, se in entry["stages"].items()},
    }
    if "fused" in entry:
        out["fused"] = section(entry["fused"])
    return out


def diff_shard_baseline(
    configs: Dict[str, dict],
    baseline: dict,
    tolerance: float = REGRESSION_TOLERANCE,
) -> Tuple[List[str], List[str]]:
    """(problems, notes). Collective-census drift fails exactly (a
    changed census is a compiled-traffic-shape change); comm-byte
    GROWTH beyond tolerance fails, shrinks note a refresh."""
    problems: List[str] = []
    notes: List[str] = []
    base_configs = baseline.get("configs", {})
    skipped = {n for n, e in configs.items() if "skipped" in e}

    def diff_section(tag: str, want: dict, got: dict) -> None:
        want_c, got_c = want.get("collectives", {}), got["collectives"]
        for op in sorted(set(want_c) | set(got_c)):
            w, g = want_c.get(op, 0), got_c.get(op, 0)
            if w != g:
                problems.append(
                    f"{tag}: collective census drift for {op!r}: "
                    f"baseline {w} -> now {g} (intentional? refresh "
                    "with --update-baseline)"
                )
        w, g = want.get("comm_bytes", 0), got["comm_bytes"]
        if w > 0:
            ratio = g / w
            if ratio > 1.0 + tolerance:
                problems.append(
                    f"{tag}: modeled comm bytes grew {w} -> {g} "
                    f"(+{(ratio - 1) * 100:.0f}%, tolerance "
                    f"{tolerance * 100:.0f}%) — a cross-device traffic "
                    "regression; fix it or refresh with "
                    "--update-baseline and justify in the PR"
                )
            elif ratio < 1.0 - tolerance:
                notes.append(
                    f"{tag}: modeled comm bytes shrank {w} -> {g} "
                    f"({(1 - ratio) * 100:.0f}% better) — refresh the "
                    "baseline with --update-baseline to lock it in"
                )
        elif g > 0:
            problems.append(
                f"{tag}: baseline has zero comm bytes but this run "
                f"moved {g} — refresh with --update-baseline"
            )

    for name, entry in configs.items():
        if name in skipped:
            continue
        if name not in base_configs:
            problems.append(
                f"shard baseline has no config {name!r} — run with "
                "--update-baseline"
            )
            continue
        base = base_configs[name]
        if base.get("stage_set") != entry["stage_set"]:
            problems.append(
                f"{name}: compiled stage set changed "
                f"{base.get('stage_set')} -> {entry['stage_set']} — "
                "refresh with --update-baseline"
            )
        if base.get("mesh_shape") != entry["mesh_shape"]:
            problems.append(
                f"{name}: mesh shape changed {base.get('mesh_shape')} "
                f"-> {entry['mesh_shape']} — refresh with "
                "--update-baseline"
            )
        base_stages = base.get("stages", {})
        for stage, s_entry in entry["stages"].items():
            if stage not in base_stages:
                problems.append(
                    f"shard baseline has no stage {name}.{stage} — "
                    "refresh with --update-baseline"
                )
                continue
            diff_section(f"{name}.{stage}", base_stages[stage], s_entry)
        for stage in base_stages:
            if stage not in entry["stages"]:
                problems.append(
                    f"shard baseline stage {name}.{stage} no longer "
                    "produced — refresh with --update-baseline"
                )
        if "fused" in entry:
            if "fused" not in base:
                problems.append(
                    f"shard baseline has no fused section for {name!r} "
                    "— refresh with --update-baseline"
                )
            else:
                diff_section(f"{name}.fused", base["fused"],
                             entry["fused"])
        elif "fused" in base:
            problems.append(
                f"shard baseline fused section for {name!r} no longer "
                "produced — refresh with --update-baseline"
            )
    for name in base_configs:
        if name not in configs and name not in skipped:
            problems.append(
                f"shard baseline config {name!r} no longer produced — "
                "refresh with --update-baseline"
            )
    return problems, notes


def baseline_stage_comms(
    baseline_path: Optional[str] = None, config: str = CANONICAL_CONFIG
) -> Dict[str, float]:
    """{stage: modeled comms fraction} from the checked-in shard
    baseline's canonical config — the join telemetry/profile.py's
    report annotates its stage table with. {} when no baseline (or no
    such config) exists; never raises."""
    path = baseline_path or BASELINE_PATH
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    cfg = (data.get("configs") or {}).get(config) or {}
    out: Dict[str, float] = {}
    for stage, entry in (cfg.get("stages") or {}).items():
        frac = entry.get("comms_fraction")
        if isinstance(frac, (int, float)):
            out[stage] = float(frac)
    return out


# ---------------------------------------------------------------------------
# the checker
# ---------------------------------------------------------------------------


def check_shard(
    update_baseline: bool = False,
    baseline_path: Optional[str] = None,
    matrix: Optional[Tuple[Tuple[str, dict, Tuple[str, ...], bool], ...]]
    = None,
    tolerance: float = REGRESSION_TOLERANCE,
) -> dict:
    """Run the srshard gate over the mesh matrix; returns the report
    dict rendered by report.render_shard_text (and embedded in the CLI
    JSON). Hosts with fewer than 8 devices skip every config (skipped
    != missing — the baseline diff exempts them and the refresh
    preserves their checked-in entries)."""
    import jax

    from ..analysis.cost import stage_costs
    from ..models.options import make_options
    from ..parallel.mesh import describe_mesh, make_mesh, spec_table

    baseline_path = baseline_path or BASELINE_PATH
    devices = jax.devices()
    out_configs: Dict[str, dict] = {}
    problems: List[str] = []
    notes: List[str] = []
    cross_tenant_total = 0
    max_repl = 0.0
    for name, extra, stage_set, fused in (matrix or _MESH_MATRIX):
        if len(devices) < 8:
            out_configs[name] = {
                "skipped": f"{len(devices)} device(s) — the srshard "
                "mesh matrix needs 8"
            }
            continue
        options = make_options(**{**_SHARD_KWARGS, **extra})
        tenant_mode = options.tenants > 1
        mesh = make_mesh(
            options, options.npopulations, devices=devices[:8],
            row_shards=extra.get("row_shards", 1),
            tenants=options.tenants,
        )
        import dataclasses

        solo_opts = (
            dataclasses.replace(options, tenants=1) if tenant_mode
            else options
        )
        flops_by_stage = {
            s: c["flops"] * (options.tenants if tenant_mode else 1)
            for s, c in stage_costs(solo_opts, _NFEAT, _NROWS).items()
        }
        entry: dict = {
            "mesh_shape": describe_mesh(mesh, devices[:8])["mesh_shape"],
            "n_devices": int(mesh.devices.size),
            "stage_set": list(stage_set),
            "specs": spec_table(mesh, options),
            "stages": {},
        }
        progs = (
            _tenant_stage_programs(options, stage_set) if tenant_mode
            else _solo_stage_programs(options, stage_set)
        )
        for stage, (fn, args) in progs.items():
            s_entry, s_problems = _check_stage(
                name, stage, fn, args, mesh, options,
                flops_by_stage[stage], tenant_mode,
            )
            # stage factors stay per-entry informational: GSPMD chooses
            # stage-program outputs freely (e.g. on a (1, 8) mesh the
            # carry replicates across rows by design); only the fused
            # programs' contract-pinned outputs roll up into the gate's
            # headline factor
            entry["stages"][stage] = s_entry
            problems += s_problems
        if fused:
            # whole-iteration compute = the per-iteration stage flops
            # (init is a one-shot program, not part of the iteration)
            compute = sum(
                v for s, v in flops_by_stage.items() if s != "init"
            )
            f_entry, f_problems = _check_fused(
                name, options, mesh, tenant_mode, compute
            )
            entry["fused"] = f_entry
            problems += f_problems
            cross_tenant_total += f_entry["cross_tenant_collectives"]
            max_repl = max(max_repl, f_entry["max_replication_factor"])
            if not tenant_mode:
                problems += _memo_vocabulary_problems(
                    name, mesh, _SHARD_KWARGS
                )
        else:
            notes.append(
                f"{name}: fused iteration not compiled on this mesh "
                "(compile-cost budget; the canonical "
                f"{CANONICAL_CONFIG} config covers it)"
            )
        missing = [s for s in _ALL_STAGES if s not in stage_set]
        if missing:
            notes.append(
                f"{name}: stage(s) {', '.join(missing)} not compiled "
                "on this mesh (compile-cost budget; the canonical "
                f"{CANONICAL_CONFIG} config covers them)"
            )
        out_configs[name] = entry

    baseline_checked = baseline_match = False
    if update_baseline:
        from .report import build_baseline_configs, write_baseline_json

        payload = {
            "schema_version": 1,
            "jax_version": jax.__version__,
            "model_device_kind": MODEL_DEVICE_KIND,
            # skipped configs (a <8-device host) keep their prior
            # checked-in entries — see report.build_baseline_configs
            "configs": build_baseline_configs(
                baseline_path, out_configs, _baseline_entry
            ),
        }
        write_baseline_json(baseline_path, payload)
    elif os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f)
        baseline_checked = True
        base_problems, base_notes = diff_shard_baseline(
            out_configs, baseline, tolerance
        )
        baseline_match = not base_problems
        problems += base_problems
        notes += base_notes
        if baseline.get("jax_version") != jax.__version__:
            baseline_match = False
            problems.append(
                "shard baseline was written under jax "
                f"{baseline.get('jax_version')} but this is "
                f"{jax.__version__} — refresh with --update-baseline"
            )
    else:
        problems.append(
            f"no shard baseline at {baseline_path} — create one with "
            "--update-baseline"
        )

    canonical = out_configs.get(CANONICAL_CONFIG, {})
    return {
        "ok": not problems,
        "problems": problems,
        "notes": notes,
        "configs": out_configs,
        "baseline_checked": baseline_checked,
        "baseline_match": baseline_match,
        "baseline_path": baseline_path,
        "jax_version": jax.__version__,
        "model_device_kind": MODEL_DEVICE_KIND,
        "cross_tenant_collectives": int(cross_tenant_total),
        "max_replication_factor": round(max_repl, 3),
        "comms_fraction": (
            canonical.get("fused", {}).get("comms_fraction")
            if "skipped" not in canonical else None
        ),
    }
