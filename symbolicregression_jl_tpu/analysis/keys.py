"""srkey — the Options compile-identity contract checker (fifth engine).

Everything the serving tier trusts hangs off ``Options._graph_key()``
(models/options.py): it decides which jobs share a warm compile in
``serving.JobServer`` buckets, which lru-cached jit factory closures are
reused, and — with ``cache.memo.dataset_fingerprint`` — which memo-bank
entries may be served across runs. srkey machine-checks that contract
instead of trusting a comment convention:

1. **Registry completeness** — every ``Options`` field is declared in
   exactly one of ``GRAPH_FIELDS`` / ``TRACED_SCALAR_FIELDS`` /
   ``ORCHESTRATION_FIELDS`` (models/options.py). An unclassified or
   doubly-classified field fails immediately (and skips the rest: the
   later checks are meaningless against a broken registry), so every
   future PR that adds a knob is forced to state its compile contract.
2. **Key coverage (AST)** — ``_graph_key``'s source reads ``self.<f>``
   for every graph field and for NO orchestration/scalar field.
3. **Per-field key semantics** — perturbing a graph field changes the
   key; perturbing an orchestration field leaves key AND traced scalars
   unchanged; perturbing a traced scalar leaves the key unchanged while
   ``traced_scalars()`` differs. Every field must have a perturbation
   spec in ``ALT_SPECS`` (a missing spec is itself a finding).
4. **Fingerprint coverage** — every result-affecting eval-context field
   perturbs ``dataset_fingerprint`` (and so does the dataset itself),
   so a shared memo bank can never serve stale fitness; an
   all-orchestration perturbation leaves the fingerprint unchanged.
5. **Differential verification by tracing** — over the compile-surface
   base kwargs (solo + tenant-batched): perturb ALL orchestration
   fields at once and assert the jaxprs of the production programs
   (``memory.build_stage_programs`` + the fused iteration) are
   byte-identical to the unperturbed trace; same for all traced
   scalars (their VALUES enter jit as f32 avals, never as constants).
   On a mismatch the perturbation set is bisected by group halving, so
   the report names the leaking field(s), not just "something leaked".

The factory lru_caches (api.py) key on Options hash/eq — which IS the
graph key — so a perturbed-orchestration Options would hit the cache
entry whose closure closes over the BASE options and mask any leak.
Every trace set therefore clears those caches first; that also means a
green srkey run proves the caches may legitimately share closures
across orchestration perturbations.

Runs entirely on CPU (tracing is platform-independent) and executes
nothing; srkey adds zero primitives to any jitted program.
"""

from __future__ import annotations

import ast
import dataclasses
import inspect
import textwrap
from typing import Dict, List, Optional, Sequence, Tuple

from .compile_surface import _BASE_KWARGS, _NFEAT, _NROWS

#: Differential-tracing configs: the solo base surface and the
#: tenant-batched (vmapped) serving surface — the two program families
#: warm-compile buckets actually serve (compile_surface._MATRIX rows).
DEFAULT_TRACE_CONFIGS: Tuple[Tuple[str, dict], ...] = (
    ("base", {}),
    ("tenants2", dict(tenants=2)),
)


def _alt_loss_fn(tree, X, y, weights, options):  # pragma: no cover
    """Module-level custom-objective stand-in for the loss_function
    perturbation (never traced by srkey — key semantics only)."""
    return 0.0


#: Per-field perturbation specs: kwargs overlays on the compile-surface
#: base config, each changing that field to a DIFFERENT valid value.
#: Every Options field must have one — srkey reports a missing spec, so
#: a new knob cannot land without stating how to perturb it.
ALT_SPECS: Dict[str, dict] = {
    # --- graph fields -------------------------------------------------
    "binary_operators": dict(binary_operators=("+", "-")),
    "unary_operators": dict(unary_operators=("sin",)),
    "npopulations": dict(npopulations=3),
    "npop": dict(npop=16),
    "ncycles_per_iteration": dict(ncycles_per_iteration=3),
    "tournament_selection_n": dict(tournament_selection_n=6),
    "topn": dict(topn=6),
    "maxsize": dict(maxsize=10),
    "maxdepth": dict(maxdepth=6),
    "max_len": dict(max_len=24),
    "loss": dict(loss="L1DistLoss"),
    "loss_function": dict(loss_function=_alt_loss_fn),
    "annealing": dict(annealing=True),
    "use_frequency": dict(use_frequency=False),
    "use_frequency_in_tournament": dict(use_frequency_in_tournament=False),
    "mutation_weights": dict(mutation_weights=dict(mutate_constant=1.0)),
    "crossover_probability": dict(crossover_probability=0.1),
    "migration": dict(migration=False),
    "hof_migration": dict(hof_migration=False),
    "should_optimize_constants": dict(should_optimize_constants=False),
    "optimizer_algorithm": dict(optimizer_algorithm="NelderMead"),
    "optimizer_probability": dict(optimizer_probability=0.5),
    "optimizer_nrestarts": dict(optimizer_nrestarts=1),
    "optimizer_iterations": dict(optimizer_iterations=4),
    "optimizer_backend": dict(optimizer_backend="jnp"),
    "batching": dict(batching=True),
    "batch_size": dict(batch_size=32),
    "independent_island_batches": dict(independent_island_batches=True),
    "constraints": dict(constraints={"*": (3, 3)}),
    "nested_constraints": dict(nested_constraints={"cos": {"cos": 0}}),
    "complexity_of_operators": dict(complexity_of_operators={"+": 2}),
    "complexity_of_constants": dict(complexity_of_constants=2),
    "complexity_of_variables": dict(complexity_of_variables=2),
    "recorder": dict(recorder=True),
    "cache_fitness": dict(cache_fitness=True),
    "cache_device_slots": dict(cache_device_slots=16),
    "n_parallel_tournaments": dict(n_parallel_tournaments=2),
    "eval_backend": dict(eval_backend="jnp"),
    "kernel_program": dict(kernel_program="postfix"),
    "kernel_leaf_skip": dict(kernel_leaf_skip=True),
    "eval_bucket_ladder": dict(eval_bucket_ladder=(0.5, 1.0)),
    "eval_rows_per_tile": dict(eval_rows_per_tile=16),
    "max_cycles_per_dispatch": dict(max_cycles_per_dispatch=1),
    "row_shards": dict(row_shards=2),
    "precision": dict(precision="bfloat16"),
    "tenants": dict(tenants=2),
    # --- traced scalars ----------------------------------------------
    "parsimony": dict(parsimony=0.01),
    "alpha": dict(alpha=0.2),
    "perturbation_factor": dict(perturbation_factor=0.1),
    "probability_negate_constant": dict(probability_negate_constant=0.02),
    "adaptive_parsimony_scaling": dict(adaptive_parsimony_scaling=10.0),
    "tournament_selection_p": dict(tournament_selection_p=0.9),
    "fraction_replaced": dict(fraction_replaced=0.01),
    "fraction_replaced_hof": dict(fraction_replaced_hof=0.05),
    # --- orchestration ------------------------------------------------
    "skip_mutation_failures": dict(skip_mutation_failures=False),
    "fast_cycle": dict(fast_cycle=True),
    "warmup_maxsize_by": dict(warmup_maxsize_by=0.5),
    "early_stop_condition": dict(early_stop_condition=1e-8),
    "timeout_in_seconds": dict(timeout_in_seconds=60.0),
    "max_evals": dict(max_evals=1000),
    "seed": dict(seed=7),
    "deterministic": dict(deterministic=False),
    "verbosity": dict(verbosity=1),
    "progress": dict(progress=True),
    # {tenant} templates keep the specs valid under the tenant-batched
    # trace config too (TenantIsolationError otherwise)
    "output_file": dict(output_file="hof_{tenant}.csv"),
    "save_to_file": dict(save_to_file=False),
    "terminal_width": dict(terminal_width=80),
    "define_helper_functions": dict(define_helper_functions=False),
    "recorder_file": dict(recorder_file="other_recorder.json"),
    "telemetry": dict(telemetry=True),
    "telemetry_dir": dict(telemetry_dir="tmp_srkey_tel"),
    "telemetry_every": dict(telemetry_every=2),
    "telemetry_run_id": dict(telemetry_run_id="srkey-run"),
    "telemetry_attempt": dict(telemetry_attempt=2),
    "profile_trace_dir": dict(profile_trace_dir="tmp_srkey_trace"),
    "snapshot_path": dict(snapshot_path="snap_{tenant}.npz"),
    # companion kwarg required by __post_init__ validation; both
    # fields are orchestration-classified, so the class invariants
    # (key + scalars unchanged) still hold for the pair
    "snapshot_every_dispatches": dict(
        snapshot_every_dispatches=3, snapshot_path="snap_{tenant}.npz"
    ),
    "cache_capacity": dict(cache_capacity=128),
    "data_policy": dict(data_policy="mask"),
    "island_axis": dict(island_axis="isl"),
    "row_axis": dict(row_axis="r"),
    "tenant_axis": dict(tenant_axis="t"),
}

#: Eval-context fields whose perturbation must change the memo
#: fingerprint — anything that can move a full-data loss VALUE (even in
#: ULPs) or reinterpret program bytes. ``eval_backend`` uses "pallas"
#: here, not ALT_SPECS' "jnp": the fingerprint RESOLVES "auto" (which
#: lands on "jnp" for the small CPU rescore batch), so only the literal
#: non-auto alternative actually exercises the coverage.
FINGERPRINT_FIELDS: Tuple[Tuple[str, dict], ...] = (
    ("binary_operators", ALT_SPECS["binary_operators"]),
    ("unary_operators", ALT_SPECS["unary_operators"]),
    ("loss", ALT_SPECS["loss"]),
    ("loss_function", ALT_SPECS["loss_function"]),
    ("precision", ALT_SPECS["precision"]),
    ("eval_backend", dict(eval_backend="pallas")),
    ("kernel_program", ALT_SPECS["kernel_program"]),
    ("kernel_leaf_skip", ALT_SPECS["kernel_leaf_skip"]),
    ("row_shards", ALT_SPECS["row_shards"]),
    ("eval_rows_per_tile", ALT_SPECS["eval_rows_per_tile"]),
    ("tenants", ALT_SPECS["tenants"]),
)


# ---------------------------------------------------------------------------
# registry + AST coverage
# ---------------------------------------------------------------------------


def _registry(_override=None) -> Tuple[Tuple[str, ...], ...]:
    """(graph, scalars, orchestration) — the declared classification.
    ``_override`` substitutes an injected registry for the tests that
    prove srkey fails on a broken one."""
    if _override is not None:
        return tuple(tuple(t) for t in _override)
    from ..models.options import (
        GRAPH_FIELDS,
        ORCHESTRATION_FIELDS,
        TRACED_SCALAR_FIELDS,
    )

    return GRAPH_FIELDS, TRACED_SCALAR_FIELDS, ORCHESTRATION_FIELDS


def _registry_problems(graph, scalars, orch) -> List[str]:
    from ..models.options import Options

    problems: List[str] = []
    declared: Dict[str, List[str]] = {}
    for cls, fields in (
        ("GRAPH_FIELDS", graph),
        ("TRACED_SCALAR_FIELDS", scalars),
        ("ORCHESTRATION_FIELDS", orch),
    ):
        for f in fields:
            declared.setdefault(f, []).append(cls)
    actual = {f.name for f in dataclasses.fields(Options)}
    for f in sorted(actual - set(declared)):
        problems.append(
            f"field {f!r} is UNCLASSIFIED — declare it in exactly one "
            "of GRAPH_FIELDS / TRACED_SCALAR_FIELDS / "
            "ORCHESTRATION_FIELDS (models/options.py)"
        )
    for f, classes in sorted(declared.items()):
        if f not in actual:
            problems.append(
                f"registry declares {f!r} ({', '.join(classes)}) but "
                "Options has no such field"
            )
        elif len(classes) > 1:
            problems.append(
                f"field {f!r} is doubly classified: {', '.join(classes)}"
            )
    return problems


def _graph_key_reads() -> List[str]:
    """Every ``self.<attr>`` read in Options._graph_key, via AST."""
    from ..models.options import Options

    src = textwrap.dedent(inspect.getsource(Options._graph_key))
    reads: List[str] = []
    for node in ast.walk(ast.parse(src)):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            reads.append(node.attr)
    return reads


def _coverage_problems(graph, scalars, orch) -> Tuple[List[str], dict]:
    reads = set(_graph_key_reads())
    problems: List[str] = []
    missing = sorted(set(graph) - reads)
    for f in missing:
        problems.append(
            f"graph field {f!r} is ABSENT from _graph_key — two Options "
            "differing only in it would share a warm-compile bucket "
            "compiled for the other's value"
        )
    foreign = sorted(reads & (set(scalars) | set(orch)))
    for f in foreign:
        cls = (
            "traced-scalar" if f in set(scalars) else "orchestration"
        )
        problems.append(
            f"{cls} field {f!r} is read in _graph_key — sweeping it "
            "would recompile (scalars) or fragment warm-cache buckets "
            "for a host-only knob (orchestration)"
        )
    detail = {
        "reads": sorted(reads),
        "missing_from_key": missing,
        "foreign_in_key": foreign,
    }
    return problems, detail


# ---------------------------------------------------------------------------
# per-field key / scalar semantics
# ---------------------------------------------------------------------------


def _scalar_values(options) -> Tuple[float, ...]:
    return tuple(float(v) for v in options.traced_scalars())


def _semantics_problems(graph, scalars, orch) -> Tuple[List[str], dict]:
    from ..models.options import make_options

    problems: List[str] = []
    base = make_options(**_BASE_KWARGS)
    base_key = base._graph_key()
    base_scalars = _scalar_values(base)
    missing_specs: List[str] = []
    checked = 0
    for field in sorted(set(graph) | set(scalars) | set(orch)):
        spec = ALT_SPECS.get(field)
        if spec is None:
            missing_specs.append(field)
            problems.append(
                f"no perturbation spec for field {field!r} in "
                "analysis/keys.py ALT_SPECS — srkey cannot verify its "
                "class"
            )
            continue
        try:
            alt = make_options(**{**_BASE_KWARGS, **spec})
        except Exception as e:
            problems.append(
                f"perturbation spec for {field!r} failed to construct: "
                f"{type(e).__name__}: {e}"
            )
            continue
        if getattr(alt, field) == getattr(base, field):
            problems.append(
                f"perturbation spec for {field!r} does not change the "
                f"field (still {getattr(base, field)!r})"
            )
            continue
        checked += 1
        key_changed = alt._graph_key() != base_key
        scalars_changed = _scalar_values(alt) != base_scalars
        if field in set(graph) and not key_changed:
            problems.append(
                f"graph field {field!r}: perturbation does NOT change "
                "_graph_key — a warm bucket would serve a program "
                "compiled for the other value"
            )
        elif field in set(scalars):
            if key_changed:
                problems.append(
                    f"traced scalar {field!r}: perturbation changes "
                    "_graph_key — sweeping it would recompile instead "
                    "of re-binding the traced argument"
                )
            if not scalars_changed:
                problems.append(
                    f"traced scalar {field!r}: perturbation does not "
                    "change traced_scalars() — the jitted program would "
                    "never see the new value"
                )
        elif field in set(orch):
            if key_changed:
                problems.append(
                    f"orchestration field {field!r}: perturbation "
                    "changes _graph_key — a host-only knob is "
                    "fragmenting warm-compile buckets"
                )
            if scalars_changed:
                problems.append(
                    f"orchestration field {field!r}: perturbation "
                    "changes traced_scalars()"
                )
    detail = {"checked": checked, "missing_specs": missing_specs}
    return problems, detail


# ---------------------------------------------------------------------------
# memo-fingerprint coverage
# ---------------------------------------------------------------------------


def _fingerprint_problems() -> Tuple[List[str], dict]:
    import numpy as np

    from ..cache.memo import dataset_fingerprint
    from ..models.options import make_options

    problems: List[str] = []
    X = (
        np.arange(_NFEAT * _NROWS, dtype=np.float32).reshape(
            _NFEAT, _NROWS
        )
        / 7.0
    )
    y = np.arange(_NROWS, dtype=np.float32) / 3.0
    base = make_options(**_BASE_KWARGS)
    base_fp = dataset_fingerprint(X, y, None, base)
    covered: List[str] = []
    for field, spec in FINGERPRINT_FIELDS:
        alt = make_options(**{**_BASE_KWARGS, **spec})
        if dataset_fingerprint(X, y, None, alt) == base_fp:
            problems.append(
                f"eval-context field {field!r}: perturbation does NOT "
                "change dataset_fingerprint — a shared memo bank could "
                "serve losses computed under the other value"
            )
        else:
            covered.append(field)
    # the dataset itself is the other half of the fingerprint
    y2 = y.copy()
    y2[0] += 1.0
    if dataset_fingerprint(X, y2, None, base) == base_fp:
        problems.append(
            "dataset bytes do NOT change dataset_fingerprint — two "
            "different datasets would share a memo bank"
        )
    # ...and a pure-orchestration perturbation must NOT split banks
    orch_spec: Dict[str, object] = {}
    for f in _registry()[2]:
        orch_spec.update(ALT_SPECS.get(f, {}))
    alt = make_options(**{**_BASE_KWARGS, **orch_spec})
    if dataset_fingerprint(X, y, None, alt) != base_fp:
        problems.append(
            "an all-orchestration perturbation changed "
            "dataset_fingerprint — host-only knobs are fragmenting "
            "memo banks"
        )
    detail = {"covered": covered, "dataset_bytes": True}
    return problems, detail


# ---------------------------------------------------------------------------
# differential verification by tracing
# ---------------------------------------------------------------------------


def _clear_factory_caches() -> None:
    """The api.py jit-factory lru_caches key on Options hash/eq — the
    graph key — so a perturbed-orchestration Options HITS the base
    entry, whose closure closes over the base options; a leak would be
    invisible. Cleared before every trace set so each trace closes over
    exactly its own Options."""
    from .. import api

    api._make_init_fn_cached.cache_clear()
    api._make_iteration_fn_cached.cache_clear()
    api._make_phase_fns_cached.cache_clear()


def trace_programs(options) -> Dict[str, str]:
    """Byte-comparable jaxpr text of every production program for one
    Options: the per-stage decomposition (memory.build_stage_programs)
    plus the fused whole-iteration jit."""
    import jax

    from ..api import _make_iteration_fn
    from .compile_surface import _abstract_inputs
    from .memory import build_stage_programs

    _clear_factory_caches()
    progs: Dict[str, str] = {}
    for stage, (fn, args) in build_stage_programs(options).items():
        progs[stage] = str(jax.make_jaxpr(fn)(*args))
    I = options.npopulations
    states, key, cm, X, y, bl, scalars, memo, _ = _abstract_inputs(
        options, I
    )
    it_fn = _make_iteration_fn(options, False)
    args = (states, key, cm, X, y, bl, scalars) + (
        (memo,) if memo is not None else ()
    )
    progs["iteration"] = str(jax.make_jaxpr(it_fn)(*args))
    return progs


def _diff_stages(base: Dict[str, str], got: Dict[str, str]) -> List[str]:
    return sorted(
        s for s in base if got.get(s) != base[s]
    ) + sorted(s for s in got if s not in base)


def _merged_spec(fields: Sequence[str]) -> dict:
    spec: Dict[str, object] = {}
    for f in fields:
        spec.update(ALT_SPECS.get(f, {}))
    return spec


def _bisect_culprits(
    cfg_kwargs: dict, base_progs: Dict[str, str], fields: List[str]
) -> List[str]:
    """Group-halving search for the field(s) whose perturbation changes
    a traced program — O(c·log n) trace sets for c culprits, run only
    after the all-at-once set mismatched."""
    from ..models.options import make_options

    culprits: List[str] = []

    def rec(group: List[str]) -> None:
        if not group:
            return
        progs = trace_programs(
            make_options(**{**cfg_kwargs, **_merged_spec(group)})
        )
        if not _diff_stages(base_progs, progs):
            return
        if len(group) == 1:
            culprits.append(group[0])
            return
        mid = len(group) // 2
        rec(group[:mid])
        rec(group[mid:])

    rec(list(fields))
    return sorted(culprits)


def _differential_problems(
    configs: Tuple[Tuple[str, dict], ...], scalars, orch
) -> Tuple[List[str], dict]:
    from ..models.options import make_options

    problems: List[str] = []
    detail: Dict[str, dict] = {}
    for name, extra in configs:
        cfg_kwargs = {**_BASE_KWARGS, **extra}
        base_progs = trace_programs(make_options(**cfg_kwargs))
        entry = {
            "stages": sorted(base_progs),
            "orchestration_invariant": True,
            "scalar_invariant": True,
            "culprits": [],
        }
        # all orchestration knobs at once: one extra trace set on the
        # green path; bisect to name culprits only on a mismatch
        for cls_name, fields, flag in (
            ("orchestration", [f for f in orch], "orchestration_invariant"),
            ("traced-scalar", [f for f in scalars], "scalar_invariant"),
        ):
            alt = make_options(
                **{**cfg_kwargs, **_merged_spec(fields)}
            )
            diff = _diff_stages(base_progs, trace_programs(alt))
            if diff:
                entry[flag] = False
                culprits = _bisect_culprits(
                    cfg_kwargs, base_progs, fields
                )
                entry["culprits"] += culprits
                problems.append(
                    f"{name}: {cls_name} perturbation changed traced "
                    f"program(s) {diff} — leaking field(s): "
                    f"{culprits or ['<interaction of several fields>']} "
                    "(a warm-compile bucket would serve a graph "
                    "compiled for another config's value)"
                )
        detail[name] = entry
    return problems, detail


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


def check_keys(
    configs: Optional[Tuple[Tuple[str, dict], ...]] = None,
    trace: bool = True,
    _override=None,
) -> dict:
    """Run the full srkey check; returns the report dict rendered by
    report.render_keys_text (docs/static_analysis.md "srkey")."""
    graph, scalars, orch = _registry(_override)
    notes: List[str] = []
    problems = _registry_problems(graph, scalars, orch)
    result = {
        "ok": False,
        "problems": problems,
        "notes": notes,
        "fields": {
            "graph": len(graph),
            "traced_scalar": len(scalars),
            "orchestration": len(orch),
        },
        "traced": False,
    }
    if problems:
        # fail fast: coverage/semantics/differential against a broken
        # registry would only repeat the same finding noisily
        notes.append(
            "registry is incomplete/inconsistent — key coverage, "
            "semantics, fingerprint, and differential checks skipped"
        )
        return result

    cov_problems, cov_detail = _coverage_problems(graph, scalars, orch)
    problems += cov_problems
    result["key_coverage"] = cov_detail

    sem_problems, sem_detail = _semantics_problems(graph, scalars, orch)
    problems += sem_problems
    result["semantics"] = sem_detail

    fp_problems, fp_detail = _fingerprint_problems()
    problems += fp_problems
    result["fingerprint"] = fp_detail

    if trace:
        diff_problems, diff_detail = _differential_problems(
            tuple(configs if configs is not None else
                  DEFAULT_TRACE_CONFIGS),
            scalars, orch,
        )
        problems += diff_problems
        result["configs"] = diff_detail
        result["traced"] = True
    else:
        notes.append("differential tracing skipped (trace=False)")

    result["ok"] = not problems
    return result
