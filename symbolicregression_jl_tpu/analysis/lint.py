"""srlint — JAX-aware AST linter for the TPU hot path.

Builds a call graph rooted at the ``jax.jit`` entry points of the package
(``api.py``, ``ops/``, ...) and checks the invariants in rules.py inside
everything reachable from a jitted function. Pure AST work: nothing is
imported or executed, so linting is fast, safe on broken trees, and
independent of the installed accelerator.

Resolution model (best-effort, precision over recall):

- every module is parsed and its defs/imports indexed with full lexical
  scoping (nested functions, function-local imports);
- a call ``f(...)`` resolves through the scope chain to a local def, a
  module-level def, or an imported symbol; ``mod.f(...)`` resolves through
  the import table (``import jax.numpy as jnp`` => ``jnp.zeros`` is
  ``jax.numpy.zeros``; ``from .models.evolve import s_r_cycle_islands``
  resolves package-relative);
- jit roots: ``jax.jit(f)`` / ``jax.jit(lambda: ...)`` calls, ``@jax.jit``
  decorators, and ``@functools.partial(jax.jit, ...)`` decorators;
- reachability additionally follows function-valued arguments (``vmap(f)``,
  ``lax.scan(body, ...)``, ``tree_map(lambda ...)``), so closure bodies
  that only ever run inside a trace are still covered.

Unresolvable calls (attribute chains on objects, dynamic dispatch) are
ignored rather than guessed at — srlint prefers a small number of real
findings to a wall of maybes.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .rules import (
    HOT_PATH_PREFIXES,
    Violation,
    parse_pragma,
)

# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FuncInfo:
    """One function (def or lambda) in the scanned tree."""

    module: "ModuleInfo"
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    params: Tuple[str, ...]
    has_var_kwargs: bool
    scope: "Scope"
    is_jit_root: bool = False
    is_batched_body: bool = False  # passed to jax.vmap / lax.scan / lax.map
    callees: Set[int] = dataclasses.field(default_factory=set)  # id(FuncInfo)

    @property
    def label(self) -> str:
        return f"{self.module.relpath}:{self.qualname}"


class Scope:
    """Lexical scope: name -> ('func', FuncInfo) | ('import', dotted)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self.bindings: Dict[str, Tuple[str, object]] = {}

    def bind(self, name: str, kind: str, target) -> None:
        self.bindings[name] = (kind, target)

    def lookup(self, name: str) -> Optional[Tuple[str, object]]:
        s: Optional[Scope] = self
        while s is not None:
            if name in s.bindings:
                return s.bindings[name]
            s = s.parent
        return None


@dataclasses.dataclass
class ModuleInfo:
    path: str
    relpath: str  # relative to the scan root, posix separators
    modname: str  # dotted, relative to the scan root ("models.evolve")
    tree: ast.Module
    lines: List[str]
    is_pkg: bool = False  # this file is an __init__.py
    scope: Scope = dataclasses.field(default_factory=Scope)
    functions: Dict[int, FuncInfo] = dataclasses.field(default_factory=dict)
    toplevel: Dict[str, FuncInfo] = dataclasses.field(default_factory=dict)


def _params_of(node) -> Tuple[Tuple[str, ...], bool]:
    a = node.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    return tuple(names), a.kwarg is not None


def _resolve_relative_import(
    modname: str, level: int, target: str, is_pkg: bool
) -> str:
    """'models.fitness' + from ..cache.dedup (level=2) -> 'cache.dedup'.

    A plain module drops `level` trailing components of its own dotted
    name (the first dot strips the module name itself); a package
    __init__ drops level-1 (the first dot means the package)."""
    parts = modname.split(".") if modname else []
    drop = level - 1 if is_pkg else level
    base = parts[: max(len(parts) - drop, 0)]
    return ".".join(base + ([target] if target else [])).strip(".")


class _IndexVisitor(ast.NodeVisitor):
    """Pass 1: build the scope tree, FuncInfo index, and import tables."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.scope_stack = [mod.scope]
        self.qual_stack: List[str] = []

    # -- imports --------------------------------------------------------
    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            target = alias.name if alias.asname else alias.name.split(".")[0]
            self.scope_stack[-1].bind(name, "import", target)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        base = node.module or ""
        if node.level:
            base = _resolve_relative_import(
                self.mod.modname, node.level, base, self.mod.is_pkg
            )
        for alias in node.names:
            name = alias.asname or alias.name
            target = f"{base}.{alias.name}" if base else alias.name
            self.scope_stack[-1].bind(name, "import", target)

    # -- defs -----------------------------------------------------------
    def _enter_function(self, node, name: str):
        qual = ".".join(self.qual_stack + [name]) if self.qual_stack else name
        params, has_kw = _params_of(node)
        scope = Scope(self.scope_stack[-1])
        for p in params:
            scope.bind(p, "param", None)
        info = FuncInfo(
            module=self.mod, qualname=qual, node=node,
            params=params, has_var_kwargs=has_kw, scope=scope,
        )
        self.mod.functions[id(node)] = info
        if len(self.scope_stack) == 1 and not isinstance(node, ast.Lambda):
            self.mod.toplevel[name] = info
        if not isinstance(node, ast.Lambda):
            self.scope_stack[-1].bind(name, "func", info)
        # decorators and argument defaults evaluate in the ENCLOSING scope
        if not isinstance(node, ast.Lambda):
            for deco in node.decorator_list:
                self.visit(deco)
        for d in node.args.defaults + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            self.visit(d)
        self.scope_stack.append(scope)
        self.qual_stack.append(name)
        if isinstance(node, ast.Lambda):
            self.visit(node.body)
        else:
            for stmt in node.body:
                self.visit(stmt)
        self.qual_stack.pop()
        self.scope_stack.pop()

    def visit_FunctionDef(self, node):
        self._enter_function(node, node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        self._enter_function(node, f"<lambda:{node.lineno}>")

    def visit_ClassDef(self, node: ast.ClassDef):
        self.qual_stack.append(node.name)
        for stmt in node.body:
            self.visit(stmt)
        self.qual_stack.pop()


def _dotted(node) -> Optional[str]:
    """a.b.c attribute/name chain as a dotted string, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Linter:
    """Scan a directory tree of Python files and report rule violations."""

    def __init__(self, root: str, repo_root: Optional[str] = None):
        self.root = os.path.abspath(root)
        self.repo_root = os.path.abspath(repo_root or self.root)
        self.modules: List[ModuleInfo] = []
        self.violations: List[Violation] = []
        self._func_by_id: Dict[int, FuncInfo] = {}
        # SR010: orchestration-classified Options field names, AST-
        # extracted from the scanned modules' own top-level
        # `ORCHESTRATION_FIELDS = (...)` tuple (models/options.py in the
        # package scan; fixtures declare their own) — lint stays pure
        # AST, nothing is imported
        self.orchestration_fields: Set[str] = set()

    # -- loading --------------------------------------------------------
    def load(self, files: Optional[Sequence[str]] = None) -> "Linter":
        if files is None:
            files = []
            for dirpath, dirnames, filenames in os.walk(self.root):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        files.append(os.path.join(dirpath, fn))
        for path in files:
            path = os.path.abspath(path)
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            modname = rel[:-3].replace("/", ".")
            is_pkg = modname == "__init__" or modname.endswith(".__init__")
            if is_pkg:
                modname = modname[: -len("__init__")].rstrip(".")
            with open(path, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
            mod = ModuleInfo(
                path=path, relpath=rel, modname=modname, tree=tree,
                lines=src.splitlines(), is_pkg=is_pkg,
            )
            _IndexVisitor(mod).visit(tree)
            self.modules.append(mod)
            for info in mod.functions.values():
                self._func_by_id[id(info)] = info
            self.orchestration_fields |= _declared_orchestration_fields(
                tree
            )
        return self

    # -- resolution -----------------------------------------------------
    def _resolve_target(
        self, scope: Scope, dotted: str
    ) -> Tuple[Optional[FuncInfo], Optional[str]]:
        """(internal FuncInfo | None, canonical external/dotted name | None).

        'jnp.zeros' -> (None, 'jax.numpy.zeros');
        's_r_cycle_islands' -> (FuncInfo, 'models.evolve.s_r_cycle_islands').
        """
        head, _, rest = dotted.partition(".")
        hit = scope.lookup(head)
        if hit is None:
            return None, dotted
        kind, target = hit
        if kind == "func":
            return (target if not rest else None), dotted
        if kind == "param":
            return None, None  # call through a parameter: opaque
        # import
        full = f"{target}.{rest}" if rest else str(target)
        func = self._lookup_module_symbol(full)
        return func, full

    def _lookup_module_symbol(self, full: str) -> Optional[FuncInfo]:
        """'models.evolve.s_r_cycle_islands' -> FuncInfo if scanned."""
        modname, _, sym = full.rpartition(".")
        for mod in self.modules:
            if mod.modname == modname and sym in mod.toplevel:
                return mod.toplevel[sym]
            if mod.modname == full:  # bare module import
                return None
        return None

    # -- jit roots + call edges ----------------------------------------
    _JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
    _PARTIAL_NAMES = {"functools.partial", "partial"}
    # transforms whose first function argument becomes a BATCHED body
    # (SR012: sharding constraints inside reference dims the batched
    # trace cannot see)
    _BATCH_NAMES = {"jax.vmap", "jax.lax.scan", "jax.lax.map"}

    def build_graph(self) -> None:
        for mod in self.modules:
            self._walk_calls(mod)
        # BFS over callee edges from jit roots
        self.jit_reachable: Set[int] = self._reach(
            f for f in self._func_by_id.values() if f.is_jit_root
        )
        # SR012: everything reachable from a vmap/scan/map body runs
        # under the batching transform
        self.batched_reachable: Set[int] = self._reach(
            f for f in self._func_by_id.values() if f.is_batched_body
        )

    def _reach(self, roots) -> Set[int]:
        frontier = list(roots)
        reachable: Set[int] = set(id(f) for f in frontier)
        while frontier:
            f = frontier.pop()
            for cid in f.callees:
                if cid not in reachable:
                    reachable.add(cid)
                    frontier.append(self._func_by_id[cid])
        return reachable

    def _walk_calls(self, mod: ModuleInfo) -> None:
        linter = self

        class V(ast.NodeVisitor):
            def __init__(self):
                # module-level code gets a synthetic container so jit
                # roots declared at import time are still discovered
                self.func_stack: List[Optional[FuncInfo]] = [None]

            def current(self) -> Optional[FuncInfo]:
                return self.func_stack[-1]

            def scope(self) -> Scope:
                cur = self.current()
                return cur.scope if cur is not None else mod.scope

            def visit_FunctionDef(self, node):
                info = mod.functions[id(node)]
                for deco in node.decorator_list:
                    linter._check_decorator(mod, info, deco, self.scope())
                self.func_stack.append(info)
                self.generic_visit(node)
                self.func_stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                info = mod.functions[id(node)]
                self.func_stack.append(info)
                self.generic_visit(node)
                self.func_stack.pop()

            def visit_Call(self, node: ast.Call):
                linter._record_call(mod, self.current(), node, self.scope())
                self.generic_visit(node)

            def visit_Assign(self, node: ast.Assign):
                # `fast_step = jax.jit(step)`: bind the alias to the
                # wrapped FuncInfo so later `fast_step(...)` calls resolve
                # to the jit root (SR008 needs the call edge; the root
                # marking itself happens in visit_Call below)
                if (
                    len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                ):
                    full = linter._canonical(self.scope(), node.value.func)
                    if full in linter._JIT_NAMES and node.value.args:
                        wrapped = linter._funcinfo_of_expr(
                            self.scope(), mod, node.value.args[0]
                        )
                        if wrapped is not None:
                            self.scope().bind(
                                node.targets[0].id, "func", wrapped
                            )
                self.generic_visit(node)

            def visit_Name(self, node: ast.Name):
                # conservative closure edges: any reference to a known
                # function inside a traced body probably runs at trace
                # time (lax.switch branch lists, dict dispatch tables,
                # tuples of callbacks)
                cur = self.current()
                if cur is not None and isinstance(node.ctx, ast.Load):
                    hit = self.scope().lookup(node.id)
                    if hit is not None and hit[0] == "func":
                        cur.callees.add(id(hit[1]))

            def visit_Attribute(self, node: ast.Attribute):
                cur = self.current()
                if cur is not None and isinstance(node.ctx, ast.Load):
                    d = _dotted(node)
                    if d is not None:
                        f, _ = linter._resolve_target(self.scope(), d)
                        if f is not None:
                            cur.callees.add(id(f))
                self.generic_visit(node)

        V().visit(mod.tree)

    def _canonical(self, scope: Scope, node) -> Optional[str]:
        d = _dotted(node)
        if d is None:
            return None
        _, full = self._resolve_target(scope, d)
        return full

    def _funcinfo_of_expr(self, scope: Scope, mod, node) -> Optional[FuncInfo]:
        if isinstance(node, ast.Lambda):
            return mod.functions.get(id(node))
        d = _dotted(node)
        if d is None:
            return None
        func, _ = self._resolve_target(scope, d)
        return func

    def _record_call(
        self, mod: ModuleInfo, current: Optional[FuncInfo],
        node: ast.Call, scope: Scope,
    ) -> None:
        callee = self._funcinfo_of_expr(scope, mod, node.func)
        if callee is not None and current is not None:
            current.callees.add(id(callee))
        full = self._canonical(scope, node.func)
        # jax.jit(f, ...) / jax.jit(lambda: ...) as an expression
        if full in self._JIT_NAMES and node.args:
            wrapped = self._funcinfo_of_expr(scope, mod, node.args[0])
            if wrapped is not None:
                wrapped.is_jit_root = True
                self._check_static_argnames(mod, node, wrapped)
                self._check_donation(mod, node, wrapped, node)
        # vmap(f)/scan(body, ...)/map(f, ...): f becomes a batched body
        if full in self._BATCH_NAMES and node.args:
            body = self._funcinfo_of_expr(scope, mod, node.args[0])
            if body is not None:
                body.is_batched_body = True
        # function-valued arguments (vmap/scan/tree_map/closures)
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            f = self._funcinfo_of_expr(scope, mod, arg)
            if f is not None and current is not None:
                current.callees.add(id(f))

    def _check_decorator(
        self, mod: ModuleInfo, info: FuncInfo, deco, scope: Scope
    ) -> None:
        full = self._canonical(scope, deco)
        if full in self._JIT_NAMES:
            info.is_jit_root = True
            # a bare @jax.jit cannot carry donate_argnums at all
            self._check_donation(mod, deco, info, None)
            return
        if isinstance(deco, ast.Call):
            cfull = self._canonical(scope, deco.func)
            if cfull in self._JIT_NAMES:
                info.is_jit_root = True
                self._check_static_argnames(mod, deco, info)
                self._check_donation(mod, deco, info, deco)
            elif cfull in self._PARTIAL_NAMES and deco.args:
                inner = self._canonical(scope, deco.args[0])
                if inner in self._JIT_NAMES:
                    info.is_jit_root = True
                    self._check_static_argnames(mod, deco, info)
                    self._check_donation(mod, deco, info, deco)

    # -- SR005 ----------------------------------------------------------
    def _check_static_argnames(
        self, mod: ModuleInfo, call: ast.Call, wrapped: FuncInfo
    ) -> None:
        for kw in call.keywords:
            if kw.arg != "static_argnames":
                continue
            names = _literal_str_seq(kw.value)
            if names is None or wrapped.has_var_kwargs:
                return
            missing = [n for n in names if n not in wrapped.params]
            for n in missing:
                self._add(
                    mod, call, "SR005",
                    f"static_argnames references {n!r} but "
                    f"{wrapped.qualname}() has no such parameter "
                    f"(params: {', '.join(wrapped.params) or 'none'})",
                    function=wrapped.qualname,
                )

    # -- SR006 ----------------------------------------------------------
    _DONATE_KWARGS = ("donate_argnums", "donate_argnames")

    def _check_donation(
        self, mod: ModuleInfo, node, wrapped: FuncInfo,
        call: Optional[ast.Call],
    ) -> None:
        """jit entry with a rebuilt-and-returned parameter (the static
        signature of a carry) but no donate_argnums/donate_argnames.
        `call` is the jit/partial Call carrying the keywords; None for a
        bare @jax.jit decorator (which cannot donate at all)."""
        static: Tuple[str, ...] = ()
        if call is not None:
            kws = [kw.arg for kw in call.keywords]
            if None in kws:  # **kwargs forwarding: opaque, skip
                return
            if any(k in self._DONATE_KWARGS for k in kws):
                return
            for kw in call.keywords:
                if kw.arg == "static_argnames":
                    static = tuple(_literal_str_seq(kw.value) or ())
        for name in _rebuilt_returned_params(wrapped):
            if name in static:  # static config values, not carries
                continue
            self._add(
                mod, node, "SR006",
                f"jit entry {wrapped.qualname}() rebuilds and returns "
                f"its parameter {name!r} (a carry) but donates no "
                "buffers — list it in donate_argnums/donate_argnames so "
                "XLA reuses the carry's HBM in place",
                function=wrapped.qualname,
            )

    # -- violation plumbing --------------------------------------------
    def _add(
        self, mod: ModuleInfo, node, rule_id: str, message: str,
        function: Optional[str] = None,
    ) -> None:
        suppressed = False
        for ln in {getattr(node, "lineno", 0),
                   getattr(node, "end_lineno", 0) or 0}:
            if 1 <= ln <= len(mod.lines):
                ids = parse_pragma(mod.lines[ln - 1])
                if ids and rule_id in ids:
                    suppressed = True
        self.violations.append(
            Violation(
                rule_id=rule_id,
                path=os.path.relpath(mod.path, self.repo_root).replace(
                    os.sep, "/"
                ),
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                message=message,
                function=function,
                suppressed=suppressed,
            )
        )

    # -- rule scans -----------------------------------------------------
    def run_checks(self) -> List[Violation]:
        self.build_graph()
        for mod in self.modules:
            hot = any(
                mod.relpath == p or mod.relpath.startswith(p)
                for p in self._hot_prefixes()
            )
            if hot:
                self._scan_implicit_dtype(mod)
            for info in mod.functions.values():
                if id(info) in self.jit_reachable:
                    self._scan_jit_function(mod, info)
                    self._scan_orchestration_reads(mod, info)
                else:
                    # SR008 is about HOST code feeding synced values back
                    # into jitted entries; jit-reachable bodies are
                    # already covered by SR001
                    self._scan_host_roundtrip(mod, info)
                # SR011 applies everywhere: key/fingerprint computations
                # are host-side code by construction
                self._scan_id_in_key(mod, info)
                if id(info) in self.batched_reachable:
                    self._scan_sharding_in_batched(mod, info)
        self.violations.sort(key=lambda v: (v.path, v.line, v.rule_id))
        return self.violations

    def _hot_prefixes(self) -> Tuple[str, ...]:
        # package scan: api.py/ops/... live at the scan root. Fixture
        # scans (tests) reuse the same prefixes plus everything at root.
        return tuple(
            p if p.endswith("/") else p + ".py" for p in HOT_PATH_PREFIXES
        ) + ("fixture_",)

    # SR004 ------------------------------------------------------------
    # constructor -> positional index of its dtype parameter
    _IMPLICIT_DTYPE_FNS = {
        "jax.numpy.zeros": 1, "jax.numpy.ones": 1, "jax.numpy.empty": 1,
        "jax.numpy.full": 2, "jax.numpy.arange": 3,
    }

    def _scan_implicit_dtype(self, mod: ModuleInfo) -> None:
        linter = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.scope_stack = [mod.scope]

            def visit_FunctionDef(self, node):
                self.scope_stack.append(mod.functions[id(node)].scope)
                self.generic_visit(node)
                self.scope_stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                self.scope_stack.append(mod.functions[id(node)].scope)
                self.generic_visit(node)
                self.scope_stack.pop()

            def visit_Call(self, node: ast.Call):
                full = linter._canonical(self.scope_stack[-1], node.func)
                if (
                    full in linter._IMPLICIT_DTYPE_FNS
                    and not any(kw.arg == "dtype" for kw in node.keywords)
                    and len(node.args) <= linter._IMPLICIT_DTYPE_FNS[full]
                ):
                    short = full.replace("jax.numpy.", "jnp.")
                    linter._add(
                        mod, node, "SR004",
                        f"{short}(...) without an explicit dtype= in a "
                        "hot-path module: the produced buffer's dtype "
                        "follows jax_enable_x64 / weak-type promotion",
                    )
                self.generic_visit(node)

        V().visit(mod.tree)

    # SR001 + SR002 + SR003 (jit-reachable functions only) -------------
    _HOST_SYNC_CALLS = {
        "numpy.asarray", "numpy.array", "jax.device_get",
        "jax.block_until_ready",
    }
    _HOST_SYNC_METHODS = {"item", "block_until_ready", "tolist"}
    # jnp/jax calls that return host (static) values, not tracers
    _STATIC_RESULT_FNS = {
        "jax.numpy.issubdtype", "jax.numpy.result_type",
        "jax.numpy.promote_types", "jax.numpy.dtype", "jax.numpy.shape",
        "jax.numpy.ndim", "jax.numpy.iinfo", "jax.numpy.finfo",
        "jax.eval_shape", "jax.dtypes.issubdtype", "jax.dtypes.result_type",
    }
    _TRACER_PREFIXES = (
        "jax.numpy.", "jax.lax.", "jax.random.", "jax.nn.", "jax.scipy.",
        "jax.ops.",
    )
    _STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}
    # SR007: constructors whose output is inherently a multiple of the
    # input bytes; tile/repeat only with a LITERAL factor >= the
    # threshold (non-literal factors are skipped — precision over recall)
    _BLOWUP_ALWAYS = {
        "jax.numpy.broadcast_to", "jax.numpy.outer", "jax.numpy.kron",
        "jax.numpy.meshgrid",
    }
    _BLOWUP_FACTOR_FNS = {"jax.numpy.tile", "jax.numpy.repeat"}
    _BLOWUP_MIN_FACTOR = 8
    # SR009: ops that manufacture NaN/Inf on part of the float domain.
    # A jnp.where BRANCH calling one of these on an unclamped input is
    # the select-on-poisoned-output pitfall (both branches evaluate).
    _NAN_PRODUCING_FNS = {
        "jax.numpy.log", "jax.numpy.log2", "jax.numpy.log10",
        "jax.numpy.log1p", "jax.numpy.sqrt", "jax.numpy.power",
        "jax.numpy.float_power", "jax.numpy.arcsin", "jax.numpy.arccos",
        "jax.numpy.arccosh", "jax.numpy.arctanh", "jax.numpy.reciprocal",
        "jax.lax.log", "jax.lax.log1p", "jax.lax.sqrt", "jax.lax.rsqrt",
        "jax.lax.pow", "jax.lax.lgamma", "jax.lax.asin", "jax.lax.acos",
        "jax.lax.acosh", "jax.lax.atanh",
    }
    # ...unless the producer's input is already clamped into its domain:
    # an argument that IS a call to one of these (the safe_* pattern —
    # jnp.log(jnp.where(x > 0, x, 1.0)), jnp.sqrt(jnp.maximum(x, 0)))
    _DOMAIN_CLAMP_FNS = {
        "jax.numpy.where", "jax.numpy.clip", "jax.numpy.maximum",
        "jax.numpy.minimum", "jax.numpy.abs", "jax.numpy.absolute",
        "jax.numpy.exp", "jax.lax.clamp", "jax.lax.max", "jax.lax.min",
        "jax.lax.abs", "jax.lax.exp", "jax.lax.select",
        "jax.nn.softplus", "jax.nn.sigmoid",
    }

    def _scan_jit_function(self, mod: ModuleInfo, info: FuncInfo) -> None:
        scope = info.scope
        tainted: Set[str] = set()
        linter = self

        def arrayish(expr) -> bool:
            if isinstance(expr, ast.Name):
                return expr.id in tainted
            if isinstance(expr, ast.Call):
                full = linter._canonical(scope, expr.func)
                if full in linter._STATIC_RESULT_FNS:
                    return False
                if full is not None and full.startswith(
                    linter._TRACER_PREFIXES
                ):
                    return True
                # a call on an array-valued expression: x.at[i].set(v),
                # x.astype(...), x.sum()
                if isinstance(expr.func, ast.Attribute) and arrayish(
                    expr.func.value
                ):
                    return True
                return False
            if isinstance(expr, ast.Attribute):
                if expr.attr in linter._STATIC_ATTRS:
                    return False
                return arrayish(expr.value)
            if isinstance(expr, ast.Subscript):
                return arrayish(expr.value)
            if isinstance(expr, ast.BinOp):
                return arrayish(expr.left) or arrayish(expr.right)
            if isinstance(expr, ast.UnaryOp):
                return arrayish(expr.operand)
            if isinstance(expr, ast.Compare):
                if all(
                    isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                    for op in expr.ops
                ):
                    return False
                return arrayish(expr.left) or any(
                    arrayish(c) for c in expr.comparators
                )
            if isinstance(expr, ast.BoolOp):
                return any(arrayish(v) for v in expr.values)
            if isinstance(expr, ast.IfExp):
                return arrayish(expr.body) or arrayish(expr.orelse)
            return False

        def scan_expr(expr) -> None:
            """SR001/SR002 checks on one expression subtree (skips nested
            function bodies — they are scanned as their own functions when
            reachable)."""
            for node in ast.walk(expr):
                if not isinstance(node, ast.Call):
                    continue
                full = linter._canonical(scope, node.func)
                if full in linter._HOST_SYNC_CALLS:
                    short = full.replace("numpy.", "np.")
                    linter._add(
                        mod, node, "SR001",
                        f"{short}(...) in jit-reachable "
                        f"{info.qualname}(): host sync / device round-trip"
                        " if the value is traced",
                        function=info.qualname,
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in linter._HOST_SYNC_METHODS
                    and not node.args
                ):
                    # method form: x.item(), arr.tolist(),
                    # y.block_until_ready()
                    linter._add(
                        mod, node, "SR001",
                        f".{node.func.attr}() in jit-reachable "
                        f"{info.qualname}(): forces a blocking "
                        "device->host transfer on traced values",
                        function=info.qualname,
                    )
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in ("bool", "float", "int")
                    and len(node.args) == 1
                    and arrayish(node.args[0])
                    and linter._resolve_target(scope, node.func.id)[1]
                    == node.func.id  # not shadowed by an import/def
                ):
                    linter._add(
                        mod, node, "SR002",
                        f"{node.func.id}() concretizes a traced array in "
                        f"{info.qualname}(): TracerBoolConversionError "
                        "under jit (host sync outside)",
                        function=info.qualname,
                    )
                elif full in linter._BLOWUP_ALWAYS:
                    short = full.replace("jax.numpy.", "jnp.")
                    linter._add(
                        mod, node, "SR007",
                        f"{short}(...) materializes a broadcast in "
                        f"jit-reachable {info.qualname}(): the output "
                        "aval is a multiple of its inputs' bytes — keep "
                        "the implicit-broadcast form (XLA fuses it) or "
                        "chunk the batch",
                        function=info.qualname,
                    )
                elif full in linter._BLOWUP_FACTOR_FNS:
                    fac = _literal_int_factor(node)
                    if fac is not None and fac >= linter._BLOWUP_MIN_FACTOR:
                        short = full.replace("jax.numpy.", "jnp.")
                        linter._add(
                            mod, node, "SR007",
                            f"{short}(...) with literal factor {fac} in "
                            f"jit-reachable {info.qualname}(): "
                            f"materializes {fac}x the input bytes",
                            function=info.qualname,
                        )
                elif (
                    full in ("jax.numpy.where", "jax.lax.select")
                    and len(node.args) >= 3
                ):
                    linter._check_where_nan_branch(mod, info, node, scope)

        def scan_stmts(stmts) -> None:
            for stmt in stmts:
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)
                ):
                    continue  # separate FuncInfo
                if isinstance(stmt, (ast.If, ast.While)):
                    if arrayish(stmt.test):
                        kind = (
                            "if" if isinstance(stmt, ast.If) else "while"
                        )
                        self._add(
                            mod, stmt, "SR002",
                            f"Python `{kind}` on a traced array value in "
                            f"{info.qualname}(): use lax.cond/jnp.where "
                            "or hoist to a static Option",
                            function=info.qualname,
                        )
                    scan_expr(stmt.test)
                    scan_stmts(stmt.body)
                    scan_stmts(stmt.orelse)
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    self._check_dict_iter(mod, info, stmt.iter)
                    scan_expr(stmt.iter)
                    scan_stmts(stmt.body)
                    scan_stmts(stmt.orelse)
                    continue
                if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign, ast.Return)):
                    value = getattr(stmt, "value", None)
                    if value is not None:
                        scan_expr(value)
                        for comp in ast.walk(value):
                            if isinstance(
                                comp, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)
                            ):
                                for gen in comp.generators:
                                    self._check_dict_iter(
                                        mod, info, gen.iter
                                    )
                        # taint propagation
                        if isinstance(stmt, ast.Assign) and arrayish(value):
                            for tgt in stmt.targets:
                                for n in ast.walk(tgt):
                                    if isinstance(n, ast.Name):
                                        tainted.add(n.id)
                        elif isinstance(
                            stmt, (ast.AugAssign, ast.AnnAssign)
                        ) and arrayish(value) and isinstance(
                            stmt.target, ast.Name
                        ):
                            tainted.add(stmt.target.id)
                    continue
                # everything else: scan contained expressions + blocks
                for field in ("test", "value", "exc"):
                    v = getattr(stmt, field, None)
                    if v is not None and isinstance(v, ast.expr):
                        scan_expr(v)
                if isinstance(stmt, ast.Expr):
                    for comp in ast.walk(stmt.value):
                        if isinstance(
                            comp, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)
                        ):
                            for gen in comp.generators:
                                self._check_dict_iter(mod, info, gen.iter)
                for block in ("body", "orelse", "finalbody"):
                    b = getattr(stmt, block, None)
                    if isinstance(b, list) and b and isinstance(
                        b[0], ast.stmt
                    ):
                        scan_stmts(b)
                if isinstance(stmt, ast.Try):
                    for h in stmt.handlers:
                        scan_stmts(h.body)
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        scan_expr(item.context_expr)

        if isinstance(info.node, ast.Lambda):
            scan_expr(info.node.body)
        else:
            scan_stmts(info.node.body)

    # SR009 ------------------------------------------------------------
    def _is_domain_clamped(self, scope: Scope, arg) -> bool:
        """True when `arg` is already forced into an op's domain: a call
        to a clamping fn (the safe_* inner-where pattern), a literal, or
        a unary +/- of either. Precision over recall: a Name or an
        arithmetic expression is treated as UNclamped only at the
        producer's direct argument position (names that were clamped
        upstream are invisible to the AST — flag-and-pragma is the
        documented escape)."""
        if isinstance(arg, ast.Constant):
            return True
        if isinstance(arg, ast.UnaryOp):
            return self._is_domain_clamped(scope, arg.operand)
        if isinstance(arg, ast.Call):
            full = self._canonical(scope, arg.func)
            if full in self._DOMAIN_CLAMP_FNS:
                return True
            # method-form clamps: x.clip(...), jnp.abs via attr chains
            if isinstance(arg.func, ast.Attribute) and arg.func.attr in (
                "clip",
            ):
                return True
        return False

    def _check_where_nan_branch(
        self, mod: ModuleInfo, info: FuncInfo, node: ast.Call, scope: Scope
    ) -> None:
        """SR009: a jnp.where/lax.select whose value branch applies a
        NaN-producing op to an unclamped input. Both branches of a
        select evaluate, so the out-of-domain lanes compute anyway —
        the guard must clamp the INPUT, not select on the poisoned
        output (rules.py SR009; fixtures fixture_sr009.py)."""
        for branch in node.args[1:3]:
            hit = None
            if isinstance(branch, ast.Call):
                bfull = self._canonical(scope, branch.func)
                if bfull in self._NAN_PRODUCING_FNS and branch.args:
                    if not self._is_domain_clamped(scope, branch.args[0]):
                        hit = (
                            f"{(bfull or '?').replace('jax.numpy.', 'jnp.')}"
                            "(<unclamped>)"
                        )
            elif isinstance(branch, ast.BinOp) and isinstance(
                branch.op, ast.Div
            ):
                if not self._is_domain_clamped(scope, branch.right):
                    hit = "a division with an unclamped denominator"
            elif isinstance(branch, ast.BinOp) and isinstance(
                branch.op, ast.Pow
            ):
                exp = branch.right
                frac_exp = isinstance(exp, ast.Constant) and isinstance(
                    exp.value, float
                ) and not float(exp.value).is_integer()
                if frac_exp and not self._is_domain_clamped(
                    scope, branch.left
                ):
                    hit = "a fractional power of an unclamped base"
            if hit is not None:
                self._add(
                    mod, node, "SR009",
                    f"jnp.where branch computes {hit} in jit-reachable "
                    f"{info.qualname}(): both branches evaluate, so the "
                    "untaken lanes still manufacture NaN/Inf (NaN grads "
                    "through 0*NaN) — clamp the op's INPUT "
                    "(jnp.where(ok, x, safe)/maximum/clip inside the "
                    "call), don't select on the poisoned output",
                    function=info.qualname,
                )

    # SR008 (host-side functions only) ---------------------------------
    def _scan_host_roundtrip(self, mod: ModuleInfo, info: FuncInfo) -> None:
        """A value pulled to the host (np.asarray / device_get / .item())
        and then passed as an argument to a resolvable jit root never
        needed to leave the device. Taint is name-level within one
        function body (no propagation through further assignments)."""
        if isinstance(info.node, ast.Lambda):
            return
        scope = info.scope
        tainted: Set[str] = set()
        linter = self

        def is_sync(node) -> bool:
            if not isinstance(node, ast.Call):
                return False
            full = linter._canonical(scope, node.func)
            if full in linter._HOST_SYNC_CALLS:
                return True
            return (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in linter._HOST_SYNC_METHODS
                and not node.args
            )

        def has_sync(expr) -> bool:
            return any(is_sync(n) for n in ast.walk(expr))

        def check_call(node: ast.Call) -> None:
            callee = linter._funcinfo_of_expr(scope, mod, node.func)
            if callee is None or not callee.is_jit_root:
                return
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if has_sync(arg) or (
                    isinstance(arg, ast.Name) and arg.id in tainted
                ):
                    linter._add(
                        mod, node, "SR008",
                        "host-synchronized value fed straight back into "
                        f"jitted {callee.qualname}() from "
                        f"{info.qualname}(): pays a device->host sync + "
                        "host->device transfer and defeats buffer "
                        "donation — pass the device array directly",
                        function=info.qualname,
                    )

        def scan(stmts) -> None:
            for stmt in stmts:
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)
                ):
                    continue  # separate FuncInfo / class body
                for _field, value in ast.iter_fields(stmt):
                    vals = value if isinstance(value, list) else [value]
                    for v in vals:
                        if isinstance(v, ast.expr):
                            for n in ast.walk(v):
                                if isinstance(n, ast.Call):
                                    check_call(n)
                if isinstance(stmt, ast.Assign):
                    sync = has_sync(stmt.value)
                    for tgt in stmt.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                if sync:
                                    tainted.add(n.id)
                                else:
                                    # reassignment from a non-sync
                                    # value kills the taint — the name
                                    # no longer holds the host copy
                                    tainted.discard(n.id)
                for block in ("body", "orelse", "finalbody"):
                    b = getattr(stmt, block, None)
                    if isinstance(b, list) and b and isinstance(
                        b[0], ast.stmt
                    ):
                        scan(b)
                if isinstance(stmt, ast.Try):
                    for h in stmt.handlers:
                        scan(h.body)

        scan(info.node.body)

    # SR003 ------------------------------------------------------------
    def _check_dict_iter(self, mod: ModuleInfo, info: FuncInfo, it) -> None:
        if (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Attribute)
            and it.func.attr in ("items", "keys", "values")
            and not it.args
        ):
            self._add(
                mod, it, "SR003",
                f"unsorted .{it.func.attr}() iteration in jit-reachable "
                f"{info.qualname}(): wrap in sorted(...) so pytree/jaxpr "
                "construction order is deterministic across hosts",
                function=info.qualname,
            )

    # SR010 ------------------------------------------------------------
    #: receiver names treated as "an Options instance" for SR010 —
    #: precision over recall: `options.seed` and `opts.verbosity` are
    #: flagged, `args.seed` on some argparse namespace is not
    _OPTIONS_RECEIVERS = {"options", "opts", "opt", "o"}

    def _scan_orchestration_reads(
        self, mod: ModuleInfo, info: FuncInfo
    ) -> None:
        """SR010: a read of an orchestration-classified options.<field>
        inside jit-reachable code. Orchestration fields are absent from
        Options._graph_key BY CONTRACT, so a traced read bakes the first
        caller's value into a compiled graph that hash-equal Options
        with a different value will share (rules.py SR010; the srkey
        engine catches the same leak end-to-end by differential
        tracing)."""
        if not self.orchestration_fields:
            return
        for node in _own_body_nodes(info.node):
            if not (
                isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and node.attr in self.orchestration_fields
            ):
                continue
            base = node.value
            recv = (
                base.id if isinstance(base, ast.Name)
                else base.attr if isinstance(base, ast.Attribute)
                else None
            )
            if recv is None or not (
                recv in self._OPTIONS_RECEIVERS
                or recv.lower().endswith("options")
            ):
                continue
            self._add(
                mod, node, "SR010",
                f"{recv}.{node.attr} read in jit-reachable "
                f"{info.qualname}(): {node.attr!r} is orchestration-"
                "classified (absent from Options._graph_key), so the "
                "first caller's value is baked into a compiled graph "
                "that hash-equal Options with a different value will "
                "share — hoist the read to the host loop, or reclassify "
                "the field in models/options.py",
                function=info.qualname,
            )

    # SR011 ------------------------------------------------------------
    #: a function whose qualname mentions one of these is (heuristically)
    #: computing an identity that may outlive its inputs
    _KEYISH_NAME_PARTS = ("key", "hash", "fingerprint", "memo")

    def _scan_id_in_key(self, mod: ModuleInfo, info: FuncInfo) -> None:
        """SR011: builtin id() inside a hash/key/fingerprint/memo
        computation. id() is only unique among live objects — once the
        callable is collected the id is reused, so a key derived from it
        can alias two distinct callables (rules.py SR011; fix with
        models/options.py::callable_token)."""
        low = info.qualname.lower()
        if not any(p in low for p in self._KEYISH_NAME_PARTS):
            return
        for node in _own_body_nodes(info.node):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "id"
                and len(node.args) == 1
                and not node.keywords
            ):
                continue
            func, full = self._resolve_target(info.scope, "id")
            if func is not None or full != "id":
                continue  # shadowed by a local def or an import
            self._add(
                mod, node, "SR011",
                f"id(...) inside {info.qualname}(): ids are reused "
                "after garbage collection, so an identity key derived "
                "from id() can alias two distinct callables over the "
                "process lifetime — use models/options.py::"
                "callable_token (monotonic, pinned by a strong "
                "reference) instead",
                function=info.qualname,
            )

    # SR012 ------------------------------------------------------------
    _SHARDING_CALLS = {
        "jax.lax.with_sharding_constraint":
            "jax.lax.with_sharding_constraint",
        "jax.experimental.pjit.with_sharding_constraint":
            "with_sharding_constraint",
        "jax.sharding.NamedSharding": "NamedSharding",
        "jax.NamedSharding": "NamedSharding",
    }

    def _scan_sharding_in_batched(
        self, mod: ModuleInfo, info: FuncInfo
    ) -> None:
        """SR012: with_sharding_constraint / NamedSharding inside a
        vmapped/scanned body whose mesh comes from OUTSIDE the function
        (a free variable, not a parameter or local): the constraint
        names axes against dims the batched trace cannot see (rules.py
        SR012 — the static form of api.py's inner_mesh=None rule).
        Mesh-as-parameter is exempt: the caller decides whether a mesh
        exists (migration.py's pin_replicated pattern)."""
        local_stores = {
            n.id for n in _own_body_nodes(info.node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
        }
        calls = [
            (n, self._SHARDING_CALLS.get(
                self._canonical(info.scope, n.func) or ""
            ))
            for n in _own_body_nodes(info.node)
            if isinstance(n, ast.Call)
        ]
        # a NamedSharding nested inside a with_sharding_constraint call
        # is the same finding — report the constraint once
        inside_constraint = {
            id(sub)
            for n, short in calls if short and short != "NamedSharding"
            for sub in ast.walk(n) if sub is not n
        }
        for node, short in calls:
            if short is None:
                continue
            if short == "NamedSharding" and id(node) in inside_constraint:
                continue
            free_meshes = sorted({
                n.id
                for arg in list(node.args)
                + [kw.value for kw in node.keywords]
                for n in ast.walk(arg)
                if isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)
                and "mesh" in n.id.lower()
                and n.id not in info.params
                and n.id not in local_stores
            })
            if not free_meshes:
                continue
            self._add(
                mod, node, "SR012",
                f"{short}(...) referencing outer mesh "
                f"{', '.join(free_meshes)} inside batched body "
                f"{info.qualname}() (reachable from jax.vmap/lax.scan/"
                "lax.map): the constraint names mesh axes against dims "
                "the batched trace cannot see — hoist placement to the "
                "enclosing jit's in/out shardings, or pass the mesh as "
                "a parameter so the caller can thread None under vmap "
                "(api.py's inner_mesh rule)",
                function=info.qualname,
            )


def _rebuilt_returned_params(info: FuncInfo) -> List[str]:
    """Parameters that are reassigned in the body AND reachable from a
    return value — the static signature of a carry (SR006). Reachability
    follows local aliases transitively (``outs = (states, ghof)`` then
    ``return outs`` still exposes ``states``); nested function bodies are
    separate FuncInfos and excluded."""
    node = info.node
    if isinstance(node, ast.Lambda):
        return []
    rebuilt: Set[str] = set()
    returned: Set[str] = set()
    # name -> names appearing in its assigned value(s), for the closure
    aliases: Dict[str, Set[str]] = {}

    def scan(stmts) -> None:
        for stmt in stmts:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(stmt, ast.Assign):
                value_names = {
                    n.id for n in ast.walk(stmt.value)
                    if isinstance(n, ast.Name)
                }
                for tgt in stmt.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            rebuilt.add(n.id)
                            aliases.setdefault(n.id, set()).update(
                                value_names
                            )
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(stmt.target, ast.Name):
                    rebuilt.add(stmt.target.id)
                    if stmt.value is not None:
                        aliases.setdefault(stmt.target.id, set()).update(
                            n.id for n in ast.walk(stmt.value)
                            if isinstance(n, ast.Name)
                        )
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                returned.update(
                    n.id for n in ast.walk(stmt.value)
                    if isinstance(n, ast.Name)
                )
            for block in ("body", "orelse", "finalbody"):
                b = getattr(stmt, block, None)
                if isinstance(b, list) and b and isinstance(b[0], ast.stmt):
                    scan(b)
            if isinstance(stmt, ast.Try):
                for h in stmt.handlers:
                    scan(h.body)

    scan(node.body)
    # transitive closure of "reachable from a return value"
    frontier = list(returned)
    while frontier:
        name = frontier.pop()
        for src in aliases.get(name, ()):
            if src not in returned:
                returned.add(src)
                frontier.append(src)
    return sorted(set(info.params) & rebuilt & returned)


def _literal_int_factor(node: ast.Call) -> Optional[int]:
    """The literal tile/repeat factor of a jnp.tile/jnp.repeat call, or
    None when it isn't a compile-time int/tuple-of-ints."""
    val = node.args[1] if len(node.args) > 1 else None
    if val is None:
        for kw in node.keywords:
            if kw.arg in ("reps", "repeats"):
                val = kw.value
    if val is None:
        return None
    if isinstance(val, ast.Constant) and isinstance(val.value, int):
        return val.value
    if isinstance(val, (ast.Tuple, ast.List)):
        prod = 1
        for elt in val.elts:
            if not (
                isinstance(elt, ast.Constant)
                and isinstance(elt.value, int)
            ):
                return None
            prod *= elt.value
        return prod
    return None


def _own_body_nodes(node):
    """Every AST node of a function, EXCLUDING nested def/lambda/class
    subtrees (those are separate FuncInfos and scanned on their own)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)
        ):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _declared_orchestration_fields(tree: ast.Module) -> Set[str]:
    """String elements of a top-level ``ORCHESTRATION_FIELDS = (...)``
    assignment (SR010's vocabulary — models/options.py declares the real
    one; lint fixtures declare their own)."""
    out: Set[str] = set()
    for stmt in tree.body:
        if not (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and stmt.targets[0].id == "ORCHESTRATION_FIELDS"
            and isinstance(stmt.value, (ast.Tuple, ast.List))
        ):
            continue
        for elt in stmt.value.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
    return out


def _literal_str_seq(node) -> Optional[List[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return out
    return None


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def lint_paths(
    root: str,
    files: Optional[Sequence[str]] = None,
    repo_root: Optional[str] = None,
) -> List[Violation]:
    """Lint every .py under `root` (or just `files`); returns ALL
    violations including pragma-suppressed ones (filter on .suppressed)."""
    linter = Linter(root, repo_root=repo_root).load(files)
    return linter.run_checks()


def lint_package(repo_root: Optional[str] = None) -> List[Violation]:
    """Lint the installed symbolicregression_jl_tpu package tree."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root is None:
        repo_root = os.path.dirname(pkg_dir)
    return lint_paths(pkg_dir, repo_root=repo_root)
