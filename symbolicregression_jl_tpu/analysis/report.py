"""Reporters shared by srlint and the compile-surface checker.

Both engines produce one `AnalysisReport`; the CLI renders it as text
(human, one finding per line, grep-friendly) or JSON (machine, stable
schema — tests/test_analysis.py pins it).

JSON schema (schema_version 1):

    {
      "schema_version": 1,
      "tool": "srlint",
      "ok": bool,                     # no active violations
      "counts": {"SR001": n, ...},    # active (non-suppressed) per rule
      "suppressed": int,              # pragma-suppressed findings
      "violations": [Violation.to_dict(), ...],
      "surface": {...} | null,        # compile-surface section, if run
      "memory": {...} | null,         # srmem section, if run
      "cost": {...} | null,           # srcost section, if run
      "keys": {...} | null,           # srkey section, if run
      "shard": {...} | null           # srshard section, if run
    }
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

from .rules import RULES, Violation


@dataclasses.dataclass
class AnalysisReport:
    violations: List[Violation] = dataclasses.field(default_factory=list)
    surface: Optional[dict] = None  # compile_surface.check_surface() output
    memory: Optional[dict] = None  # memory.check_memory() output
    cost: Optional[dict] = None  # cost.check_cost() output
    keys: Optional[dict] = None  # keys.check_keys() output
    shard: Optional[dict] = None  # shard.check_shard() output

    @property
    def active(self) -> List[Violation]:
        return [v for v in self.violations if not v.suppressed]

    @property
    def ok(self) -> bool:
        if self.active:
            return False
        if self.surface is not None and not self.surface.get("ok", True):
            return False
        if self.memory is not None and not self.memory.get("ok", True):
            return False
        if self.cost is not None and not self.cost.get("ok", True):
            return False
        if self.keys is not None and not self.keys.get("ok", True):
            return False
        if self.shard is not None and not self.shard.get("ok", True):
            return False
        return True

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for v in self.active:
            out[v.rule_id] = out.get(v.rule_id, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "schema_version": 1,
            "tool": "srlint",
            "ok": self.ok,
            "counts": self.counts(),
            "suppressed": sum(1 for v in self.violations if v.suppressed),
            "violations": [v.to_dict() for v in self.violations],
            "surface": self.surface,
            "memory": self.memory,
            "cost": self.cost,
            "keys": self.keys,
            "shard": self.shard,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    def to_text(self) -> str:
        lines: List[str] = []
        for v in self.active:
            rule = RULES[v.rule_id]
            where = f"{v.path}:{v.line}:{v.col}"
            fn = f" [{v.function}]" if v.function else ""
            lines.append(
                f"{where}: {v.rule_id} ({rule.name}){fn}: {v.message}"
            )
        n_sup = sum(1 for v in self.violations if v.suppressed)
        counts = self.counts()
        if counts:
            by_rule = ", ".join(
                f"{rid} x{n}" for rid, n in sorted(counts.items())
            )
            lines.append(
                f"srlint: {len(self.active)} violation(s) ({by_rule})"
                + (f", {n_sup} suppressed by pragma" if n_sup else "")
            )
        else:
            lines.append(
                "srlint: clean"
                + (f" ({n_sup} suppressed by pragma)" if n_sup else "")
            )
        if self.surface is not None:
            lines.append(render_surface_text(self.surface))
        if self.memory is not None:
            lines.append(render_memory_text(self.memory))
        if self.cost is not None:
            lines.append(render_cost_text(self.cost))
        if self.keys is not None:
            lines.append(render_keys_text(self.keys))
        if self.shard is not None:
            lines.append(render_shard_text(self.shard))
        return "\n".join(lines)


def write_baseline_json(path: str, payload: dict) -> None:
    """The one writer every checked-in analysis baseline goes through:
    sorted keys, fixed 2-space indent, trailing newline — so a refresh
    (e.g. after threading buffer donation) diffs only the values that
    actually moved."""
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def build_baseline_configs(
    baseline_path: str, out_configs: dict, build_entry
) -> dict:
    """The one refresh rule both baseline engines share: build each
    config's baseline record with ``build_entry(entry)``, EXCEPT skipped
    configs (e.g. the sharded surface on a single-device host) — those
    are never written from the current (empty) run, but an entry the
    checked-in baseline already has is PRESERVED, so refreshing on a
    host that cannot produce a config never deletes its gate for the
    hosts that can."""
    prior: dict = {}
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as f:
                prior = json.load(f).get("configs", {})
        except (OSError, json.JSONDecodeError):
            prior = {}
    new_configs: dict = {}
    for name, entry in out_configs.items():
        if "skipped" in entry:
            if name in prior:
                new_configs[name] = prior[name]
            continue
        new_configs[name] = build_entry(entry)
    return new_configs


def _mb(n: int) -> str:
    return f"{n / 1e6:.2f}MB" if n >= 100_000 else f"{n}B"


def render_memory_text(memory: dict) -> str:
    lines: List[str] = []
    for problem in memory.get("problems", []):
        lines.append(f"srmem: {problem}")
    for note in memory.get("notes", []):
        lines.append(f"srmem: note: {note}")
    configs = memory.get("configs", {})
    for name in sorted(configs):
        entry = configs[name]
        if "skipped" in entry:  # e.g. sharded on a single-device host
            lines.append(f"srmem: {name}: skipped ({entry['skipped']})")
            continue
        stages = entry.get("stages", {})
        top = max(
            stages.items(),
            key=lambda kv: kv[1].get("peak_modeled_bytes", 0),
            default=(None, None),
        )[0]
        lines.append(
            f"srmem: {name}: peak {_mb(entry['peak_modeled_bytes'])} "
            f"temps + {_mb(entry['args_bytes'])} args"
            + (f" (dominant stage: {top})" if top else "")
        )
    status = "ok" if memory.get("ok", False) else "FAIL"
    lines.append(
        f"srmem: {status} — {len(configs)} config(s), budget "
        f"{memory.get('hbm_budget_gb', 0):g}GB"
        + (
            " (baseline match)"
            if memory.get("baseline_match") else
            (" (baseline MISMATCH)" if memory.get("baseline_checked")
             else " (no baseline check)")
        )
    )
    return "\n".join(lines)


def _eng(n: float) -> str:
    return f"{n:.3g}" if n < 1e4 else f"{n:.2e}"


def render_cost_text(cost: dict) -> str:
    lines: List[str] = []
    for problem in cost.get("problems", []):
        lines.append(f"srcost: {problem}")
    for note in cost.get("notes", []):
        lines.append(f"srcost: note: {note}")
    configs = cost.get("configs", {})
    for name in sorted(configs):
        entry = configs[name]
        stages = entry.get("stages", {})
        top = max(
            stages.items(), key=lambda kv: kv[1].get("flops", 0),
            default=(None, None),
        )[0]
        lines.append(
            f"srcost: {name}: {_eng(entry['flops'])} element-ops, "
            f"{_eng(entry['bytes'])} bytes, padded waste "
            f"{entry.get('padded_waste_fraction', 0) * 100:.0f}%"
            + (f" (dominant stage: {top})" if top else "")
        )
    status = "ok" if cost.get("ok", False) else "FAIL"
    lines.append(
        f"srcost: {status} — {len(configs)} config(s)"
        + (
            " (baseline match)"
            if cost.get("baseline_match") else
            (" (baseline MISMATCH)" if cost.get("baseline_checked")
             else " (no baseline check)")
        )
    )
    return "\n".join(lines)


def render_keys_text(keys: dict) -> str:
    lines: List[str] = []
    for problem in keys.get("problems", []):
        lines.append(f"srkey: {problem}")
    for note in keys.get("notes", []):
        lines.append(f"srkey: note: {note}")
    configs = keys.get("configs", {})
    for name in sorted(configs):
        entry = configs[name]
        verdicts = []
        for label, flag in (
            ("orchestration", "orchestration_invariant"),
            ("scalar", "scalar_invariant"),
        ):
            verdicts.append(
                f"{label} invariant" if entry.get(flag)
                else f"{label} LEAKS"
            )
        culprits = entry.get("culprits") or []
        lines.append(
            f"srkey: {name}: {', '.join(verdicts)}"
            + (f" (culprits: {', '.join(culprits)})" if culprits else "")
        )
    f = keys.get("fields", {})
    status = "ok" if keys.get("ok", False) else "FAIL"
    lines.append(
        f"srkey: {status} — {f.get('graph', 0)} graph + "
        f"{f.get('traced_scalar', 0)} traced-scalar + "
        f"{f.get('orchestration', 0)} orchestration field(s)"
        + (
            f", differentially traced over {len(configs)} config(s)"
            if keys.get("traced") else ", differential tracing skipped"
        )
    )
    return "\n".join(lines)


def render_shard_text(shard: dict) -> str:
    lines: List[str] = []
    for problem in shard.get("problems", []):
        lines.append(f"srshard: {problem}")
    for note in shard.get("notes", []):
        lines.append(f"srshard: note: {note}")
    configs = shard.get("configs", {})
    for name in sorted(configs):
        entry = configs[name]
        if "skipped" in entry:
            lines.append(f"srshard: {name}: skipped ({entry['skipped']})")
            continue
        shape = "x".join(
            str(s) for s in (entry.get("mesh_shape") or {}).values()
        )
        n_coll = sum(
            sum(s.get("collectives", {}).values())
            for s in entry.get("stages", {}).values()
        )
        comm = sum(
            s.get("comm_bytes", 0)
            for s in entry.get("stages", {}).values()
        )
        line = (
            f"srshard: {name}: mesh {shape}, "
            f"{len(entry.get('stages', {}))} stage(s), {n_coll} "
            f"collective(s), {_mb(comm)} comm"
        )
        fused = entry.get("fused")
        if fused:
            line += (
                f"; fused {sum(fused['collectives'].values())} "
                f"collective(s), {_mb(fused['comm_bytes'])} comm, "
                f"comms share {fused['comms_fraction'] * 100:.1f}%, "
                f"max replication x{fused['max_replication_factor']:g}"
            )
        lines.append(line)
    status = "ok" if shard.get("ok", False) else "FAIL"
    cross = shard.get("cross_tenant_collectives", 0)
    lines.append(
        f"srshard: {status} — {len(configs)} config(s), "
        + (
            "zero cross-tenant collectives"
            if not cross else f"{cross} CROSS-TENANT collective(s)"
        )
        + f", max replication x{shard.get('max_replication_factor', 0):g}"
        + (
            " (baseline match)"
            if shard.get("baseline_match") else
            (" (baseline MISMATCH)" if shard.get("baseline_checked")
             else " (no baseline check)")
        )
    )
    return "\n".join(lines)


def render_surface_text(surface: dict) -> str:
    lines: List[str] = []
    for problem in surface.get("problems", []):
        lines.append(f"compile-surface: {problem}")
    configs = surface.get("configs", {})
    total = sum(c.get("total_primitives", 0) for c in configs.values())
    status = "ok" if surface.get("ok", False) else "FAIL"
    lines.append(
        f"compile-surface: {status} — {len(configs)} config(s), "
        f"{total} primitives total"
        + (
            " (baseline match)"
            if surface.get("baseline_match") else
            (" (baseline MISMATCH)" if surface.get("baseline_checked")
             else " (no baseline check)")
        )
    )
    return "\n".join(lines)
