"""CLI for the static-analysis subsystem.

    python -m symbolicregression_jl_tpu.analysis [--format text|json]
        [--only lint|surface|memory|cost|keys|shard[,...]]
        [--update-baseline] [--hbm-budget-gb G] [--xla-memory]

``--only`` accepts a comma-separated subset (``--only lint,keys``).
Exit status: 0 when clean, 1 on violations / surface problems / HBM
budget, cost, key-contract, or baseline regressions (CI contract —
benchmark/suite.py and scripts/lint.py both rely on it). Platform
handling: see `analysis.pin_platform`.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    from . import add_engine_args, pin_platform, run_analysis

    ap = argparse.ArgumentParser(
        prog="python -m symbolicregression_jl_tpu.analysis",
        description="srlint + compile-surface checker + srmem "
        "HBM-footprint gate + srcost analytic cost gate + srkey "
        "Options-contract checker + srshard sharding-contract gate "
        "(docs/static_analysis.md)",
    )
    add_engine_args(ap)
    ns = ap.parse_args(argv)

    pin_platform()
    report = run_analysis(
        lint=ns.only is None or "lint" in ns.only,
        surface=ns.only is None or "surface" in ns.only,
        memory=ns.only is None or "memory" in ns.only,
        cost=ns.only is None or "cost" in ns.only,
        keys=ns.only is None or "keys" in ns.only,
        shard=ns.only is None or "shard" in ns.only,
        update_baseline=ns.update_baseline,
        hbm_budget_gb=ns.hbm_budget_gb,
        xla_memory=ns.xla_memory,
    )
    print(report.to_json() if ns.format == "json" else report.to_text())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
