"""Static analysis for the TPU hot path: srlint + compile-surface checker.

Two engines, one CLI (``python -m symbolicregression_jl_tpu.analysis``):

- **srlint** (lint.py / rules.py): a JAX-aware AST linter that builds a
  call graph rooted at the package's ``jax.jit`` entry points and flags
  host syncs, tracer control flow, nondeterministic dict iteration,
  implicit dtypes, and stale ``static_argnames`` — with
  ``# srlint: disable=RULE`` pragmas.
- **compile-surface checker** (compile_surface.py): traces the jitted
  iteration/phase closures over a matrix of Options configs, asserts aval
  stability across iterations and the IslandState output contract, rejects
  callback/float64 primitives leaking into the jaxpr, and diffs primitive
  counts against the checked-in ``compile_baseline.json``.

See docs/static_analysis.md for the rule catalog and workflows.
"""

from .lint import Linter, lint_package, lint_paths
from .report import AnalysisReport
from .rules import RULES, Rule, Violation

__all__ = [
    "AnalysisReport",
    "Linter",
    "RULES",
    "Rule",
    "Violation",
    "add_engine_args",
    "lint_package",
    "lint_paths",
    "pin_platform",
    "run_analysis",
]


def pin_platform() -> None:
    """Pin JAX to CPU before any backend initializes (the analysis only
    parses and traces — platform-independent work — and this image's
    sitecustomize would otherwise route backend init at the experimental
    TPU tunnel and hang on its single slot; same guard as
    tests/conftest.py). SRTPU_ANALYSIS_PLATFORM overrides; empty string
    leaves the default resolution alone. Shared by the two CLI entry
    points (analysis.__main__ and scripts/lint.py)."""
    import os

    platform = os.environ.get("SRTPU_ANALYSIS_PLATFORM", "cpu")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)


def add_engine_args(parser) -> None:
    """The engine-selection CLI options both entry points expose."""
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (default: text)",
    )
    parser.add_argument(
        "--only", choices=("lint", "surface"), default=None,
        help="run a single engine (default: both)",
    )
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite analysis/compile_baseline.json from this tree's "
        "primitive census instead of diffing against it",
    )


def run_analysis(
    lint: bool = True,
    surface: bool = True,
    update_baseline: bool = False,
) -> AnalysisReport:
    """Run srlint and/or the compile-surface checker on this repo.

    Importing compile_surface pulls in jax; callers that only lint stay
    AST-only (no backend initialization)."""
    report = AnalysisReport()
    if lint:
        report.violations = lint_package()
    if surface:
        from .compile_surface import check_surface

        report.surface = check_surface(update_baseline=update_baseline)
    return report
