"""Static analysis for the TPU hot path: srlint + compile-surface checker
+ srmem HBM-footprint analyzer + srcost cost model + srkey contract
checker + srshard sharding-contract checker.

Six engines, one CLI (``python -m symbolicregression_jl_tpu.analysis``):

- **srlint** (lint.py / rules.py): a JAX-aware AST linter that builds a
  call graph rooted at the package's ``jax.jit`` entry points and flags
  host syncs, tracer control flow, nondeterministic dict iteration,
  implicit dtypes, stale ``static_argnames``, undonated carries, broadcast
  materializations, and host round-trips into jitted code — with
  ``# srlint: disable=RULE`` pragmas.
- **compile-surface checker** (compile_surface.py): traces the jitted
  iteration/phase closures over a matrix of Options configs, asserts aval
  stability across iterations and the IslandState output contract, rejects
  callback/float64 primitives leaking into the jaxpr, and diffs primitive
  counts against the checked-in ``compile_baseline.json``.
- **srmem** (memory.py): a jaxpr-walking live-buffer estimator that models
  peak temp HBM per config and per stage, diffs against the checked-in
  ``memory_baseline.json`` (>10% regressions fail), and gates every config
  against an HBM budget (default 16GB, one v5e).
- **srcost** (cost.py): a jaxpr-walking analytic cost model (per-primitive
  FLOPs, bytes moved, padded-waste fraction, scan trip counts included)
  attributed per search stage, diffed against the checked-in
  ``cost_baseline.json`` (>10% regressions fail) — the modeled half of
  the srprof roofline join (telemetry/profile.py).
- **srkey** (keys.py): the Options compile-identity contract checker —
  verifies the GRAPH/TRACED_SCALAR/ORCHESTRATION field classification in
  models/options.py is complete, that ``_graph_key`` covers exactly the
  graph fields, and (by differential tracing of the production programs)
  that orchestration fields never leak into jitted graphs while traced
  scalars re-bind without recompiling.
- **srshard** (shard.py): the SPMD sharding-contract checker — AOT-lowers
  the production stage programs and the fused iteration over a matrix of
  8-device meshes (1x8 / 2x4 / 4x2 islands x rows, plus a 2x4
  tenants x islands serving mesh), walks the compiled shardings to
  assert the island/tenant contract end-to-end, flags replication
  blowups by leaf name, proves the tenant axis stays collective-free
  (bisecting any leak to the culprit leaf), and prices every collective
  with a ring model over tabled ICI bandwidths — gated against the
  checked-in ``shard_baseline.json`` (census drift or >10% comm-byte
  growth fails).

See docs/static_analysis.md for the rule catalog and workflows.
"""

import argparse
from typing import Optional

from .lint import Linter, lint_package, lint_paths
from .report import AnalysisReport
from .rules import RULES, Rule, Violation

__all__ = [
    "AnalysisReport",
    "ENGINES",
    "Linter",
    "RULES",
    "Rule",
    "Violation",
    "add_engine_args",
    "lint_package",
    "lint_paths",
    "pin_platform",
    "run_analysis",
]

#: The engine names ``--only`` accepts (comma-separated subsets).
ENGINES = ("lint", "surface", "memory", "cost", "keys", "shard")


def _parse_only(text: str):
    """argparse type for ``--only``: 'lint' or 'lint,keys' -> frozenset."""
    names = tuple(s.strip() for s in text.split(",") if s.strip())
    bad = sorted(set(names) - set(ENGINES))
    if bad or not names:
        raise argparse.ArgumentTypeError(
            f"unknown engine(s) {bad or [text]} — choose from "
            + ", ".join(ENGINES)
        )
    return frozenset(names)


def pin_platform() -> None:
    """Pin JAX to CPU before any backend initializes (the analysis only
    parses and traces — platform-independent work — and this image's
    sitecustomize would otherwise route backend init at the experimental
    TPU tunnel and hang on its single slot; same guard as
    tests/conftest.py). On the CPU pin, additionally force 8 virtual
    host devices (the tests/conftest.py harness) so the compile-surface
    `sharded` config always has a mesh to partition against — on one
    real device the collective census could never run and the sharded
    gate would silently skip. SRTPU_ANALYSIS_PLATFORM overrides; empty
    string leaves the default resolution alone. Shared by the two CLI
    entry points (analysis.__main__ and scripts/lint.py)."""
    import os

    platform = os.environ.get("SRTPU_ANALYSIS_PLATFORM", "cpu")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
        if platform == "cpu":
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()


def add_engine_args(parser) -> None:
    """The engine-selection CLI options both entry points expose."""
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (default: text)",
    )
    parser.add_argument(
        "--only", type=_parse_only, default=None,
        metavar="ENGINE[,ENGINE...]",
        help="run a subset of engines, comma-separated (choices: "
        + ", ".join(ENGINES) + "; default: all six)",
    )
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the checked-in baselines (compile_baseline.json / "
        "memory_baseline.json / cost_baseline.json / shard_baseline.json"
        ") for the engines being run, instead of diffing against them",
    )
    parser.add_argument(
        "--hbm-budget-gb", type=float, default=None, metavar="G",
        help="srmem: fail any config whose modeled HBM footprint "
        "exceeds G gigabytes (default: 16, one v5e chip)",
    )
    parser.add_argument(
        "--xla-memory", action="store_true",
        help="srmem: additionally AOT-compile each config on the "
        "current backend and report XLA's own memory analysis (slower; "
        "informational only — the gate diffs the modeled numbers)",
    )


def run_analysis(
    lint: bool = True,
    surface: bool = True,
    memory: bool = True,
    cost: bool = True,
    keys: bool = True,
    shard: bool = True,
    update_baseline: bool = False,
    hbm_budget_gb: Optional[float] = None,
    xla_memory: bool = False,
) -> AnalysisReport:
    """Run srlint / the compile-surface checker / srmem / srcost / srkey
    / srshard on this repo.

    Importing compile_surface, memory, cost, keys, or shard pulls in
    jax; callers that only lint stay AST-only (no backend
    initialization)."""
    report = AnalysisReport()
    if lint:
        report.violations = lint_package()
    if surface:
        from .compile_surface import check_surface

        report.surface = check_surface(update_baseline=update_baseline)
    if memory:
        from .memory import DEFAULT_HBM_BUDGET_GB, check_memory

        report.memory = check_memory(
            update_baseline=update_baseline,
            hbm_budget_gb=(
                DEFAULT_HBM_BUDGET_GB if hbm_budget_gb is None
                else hbm_budget_gb
            ),
            xla_memory=xla_memory,
        )
    if cost:
        from .cost import check_cost

        report.cost = check_cost(update_baseline=update_baseline)
    if keys:
        from .keys import check_keys

        report.keys = check_keys()
    if shard:
        from .shard import check_shard

        report.shard = check_shard(update_baseline=update_baseline)
    return report
