"""Compile-surface contract checker for the jitted search closures.

Traces the production iteration closures (``api._make_iteration_fn``) and
the chunked-dispatch phase closures (``api._make_phase_fns``) over a
matrix of Options configs WITHOUT running them — ``jax.eval_shape`` for
the output-aval contract, ``jax.make_jaxpr`` for the primitive census —
and enforces:

- **aval stability**: the IslandState the iteration returns has exactly
  the avals of the IslandState it consumed, so the host loop can feed
  outputs back as inputs forever without a silent recompile (aval drift
  is how "one iteration = one compile" quietly becomes "one iteration =
  one compile *each time*");
- **IslandState output contract**: same pytree structure in and out, and
  the merged hall of fame is exactly the per-island HoF minus the island
  axis;
- **no host leaks**: no ``pure_callback``/``io_callback`` primitives in
  any sub-jaxpr, and no float64 aval anywhere when the config's working
  precision is float32 (an f64 leak means an accidental
  weak-type/promotion escape that doubles VMEM and silently splits the
  kernel cache);
- **compile-size budget**: the recursive primitive count per config is
  diffed against the checked-in ``compile_baseline.json`` — a graph that
  grows primitives fails loudly instead of shipping a 2x compile-time
  regression (refresh intentionally with ``--update-baseline``).

Everything runs on CPU: tracing is platform-independent, so the check
needs no TPU (the Pallas kernel path resolves away at the small matrix
batch sizes — the traced graph is the jnp interpreter composition, which
is the same program structure the kernel path feeds).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "compile_baseline.json"
)

#: The Options matrix: cache on/off, island count, pop size, chunked
#: dispatch. Small shapes — tracing cost only, never executed.
_BASE_KWARGS = dict(
    binary_operators=("+", "-", "*"),
    unary_operators=("cos",),
    npopulations=2,
    npop=12,
    ncycles_per_iteration=2,
    maxsize=8,
    tournament_selection_n=4,
    topn=4,
    verbosity=0,
    progress=False,
)

_MATRIX: Tuple[Tuple[str, dict], ...] = (
    ("base", {}),
    ("cache", dict(cache_fitness=True, cache_device_slots=8)),
    ("islands4", dict(npopulations=4)),
    ("pop32", dict(npop=32)),
    # length-bucketed eval graphs (docs/eval_pipeline.md): the ladder
    # replaces the flat lockstep scan with per-bucket bounded loops —
    # a distinct compiled surface whose aval contract must still hold
    ("bucketed", dict(eval_bucket_ladder=(0.5, 1.0))),
    # row-sharded deterministic-reduction graphs (ISSUE 15,
    # docs/robustness_numeric.md): row_shards > 1 swaps every scoring /
    # constant-optimizer row reduction for the fixed-order pairwise
    # tree (ops/losses.py::pairwise_sum) — a distinct compiled surface
    # (row_shards is in _graph_key). The `sharded` config pins the
    # mesh/collective side; this one pins the REDUCTION program itself
    # (traced meshless — the graph is identical with or without the
    # mesh, which is exactly the bit-identity contract).
    ("rowsharded", dict(row_shards=2)),
    # tenant-batched serving surface (ISSUE 16, docs/serving.md):
    # tenants > 1 vmaps the whole per-tenant iteration body over a
    # leading tenants axis — the distinct compiled program every
    # srserve bucket reuses warm. Same aval-stability bar as solo:
    # the (T, I, ...) carry must round-trip exactly, and the merged
    # HoF is the per-island HoF minus the ISLAND axis only (the
    # tenants axis survives the merge). Traced meshless, like
    # rowsharded: the serving (tenants, islands) mesh pins layout,
    # never the graph.
    ("tenants2", dict(tenants=2)),
)

#: config name for the phased (chunked-dispatch) closure set
_CHUNKED = ("chunked", dict(max_cycles_per_dispatch=1))

#: the island-sharded production surface (docs/multichip.md): the fused
#: iteration jit carrying explicit NamedSharding in/out specs over an
#: (islands, rows) mesh. 8 islands so an 8-virtual-device CPU harness
#: (tests/conftest.py, analysis pin_platform) shards 1 island/device.
#: Checked like every other config PLUS a collective census: the
#: partitioned program's all-gather/all-reduce counts are part of the
#: checked-in baseline, so a change that silently multiplies cross-chip
#: traffic (or partitions the migration gather away entirely) fails CI.
_SHARDED = ("sharded", dict(npopulations=8))

#: HLO instruction names counted by the collective census (async
#: -start/-done pairs count once, via the -start spelling).
_COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

_NFEAT, _NROWS = 3, 32


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------


def count_primitives(jaxpr) -> Dict[str, int]:
    """Recursive primitive census of a (Closed)Jaxpr: every sub-jaxpr in
    eqn params (pjit bodies, scan/while/cond branches, custom_* rules) is
    descended into, so the count reflects the whole compiled program."""
    import jax.core as jcore

    counts: Dict[str, int] = {}

    def walk(jx) -> None:
        if hasattr(jx, "jaxpr"):  # ClosedJaxpr
            jx = jx.jaxpr
        for eqn in jx.eqns:
            counts[eqn.primitive.name] = (
                counts.get(eqn.primitive.name, 0) + 1
            )
            for sub in _sub_jaxprs(eqn.params):
                walk(sub)

    def _sub_jaxprs(params):
        for v in params.values():
            if isinstance(v, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                yield v
            elif isinstance(v, (list, tuple)):
                for item in v:
                    if isinstance(item, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                        yield item

    walk(jaxpr)
    return counts


def _walk_avals(jaxpr):
    """Yield every variable aval in the jaxpr tree (inputs, outputs,
    intermediates, all sub-jaxprs)."""
    import jax.core as jcore

    def walk(jx):
        if hasattr(jx, "jaxpr"):
            jx = jx.jaxpr
        for v in jx.invars + jx.outvars + jx.constvars:
            if hasattr(v, "aval"):
                yield v.aval
        for eqn in jx.eqns:
            for v in list(eqn.invars) + list(eqn.outvars):
                if hasattr(v, "aval"):
                    yield v.aval
            for pv in eqn.params.values():
                subs = pv if isinstance(pv, (list, tuple)) else [pv]
                for s in subs:
                    if isinstance(s, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                        yield from walk(s)

    return walk(jaxpr)


def collective_census(hlo_text: str) -> Dict[str, int]:
    """Count cross-device collective instructions in optimized HLO text.
    Matches instruction applications (`" all-gather("` etc.); async
    collectives are counted by their `-start` halves so a sync->async
    lowering change does not read as a doubling."""
    counts: Dict[str, int] = {}
    for op in _COLLECTIVE_OPS:
        n = hlo_text.count(f" {op}(") + hlo_text.count(f" {op}-start(")
        if n:
            counts[op] = n
    return counts


def forbidden_primitives(counts: Dict[str, int]) -> List[str]:
    return sorted(
        name for name in counts
        if "callback" in name or name in ("infeed", "outfeed")
    )


def float64_leaks(jaxpr) -> List[str]:
    import numpy as np

    leaks = set()
    for aval in _walk_avals(jaxpr):
        dt = getattr(aval, "dtype", None)
        if dt is not None and dt in (np.float64, np.complex128):
            leaks.add(f"{dt}{getattr(aval, 'shape', ())}")
    return sorted(leaks)


# ---------------------------------------------------------------------------
# aval contracts
# ---------------------------------------------------------------------------


def _aval_mismatches(tag: str, got, want) -> List[str]:
    """Structure + leaf shape/dtype equality of two eval_shape pytrees."""
    import jax

    problems: List[str] = []
    tg = jax.tree_util.tree_structure(got)
    tw = jax.tree_util.tree_structure(want)
    if tg != tw:
        return [f"{tag}: pytree structure changed: {tg} != {tw}"]
    # structures are equal, so the flattened leaf orders correspond 1:1
    got_leaves = jax.tree_util.tree_flatten_with_path(got)[0]
    want_leaves = jax.tree_util.tree_leaves(want)
    for (path, g), w in zip(got_leaves, want_leaves):
        if g.shape != w.shape or g.dtype != w.dtype:
            pstr = jax.tree_util.keystr(path)
            problems.append(
                f"{tag}{pstr}: aval drift {w.shape}/{w.dtype} -> "
                f"{g.shape}/{g.dtype}"
            )
    return problems


# ---------------------------------------------------------------------------
# the checker
# ---------------------------------------------------------------------------


def _abstract_inputs(options, I: int):
    """Aval-only inputs for one iteration: (states, key, cm, X, y, bl,
    scalars, memo-or-None). With ``options.tenants > 1`` every
    per-tenant aval gains the leading tenants axis (keys ``(T, I, 2)``,
    data ``(T, ...)``, per-iteration key ``(T, 2)``) — the shapes
    serving/batched.py feeds the vmapped factories."""
    import jax
    import jax.numpy as jnp

    from ..api import _make_init_fn

    T = options.tenants
    if T > 1:
        X = jax.ShapeDtypeStruct((T, _NFEAT, _NROWS), jnp.float32)
        y = jax.ShapeDtypeStruct((T, _NROWS), jnp.float32)
        bl = jax.ShapeDtypeStruct((T,), jnp.float32)
        key = jax.eval_shape(
            lambda: jnp.stack(
                [jax.random.PRNGKey(t) for t in range(T)]
            )
        )
        keys = jax.eval_shape(
            lambda k: jax.vmap(lambda kk: jax.random.split(kk, I))(k),
            key,
        )
    else:
        X = jax.ShapeDtypeStruct((_NFEAT, _NROWS), jnp.float32)
        y = jax.ShapeDtypeStruct((_NROWS,), jnp.float32)
        bl = jax.ShapeDtypeStruct((), jnp.float32)
        key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
        keys = jax.eval_shape(
            lambda k: jax.random.split(k, I), jax.random.PRNGKey(0)
        )
    cm = jax.ShapeDtypeStruct((), jnp.int32)
    scalars = options.traced_scalars()
    init_fn = _make_init_fn(options, _NFEAT, False)
    states = jax.eval_shape(init_fn, keys, X, y, bl, scalars)
    memo = None
    if options.cache_fitness:
        from ..cache.dedup import empty_device_memo

        memo = jax.eval_shape(
            lambda: empty_device_memo(
                options.cache_device_slots, options.dtype
            )
        )
        if T > 1:
            memo = jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(
                    (T,) + l.shape, l.dtype
                ),
                memo,
            )
    return states, key, cm, X, y, bl, scalars, memo, keys


def _check_iteration_config(
    name: str, options, mesh=None
) -> Tuple[dict, List[str]]:
    """Fused single-jit iteration: aval stability + contract + census.

    mesh: additionally AOT-compiles the sharded program over it and
    records (a) the collective census of the partitioned HLO (part of
    the baseline diff) and (b) the output-sharding CONTRACT — every
    carried IslandState leaf must come back island-sharded and the
    merged HoF replicated; a partitioner change that silently
    replicates the carry fails here, not in production."""
    import jax

    from ..api import _make_iteration_fn

    problems: List[str] = []
    I = options.npopulations
    states, key, cm, X, y, bl, scalars, memo, _ = _abstract_inputs(
        options, I
    )
    it_fn = _make_iteration_fn(options, False, mesh=mesh)
    args = (states, key, cm, X, y, bl, scalars) + (
        (memo,) if memo is not None else ()
    )
    outs = jax.eval_shape(it_fn, *args)
    out_states, ghof = outs[0], outs[1]
    problems += _aval_mismatches(f"{name}: IslandState", out_states, states)
    # merged HoF contract: per-island hof minus the ISLAND axis — the
    # leading axis solo, axis 1 when a tenants axis rides in front
    # (tenant t's merged HoF survives per tenant; serving bit-identity)
    _drop_island = (
        (lambda s: s[:1] + s[2:]) if options.tenants > 1
        else (lambda s: s[1:])
    )
    want_ghof = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(_drop_island(l.shape), l.dtype),
        states.hof,
    )
    problems += _aval_mismatches(f"{name}: merged HoF", ghof, want_ghof)

    jaxpr = jax.make_jaxpr(it_fn)(*args)
    counts = count_primitives(jaxpr)
    for p in forbidden_primitives(counts):
        problems.append(
            f"{name}: forbidden host-callback primitive {p!r} "
            f"x{counts[p]} in the iteration jaxpr"
        )
    if options.precision == "float32":
        for leak in float64_leaks(jaxpr):
            problems.append(
                f"{name}: float64 aval {leak} leaked into a float32 "
                "iteration graph"
            )
    entry = {
        "primitives": dict(sorted(counts.items())),
        "total_primitives": int(sum(counts.values())),
        "stable_avals": not any("aval drift" in p or "structure" in p
                                for p in problems),
    }
    if mesh is not None:
        compiled = it_fn.lower(*args).compile()
        entry["n_devices"] = int(mesh.devices.size)
        entry["collectives"] = collective_census(compiled.as_text())
        if not entry["collectives"]:
            problems.append(
                f"{name}: the partitioned iteration compiled to ZERO "
                "cross-device collectives — the islands axis was "
                "partitioned away (migration/HoF-merge no longer "
                "communicate)"
            )
        problems += _sharding_contract_problems(
            name, options, compiled, states
        )
    return entry, problems


def _sharding_contract_problems(
    name: str, options, compiled, states_aval
) -> List[str]:
    """Assert the compiled output shardings: IslandState leaves pinned to
    the island axis, merged HoF fully replicated."""
    problems: List[str] = []
    try:
        out_sh = compiled.output_shardings
    except Exception as e:  # pragma: no cover - jax API variance
        return [f"{name}: could not read compiled output shardings: {e}"]
    import jax

    st_sh, ghof_sh = out_sh[0], out_sh[1]
    n_sh = len(jax.tree_util.tree_leaves(st_sh))
    n_aval = len(jax.tree_util.tree_leaves(states_aval))
    if n_sh != n_aval:
        problems.append(
            f"{name}: compiled output-sharding tree has {n_sh} leaves "
            f"but the IslandState aval has {n_aval} — the contract "
            "check no longer covers the carry"
        )
    for path, sh in jax.tree_util.tree_flatten_with_path(st_sh)[0]:
        spec = tuple(getattr(sh, "spec", ()) or ())
        if not spec or spec[0] != options.island_axis:
            problems.append(
                f"{name}: carried IslandState leaf"
                f"{jax.tree_util.keystr(path)} comes back with sharding "
                f"{sh} instead of island-axis sharding — a replicated "
                "carry serializes every later iteration on one device"
            )
    for path, sh in jax.tree_util.tree_flatten_with_path(ghof_sh)[0]:
        if not sh.is_fully_replicated:
            problems.append(
                f"{name}: merged HoF leaf{jax.tree_util.keystr(path)} "
                f"is not replicated ({sh}) — host-side candidate "
                "extraction would gather per-iteration"
            )
    return problems


def _check_phase_config(name: str, options) -> Tuple[dict, List[str]]:
    """Chunked-dispatch phase closures: each phase is its own program."""
    import jax
    import jax.numpy as jnp

    from ..api import _make_phase_fns

    problems: List[str] = []
    I = options.npopulations
    states, key, cm, X, y, bl, scalars, memo, keys = _abstract_inputs(
        options, I
    )
    fns = _make_phase_fns(options, False)
    k = options.max_cycles_per_dispatch
    temps = jax.ShapeDtypeStruct((k,), jnp.float32)
    phase_args = {
        # is_last positional: the phase jits take it via static_argnums
        # (kwargs are rejected once a jit carries explicit in_shardings)
        "cycle": lambda f: f(
            states, cm, X, y, None, bl, scalars, temps, True
        ),
        "simplify": lambda f: f(
            states, cm, X, y, None, bl, scalars, memo=memo
        ),
        "optimize": lambda f: f(keys, states, X, y, None, bl, scalars),
        "optimize_mut": lambda f: f(keys, states, X, y, None, bl, scalars),
        "merge_migrate": lambda f: f(key, states, scalars),
    }
    entry: dict = {"phases": {}, "total_primitives": 0}
    for phase, call in phase_args.items():
        fn = fns[phase]
        outs = jax.eval_shape(lambda *a, _c=call, _f=fn: _c(_f))
        # cycle/simplify/optimize return the IslandState itself (a
        # namedtuple); merge_migrate returns a plain (states, ghof) tuple
        is_bare_tuple = (
            isinstance(outs, tuple) and not hasattr(outs, "_fields")
        )
        out_states = outs[0] if is_bare_tuple else outs
        tag = f"{name}.{phase}"
        problems += _aval_mismatches(
            f"{tag}: IslandState", out_states, states
        )
        jaxpr = jax.make_jaxpr(lambda _c=call, _f=fn: _c(_f))()
        counts = count_primitives(jaxpr)
        for p in forbidden_primitives(counts):
            problems.append(
                f"{tag}: forbidden host-callback primitive {p!r}"
            )
        if options.precision == "float32":
            for leak in float64_leaks(jaxpr):
                problems.append(
                    f"{tag}: float64 aval {leak} in a float32 graph"
                )
        entry["phases"][phase] = {
            "primitives": dict(sorted(counts.items())),
            "total_primitives": int(sum(counts.values())),
        }
        entry["total_primitives"] += int(sum(counts.values()))
    # flatten for the baseline diff
    entry["primitives"] = {}
    for phase, ph in entry["phases"].items():
        for prim, n in ph["primitives"].items():
            entry["primitives"][prim] = entry["primitives"].get(prim, 0) + n
    entry["primitives"] = dict(sorted(entry["primitives"].items()))
    entry["stable_avals"] = not any(
        "aval drift" in p or "structure" in p for p in problems
    )
    return entry, problems


def _sharded_check_mesh(options):
    """The (islands, rows) mesh the sharded surface config compiles
    against: up to 8 local devices, islands only (row_shards=1 — the
    bit-identity configuration). None when this host has one device."""
    import jax

    from ..parallel.mesh import make_mesh

    devices = jax.devices()
    if len(devices) < 2:
        return None
    return make_mesh(
        options, options.npopulations, devices=devices[:8], row_shards=1
    )


def diff_baseline(
    configs: Dict[str, dict], baseline: dict
) -> List[str]:
    """Primitive-count diff vs the checked-in baseline: any change fails
    (refresh with --update-baseline when intentional)."""
    problems: List[str] = []
    base_configs = baseline.get("configs", {})
    skipped = {
        name for name, entry in configs.items() if "skipped" in entry
    }
    for name, entry in configs.items():
        if name in skipped:
            continue  # e.g. sharded on a single-device host
        if name not in base_configs:
            problems.append(
                f"baseline has no config {name!r} — run with "
                "--update-baseline"
            )
            continue
        want = base_configs[name].get("primitives", {})
        got = entry["primitives"]
        for prim in sorted(set(want) | set(got)):
            w, g = want.get(prim, 0), got.get(prim, 0)
            if w != g:
                problems.append(
                    f"{name}: primitive count drift for {prim!r}: "
                    f"baseline {w} -> now {g} (intentional? refresh with "
                    "--update-baseline)"
                )
        # collective census (sharded configs): any drift in cross-device
        # traffic shape is a compile-surface change, gated like the
        # primitive counts
        want_c = base_configs[name].get("collectives")
        got_c = entry.get("collectives")
        if want_c is not None or got_c is not None:
            for op in sorted(set(want_c or {}) | set(got_c or {})):
                w, g = (want_c or {}).get(op, 0), (got_c or {}).get(op, 0)
                if w != g:
                    problems.append(
                        f"{name}: collective census drift for {op!r}: "
                        f"baseline {w} -> now {g} (intentional? refresh "
                        "with --update-baseline)"
                    )
    for name in base_configs:
        if name not in configs and name not in skipped:
            problems.append(
                f"baseline config {name!r} no longer produced — refresh "
                "with --update-baseline"
            )
    return problems


def check_surface(
    update_baseline: bool = False,
    baseline_path: Optional[str] = None,
    configs: Optional[Tuple[Tuple[str, dict], ...]] = None,
    include_chunked: bool = True,
) -> dict:
    """Run the full compile-surface check; returns the report dict
    (schema: report.render_surface_text / docs/static_analysis.md)."""
    import jax

    from ..models.options import make_options

    baseline_path = baseline_path or BASELINE_PATH
    matrix = list(configs if configs is not None else _MATRIX)
    out_configs: Dict[str, dict] = {}
    problems: List[str] = []
    for name, extra in matrix:
        options = make_options(**{**_BASE_KWARGS, **extra})
        entry, probs = _check_iteration_config(name, options)
        out_configs[name] = entry
        problems += probs
    if include_chunked and configs is None:
        name, extra = _CHUNKED
        options = make_options(**{**_BASE_KWARGS, **extra})
        entry, probs = _check_phase_config(name, options)
        out_configs[name] = entry
        problems += probs
    if configs is None:
        name, extra = _SHARDED
        options = make_options(**{**_BASE_KWARGS, **extra})
        mesh = _sharded_check_mesh(options)
        if mesh is None:
            # diffed as "skipped", never as a missing config: a
            # single-device host cannot partition anything
            out_configs[name] = {
                "skipped": f"{len(jax.devices())} device(s) — the "
                "sharded surface needs >= 2"
            }
        else:
            entry, probs = _check_iteration_config(name, options, mesh)
            out_configs[name] = entry
            problems += probs

    baseline_checked = baseline_match = False
    if update_baseline:
        from .report import write_baseline_json

        from .report import build_baseline_configs

        payload = {
            "schema_version": 1,
            "jax_version": jax.__version__,
            # skipped configs (sharded on a single-device host) keep
            # their prior checked-in entry instead of being deleted —
            # see report.build_baseline_configs
            "configs": build_baseline_configs(
                baseline_path, out_configs,
                lambda entry: {
                    "primitives": entry["primitives"],
                    "total_primitives": entry["total_primitives"],
                    **({"collectives": entry["collectives"],
                        "n_devices": entry["n_devices"]}
                       if "collectives" in entry else {}),
                },
            ),
        }
        write_baseline_json(baseline_path, payload)
    elif os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f)
        baseline_checked = True
        base_problems = diff_baseline(out_configs, baseline)
        baseline_match = not base_problems
        problems += base_problems
        if baseline.get("jax_version") != jax.__version__:
            # a jax upgrade legitimately moves primitive counts; make the
            # remedy obvious instead of failing with raw drift lines
            baseline_match = False
            problems.append(
                "baseline was written under jax "
                f"{baseline.get('jax_version')} but this is "
                f"{jax.__version__} — refresh with --update-baseline"
            )
    else:
        problems.append(
            f"no compile baseline at {baseline_path} — create one with "
            "--update-baseline"
        )

    return {
        "ok": not problems,
        "problems": problems,
        "configs": out_configs,
        "baseline_checked": baseline_checked,
        "baseline_match": baseline_match,
        "baseline_path": baseline_path,
        "jax_version": jax.__version__,
    }
