"""srmem — static HBM-footprint analyzer for the search hot path.

The search dies on-chip at >=64 islands with an opaque UNAVAILABLE
error; AOT memory analysis attributes it to temp buffers of 11.7GB at
64x256 and 45GB at 64x1000 on a 16GB v5e, dominated by
``optimize_islands_constants``. Nothing in CI noticed when a change
doubled peak HBM — this engine is the gate that does.

Three layers, all trace-only (``jax.make_jaxpr`` / ``jax.eval_shape``
over aval inputs; nothing executes, so it runs on CPU in CI):

- **live-buffer estimator** (`live_buffer_peak`): walks a jaxpr with a
  linear-liveness model — an equation's outputs go live, a value dies
  after its last use, sub-jaxprs (scan/while/cond/pjit bodies) peak
  while their caller's live set is held — and reports the peak live
  temp bytes plus the per-equation "aval blowup" census (the SR007
  signature: one equation whose output is many times its inputs'
  bytes, measured with real byte counts instead of the AST heuristic).
- **per-stage attribution** (`build_stage_programs`): the same Options
  matrix ``compile_surface`` traces, decomposed into the production
  stages (init / cycle / mutate / eval / simplify / optimize /
  merge_migrate) so a regression names the stage that grew. Where the
  backend provides it, ``jit(...).lower().compile().memory_analysis()``
  numbers ride along (`xla_stage_analysis`) — that is the exact XLA
  buffer-assignment accounting, and scripts/tpu_mem_analysis.py uses it
  against the real TPU target.
- **baseline + budget gate** (`check_memory`): per-config peaks diff
  against the checked-in ``memory_baseline.json`` — CI fails on a >10%
  modeled-peak regression or on any config whose modeled footprint
  exceeds the HBM budget (default 16GB, one v5e chip). Shrinking peaks
  never fail; they surface as refresh notes.

CLI: ``python -m symbolicregression_jl_tpu.analysis --only memory
[--hbm-budget-gb G] [--update-baseline]`` (docs/static_analysis.md).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from .compile_surface import (
    _BASE_KWARGS,
    _MATRIX,
    _NFEAT,
    _NROWS,
    _abstract_inputs,
)

BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "memory_baseline.json"
)

#: One v5e chip's HBM — the part the 64-island search OOMs on.
DEFAULT_HBM_BUDGET_GB = 16.0

#: Modeled-peak growth beyond this fraction of the baseline fails CI.
REGRESSION_TOLERANCE = 0.10

#: An equation is a "blowup" when its output aval exceeds this multiple
#: of its inputs' total bytes AND this absolute size (tiny broadcasts —
#: iotas, masks — are normal and uninteresting).
BLOWUP_FACTOR = 8.0
BLOWUP_MIN_BYTES = 1 << 20  # 1 MiB
_TOP_BLOWUPS = 5


# ---------------------------------------------------------------------------
# live-buffer estimator
# ---------------------------------------------------------------------------


def aval_bytes(aval) -> int:
    """Bytes of one abstract value (0 for tokens/opaque avals)."""
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n * int(dtype.itemsize)


def _sub_jaxprs(params):
    import jax.core as jcore

    for v in params.values():
        if isinstance(v, (jcore.Jaxpr, jcore.ClosedJaxpr)):
            yield v
        elif isinstance(v, (list, tuple)):
            for item in v:
                if isinstance(item, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                    yield item


def live_buffer_peak(jaxpr) -> dict:
    """Linear-liveness estimate of one (Closed)Jaxpr.

    Returns ``{"peak_bytes", "args_bytes", "out_bytes", "blowups"}``:
    peak live TEMP bytes (equation outputs that have not yet died;
    jaxpr inputs are accounted separately as args_bytes), and the
    largest per-equation aval blowups. A sub-jaxpr's peak is charged
    while every value live at its call site is held — the same
    worst-case XLA's buffer assignment must accommodate when it cannot
    overlap the regions. The model ignores fusion and rematerialization,
    so it is an upper-ish bound whose VALUE drifts from XLA's exact
    number but whose RATIO between two versions of the same program
    tracks real regressions — which is all the baseline gate needs."""
    import jax.core as jcore

    blowups: List[dict] = []

    def walk(jx) -> int:
        if hasattr(jx, "jaxpr"):
            jx = jx.jaxpr
        last_use: Dict = {}
        for i, eqn in enumerate(jx.eqns):
            for v in eqn.invars:
                if isinstance(v, jcore.Var):
                    last_use[id(v)] = i
        outset = {
            id(v) for v in jx.outvars if isinstance(v, jcore.Var)
        }
        live_bytes: Dict[int, Tuple] = {}  # id(var) -> (var, bytes)
        live = 0
        peak = 0
        for i, eqn in enumerate(jx.eqns):
            out_b = 0
            for v in eqn.outvars:
                b = aval_bytes(v.aval)
                out_b += b
                live_bytes[id(v)] = (v, b)
                live += b
            in_b = sum(
                aval_bytes(v.aval)
                for v in eqn.invars
                if isinstance(v, jcore.Var)
            )
            inner = 0
            for sub in _sub_jaxprs(eqn.params):
                inner = max(inner, walk(sub))
            peak = max(peak, live + inner)
            if (
                in_b > 0
                and out_b >= BLOWUP_MIN_BYTES
                and out_b > BLOWUP_FACTOR * in_b
            ):
                blowups.append({
                    "primitive": eqn.primitive.name,
                    "out_bytes": int(out_b),
                    "in_bytes": int(in_b),
                    "factor": round(out_b / in_b, 1),
                })
            # release every value whose last use is this equation
            # (including dead stores: outvars never read again)
            for v in list(eqn.invars) + list(eqn.outvars):
                vid = id(v)
                if vid in live_bytes and vid not in outset:
                    if last_use.get(vid, i) <= i:
                        live -= live_bytes.pop(vid)[1]
        return peak

    peak = walk(jaxpr)
    inner = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    args = sum(
        aval_bytes(v.aval) for v in inner.invars + inner.constvars
    )
    outs = sum(aval_bytes(v.aval) for v in inner.outvars)
    blowups.sort(key=lambda b: -b["out_bytes"])
    return {
        "peak_bytes": int(peak),
        "args_bytes": int(args),
        "out_bytes": int(outs),
        "blowups": blowups[:_TOP_BLOWUPS],
    }


# ---------------------------------------------------------------------------
# stage programs
# ---------------------------------------------------------------------------


def build_stage_programs(
    options, nfeatures: int = _NFEAT, nrows: int = _NROWS
) -> Dict[str, Tuple]:
    """Ordered ``{stage: (fn, aval_args)}`` decomposing one production
    iteration (plus init) into independently traceable programs. The
    stage set mirrors the hot path: the cycle scan splits into its two
    expensive halves (mutate = tree surgery, eval = the fused scoring
    call over all islands' children) so blowups attribute to the half
    that owns them. scripts/tpu_mem_analysis.py AOT-compiles exactly
    these against the TPU target.

    ``options.tenants > 1`` attributes PER-TENANT: the tenant-batched
    iteration is the vmap of the per-tenant body, so each stage's
    footprint is the solo stage's times the tenant count — the stage
    decomposition traces the solo body and the whole-program number in
    ``_analyze_config`` carries the tenants axis."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from ..models import evolve
    from ..models.fitness import score_trees
    from ..parallel.migration import merge_hofs_across_islands, migrate

    if options.tenants > 1:
        options = dataclasses.replace(options, tenants=1)
    I = options.npopulations
    states, key, cm, X, y, bl, scalars, memo, keys = _abstract_inputs(
        options, I
    )
    if (nfeatures, nrows) != (_NFEAT, _NROWS):
        X = jax.ShapeDtypeStruct((nfeatures, nrows), options.dtype)
        y = jax.ShapeDtypeStruct((nrows,), options.dtype)

    def init_stage(keys, X, y, bl, scalars):
        from ..api import _make_init_fn

        return _make_init_fn(options, nfeatures, False)(
            keys, X, y, bl, scalars
        )

    def cycle(states, cm, X, y, bl, scalars):
        o = options.bind_scalars(scalars)
        return evolve.s_r_cycle_islands(states, cm, X, y, None, bl, o)

    def mutate(states, cm, scalars):
        o = options.bind_scalars(scalars)
        temp = jnp.float32(1.0)
        return jax.vmap(
            lambda st: evolve._propose_children(
                st, temp, cm, nfeatures, o
            )
        )(states)

    def simplify(states, cm, X, y, bl, scalars):
        o = options.bind_scalars(scalars)
        return evolve.simplify_population_islands(
            states, cm, X, y, None, bl, o
        )

    def optimize(keys, states, X, y, bl, scalars):
        o = options.bind_scalars(scalars)
        return evolve.optimize_islands_constants(
            keys, states, X, y, None, bl, o
        )

    def merge_migrate(key, states, scalars):
        o = options.bind_scalars(scalars)
        ghof = merge_hofs_across_islands(states.hof)
        return migrate(key, states, ghof, o)

    # the eval stage scores the flat all-islands children batch — the
    # shape the mutate stage emits
    props = jax.eval_shape(mutate, states, cm, scalars)
    children_flat = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(
            (l.shape[0] * l.shape[1],) + l.shape[2:], l.dtype
        ),
        props.children,
    )

    def eval_stage(children, X, y, bl, scalars):
        o = options.bind_scalars(scalars)
        return score_trees(children, X, y, None, bl, o)

    stages = {
        "init": (init_stage, (keys, X, y, bl, scalars)),
        "cycle": (cycle, (states, cm, X, y, bl, scalars)),
        "mutate": (mutate, (states, cm, scalars)),
        "eval": (eval_stage, (children_flat, X, y, bl, scalars)),
        "simplify": (simplify, (states, cm, X, y, bl, scalars)),
        "optimize": (optimize, (keys, states, X, y, bl, scalars)),
        "merge_migrate": (merge_migrate, (key, states, scalars)),
    }
    # one stage vocabulary across the repo: srmem attribution, telemetry
    # spans, and XLA-profile annotations all join on these names — a
    # rename here without telemetry.spans.STAGES breaks that join
    from ..telemetry.spans import STAGES

    assert tuple(stages) == STAGES, (tuple(stages), STAGES)
    return stages


def xla_stage_analysis(fn, args) -> dict:
    """AOT-compile one stage for the CURRENT backend and return XLA's
    own buffer-assignment numbers, or a structured error. Nothing
    executes — safe against a flaky TPU tunnel window."""
    import jax

    try:
        compiled = jax.jit(fn).lower(*args).compile()
    except Exception as e:  # compile failure is a report, not a crash
        return {
            "error": f"{type(e).__name__}: {str(e)[:160]}",
        }
    ma = compiled.memory_analysis()
    if ma is None:
        return {"unavailable": True}
    return {
        "temp_bytes": int(ma.temp_size_in_bytes),
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "platform": jax.default_backend(),
    }


# ---------------------------------------------------------------------------
# per-config analysis
# ---------------------------------------------------------------------------


def _analyze_config(
    name: str, options, xla_memory: bool, mesh=None
) -> Tuple[dict, List[str]]:
    """One Options config: fused-iteration peak (the headline number —
    that is the program the production host loop dispatches) plus the
    per-stage breakdown. mesh traces the island-sharded production jit
    (explicit in/out shardings; the `sharded` config) — the modeled
    bytes are GLOBAL (the liveness walk sees logical avals), so its gate
    catches whole-program regressions while the per-device footprint is
    that number over the island shards."""
    import jax

    from ..api import _make_iteration_fn

    problems: List[str] = []
    I = options.npopulations
    states, key, cm, X, y, bl, scalars, memo, _ = _abstract_inputs(
        options, I
    )
    it_fn = _make_iteration_fn(options, False, mesh=mesh)
    args = (states, key, cm, X, y, bl, scalars) + (
        (memo,) if memo is not None else ()
    )
    est = live_buffer_peak(jax.make_jaxpr(it_fn)(*args))

    entry = {
        "peak_modeled_bytes": est["peak_bytes"],
        "args_bytes": est["args_bytes"],
        "out_bytes": est["out_bytes"],
        "blowups": est["blowups"],
        "stages": {},
    }
    for stage, (fn, sargs) in build_stage_programs(options).items():
        s_est = live_buffer_peak(jax.make_jaxpr(fn)(*sargs))
        entry["stages"][stage] = {
            "peak_modeled_bytes": s_est["peak_bytes"],
            "blowups": s_est["blowups"],
        }
    if xla_memory:
        entry["xla"] = xla_stage_analysis(it_fn, args)
    return entry, problems


def diff_memory_baseline(
    configs: Dict[str, dict],
    baseline: dict,
    tolerance: float = REGRESSION_TOLERANCE,
) -> Tuple[List[str], List[str]]:
    """(problems, notes): peaks that GREW beyond tolerance fail; peaks
    that shrank beyond it only suggest a refresh (improvements must
    never break CI, but a stale baseline hides the next regression)."""
    problems: List[str] = []
    notes: List[str] = []
    base_configs = baseline.get("configs", {})
    skipped = {
        name for name, entry in configs.items() if "skipped" in entry
    }

    def check(tag: str, want: int, got: int) -> None:
        if want <= 0:
            return
        ratio = got / want
        if ratio > 1.0 + tolerance:
            problems.append(
                f"{tag}: modeled peak grew {want} -> {got} bytes "
                f"(+{(ratio - 1) * 100:.0f}%, tolerance "
                f"{tolerance * 100:.0f}%) — an HBM regression; fix it or "
                "refresh with --update-baseline and justify in the PR"
            )
        elif ratio < 1.0 - tolerance:
            notes.append(
                f"{tag}: modeled peak shrank {want} -> {got} bytes "
                f"({(1 - ratio) * 100:.0f}% better) — refresh the "
                "baseline with --update-baseline to lock it in"
            )

    for name, entry in configs.items():
        if name in skipped:
            continue  # e.g. sharded on a single-device host
        if name not in base_configs:
            problems.append(
                f"memory baseline has no config {name!r} — run with "
                "--update-baseline"
            )
            continue
        base = base_configs[name]
        check(name, base.get("peak_modeled_bytes", 0),
              entry["peak_modeled_bytes"])
        base_stages = base.get("stages", {})
        for stage, s_entry in entry["stages"].items():
            if stage in base_stages:
                check(
                    f"{name}.{stage}",
                    base_stages[stage].get("peak_modeled_bytes", 0),
                    s_entry["peak_modeled_bytes"],
                )
            else:
                problems.append(
                    f"memory baseline has no stage {name}.{stage} — "
                    "refresh with --update-baseline"
                )
        for stage in base_stages:
            if stage not in entry["stages"]:
                problems.append(
                    f"memory baseline stage {name}.{stage} no longer "
                    "produced — its recorded peak would silently stop "
                    "being gated; refresh with --update-baseline"
                )
    for name in base_configs:
        if name not in configs and name not in skipped:
            problems.append(
                f"memory baseline config {name!r} no longer produced — "
                "refresh with --update-baseline"
            )
    return problems, notes


def check_memory(
    update_baseline: bool = False,
    baseline_path: Optional[str] = None,
    configs: Optional[Tuple[Tuple[str, dict], ...]] = None,
    hbm_budget_gb: float = DEFAULT_HBM_BUDGET_GB,
    xla_memory: bool = False,
    tolerance: float = REGRESSION_TOLERANCE,
) -> dict:
    """Run the srmem gate; returns the report dict rendered by
    report.render_memory_text (and embedded in the CLI JSON)."""
    import jax

    from ..models.options import make_options
    from .report import write_baseline_json

    baseline_path = baseline_path or BASELINE_PATH
    matrix = list(configs if configs is not None else _MATRIX)
    budget_bytes = int(hbm_budget_gb * 1e9)
    out_configs: Dict[str, dict] = {}
    problems: List[str] = []
    notes: List[str] = []
    if configs is None:
        # the island-sharded production surface rides the same gate
        # (docs/multichip.md); skipped — never missing — on one device
        from .compile_surface import _SHARDED, _sharded_check_mesh

        matrix = matrix + [_SHARDED]
    for name, extra in matrix:
        options = make_options(**{**_BASE_KWARGS, **extra})
        mesh = None
        if configs is None and name == _SHARDED[0]:
            mesh = _sharded_check_mesh(options)
            if mesh is None:
                out_configs[name] = {
                    "skipped": f"{len(jax.devices())} device(s) — the "
                    "sharded surface needs >= 2"
                }
                continue
        entry, probs = _analyze_config(name, options, xla_memory, mesh)
        out_configs[name] = entry
        problems += probs
        # the resident footprint one dispatch needs: its arguments (the
        # carried IslandState + dataset) plus the modeled live temps
        footprint = entry["args_bytes"] + entry["peak_modeled_bytes"]
        entry["footprint_bytes"] = int(footprint)
        if footprint > budget_bytes:
            worst = entry["blowups"][:1]
            hint = (
                f" (largest blowup: {worst[0]['primitive']} "
                f"{worst[0]['out_bytes']} bytes)" if worst else ""
            )
            problems.append(
                f"{name}: modeled HBM footprint {footprint} bytes "
                f"exceeds the {hbm_budget_gb:g}GB budget "
                f"({budget_bytes} bytes){hint}"
            )

    baseline_checked = baseline_match = False
    if update_baseline:
        from .report import build_baseline_configs

        payload = {
            "schema_version": 1,
            "jax_version": jax.__version__,
            # skipped configs (sharded on one device) keep their prior
            # checked-in entry — see report.build_baseline_configs
            "configs": build_baseline_configs(
                baseline_path, out_configs,
                lambda e: {
                    "peak_modeled_bytes": e["peak_modeled_bytes"],
                    "args_bytes": e["args_bytes"],
                    "stages": {
                        s: {"peak_modeled_bytes":
                            se["peak_modeled_bytes"]}
                        for s, se in e["stages"].items()
                    },
                },
            ),
        }
        write_baseline_json(baseline_path, payload)
    elif os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f)
        baseline_checked = True
        base_problems, base_notes = diff_memory_baseline(
            out_configs, baseline, tolerance
        )
        baseline_match = not base_problems
        problems += base_problems
        notes += base_notes
        if baseline.get("jax_version") != jax.__version__:
            baseline_match = False
            problems.append(
                "memory baseline was written under jax "
                f"{baseline.get('jax_version')} but this is "
                f"{jax.__version__} — refresh with --update-baseline"
            )
    else:
        problems.append(
            f"no memory baseline at {baseline_path} — create one with "
            "--update-baseline"
        )

    return {
        "ok": not problems,
        "problems": problems,
        "notes": notes,
        "configs": out_configs,
        "baseline_checked": baseline_checked,
        "baseline_match": baseline_match,
        "baseline_path": baseline_path,
        "hbm_budget_gb": hbm_budget_gb,
        "tolerance": tolerance,
        "jax_version": jax.__version__,
    }
