"""srlint rule catalog: the compile-surface invariants the hot path relies
on (see docs/static_analysis.md for the full catalog with examples).

Each rule is a named, documented invariant; lint.py owns the AST machinery
that detects violations. Keeping the catalog separate means the rule set is
greppable, the reporter can render help text without importing the checker,
and new rules register in exactly one place.

Why these invariants matter (ISSUE 3 motivation): the engine's hot path is
a handful of jitted closures whose TPU performance hinges on properties no
stock linter checks — no host syncs inside the cycle, no Python control
flow on tracers, deterministic pytree construction, explicit dtypes on
device buffers, and jit wrappers whose static_argnames actually exist.
Kozax (arXiv:2502.03047) and TensorGP (arXiv:2103.07512) both report that
accidental retraces and host round-trips dominate GP-on-accelerator
slowdowns; srlint enforces the invariants mechanically on every PR.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

#: Pragma spelling, e.g. ``x = np.asarray(v)  # srlint: disable=SR001``.
#: Multiple rules: ``# srlint: disable=SR001,SR004``. A justification after
#: the rule list (`` -- static table``) is conventional and encouraged.
PRAGMA_PREFIX = "srlint:"


@dataclasses.dataclass(frozen=True)
class Rule:
    """One checkable invariant."""

    id: str  # "SR001"
    name: str  # short kebab-case slug
    summary: str  # one line for reports
    rationale: str  # why violating it costs performance/correctness


RULES: Dict[str, Rule] = {
    r.id: r
    for r in [
        Rule(
            id="SR001",
            name="host-sync-in-jit",
            summary=(
                "host-synchronizing call (np.asarray/np.array, "
                "jax.device_get, .item(), block_until_ready) reachable "
                "from jitted code"
            ),
            rationale=(
                "Inside a traced function these either fail on tracers or "
                "— worse, when they sneak in via a host round-trip — force "
                "a device sync per call, serializing the dispatch pipeline "
                "the whole engine is built to keep full."
            ),
        ),
        Rule(
            id="SR002",
            name="tracer-control-flow",
            summary=(
                "Python if/while (or bool()/float()/int()) on a value "
                "produced by jax/jnp array math in jit-reachable code"
            ),
            rationale=(
                "Concretizing a tracer raises TracerBoolConversionError "
                "under jit; where the branch happens to run outside jit it "
                "silently forces a blocking device->host transfer and "
                "re-trace per distinct outcome. Use lax.cond/lax.select/"
                "jnp.where, or hoist the decision to a static Option."
            ),
        ),
        Rule(
            id="SR003",
            name="unsorted-dict-iteration",
            summary=(
                "iteration over dict .keys()/.values()/.items() without "
                "sorted() in jit-reachable code"
            ),
            rationale=(
                "Pytree registration and jaxpr construction consume "
                "iteration order; insertion order that differs between "
                "processes (multi-host SPMD) or between calls yields "
                "different jaxprs for the same logical program — silent "
                "recompiles at best, cross-host program divergence at "
                "worst. Wrap the iterable in sorted()."
            ),
        ),
        Rule(
            id="SR004",
            name="implicit-dtype",
            summary=(
                "jnp.zeros/ones/full/empty/arange without an explicit "
                "dtype= in a hot-path module"
            ),
            rationale=(
                "Default dtypes follow jax_enable_x64 and weak-type "
                "promotion: the same line builds f32 buffers in one "
                "process and f64 in another (the float64 search path "
                "flips x64 on), changing avals and forcing recompiles — "
                "or quietly doubling VMEM traffic. Hot-path buffers name "
                "their dtype."
            ),
        ),
        Rule(
            id="SR005",
            name="stale-static-argnames",
            summary=(
                "jax.jit static_argnames references a parameter the "
                "wrapped function does not define"
            ),
            rationale=(
                "jit only validates static_argnames when the name is "
                "actually passed by keyword; a renamed parameter leaves a "
                "stale name that silently stops being static — every call "
                "with a new value then retraces (or traces a value that "
                "was meant to be a Python constant)."
            ),
        ),
        Rule(
            id="SR006",
            name="missing-carry-donation",
            summary=(
                "jit entry whose carry-shaped argument (a parameter that "
                "is rebuilt and returned) is not listed in "
                "donate_argnums/donate_argnames"
            ),
            rationale=(
                "A feed-outputs-back-as-inputs carry (IslandState, RNG "
                "keys, HoF tables) that is not donated keeps TWO copies "
                "of every carried buffer resident in HBM across the "
                "dispatch — at 64x1000 islands that is the difference "
                "between fitting a 16GB v5e and an opaque UNAVAILABLE "
                "OOM. Detection is heuristic (a parameter reassigned in "
                "the body and reachable from a return value); jit calls "
                "forwarding **kwargs are skipped."
            ),
        ),
        Rule(
            id="SR007",
            name="aval-bytes-blowup",
            summary=(
                "broadcast materialization (jnp.broadcast_to/outer/kron/"
                "meshgrid, or tile/repeat with a literal factor >= "
                "8) in jit-reachable code"
            ),
            rationale=(
                "An equation whose output aval is many times the bytes "
                "of its inputs is the static signature of the temp-"
                "buffer blowups that OOM the search at scale (45GB of "
                "temps at 64x1000 on a 16GB part, dominated by one "
                "materialized broadcast in constant optimization). "
                "Prefer keeping the expression in implicitly-broadcast "
                "form (XLA fuses it) or chunking the batch; the srmem "
                "engine (analysis/memory.py) measures the same "
                "signature on the traced jaxpr with real byte counts."
            ),
        ),
        Rule(
            id="SR008",
            name="host-roundtrip-into-jit",
            summary=(
                "host-synchronized value (np.asarray/np.array, "
                "jax.device_get, .item()) passed straight back into a "
                "jitted entry point"
            ),
            rationale=(
                "Pulling a device value to the host and immediately "
                "feeding it back into jitted code pays a blocking "
                "device->host sync, a host->device transfer, AND breaks "
                "XLA's ability to alias/donate the buffer — the value "
                "never needed to leave the device. Keep it as a jax "
                "Array (jit accepts device arrays directly)."
            ),
        ),
        Rule(
            id="SR009",
            name="where-after-nan-producing-op",
            summary=(
                "jnp.where branch applies a NaN-producing op (log/sqrt/"
                "arcsin/power/division, ...) to an unclamped input in "
                "jit-reachable code"
            ),
            rationale=(
                "jnp.where evaluates BOTH branches: selecting on the "
                "output of jnp.log(x) still computes log over the "
                "out-of-domain lanes, so the untaken branch "
                "manufactures NaN/Inf — harmless to the forward value "
                "but poisonous to jax.grad (the cotangent through the "
                "untaken branch multiplies 0 * NaN = NaN, the classic "
                "where-grad pitfall) and to any isfinite-based "
                "containment reading the intermediate. The guard must "
                "clamp the INPUT (jnp.log(jnp.where(x > 0, x, 1.0)), "
                "jnp.maximum, jnp.clip), not select on the poisoned "
                "output — exactly how ops/operators.py's safe_* "
                "operators are written (docs/robustness_numeric.md)."
            ),
        ),
        Rule(
            id="SR010",
            name="orchestration-field-in-jit",
            summary=(
                "read of an orchestration-classified options.<field> "
                "(models/options.py ORCHESTRATION_FIELDS) in "
                "jit-reachable code"
            ),
            rationale=(
                "Orchestration fields are host-side by contract: they "
                "are deliberately ABSENT from Options._graph_key, so "
                "two Options differing only in one share a warm-compile "
                "bucket and one lru-cached factory closure. A "
                "jit-reachable read bakes the FIRST caller's value into "
                "the shared compiled graph — every later config served "
                "from that bucket silently runs with the wrong value "
                "(the exact failure srkey's differential trace detects "
                "end-to-end). Either the read belongs on the host loop, "
                "or the field is misclassified and must move to "
                "GRAPH_FIELDS / TRACED_SCALAR_FIELDS."
            ),
        ),
        Rule(
            id="SR011",
            name="callable-id-in-key",
            summary=(
                "id() of a (possibly-callable) value used inside a "
                "hash/key/fingerprint/memo computation"
            ),
            rationale=(
                "CPython reuses id() after garbage collection: a key "
                "derived from id(fn) can alias two DISTINCT callables "
                "observed at different times — a warm-compile bucket or "
                "memo fingerprint keyed that way serves results "
                "compiled for a different custom loss. Key callables "
                "with models/options.py::callable_token (a "
                "process-lifetime monotonic token pinned by a strong "
                "reference) instead."
            ),
        ),
        Rule(
            id="SR012",
            name="sharding-constraint-in-batched-body",
            summary=(
                "with_sharding_constraint / NamedSharding construction "
                "inside a vmapped or scanned body referencing an outer "
                "mesh object"
            ),
            rationale=(
                "A sharding constraint inside a jax.vmap / lax.scan / "
                "lax.map body names mesh axes against array dims the "
                "BATCHED trace cannot see: the constraint either "
                "crashes on rank mismatch or silently pins the wrong "
                "dims once the batching transform inserts the leading "
                "axis. Placement for a batched program belongs on the "
                "jit's in/out shardings (api.py threads inner_mesh=None "
                "into the tenant-vmapped iteration for exactly this "
                "reason, and srshard's constraint census asserts the "
                "compiled tenant body carries zero "
                "sharding_constraint primitives). Helpers that take the "
                "mesh as a PARAMETER are exempt — their callers decide "
                "whether a mesh exists (parallel/migration.py's "
                "pin_replicated pattern)."
            ),
        ),
    ]
}

#: Modules (package-relative path prefixes) where SR004 applies: the code
#: that builds device buffers on the search hot path. utils/ and scripts
#: are host-side orchestration and excluded by default.
HOT_PATH_PREFIXES: Tuple[str, ...] = (
    "api",
    "ops/",
    "models/",
    "cache/",
    "parallel/",
)


@dataclasses.dataclass(frozen=True)
class Violation:
    """One rule hit, locatable and machine-renderable."""

    rule_id: str
    path: str  # repo-relative file path
    line: int
    col: int
    message: str
    function: Optional[str] = None  # enclosing function qualname
    suppressed: bool = False  # True when a pragma disabled it

    def to_dict(self) -> dict:
        return {
            "rule": self.rule_id,
            "name": RULES[self.rule_id].name,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "function": self.function,
            "message": self.message,
            "suppressed": self.suppressed,
        }


def parse_pragma(comment_text: str) -> Optional[Tuple[str, ...]]:
    """Extract the disabled rule ids from a source line, or None.

    Recognizes ``# srlint: disable=SR001`` and
    ``# srlint: disable=SR001,SR004 -- justification text``.
    """
    idx = comment_text.find(PRAGMA_PREFIX)
    if idx < 0:
        return None
    rest = comment_text[idx + len(PRAGMA_PREFIX):].strip()
    if not rest.startswith("disable="):
        return None
    parts = rest[len("disable="):].split()
    if not parts:  # malformed half-typed pragma: "# srlint: disable="
        return None
    ids = tuple(s.strip() for s in parts[0].split(",") if s.strip())
    return ids or None
