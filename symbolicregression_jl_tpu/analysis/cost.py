"""srcost — analytic per-stage cost model for the search hot path.

ROADMAP #2's exit criterion is a captured roofline fraction, and the
telemetry stack (PR 6/8) measures per-stage WALL time — but nothing said
what the compiled programs *should* cost, so "is the kernel fast?" was
answered by eyeballing trees-rows/s against a hand-picked anchor.
TensorGP (arxiv 2103.07512) shows padded-lockstep waste dominates
tensorized GP and is only visible with per-op FLOP/byte accounting; the
Julia->TPU line (arxiv 1810.09868) uses exactly this kind of XLA-level
accounting to guide the port. This module is the modeled half of that
loop; ``telemetry/profile.py`` joins it with the measured half.

Three layers, all trace-only (``jax.make_jaxpr`` over aval inputs;
nothing executes, so it runs on CPU in CI):

- **per-jaxpr cost estimate** (:func:`jaxpr_cost`): walks a jaxpr with a
  per-primitive element-op weight table (``FLOP_WEIGHTS``) and a bytes-
  moved model (input + output aval bytes per equation), descending into
  sub-jaxprs and multiplying ``scan`` bodies by their trip count. The
  "flops" it reports are *vector element-ops of any numeric dtype* —
  the quantity the VPU issue rate bounds (benchmark/roofline.py uses the
  same convention), not strict IEEE FLOPs. It also reports the
  **padded-waste fraction**: the share of modeled element-ops spent in
  masking/select machinery (``MASK_PRIMITIVES``) — the ops that exist
  purely to keep padded-lockstep execution correct (PAD-slot muxes,
  domain masks, validity selects), the TensorGP waste signature made
  machine-readable.
- **per-stage attribution** (:func:`stage_costs`): the same seven-stage
  decomposition ``analysis/memory.py::build_stage_programs`` traces
  (init / cycle / mutate / eval / simplify / optimize / merge_migrate),
  so modeled cost joins measured spans and srmem HBM attribution on one
  stage vocabulary.
- **baseline gate** (:func:`check_cost`): per-config flops/bytes diffed
  against the checked-in ``cost_baseline.json`` over the compile_surface
  Options matrix — CI fails on a >10% modeled-cost regression, exactly
  like the compile/memory baselines. Shrinking costs never fail; they
  surface as refresh notes.

The model ignores fusion, CSE, and rematerialization: its VALUE drifts
from what XLA executes, but the RATIO between two versions of the same
program tracks real regressions — which is what the gate needs — and
the magnitude is a sound upper-ish anchor for the roofline join.

CLI: ``python -m symbolicregression_jl_tpu.analysis --only cost
[--update-baseline]`` (docs/static_analysis.md, docs/observability.md).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from .compile_surface import _BASE_KWARGS, _MATRIX, _NFEAT, _NROWS
from .memory import aval_bytes, build_stage_programs

BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "cost_baseline.json"
)

#: Modeled-cost growth beyond this fraction of the baseline fails CI.
REGRESSION_TOLERANCE = 0.10

#: element-op weight per output element (or per input element for
#: reductions). Aligned with benchmark/roofline.py's VPU-issue cost
#: table: arithmetic 1, div/sqrt 4, transcendentals 8, pow 12. Unlisted
#: primitives: 1 if they produce numeric output (the conservative
#: "it issues at least one vector op" default), except the pure data-
#: movement set below, which models as bytes only.
FLOP_WEIGHTS: Dict[str, float] = {
    "div": 4.0, "sqrt": 4.0, "rsqrt": 4.0, "cbrt": 8.0,
    "exp": 8.0, "exp2": 8.0, "expm1": 9.0, "log": 8.0, "log1p": 9.0,
    "sin": 8.0, "cos": 8.0, "tan": 10.0, "tanh": 9.0,
    "asin": 10.0, "acos": 10.0, "atan": 10.0, "atan2": 12.0,
    "sinh": 10.0, "cosh": 10.0, "asinh": 12.0, "acosh": 12.0,
    "atanh": 12.0, "erf": 10.0, "erfc": 10.0, "erf_inv": 12.0,
    "lgamma": 16.0, "digamma": 16.0, "pow": 12.0, "integer_pow": 4.0,
    "rem": 6.0, "logistic": 9.0, "cumsum": 1.0, "cumlogsumexp": 9.0,
    # counter-based RNG: a multi-round integer hash per emitted element
    "threefry2x32": 16.0, "random_bits": 16.0, "random_seed": 1.0,
    "random_wrap": 0.0, "random_fold_in": 16.0,
    "select_n": 1.0, "clamp": 2.0, "sort": 8.0,  # ~log2(n) passes
}

#: primitives that move/reshape data without issuing vector math: they
#: contribute bytes, never element-ops.
DATA_MOVEMENT = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "squeeze", "concatenate",
    "slice", "dynamic_slice", "dynamic_update_slice", "gather",
    "scatter", "rev", "pad", "iota", "convert_element_type",
    "bitcast_convert_type", "copy", "device_put", "stop_gradient",
    "split", "expand_dims", "random_wrap",
})

#: the padded-lockstep machinery: masks, compares, selects, and pads
#: that exist to keep every tree/slot/row in lockstep over PAD slots
#: and domain-invalid lanes. Their share of total modeled element-ops
#: is the padded-waste fraction (the TensorGP waste signature).
MASK_PRIMITIVES = frozenset({
    "select_n", "pad", "clamp", "is_finite",
    "eq", "ne", "lt", "le", "gt", "ge",
    "and", "or", "not", "xor",
})

#: reductions price by INPUT element count (the work is over the
#: reduced operand, not the small output).
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
    "reduce_precision", "cumsum", "cummax", "cummin", "cumprod",
    "cumlogsumexp", "sort",
})

_TOP_PRIMS = 8


def _aval_elems(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n


def _sub_jaxprs(params):
    import jax.core as jcore

    for v in params.values():
        if isinstance(v, (jcore.Jaxpr, jcore.ClosedJaxpr)):
            yield v
        elif isinstance(v, (list, tuple)):
            for item in v:
                if isinstance(item, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                    yield item


def _dot_general_flops(eqn) -> float:
    """2*M*N*K multiply-accumulates of a dot_general (batch dims fold
    into M)."""
    out_elems = sum(_aval_elems(v.aval) for v in eqn.outvars)
    dnums = eqn.params.get("dimension_numbers")
    contract = dnums[0][0] if dnums else ()
    lhs = eqn.invars[0].aval
    k = 1
    for d in contract:
        k *= int(lhs.shape[d])
    return 2.0 * out_elems * k


def eqn_cost(eqn) -> Tuple[float, int, float]:
    """(element_ops, bytes_moved, mask_element_ops) of ONE equation,
    sub-jaxprs excluded (the walker descends into those itself)."""
    import jax.core as jcore

    name = eqn.primitive.name
    in_b = sum(
        aval_bytes(v.aval) for v in eqn.invars
        if isinstance(v, jcore.Var) or hasattr(v, "aval")
    )
    out_b = sum(aval_bytes(v.aval) for v in eqn.outvars)
    bytes_moved = in_b + out_b
    if any(_sub_jaxprs(eqn.params)):
        # control-flow shells (scan/while/cond/pjit): all cost lives in
        # the body the walker descends into
        return 0.0, bytes_moved, 0.0
    if name in DATA_MOVEMENT:
        return 0.0, bytes_moved, 0.0
    if name == "dot_general":
        return _dot_general_flops(eqn), bytes_moved, 0.0
    if name in _REDUCE_PRIMS or name.startswith("reduce_"):
        elems = sum(_aval_elems(v.aval) for v in eqn.invars
                    if hasattr(v, "aval"))
        weight = FLOP_WEIGHTS.get(name, 1.0)
    else:
        elems = sum(_aval_elems(v.aval) for v in eqn.outvars)
        weight = FLOP_WEIGHTS.get(name, 1.0)
    flops = weight * elems
    mask = flops if name in MASK_PRIMITIVES else 0.0
    return flops, bytes_moved, mask


def jaxpr_cost(jaxpr) -> dict:
    """Modeled cost of one (Closed)Jaxpr.

    Returns ``{"flops", "bytes", "mask_flops", "padded_waste_fraction",
    "by_primitive", "while_loops"}``. ``scan`` bodies multiply by their
    ``length`` trip count; ``while`` bodies (trip count unknowable from
    the jaxpr) count ONCE and are tallied in ``while_loops`` — the
    modeled numbers are a lower bound wherever that tally is nonzero
    (the BFGS optimizer's bounded iteration loops are the main source).
    ``cond`` branches take the most expensive branch — by element-ops,
    bytes as the tie-break (the lockstep engine usually executes both
    sides' select form anyway)."""
    by_prim: Dict[str, float] = {}
    state = {"while": 0}

    def walk(jx, mult: float) -> Tuple[float, float, float]:
        """Totals of one (sub-)jaxpr, already scaled by `mult` (the
        product of enclosing scan trip counts)."""
        if hasattr(jx, "jaxpr"):
            jx = jx.jaxpr
        flops = bytes_moved = mask = 0.0
        for eqn in jx.eqns:
            name = eqn.primitive.name
            subs = list(_sub_jaxprs(eqn.params))
            if not subs:
                f, b, m = eqn_cost(eqn)
                if f:
                    by_prim[name] = by_prim.get(name, 0.0) + f * mult
                flops += f * mult
                bytes_moved += b * mult
                mask += m * mult
                continue
            # control-flow shells: the shell itself moves its operand
            # bytes once per execution; the body cost multiplies by the
            # trip count where the jaxpr states one (scan)
            _, shell_b, _ = eqn_cost(eqn)
            bytes_moved += shell_b * mult
            if name == "scan":
                trips = float(eqn.params.get("length", 1))
                sf, sb, sm = walk(subs[0], mult * trips)
                flops += sf
                bytes_moved += sb
                mask += sm
            elif name == "while":
                # trip count unknowable from the jaxpr: cond + body
                # count once (a lower bound, tallied in while_loops)
                state["while"] += 1
                for sub in subs:
                    sf, sb, sm = walk(sub, mult)
                    flops += sf
                    bytes_moved += sb
                    mask += sm
            elif name == "cond":
                # most expensive branch by element-ops, bytes as the
                # tie-break — so a cond whose branches are pure data
                # movement (every sf == 0) still contributes its
                # heaviest branch's bytes instead of dropping them
                best = (0.0, 0.0, 0.0)
                for sub in subs:
                    sf, sb, sm = walk(sub, mult)
                    if (sf, sb) > (best[0], best[1]):
                        best = (sf, sb, sm)
                flops += best[0]
                bytes_moved += best[1]
                mask += best[2]
            else:  # pjit / custom_* / remat / closed_call: once
                for sub in subs:
                    sf, sb, sm = walk(sub, mult)
                    flops += sf
                    bytes_moved += sb
                    mask += sm
        return flops, bytes_moved, mask

    flops, bytes_moved, mask = walk(jaxpr, 1.0)
    top = dict(sorted(
        by_prim.items(), key=lambda kv: -kv[1]
    )[:_TOP_PRIMS])
    # io_bytes: the program's top-level inputs + outputs — what a
    # PERFECTLY fused execution must still move through HBM. `bytes`
    # above counts every intermediate (an un-fused upper bound); the
    # roofline join in telemetry/profile.py prices arithmetic intensity
    # off io_bytes so a well-fused stage is not misread as memory-bound.
    inner = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    io_bytes = sum(
        aval_bytes(v.aval)
        for v in list(inner.invars) + list(inner.constvars)
        + list(inner.outvars)
        if hasattr(v, "aval")
    )
    return {
        "flops": float(flops),
        "bytes": float(bytes_moved),
        "io_bytes": float(io_bytes),
        "mask_flops": float(mask),
        "padded_waste_fraction": (
            round(mask / flops, 6) if flops > 0 else 0.0
        ),
        "by_primitive": {k: float(v) for k, v in top.items()},
        "while_loops": state["while"],
    }


# ---------------------------------------------------------------------------
# stage attribution
# ---------------------------------------------------------------------------


def stage_costs(
    options, nfeatures: int = _NFEAT, nrows: int = _NROWS
) -> Dict[str, dict]:
    """Modeled cost per production stage — the seven-stage decomposition
    ``analysis.memory.build_stage_programs`` traces, at the given data
    shape, so the numbers join measured spans (telemetry.spans.STAGES)
    and srmem attribution on one vocabulary. Trace-only; the weighted
    path is modeled unweighted (weights add one multiply per row —
    noise at this model's resolution)."""
    import jax

    out: Dict[str, dict] = {}
    for stage, (fn, sargs) in build_stage_programs(
        options, nfeatures, nrows
    ).items():
        out[stage] = jaxpr_cost(jax.make_jaxpr(fn)(*sargs))
    return out


def iteration_cost(options) -> dict:
    """Modeled cost of the fused production iteration program (the
    headline per-config number the baseline gates)."""
    import jax

    from ..api import _make_iteration_fn
    from .compile_surface import _abstract_inputs

    I = options.npopulations
    states, key, cm, X, y, bl, scalars, memo, _ = _abstract_inputs(
        options, I
    )
    it_fn = _make_iteration_fn(options, False)
    args = (states, key, cm, X, y, bl, scalars) + (
        (memo,) if memo is not None else ()
    )
    return jaxpr_cost(jax.make_jaxpr(it_fn)(*args))


# ---------------------------------------------------------------------------
# baseline gate
# ---------------------------------------------------------------------------


def diff_cost_baseline(
    configs: Dict[str, dict],
    baseline: dict,
    tolerance: float = REGRESSION_TOLERANCE,
) -> Tuple[List[str], List[str]]:
    """(problems, notes): modeled flops/bytes that GREW beyond tolerance
    fail; shrinking beyond it only suggests a refresh (improvements
    never break CI, but a stale baseline hides the next regression)."""
    problems: List[str] = []
    notes: List[str] = []
    base_configs = baseline.get("configs", {})

    def check(tag: str, metric: str, want: float, got: float) -> None:
        if want <= 0:
            return
        ratio = got / want
        if ratio > 1.0 + tolerance:
            problems.append(
                f"{tag}: modeled {metric} grew {want:.4g} -> {got:.4g} "
                f"(+{(ratio - 1) * 100:.0f}%, tolerance "
                f"{tolerance * 100:.0f}%) — a per-dispatch cost "
                "regression; fix it or refresh with --update-baseline "
                "and justify in the PR"
            )
        elif ratio < 1.0 - tolerance:
            notes.append(
                f"{tag}: modeled {metric} shrank {want:.4g} -> {got:.4g} "
                f"({(1 - ratio) * 100:.0f}% better) — refresh the "
                "baseline with --update-baseline to lock it in"
            )

    for name, entry in configs.items():
        if name not in base_configs:
            problems.append(
                f"cost baseline has no config {name!r} — run with "
                "--update-baseline"
            )
            continue
        base = base_configs[name]
        check(name, "flops", base.get("flops", 0), entry["flops"])
        check(name, "bytes", base.get("bytes", 0), entry["bytes"])
        base_stages = base.get("stages", {})
        for stage, s_entry in entry["stages"].items():
            if stage in base_stages:
                check(f"{name}.{stage}", "flops",
                      base_stages[stage].get("flops", 0),
                      s_entry["flops"])
                check(f"{name}.{stage}", "bytes",
                      base_stages[stage].get("bytes", 0),
                      s_entry["bytes"])
            else:
                problems.append(
                    f"cost baseline has no stage {name}.{stage} — "
                    "refresh with --update-baseline"
                )
        for stage in base_stages:
            if stage not in entry["stages"]:
                problems.append(
                    f"cost baseline stage {name}.{stage} no longer "
                    "produced — its recorded cost would silently stop "
                    "being gated; refresh with --update-baseline"
                )
    for name in base_configs:
        if name not in configs:
            problems.append(
                f"cost baseline config {name!r} no longer produced — "
                "refresh with --update-baseline"
            )
    return problems, notes


def check_cost(
    update_baseline: bool = False,
    baseline_path: Optional[str] = None,
    configs: Optional[Tuple[Tuple[str, dict], ...]] = None,
    tolerance: float = REGRESSION_TOLERANCE,
) -> dict:
    """Run the srcost gate over the compile_surface Options matrix;
    returns the report dict rendered by report.render_cost_text (and
    embedded in the CLI JSON)."""
    import jax

    from ..models.options import make_options
    from .report import write_baseline_json

    baseline_path = baseline_path or BASELINE_PATH
    matrix = list(configs if configs is not None else _MATRIX)
    out_configs: Dict[str, dict] = {}
    problems: List[str] = []
    notes: List[str] = []
    for name, extra in matrix:
        options = make_options(**{**_BASE_KWARGS, **extra})
        est = iteration_cost(options)
        entry = {
            "flops": est["flops"],
            "bytes": est["bytes"],
            "padded_waste_fraction": est["padded_waste_fraction"],
            "by_primitive": est["by_primitive"],
            "while_loops": est["while_loops"],
            "stages": {},
        }
        for stage, s_est in stage_costs(options).items():
            entry["stages"][stage] = {
                "flops": s_est["flops"],
                "bytes": s_est["bytes"],
                "padded_waste_fraction": s_est["padded_waste_fraction"],
            }
        out_configs[name] = entry

    baseline_checked = baseline_match = False
    if update_baseline:
        payload = {
            "schema_version": 1,
            "jax_version": jax.__version__,
            "configs": {
                name: {
                    "flops": e["flops"],
                    "bytes": e["bytes"],
                    "padded_waste_fraction": e["padded_waste_fraction"],
                    "stages": {
                        s: {
                            "flops": se["flops"],
                            "bytes": se["bytes"],
                            "padded_waste_fraction":
                                se["padded_waste_fraction"],
                        }
                        for s, se in e["stages"].items()
                    },
                }
                for name, e in out_configs.items()
            },
        }
        write_baseline_json(baseline_path, payload)
    elif os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f)
        baseline_checked = True
        base_problems, base_notes = diff_cost_baseline(
            out_configs, baseline, tolerance
        )
        baseline_match = not base_problems
        problems += base_problems
        notes += base_notes
        if baseline.get("jax_version") != jax.__version__:
            baseline_match = False
            problems.append(
                "cost baseline was written under jax "
                f"{baseline.get('jax_version')} but this is "
                f"{jax.__version__} — refresh with --update-baseline"
            )
    else:
        problems.append(
            f"no cost baseline at {baseline_path} — create one with "
            "--update-baseline"
        )

    return {
        "ok": not problems,
        "problems": problems,
        "notes": notes,
        "configs": out_configs,
        "baseline_checked": baseline_checked,
        "baseline_match": baseline_match,
        "baseline_path": baseline_path,
        "tolerance": tolerance,
        "jax_version": jax.__version__,
    }
