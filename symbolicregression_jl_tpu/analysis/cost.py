"""srcost — analytic per-stage cost model for the search hot path.

ROADMAP #2's exit criterion is a captured roofline fraction, and the
telemetry stack (PR 6/8) measures per-stage WALL time — but nothing said
what the compiled programs *should* cost, so "is the kernel fast?" was
answered by eyeballing trees-rows/s against a hand-picked anchor.
TensorGP (arxiv 2103.07512) shows padded-lockstep waste dominates
tensorized GP and is only visible with per-op FLOP/byte accounting; the
Julia->TPU line (arxiv 1810.09868) uses exactly this kind of XLA-level
accounting to guide the port. This module is the modeled half of that
loop; ``telemetry/profile.py`` joins it with the measured half.

Three layers, all trace-only (``jax.make_jaxpr`` over aval inputs;
nothing executes, so it runs on CPU in CI):

- **per-jaxpr cost estimate** (:func:`jaxpr_cost`): walks a jaxpr with a
  per-primitive element-op weight table (``FLOP_WEIGHTS``) and a bytes-
  moved model (input + output aval bytes per equation), descending into
  sub-jaxprs and multiplying ``scan`` bodies by their trip count. The
  "flops" it reports are *vector element-ops of any numeric dtype* —
  the quantity the VPU issue rate bounds (benchmark/roofline.py uses the
  same convention), not strict IEEE FLOPs. It also reports the
  **padded-waste fraction**: the share of modeled element-ops spent in
  masking/select machinery (``MASK_PRIMITIVES``) — the ops that exist
  purely to keep padded-lockstep execution correct (PAD-slot muxes,
  domain masks, validity selects), the TensorGP waste signature made
  machine-readable.
- **per-stage attribution** (:func:`stage_costs`): the same seven-stage
  decomposition ``analysis/memory.py::build_stage_programs`` traces
  (init / cycle / mutate / eval / simplify / optimize / merge_migrate),
  so modeled cost joins measured spans and srmem HBM attribution on one
  stage vocabulary.
- **baseline gate** (:func:`check_cost`): per-config flops/bytes diffed
  against the checked-in ``cost_baseline.json`` over the compile_surface
  Options matrix — CI fails on a >10% modeled-cost regression, exactly
  like the compile/memory baselines. Shrinking costs never fail; they
  surface as refresh notes.

The model ignores fusion, CSE, and rematerialization: its VALUE drifts
from what XLA executes, but the RATIO between two versions of the same
program tracks real regressions — which is what the gate needs — and
the magnitude is a sound upper-ish anchor for the roofline join.

CLI: ``python -m symbolicregression_jl_tpu.analysis --only cost
[--update-baseline]`` (docs/static_analysis.md, docs/observability.md).
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional, Tuple

from .compile_surface import _BASE_KWARGS, _MATRIX, _NFEAT, _NROWS
from .memory import aval_bytes, build_stage_programs

BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "cost_baseline.json"
)

#: Modeled-cost growth beyond this fraction of the baseline fails CI.
REGRESSION_TOLERANCE = 0.10

#: element-op weight per output element (or per input element for
#: reductions). Aligned with benchmark/roofline.py's VPU-issue cost
#: table: arithmetic 1, div/sqrt 4, transcendentals 8, pow 12. Unlisted
#: primitives: 1 if they produce numeric output (the conservative
#: "it issues at least one vector op" default), except the pure data-
#: movement set below, which models as bytes only.
FLOP_WEIGHTS: Dict[str, float] = {
    "div": 4.0, "sqrt": 4.0, "rsqrt": 4.0, "cbrt": 8.0,
    "exp": 8.0, "exp2": 8.0, "expm1": 9.0, "log": 8.0, "log1p": 9.0,
    "sin": 8.0, "cos": 8.0, "tan": 10.0, "tanh": 9.0,
    "asin": 10.0, "acos": 10.0, "atan": 10.0, "atan2": 12.0,
    "sinh": 10.0, "cosh": 10.0, "asinh": 12.0, "acosh": 12.0,
    "atanh": 12.0, "erf": 10.0, "erfc": 10.0, "erf_inv": 12.0,
    "lgamma": 16.0, "digamma": 16.0, "pow": 12.0, "integer_pow": 4.0,
    "rem": 6.0, "logistic": 9.0, "cumsum": 1.0, "cumlogsumexp": 9.0,
    # counter-based RNG: a multi-round integer hash per emitted element
    "threefry2x32": 16.0, "random_bits": 16.0, "random_seed": 1.0,
    "random_wrap": 0.0, "random_fold_in": 16.0,
    "select_n": 1.0, "clamp": 2.0, "sort": 8.0,  # ~log2(n) passes
}

#: primitives that move/reshape data without issuing vector math: they
#: contribute bytes, never element-ops.
DATA_MOVEMENT = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "squeeze", "concatenate",
    "slice", "dynamic_slice", "dynamic_update_slice", "gather",
    "scatter", "rev", "pad", "iota", "convert_element_type",
    "bitcast_convert_type", "copy", "device_put", "stop_gradient",
    "split", "expand_dims", "random_wrap",
})

#: the padded-lockstep machinery: masks, compares, selects, and pads
#: that exist to keep every tree/slot/row in lockstep over PAD slots
#: and domain-invalid lanes. Their share of total modeled element-ops
#: is the padded-waste fraction (the TensorGP waste signature).
MASK_PRIMITIVES = frozenset({
    "select_n", "pad", "clamp", "is_finite",
    "eq", "ne", "lt", "le", "gt", "ge",
    "and", "or", "not", "xor",
})

#: reductions price by INPUT element count (the work is over the
#: reduced operand, not the small output).
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
    "reduce_precision", "cumsum", "cummax", "cummin", "cumprod",
    "cumlogsumexp", "sort",
})

_TOP_PRIMS = 8


def _aval_elems(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n


def _sub_jaxprs(params):
    import jax.core as jcore

    for v in params.values():
        if isinstance(v, (jcore.Jaxpr, jcore.ClosedJaxpr)):
            yield v
        elif isinstance(v, (list, tuple)):
            for item in v:
                if isinstance(item, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                    yield item


def _dot_general_flops(eqn) -> float:
    """2*M*N*K multiply-accumulates of a dot_general (batch dims fold
    into M)."""
    out_elems = sum(_aval_elems(v.aval) for v in eqn.outvars)
    dnums = eqn.params.get("dimension_numbers")
    contract = dnums[0][0] if dnums else ()
    lhs = eqn.invars[0].aval
    k = 1
    for d in contract:
        k *= int(lhs.shape[d])
    return 2.0 * out_elems * k


def eqn_cost(eqn) -> Tuple[float, int, float]:
    """(element_ops, bytes_moved, mask_element_ops) of ONE equation,
    sub-jaxprs excluded (the walker descends into those itself)."""
    import jax.core as jcore

    name = eqn.primitive.name
    in_b = sum(
        aval_bytes(v.aval) for v in eqn.invars
        if isinstance(v, jcore.Var) or hasattr(v, "aval")
    )
    out_b = sum(aval_bytes(v.aval) for v in eqn.outvars)
    bytes_moved = in_b + out_b
    if any(_sub_jaxprs(eqn.params)):
        # control-flow shells (scan/while/cond/pjit): all cost lives in
        # the body the walker descends into
        return 0.0, bytes_moved, 0.0
    if name in DATA_MOVEMENT:
        return 0.0, bytes_moved, 0.0
    if name == "dot_general":
        return _dot_general_flops(eqn), bytes_moved, 0.0
    if name in _REDUCE_PRIMS or name.startswith("reduce_"):
        elems = sum(_aval_elems(v.aval) for v in eqn.invars
                    if hasattr(v, "aval"))
        weight = FLOP_WEIGHTS.get(name, 1.0)
    else:
        elems = sum(_aval_elems(v.aval) for v in eqn.outvars)
        weight = FLOP_WEIGHTS.get(name, 1.0)
    flops = weight * elems
    mask = flops if name in MASK_PRIMITIVES else 0.0
    return flops, bytes_moved, mask


def jaxpr_cost(jaxpr) -> dict:
    """Modeled cost of one (Closed)Jaxpr.

    Returns ``{"flops", "bytes", "mask_flops", "padded_waste_fraction",
    "by_primitive", "while_loops"}``. ``scan`` bodies multiply by their
    ``length`` trip count; ``while`` bodies (trip count unknowable from
    the jaxpr) count ONCE and are tallied in ``while_loops`` — the
    modeled numbers are a lower bound wherever that tally is nonzero
    (the BFGS optimizer's bounded iteration loops are the main source).
    ``cond`` branches take the most expensive branch — by element-ops,
    bytes as the tie-break (the lockstep engine usually executes both
    sides' select form anyway)."""
    by_prim: Dict[str, float] = {}
    state = {"while": 0}

    def walk(jx, mult: float) -> Tuple[float, float, float]:
        """Totals of one (sub-)jaxpr, already scaled by `mult` (the
        product of enclosing scan trip counts)."""
        if hasattr(jx, "jaxpr"):
            jx = jx.jaxpr
        flops = bytes_moved = mask = 0.0
        for eqn in jx.eqns:
            name = eqn.primitive.name
            subs = list(_sub_jaxprs(eqn.params))
            if not subs:
                f, b, m = eqn_cost(eqn)
                if f:
                    by_prim[name] = by_prim.get(name, 0.0) + f * mult
                flops += f * mult
                bytes_moved += b * mult
                mask += m * mult
                continue
            # control-flow shells: the shell itself moves its operand
            # bytes once per execution; the body cost multiplies by the
            # trip count where the jaxpr states one (scan)
            _, shell_b, _ = eqn_cost(eqn)
            bytes_moved += shell_b * mult
            if name == "scan":
                trips = float(eqn.params.get("length", 1))
                sf, sb, sm = walk(subs[0], mult * trips)
                flops += sf
                bytes_moved += sb
                mask += sm
            elif name == "while":
                # trip count unknowable from the jaxpr: cond + body
                # count once (a lower bound, tallied in while_loops)
                state["while"] += 1
                for sub in subs:
                    sf, sb, sm = walk(sub, mult)
                    flops += sf
                    bytes_moved += sb
                    mask += sm
            elif name == "cond":
                # most expensive branch by element-ops, bytes as the
                # tie-break — so a cond whose branches are pure data
                # movement (every sf == 0) still contributes its
                # heaviest branch's bytes instead of dropping them
                best = (0.0, 0.0, 0.0)
                for sub in subs:
                    sf, sb, sm = walk(sub, mult)
                    if (sf, sb) > (best[0], best[1]):
                        best = (sf, sb, sm)
                flops += best[0]
                bytes_moved += best[1]
                mask += best[2]
            else:  # pjit / custom_* / remat / closed_call: once
                for sub in subs:
                    sf, sb, sm = walk(sub, mult)
                    flops += sf
                    bytes_moved += sb
                    mask += sm
        return flops, bytes_moved, mask

    flops, bytes_moved, mask = walk(jaxpr, 1.0)
    top = dict(sorted(
        by_prim.items(), key=lambda kv: -kv[1]
    )[:_TOP_PRIMS])
    # io_bytes: the program's top-level inputs + outputs — what a
    # PERFECTLY fused execution must still move through HBM. `bytes`
    # above counts every intermediate (an un-fused upper bound); the
    # roofline join in telemetry/profile.py prices arithmetic intensity
    # off io_bytes so a well-fused stage is not misread as memory-bound.
    inner = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    io_bytes = sum(
        aval_bytes(v.aval)
        for v in list(inner.invars) + list(inner.constvars)
        + list(inner.outvars)
        if hasattr(v, "aval")
    )
    return {
        "flops": float(flops),
        "bytes": float(bytes_moved),
        "io_bytes": float(io_bytes),
        "mask_flops": float(mask),
        "padded_waste_fraction": (
            round(mask / flops, 6) if flops > 0 else 0.0
        ),
        "by_primitive": {k: float(v) for k, v in top.items()},
        "while_loops": state["while"],
    }


# ---------------------------------------------------------------------------
# stage attribution
# ---------------------------------------------------------------------------


def stage_costs(
    options, nfeatures: int = _NFEAT, nrows: int = _NROWS
) -> Dict[str, dict]:
    """Modeled cost per production stage — the seven-stage decomposition
    ``analysis.memory.build_stage_programs`` traces, at the given data
    shape, so the numbers join measured spans (telemetry.spans.STAGES)
    and srmem attribution on one vocabulary. Trace-only; the weighted
    path is modeled unweighted (weights add one multiply per row —
    noise at this model's resolution)."""
    import jax

    out: Dict[str, dict] = {}
    for stage, (fn, sargs) in build_stage_programs(
        options, nfeatures, nrows
    ).items():
        out[stage] = jaxpr_cost(jax.make_jaxpr(fn)(*sargs))
    return out


def iteration_cost(options) -> dict:
    """Modeled cost of the fused production iteration program (the
    headline per-config number the baseline gates)."""
    import jax

    from ..api import _make_iteration_fn
    from .compile_surface import _abstract_inputs

    I = options.npopulations
    states, key, cm, X, y, bl, scalars, memo, _ = _abstract_inputs(
        options, I
    )
    it_fn = _make_iteration_fn(options, False)
    args = (states, key, cm, X, y, bl, scalars) + (
        (memo,) if memo is not None else ()
    )
    return jaxpr_cost(jax.make_jaxpr(it_fn)(*args))


# ---------------------------------------------------------------------------
# Pallas kernel config model (the autotuner's pre-measurement ranking)
# ---------------------------------------------------------------------------

#: element-op weight of one kernel slot's CANDIDATE computation per
#: operator name — FLOP_WEIGHTS vocabulary keyed by the operator-set
#: spelling instead of the lax primitive name.
_OP_NAME_WEIGHTS: Dict[str, float] = {
    "+": 1.0, "-": 1.0, "*": 1.0, "/": 4.0, "^": 12.0, "pow": 12.0,
    "min": 1.0, "max": 1.0, "mod": 6.0, "atan2": 12.0,
    "neg": 1.0, "abs": 1.0, "sign": 1.0, "inv": 4.0, "sqrt": 4.0,
    "cbrt": 8.0, "square": 1.0, "cube": 2.0, "exp": 8.0, "log": 8.0,
    "log2": 8.0, "log10": 8.0, "log1p": 9.0, "sin": 8.0, "cos": 8.0,
    "tan": 10.0, "sinh": 10.0, "cosh": 10.0, "tanh": 9.0, "asin": 10.0,
    "acos": 10.0, "atan": 10.0, "round": 1.0, "floor": 1.0,
    "ceil": 1.0, "relu": 1.0, "logistic": 9.0, "erf": 10.0,
    "gamma": 16.0,
}


def _pallas_slot_flops(operators, dispatch: str) -> float:
    """Modeled vector element-ops of ONE kernel slot-visit per row lane.

    The branchless kernel computes EVERY candidate on each slot (leaf
    mux + all unary + all binary + domain masks) and selects the
    opcode's result — "mux" pays a log2-deep select tree, "chain" a
    serial per-candidate select chain (same op count, longer critical
    path; modeled with a small serialization surcharge so the ranking
    prefers mux at equal measure, matching the on-chip A/B)."""
    names = list(operators.unary_names) + list(operators.binary_names)
    cand = 2.0  # leaf candidates: const splat + X gather-select
    cand += sum(_OP_NAME_WEIGHTS.get(n, 2.0) for n in names)
    n_ops = 3 + len(names)  # PAD/CONST/VAR + operators
    if dispatch == "chain":
        sel = float(n_ops) * 1.25
    else:
        sel = float(max(1, math.ceil(math.log2(n_ops))))
    mask = 2.0  # validity + poison lockstep masks per slot
    return cand + sel + mask


def pallas_config_cost(
    lengths, config: dict, nrows: int, nfeat: int, operators
) -> dict:
    """Modeled flops/bytes/padded-waste of ONE Pallas kernel
    configuration over a concrete length histogram — pure host
    arithmetic (no tracing), shared by the autotuner's pre-measurement
    ranking (tune/tuner.py) and the bucketed-kernel baseline entries.

    Mirrors the wrapper's actual geometry (ops/pallas_eval.py): trees
    sort length-major, `ladder` splits at the SAME positional
    boundaries the bucketed drivers use, each bucket re-clamps t_block
    and pads to its own grid, and every tree_unroll interleave group
    runs ceil(group_max/4) dynamic 4-slot steps — so bucketing models
    its REAL effect (smaller tail-bucket tree padding, unchanged
    slot work) rather than an assumed slot-truncation win. `fused`
    drops the (T, nrows) value write-back for per-tree scalars."""
    from ..models.fitness import _bucket_bounds
    from ..ops.pallas_eval import _SLOT_UNROLL, _round_up

    t_block = int(config.get("t_block", 256))
    r_block = int(config.get("r_block", 1024))
    dispatch = config.get("dispatch", "mux")
    tree_unroll = int(config.get("tree_unroll", 8))
    ladder = tuple(config.get("ladder", ()) or ())
    fused = bool(config.get("fused", False))

    lens = sorted(int(x) for x in lengths)
    T = len(lens)
    max_len = max(lens) if lens else 0
    L = _round_up(max(max_len, 1), _SLOT_UNROLL)
    r_block = min(r_block, _round_up(max(nrows, 1), 128))
    R_pad = _round_up(nrows, r_block)

    bounds = _bucket_bounds(T, ladder) if ladder else (0, T)
    executed = 0  # slot-visits actually advanced by the group loops
    grid_i = 0  # tree-block grid steps across all buckets
    T_pad_total = 0
    table_bytes = 0.0
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        if hi <= lo:
            continue
        b_lens = lens[lo:hi]
        Tb = len(b_lens)
        tb = min(t_block, _round_up(max(Tb, 8), tree_unroll))
        T_pad = _round_up(Tb, tb)
        b_lens = b_lens + [0] * (T_pad - Tb)
        for g in range(0, T_pad, tree_unroll):
            gmax = max(b_lens[g:g + tree_unroll])
            steps = -(-gmax // _SLOT_UNROLL)  # ceil
            executed += steps * _SLOT_UNROLL * tree_unroll
        grid_i += T_pad // tb
        T_pad_total += T_pad
        # 4 i32 scalar tables + cval, (L, T_pad) each, refetched per
        # tree block (SMEM-resident across the row-tile sweep)
        table_bytes += 5 * L * T_pad * 4

    useful = sum(lens)
    slot_flops = _pallas_slot_flops(operators, dispatch)
    flops = float(executed) * slot_flops * float(R_pad)
    # X refetched once per (tree block, row tile) grid cell
    bytes_moved = table_bytes + grid_i * nfeat * R_pad * 4.0
    if fused:
        bytes_moved += T_pad_total * 8.0  # per-tree loss + poison
        bytes_moved += grid_i * R_pad * 4.0  # y target per tree block
        flops += float(T_pad_total) * R_pad * 3.0  # elem + mask + sum
    else:
        bytes_moved += float(T_pad_total) * R_pad * 4.0  # value out
    lane_exec = float(executed) * R_pad
    lane_useful = float(useful) * nrows
    return {
        "flops": flops,
        "bytes": bytes_moved,
        "padded_waste_fraction": (
            round(1.0 - lane_useful / lane_exec, 6) if lane_exec else 0.0
        ),
        "executed_slots": executed,
        "useful_slots": useful,
    }


def rank_kernel_configs(
    configs, lengths, nrows: int, nfeat: int, operators
) -> List[Tuple[dict, dict]]:
    """Model-ranked [(config, cost), ...], best first — the autotuner's
    pre-measurement ordering so the measured sweep only runs the top
    candidates. Score = modeled element-ops + 8x bytes (the byte weight
    approximates the VPU-issue-to-HBM balance point of the tabled TPU
    peaks in benchmark/roofline.py; at this granularity only the
    ORDERING matters). Ties break on padded-waste fraction, then on the
    config's sorted repr so the ranking is deterministic."""
    scored = [
        (pallas_config_cost(lengths, c, nrows, nfeat, operators), c)
        for c in configs
    ]
    scored.sort(key=lambda sc: (
        sc[0]["flops"] + 8.0 * sc[0]["bytes"],
        sc[0]["padded_waste_fraction"],
        sorted(sc[1].items(), key=lambda kv: kv[0]),
    ))
    return [(c, s) for s, c in scored]


#: deterministic skewed length histogram for the bucketed-kernel
#: baseline entries: a GP-shaped population (short programs dominate —
#: the TensorGP waste regime) with NO RNG so the baseline is stable.
_KERNEL_COST_LENGTHS = (5,) * 6554 + (9,) * 1229 + (19,) * 409
_KERNEL_COST_NROWS = 2048
_KERNEL_COST_NFEAT = 3
_KERNEL_COST_LADDER = (0.25, 0.5, 0.75, 1.0)


def pallas_kernel_cost_entries() -> Dict[str, dict]:
    """Baseline entries for the Pallas kernel configurations (additive
    alongside the compile_surface Options matrix): the flat default,
    the bucket-laddered grid, and the bucketed+fused-epilogue kernel,
    all modeled on one deterministic skewed histogram. Gated like every
    other config so a cost-model or wrapper-geometry change that moves
    the kernel's modeled work shows up in CI."""
    from ..ops.operators import make_operator_set

    ops = make_operator_set(["+", "-", "*", "/"], ["cos", "exp"])
    base = {"t_block": 256, "r_block": 1024, "dispatch": "mux",
            "tree_unroll": 8}
    variants = {
        "pallas_postfix_flat": {**base, "ladder": ()},
        "pallas_postfix_bucketed": {**base,
                                    "ladder": _KERNEL_COST_LADDER},
        "pallas_postfix_fused": {**base, "ladder": _KERNEL_COST_LADDER,
                                 "fused": True},
    }
    out: Dict[str, dict] = {}
    for name, cfg in variants.items():
        est = pallas_config_cost(
            _KERNEL_COST_LENGTHS, cfg, _KERNEL_COST_NROWS,
            _KERNEL_COST_NFEAT, ops,
        )
        out[name] = {
            "flops": est["flops"],
            "bytes": est["bytes"],
            "padded_waste_fraction": est["padded_waste_fraction"],
            "by_primitive": {},
            "while_loops": 0,
            "stages": {},
        }
    return out


# ---------------------------------------------------------------------------
# baseline gate
# ---------------------------------------------------------------------------


def diff_cost_baseline(
    configs: Dict[str, dict],
    baseline: dict,
    tolerance: float = REGRESSION_TOLERANCE,
) -> Tuple[List[str], List[str]]:
    """(problems, notes): modeled flops/bytes that GREW beyond tolerance
    fail; shrinking beyond it only suggests a refresh (improvements
    never break CI, but a stale baseline hides the next regression)."""
    problems: List[str] = []
    notes: List[str] = []
    base_configs = baseline.get("configs", {})

    def check(tag: str, metric: str, want: float, got: float) -> None:
        if want <= 0:
            return
        ratio = got / want
        if ratio > 1.0 + tolerance:
            problems.append(
                f"{tag}: modeled {metric} grew {want:.4g} -> {got:.4g} "
                f"(+{(ratio - 1) * 100:.0f}%, tolerance "
                f"{tolerance * 100:.0f}%) — a per-dispatch cost "
                "regression; fix it or refresh with --update-baseline "
                "and justify in the PR"
            )
        elif ratio < 1.0 - tolerance:
            notes.append(
                f"{tag}: modeled {metric} shrank {want:.4g} -> {got:.4g} "
                f"({(1 - ratio) * 100:.0f}% better) — refresh the "
                "baseline with --update-baseline to lock it in"
            )

    for name, entry in configs.items():
        if name not in base_configs:
            problems.append(
                f"cost baseline has no config {name!r} — run with "
                "--update-baseline"
            )
            continue
        base = base_configs[name]
        check(name, "flops", base.get("flops", 0), entry["flops"])
        check(name, "bytes", base.get("bytes", 0), entry["bytes"])
        base_stages = base.get("stages", {})
        for stage, s_entry in entry["stages"].items():
            if stage in base_stages:
                check(f"{name}.{stage}", "flops",
                      base_stages[stage].get("flops", 0),
                      s_entry["flops"])
                check(f"{name}.{stage}", "bytes",
                      base_stages[stage].get("bytes", 0),
                      s_entry["bytes"])
            else:
                problems.append(
                    f"cost baseline has no stage {name}.{stage} — "
                    "refresh with --update-baseline"
                )
        for stage in base_stages:
            if stage not in entry["stages"]:
                problems.append(
                    f"cost baseline stage {name}.{stage} no longer "
                    "produced — its recorded cost would silently stop "
                    "being gated; refresh with --update-baseline"
                )
    for name in base_configs:
        if name not in configs:
            problems.append(
                f"cost baseline config {name!r} no longer produced — "
                "refresh with --update-baseline"
            )
    return problems, notes


def check_cost(
    update_baseline: bool = False,
    baseline_path: Optional[str] = None,
    configs: Optional[Tuple[Tuple[str, dict], ...]] = None,
    tolerance: float = REGRESSION_TOLERANCE,
) -> dict:
    """Run the srcost gate over the compile_surface Options matrix;
    returns the report dict rendered by report.render_cost_text (and
    embedded in the CLI JSON)."""
    import jax

    from ..models.options import make_options
    from .report import write_baseline_json

    baseline_path = baseline_path or BASELINE_PATH
    matrix = list(configs if configs is not None else _MATRIX)
    out_configs: Dict[str, dict] = {}
    problems: List[str] = []
    notes: List[str] = []
    for name, extra in matrix:
        options = make_options(**{**_BASE_KWARGS, **extra})
        est = iteration_cost(options)
        entry = {
            "flops": est["flops"],
            "bytes": est["bytes"],
            "padded_waste_fraction": est["padded_waste_fraction"],
            "by_primitive": est["by_primitive"],
            "while_loops": est["while_loops"],
            "stages": {},
        }
        for stage, s_est in stage_costs(options).items():
            entry["stages"][stage] = {
                "flops": s_est["flops"],
                "bytes": s_est["bytes"],
                "padded_waste_fraction": s_est["padded_waste_fraction"],
            }
        out_configs[name] = entry
    if configs is None:
        # bucketed-kernel config entries ride alongside the Options
        # matrix (additive: the Options-config entries are untouched)
        out_configs.update(pallas_kernel_cost_entries())

    baseline_checked = baseline_match = False
    if update_baseline:
        payload = {
            "schema_version": 1,
            "jax_version": jax.__version__,
            "configs": {
                name: {
                    "flops": e["flops"],
                    "bytes": e["bytes"],
                    "padded_waste_fraction": e["padded_waste_fraction"],
                    "stages": {
                        s: {
                            "flops": se["flops"],
                            "bytes": se["bytes"],
                            "padded_waste_fraction":
                                se["padded_waste_fraction"],
                        }
                        for s, se in e["stages"].items()
                    },
                }
                for name, e in out_configs.items()
            },
        }
        write_baseline_json(baseline_path, payload)
    elif os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f)
        baseline_checked = True
        base_problems, base_notes = diff_cost_baseline(
            out_configs, baseline, tolerance
        )
        baseline_match = not base_problems
        problems += base_problems
        notes += base_notes
        if baseline.get("jax_version") != jax.__version__:
            baseline_match = False
            problems.append(
                "cost baseline was written under jax "
                f"{baseline.get('jax_version')} but this is "
                f"{jax.__version__} — refresh with --update-baseline"
            )
    else:
        problems.append(
            f"no cost baseline at {baseline_path} — create one with "
            "--update-baseline"
        )

    return {
        "ok": not problems,
        "problems": problems,
        "notes": notes,
        "configs": out_configs,
        "baseline_checked": baseline_checked,
        "baseline_match": baseline_match,
        "baseline_path": baseline_path,
        "tolerance": tolerance,
        "jax_version": jax.__version__,
    }
