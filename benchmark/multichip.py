#!/usr/bin/env python
"""Real-search multi-chip capture: the production `equation_search`
sharded over an (islands, rows) mesh vs the same search on one device.

Replaces the 3-step `dryrun_multichip` as the repo's multi-device
evidence (MULTICHIP_r01-r05 recorded only that a tiny sharded step ran):
this runs the actual public search end to end — init, cycle scan,
simplify, HoF merge, migration, candidate extraction — under the
compiled sharding contract of api.py's jit factories, and reports

- trees-rows/s of the sharded and the single-device run (compile
  excluded: a warm-up search pays it first; the jit factories'
  lru_caches keep the compiled programs across `equation_search` calls);
- ``speedup_vs_single`` (single wall / sharded wall) and
  ``scaling_efficiency`` (speedup / devices used — 1.0 = perfectly
  linear island scaling, the arxiv 2501.17168 regime);
- the bit-identity verdict (row_shards=1 only: islands-only sharding
  leaves per-island math unchanged — docs/multichip.md) and the
  sharded-carry verdict (every IslandState leaf island-sharded after
  the run).

Run standalone (one JSON row per line, benchmark/suite.py row format):

    python benchmark/multichip.py --force-host 8            # CPU harness
    python benchmark/multichip.py --northstar               # 64 islands
    python benchmark/multichip.py --out MULTICHIP_LATEST.json

``--force-host N`` forces N virtual CPU devices and pins the CPU
platform BEFORE jax initializes (this image's sitecustomize would
otherwise route backend init at the axon TPU tunnel) — so callers
(bench.py, suite.py) run this file as a subprocess. Without the flag it
uses whatever devices the session has (the real-chip path when the
tunnel is up). bench.py embeds these rows in its JSON next to
``multichip_skip_reason``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: Eval-dominated default shape: big row count and population so the
#: per-iteration device work dwarfs host orchestration — the regime where
#: island scaling is measurable (and the suite `multichip` case's
#: acceptance shape: npopulations=8, row_shards=1).
DEFAULTS = dict(
    islands=8, npop=128, rows=2048, ncycles=8, maxsize=12,
    niterations=2, seed=0,
)

#: The north-star island count (BASELINE.json npopulations=64) at a
#: CPU-tractable npop; ``--northstar`` on a real pod raises npop too.
NORTHSTAR = dict(
    islands=64, npop=64, rows=1024, ncycles=4, maxsize=12,
    niterations=1, seed=0,
)


def _search_kwargs(cfg: dict) -> dict:
    return dict(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        npopulations=cfg["islands"],
        npop=cfg["npop"],
        ncycles_per_iteration=cfg["ncycles"],
        maxsize=cfg["maxsize"],
        should_optimize_constants=False,
        verbosity=0, progress=False, runtests=False,
    )


def _data(cfg: dict):
    import numpy as np

    rng = np.random.default_rng(1)
    X = rng.standard_normal((3, cfg["rows"])).astype(np.float32)
    y = (2.0 * np.cos(X[2]) + X[0] * X[0] - 0.5).astype(np.float32)
    return X, y


def _frontier(res):
    return [
        (c.complexity, c.equation, float(c.loss)) for c in res.frontier()
    ]


def _carries_sharded(state, island_axis: str):
    """True iff every leaf of the carried IslandState reports island-axis
    NamedSharding (the no-replicated-carries acceptance check)."""
    import jax
    from jax.sharding import NamedSharding

    for _, leaf in jax.tree_util.tree_flatten_with_path(
        state.island_states
    )[0]:
        sh = getattr(leaf, "sharding", None)
        spec = tuple(getattr(sh, "spec", ()) or ())
        if not (
            isinstance(sh, NamedSharding)
            and spec
            and spec[0] == island_axis
        ):
            return False
    return True


def run_capture(cfg: dict, emit=None) -> list:
    """Run the sharded-vs-single capture on this process's devices;
    returns (and optionally streams via ``emit``) suite-format rows."""
    import jax

    import symbolicregression_jl_tpu as sr
    from symbolicregression_jl_tpu import api
    from symbolicregression_jl_tpu.models.options import make_options
    from symbolicregression_jl_tpu.parallel.mesh import (
        describe_mesh,
        make_mesh,
    )

    rows: list = []

    def _row(rec):
        rows.append(rec)
        if emit is not None:
            emit(rec)
        return rec

    devices = jax.devices()
    n_dev = len(devices)
    kwargs = _search_kwargs(cfg)
    if n_dev <= 1:
        _row({
            "suite": "multichip",
            "skipped": "single-device",
            "n_devices": n_dev,
        })
        return rows
    opts_probe = make_options(**{
        k: v for k, v in kwargs.items() if k != "runtests"
    })
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("ignore")
        mesh = make_mesh(opts_probe, cfg["islands"], row_shards=1)
    if mesh is None or int(mesh.devices.size) <= 1:
        # make_mesh degraded all the way to one device (e.g. a prime
        # island count on a 2-chip host): a "sharded" run would be a
        # single-device run wearing a mesh — skip, and say why
        _row({
            "suite": "multichip",
            "skipped": "shape-indivisible",
            "n_devices": n_dev,
            "islands": cfg["islands"],
        })
        return rows
    mesh_info = describe_mesh(mesh)
    X, y = _data(cfg)

    def timed_search(single: bool, return_state: bool = False):
        orig = api.make_mesh
        if single:
            api.make_mesh = lambda *a, **k: None
        try:
            # warm-up pays every compile; the factories' lru_caches hand
            # the timed call the same compiled programs
            sr.equation_search(
                X, y, niterations=1, seed=cfg["seed"], **kwargs
            )
            t0 = time.perf_counter()
            res = sr.equation_search(
                X, y, niterations=cfg["niterations"], seed=cfg["seed"],
                return_state=return_state, **kwargs,
            )
            wall = time.perf_counter() - t0
        finally:
            api.make_mesh = orig
        return res, wall

    res_m, wall_m = timed_search(single=False, return_state=True)
    rate_m = res_m.num_evals * cfg["rows"] / wall_m
    _row({
        "suite": "multichip",
        "case": "sharded",
        "n_devices": mesh_info["n_devices"],
        "mesh_shape": mesh_info["mesh_shape"],
        "idle_devices": mesh_info["idle_devices"],
        "device_kind": mesh_info["device_kind"],
        "wall_s": wall_m,
        "num_evals": res_m.num_evals,
        "trees_rows_per_s": rate_m,
    })

    res_s, wall_s = timed_search(single=True)
    rate_s = res_s.num_evals * cfg["rows"] / wall_s
    _row({
        "suite": "multichip",
        "case": "single_device",
        "wall_s": wall_s,
        "num_evals": res_s.num_evals,
        "trees_rows_per_s": rate_s,
    })

    speedup = wall_s / wall_m if wall_m > 0 else 0.0
    _row({
        "suite": "multichip",
        "case": "summary",
        "config": {k: cfg[k] for k in (
            "islands", "npop", "rows", "ncycles", "niterations", "seed"
        )},
        "n_devices": mesh_info["n_devices"],
        "mesh_shape": mesh_info["mesh_shape"],
        "device_kind": mesh_info["device_kind"],
        # islands-only sharding leaves per-island math unchanged, so the
        # frontier must match the single-device run bit for bit
        "hof_bit_identical": _frontier(res_m) == _frontier(res_s),
        "carries_sharded": _carries_sharded(
            res_m.state[0], opts_probe.island_axis
        ),
        "speedup_vs_single": speedup,
        "scaling_efficiency": speedup / max(mesh_info["n_devices"], 1),
        "host_cpu_count": os.cpu_count(),
    })
    return rows


def write_latest(path: str, rows: list, platform: str) -> None:
    """The one writer of MULTICHIP_*.json capture artifacts (both the
    --out flag here and bench.py's on-chip branch go through it, so the
    record shape cannot drift between producers)."""
    with open(path, "w") as f:
        json.dump(
            {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
             "platform": platform,
             "rows": rows},
            f, indent=2,
        )
        f.write("\n")


def run_subprocess(extra_args=(), timeout=900, force_host=8):
    """Run this capture in a FRESH subprocess (the virtual-device force
    must precede backend init, and callers — bench.py, suite.py — own
    their own backend) and parse its JSON rows off stdout.

    Returns ``(rows, error)``: error is None when rows were captured,
    else a short "rc=N: <stderr tail>" string. Single shared
    implementation so the two call sites cannot drift."""
    import subprocess

    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--force-host", str(force_host), *extra_args,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
    except subprocess.TimeoutExpired:
        return [], f"timed out after {timeout}s"
    rows = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    if not rows:
        tail = (proc.stderr or "").strip().splitlines()[-2:]
        return [], f"rc={proc.returncode}: " + " / ".join(tail)[:200]
    return rows, None


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--force-host", type=int, default=0, metavar="N",
        help="force N virtual CPU devices (set BEFORE jax init; run "
        "this file as a subprocess when the parent already owns a "
        "backend)",
    )
    ap.add_argument("--northstar", action="store_true",
                    help="the 64-island north-star config")
    for k, v in DEFAULTS.items():
        ap.add_argument(f"--{k}", type=int, default=None)
    ap.add_argument("--out", default=None,
                    help="also write {rows: [...]} JSON to this path")
    ns = ap.parse_args()

    if ns.force_host:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={ns.force_host}"
            ).strip()
    import jax

    if ns.force_host:
        # pure-CPU capture must never touch the axon tunnel's one slot
        jax.config.update("jax_platforms", "cpu")

    cfg = dict(NORTHSTAR if ns.northstar else DEFAULTS)
    for k in DEFAULTS:
        v = getattr(ns, k)
        if v is not None:
            cfg[k] = v

    rows = run_capture(
        cfg, emit=lambda rec: print(json.dumps(rec), flush=True)
    )
    if ns.out:
        write_latest(ns.out, rows, jax.default_backend())
    summary = next(
        (r for r in rows if r.get("case") == "summary"), None
    )
    if summary is None:
        return 0  # a skip is a successful verdict, not a failure
    return 0 if summary["hof_bit_identical"] else 1


if __name__ == "__main__":
    sys.exit(main())
