#!/usr/bin/env python
"""North-star-scale Feynman recovery runs: npopulations=64 x npop=1000
(BASELINE.json config 2's population shape) over the same 12-case suite
as benchmark/feynman.py — the quality half of the TPU thesis, converting
kernel throughput into solved equations. The reference's recovery bar is
the analog: exact-form recovery within budget
(/root/reference/test/test_mixed.jl:129-141).

At this scale the per-cycle scoring batches clear `_PALLAS_MIN_WORK`, so
on TPU every candidate evaluation runs through the Pallas kernel and
constant optimization through the fused loss/grad kernels. On a 1-core
CPU one iteration of this shape takes >40 min (BASELINE.md) — this
script is only meant for chip time; it refuses to start on CPU unless
SRTPU_SCALE_CPU_OK=1.

Hard cases run first (I.8.14 / I.6.2 / I.6.2a / I.27.6 — the seed-0
misses of the small-budget benchmark) so a tunnel drop mid-suite still
captures the runs that answer BASELINE.md's open scale question. The op
set adds `square` (the probe that got I.8.14 to half-structure at small
scale, and to the EXACT form at 32x128 on CPU — BASELINE.md).

With --resume (passed by scripts/tpu_watcher.py, which persists its
guard-railed resume state to BENCH_TPU_LATEST.json before any step
runs), cases already captured ON CHIP for a (case, seed) pair at the
SAME scale/niter are skipped and their records re-printed, so a watcher
retry after a tunnel drop spends the next window on the UNFINISHED
cases instead of re-solving done ones. Without the flag (manual runs,
new rounds) every case runs — the file's records are the watcher's to
vouch for, not this script's.

Usage:
    python benchmark/feynman_scale.py [--seed N | --seeds 0,1,2]
                                      [--cases I.8.14,I.6.2] [--niter K]
                                      [--hard-only] [--resume]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from feynman import CASES  # noqa: E402  (shared 12-case table)

HARD_FIRST = ["I.8.14", "I.6.2", "I.6.2a", "I.27.6"]

CAPTURE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "BENCH_TPU_LATEST.json",
)


def load_finished_cases(niter):
    """(case, seed) pairs already measured ON CHIP in the watcher's
    capture file at the CURRENT scale and niter — a retry after a tunnel
    drop must spend its window on the unfinished cases, but a record
    from a different budget must never masquerade as this run's result.
    Only called under --resume: the watcher persists its guard-railed
    (staleness/argv-checked) resume state to the file before any step
    runs, so under the watcher the disk records are trustworthy.
    Returns {(case, seed): record_line}."""
    scale = f"{BUDGET['npopulations']}x{BUDGET['npop']}"
    try:
        with open(CAPTURE_PATH) as f:
            data = json.load(f)
        lines = data["steps"]["feynman_scale"]["json"]
    except Exception:
        return {}
    out = {}
    for j in lines:
        if (
            isinstance(j, dict)
            and j.get("platform") == "tpu"
            and "case" in j
            and "seed" in j
            and j.get("scale") == scale
            and j.get("niter") == niter
        ):
            out[(j["case"], j["seed"])] = j
    return out


BUDGET = dict(
    npop=1000,
    npopulations=64,
    ncycles_per_iteration=100,
    maxsize=18,
)
N_ROWS = 256
UNARY_OPS = ["cos", "exp", "sqrt", "square"]


def main():
    from bench import _devices_or_cpu_fallback

    devices = _devices_or_cpu_fallback(verbose=True, use_memo=True)
    if devices[0].platform == "cpu" and not os.environ.get(
        "SRTPU_SCALE_CPU_OK"
    ):
        sys.exit(
            "# feynman_scale needs the TPU (one 64x1000 iteration takes "
            ">40 min on this CPU — BASELINE.md); tunnel unavailable. Set "
            "SRTPU_SCALE_CPU_OK=1 to force."
        )

    import symbolicregression_jl_tpu as sr

    seeds = [0]
    if "--seed" in sys.argv:
        seeds = [int(sys.argv[sys.argv.index("--seed") + 1])]
    if "--seeds" in sys.argv:  # e.g. --seeds 0,1,2 (BASELINE.md 3-seed row)
        seeds = [
            int(s) for s in sys.argv[sys.argv.index("--seeds") + 1].split(",")
        ]
    niter = 8
    if "--niter" in sys.argv:
        niter = int(sys.argv[sys.argv.index("--niter") + 1])
    wanted = None
    if "--cases" in sys.argv:
        wanted = set(sys.argv[sys.argv.index("--cases") + 1].split(","))
    if "--hard-only" in sys.argv:
        wanted = set(HARD_FIRST)

    order = {n: i for i, n in enumerate(HARD_FIRST)}
    cases = sorted(CASES, key=lambda c: order.get(c[0], len(HARD_FIRST)))
    if wanted is not None:
        cases = [c for c in cases if c[0] in wanted]

    finished = (
        load_finished_cases(niter) if "--resume" in sys.argv else {}
    )
    for seed in seeds:
        _run_seed(sr, devices, cases, seed, niter, finished)


def _search_case(sr, name, seed, X, y, niter, var):
    """One case's search. With SRTPU_BENCH_SNAPSHOT_DIR exported (the
    watcher's --snapshot-dir plumbing, docs/resilience.md) the search
    runs under the resilience supervisor with a per-(case, seed)
    snapshot every dispatch: a tunnel drop or watcher-timeout kill
    mid-case costs at most one iteration, and the retry attempt RESUMES
    the interrupted case bit-identically instead of restarting it —
    this step is the watcher's longest, the one whose banked hours the
    supervised-resume accounting exists to protect. The snapshot is
    deleted after the case completes so a later round's fresh capture
    re-measures instead of short-circuiting on a stale file."""
    kw = dict(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=UNARY_OPS,
        seed=seed,
        verbosity=0,
        progress=False,
        runtests=False,
        early_stop_condition=1e-6 * var,
        **BUDGET,
    )
    # the watcher's event-log classification (resumable vs dead, and
    # the progress signal its attempt accounting compares) only works
    # if this step actually writes the telemetry trail — without it a
    # genuinely-resuming retry still burns MAX_ATTEMPTS like a dead
    # restart
    tele_dir = os.environ.get("SRTPU_BENCH_TELEMETRY_DIR")
    if tele_dir:
        kw.update(telemetry=True, telemetry_dir=tele_dir)
    snap_dir = os.environ.get("SRTPU_BENCH_SNAPSHOT_DIR")
    if not snap_dir:
        return sr.equation_search(X, y, niterations=niter, **kw)
    os.makedirs(snap_dir, exist_ok=True)
    snap = os.path.join(
        snap_dir,
        f"feynman_{name.replace('.', '_')}_s{seed}_n{niter}.ckpt",
    )
    sup = sr.supervised_search(
        X, y, niterations=niter, snapshot_path=snap,
        snapshot_every_dispatches=1, max_attempts=2,
        backoff_base_s=5.0, **kw,
    )
    for p in (snap, snap + ".bkup"):
        try:
            os.remove(p)
        except OSError:
            pass
    return sup.result


def _run_seed(sr, devices, cases, seed, niter, finished=None):
    finished = finished or {}
    solved = 0
    for name, n_vars, fn, ranges in cases:
        prior = finished.get((name, seed))
        if prior is not None:
            # already measured on chip in this capture: re-emit the
            # record (the watcher re-parses stdout on retry) and move on
            solved += bool(prior.get("solved"))
            print(json.dumps(prior), flush=True)
            continue
        rng = np.random.default_rng(seed)
        X = np.stack(
            [rng.uniform(lo, hi, N_ROWS) for lo, hi in ranges]
        ).astype(np.float32)
        y = fn(X).astype(np.float32)
        var = float(np.var(y))

        t0 = time.time()
        res = _search_case(sr, name, seed, X, y, niter, var)
        dt = time.time() - t0
        best = res.best_loss()
        norm_loss = best.loss / max(var, 1e-12)
        ok = norm_loss < 1e-4
        solved += ok
        print(
            json.dumps(
                {
                    "case": name,
                    "scale": (
                        f"{BUDGET['npopulations']}x{BUDGET['npop']}"
                    ),
                    # per-case platform stamp: a tunnel drop mid-suite
                    # must leave each finished case attributable
                    "platform": devices[0].platform,
                    "seed": seed,
                    "niter": niter,
                    "solved": bool(ok),
                    "norm_loss": float(f"{norm_loss:.3e}"),
                    "complexity": best.complexity,
                    "equation": best.equation,
                    "seconds": round(dt, 1),
                    "num_evals": round(res.num_evals),
                }
            ),
            flush=True,
        )
    print(
        json.dumps(
            {
                "suite": "feynman_scale",
                "seed": seed,
                "solved": solved,
                "of": len(cases),
                "platform": devices[0].platform,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
