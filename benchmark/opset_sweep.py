#!/usr/bin/env python
"""Per-slot overhead decomposition for the Pallas eval kernel (TPU).

Holds the tree programs FIXED (a workload built over {+,*} only) while
widening the candidate operator set the kernel computes per slot, then
fits time/iteration = fixed + per_op * vec_ops (roofline.fit_slot_model).
The fixed term is per-step overhead the VPU-issue roofline cannot see —
scalar/SMEM reads, dynamic scratch indexing, loop bookkeeping, pipeline
latency the tree interleave fails to hide — and bounds what any further
candidate-compute optimization can recover.

Usage: python benchmark/opset_sweep.py [n_inner]   (TPU only: the Pallas
kernel does not lower on CPU, so a dead tunnel exits cleanly with a note
instead of a decomposition.)
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax
    import jax.numpy as jnp

    from bench import (
        _build_workload,
        _devices_or_cpu_fallback,
        _dispatch_overhead_s,
        _feynman_data,
        time_pallas_variant,
    )

    devices = _devices_or_cpu_fallback(verbose=True, use_memo=True)
    if devices[0].platform == "cpu":
        sys.exit("# opset_sweep needs the TPU (the compiled Pallas kernel "
                 "does not lower on CPU); tunnel unavailable — exiting")
    from roofline import fit_slot_model, ops_per_slot

    from symbolicregression_jl_tpu.models.options import make_options

    n_inner = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    N_TREES = 8192

    opsets = [
        (["+", "*"], []),
        (["+", "-", "*", "/"], []),
        (["+", "-", "*", "/"], ["cos", "exp"]),
        (["+", "-", "*", "/"], ["cos", "exp", "sin", "sqrt", "log", "abs"]),
        (["+", "-", "*", "/", "pow", "max", "min"],
         ["cos", "exp", "sin", "sqrt", "log", "abs", "tanh", "cosh",
          "sinh", "atan"]),
    ]
    # one workload over the smallest common op set: the slot stream is
    # identical across runs; only the candidate mux width varies
    base_opts = make_options(binary_operators=["+", "*"], maxsize=20)
    trees = _build_workload(jax, jnp, base_opts, N_TREES, 1)
    X = jnp.asarray(_feynman_data()[0])
    dev = jax.devices()[0]
    print(f"# device: {dev} ({dev.platform})", file=sys.stderr)
    overhead = _dispatch_overhead_s(jax, jnp, dev)

    points = []
    for bins, unas in opsets:
        options = make_options(
            binary_operators=bins, unary_operators=unas, maxsize=20
        )
        ops = options.operators
        rate, per_iter, _ = time_pallas_variant(
            jax, jnp, trees, X, ops, overhead, n_inner
        )
        vec_ops = ops_per_slot(ops)
        points.append((vec_ops, per_iter))
        n_cands = 3 + len(unas) + len(bins)
        print(
            f"n_cands={n_cands:2d}  vec_ops={vec_ops:5.1f}  "
            f"{rate:.3e} t-r/s  {per_iter*1e3:7.2f} ms/iter",
            flush=True,
        )

    fit = fit_slot_model(points)
    print("slot-cost decomposition:",
          {k: f"{v:.4g}" for k, v in fit.items()})
    print(
        f"-> {100*fit['overhead_frac']:.0f}% of per-step time is fixed "
        "overhead the issue-bound model does not see; the candidate-"
        "compute-only bound over-estimates achievable throughput by "
        f"{1/max(fit['effective_bound_scale'], 1e-9):.2f}x at the bench "
        "op set",
        flush=True,
    )


if __name__ == "__main__":
    main()
