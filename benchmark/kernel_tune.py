#!/usr/bin/env python
"""A/B harness for Pallas tree-interpreter kernel variants on real TPU.

Sweeps (dispatch, tree_unroll, sort_trees, slot_loop, t_block) on the
bench.py workload shape (8192 trees x 1000 rows, maxsize 20) and prints
trees-rows/sec for each, highest last. Timing matches bench.py: n_inner
iterations inside one jit with the constant-perturbation trick, tunnel
dispatch overhead subtracted.

Usage: python benchmark/kernel_tune.py [n_inner] [--tail N] [--rows-sweep]

--tail N runs only the last N grid entries (quick probes of newly added
variants without re-sweeping the full grid).

--bucket-sweep instead measures the jnp interpreter's length-bucketed
eval dispatch (models/fitness.py eval_loss_trees_bucketed) across a
ladder grid on the bench workload, flat ladder () first as the
reference — the A/B that picks Options.eval_bucket_ladder defaults.
Runs the interpreter path regardless of device (the Pallas kernel
ignores the ladder).

--rows-sweep instead measures the default variant across dataset row
counts {128, 256, 512, 1024, 2048}: rows live on (r_sub, 128) vreg
tiles, so row counts below 1024 under-fill the 8 sublanes — 256 rows
uses 2/8 — and this sweep quantifies how much trees-rows/s that lane
waste actually costs in the in-search regime (feynman searches run at
256 rows). A near-constant ms/iter across row counts = the waste is
real (same vector work regardless of rows); trees-rows/s scaling
linearly with rows = it is not.

--autotune [--cache PATH] [--top K] [--min-work N] runs the persistent
autotuner: the srcost model (analysis/cost.py::rank_kernel_configs)
ranks the full (t_block, r_block, dispatch, tree_unroll, ladder)
candidate grid, only the top K are measured on the device, and the
winner is folded into the schema-versioned tune cache
(symbolicregression_jl_tpu/tune/tune_cache.json by default, or --cache)
under THIS device kind. On a host without a TPU the sweep falls back to
Pallas interpret mode on a shrunken workload — those timings are marked
interpret in the cache and can never be filed under a TPU device kind.
See docs/kernel_tuning.md.
"""

from __future__ import annotations

import itertools
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    # workload and timing methodology MUST stay in lockstep with the
    # headline benchmark — import its builders rather than copying them
    from bench import (
        N_ROWS,
        _build_workload,
        _devices_or_cpu_fallback,
        _dispatch_overhead_s,
        _feynman_data,
        time_pallas_variant,
    )

    _devices_or_cpu_fallback(verbose=True, use_memo=True)  # hung-tunnel watchdog
    from symbolicregression_jl_tpu.models.options import make_options

    args = sys.argv[1:]
    tail_n = None
    if "--tail" in args:  # single up-front parse of the flag and its value
        i = args.index("--tail")
        if i + 1 >= len(args):
            sys.exit("--tail requires a value: kernel_tune.py [n_inner] --tail N")
        tail_n = int(args[i + 1])
        args = args[:i] + args[i + 2:]
    rows_sweep = "--rows-sweep" in args
    args = [a for a in args if a != "--rows-sweep"]
    bucket_sweep = "--bucket-sweep" in args
    args = [a for a in args if a != "--bucket-sweep"]
    autotune = "--autotune" in args
    args = [a for a in args if a != "--autotune"]
    cache_path = None
    if "--cache" in args:
        i = args.index("--cache")
        if i + 1 >= len(args):
            sys.exit("--cache requires a path")
        cache_path = args[i + 1]
        args = args[:i] + args[i + 2:]
    top_k = 5
    if "--top" in args:
        i = args.index("--top")
        if i + 1 >= len(args):
            sys.exit("--top requires a value")
        top_k = int(args[i + 1])
        args = args[:i] + args[i + 2:]
    min_work_flag = None
    if "--min-work" in args:
        i = args.index("--min-work")
        if i + 1 >= len(args):
            sys.exit("--min-work requires a value")
        min_work_flag = int(args[i + 1])
        args = args[:i] + args[i + 2:]
    rows_max = 2048
    if "--rows-max" in args:
        i = args.index("--rows-max")
        if i + 1 >= len(args):
            sys.exit("--rows-max requires a value")
        rows_max = int(args[i + 1])
        args = args[:i] + args[i + 2:]
    n_inner = int(args[0]) if args else 20
    N_TREES, MAXSIZE = 8192, 20

    options = make_options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        maxsize=MAXSIZE,
    )
    ops = options.operators
    dev = jax.devices()[0]
    print(f"# device: {dev} ({dev.platform})", file=sys.stderr)

    trees = _build_workload(jax, jnp, options, N_TREES, 1)
    X = jnp.asarray(_feynman_data()[0])

    overhead = _dispatch_overhead_s(jax, jnp, dev)
    print(f"# dispatch overhead: {overhead*1e3:.1f} ms", file=sys.stderr)

    def run_variant(**kw):
        return time_pallas_variant(
            jax, jnp, trees, X, ops, overhead, n_inner, **kw
        )

    if autotune:
        from symbolicregression_jl_tpu.models.fitness import (
            _PALLAS_MIN_WORK,
        )
        from symbolicregression_jl_tpu.ops.pallas_eval import (
            pallas_available,
        )
        from symbolicregression_jl_tpu.tune import (
            current_device_kind,
            load_tune_cache,
            model_ranked_sweep,
            save_tune_cache,
        )
        from symbolicregression_jl_tpu.tune.tuner import sweep_to_cache

        interpret = not pallas_available()
        device_kind = current_device_kind()
        if interpret:
            # CPU fallback: interpret mode pays ~1000x per slot, so the
            # measured workload shrinks to stay tractable. The relative
            # ordering it produces is still a valid cache payload —
            # entries are marked interpret and update_tune_cache refuses
            # to file them under any TPU device kind.
            at_trees, at_X, at_inner = trees[:256], X[:, :256], 1
        else:
            at_trees, at_X, at_inner = trees, X, n_inner
        lengths = [
            int(v) for v in np.asarray(jax.device_get(at_trees.length))
        ]
        print(
            f"# autotune: device_kind={device_kind} interpret={interpret} "
            f"workload={len(lengths)}x{at_X.shape[1]} top_k={top_k}",
            file=sys.stderr, flush=True,
        )

        def measure(config):
            kw = dict(
                t_block=config["t_block"], r_block=config["r_block"],
                dispatch=config["dispatch"],
                tree_unroll=config["tree_unroll"],
            )
            if config.get("ladder"):
                kw["bucket_ladder"] = tuple(
                    float(f) for f in config["ladder"]
                )
            if interpret:
                kw["interpret"] = True
            rate, per_iter, compile_s = time_pallas_variant(
                jax, jnp, at_trees, at_X, ops, overhead, at_inner, **kw
            )
            print(json.dumps({
                "sweep": "autotune", "config": config,
                "trees_rows_per_s": rate, "per_iter_s": per_iter,
                "compile_s": compile_s, "interpret": interpret,
                "device_kind": device_kind,
            }), flush=True)
            return rate

        sweep = model_ranked_sweep(
            ops, lengths, int(at_X.shape[1]), int(at_X.shape[0]),
            measure, top_k=top_k,
        )
        # the entry is keyed by the PADDED slot count (options.max_len,
        # what trees.kind.shape[-1] is at lookup time in
        # fitness._tuned_kernel_kwargs), not the user-facing maxsize
        cache = sweep_to_cache(
            sweep, ops, options.max_len, dtype="float32",
            interpret=interpret,
            device_kind=device_kind,
            min_work=(min_work_flag if min_work_flag is not None
                      else _PALLAS_MIN_WORK),
            cache=load_tune_cache(cache_path),
        )
        if not sweep.get("best") or cache is None:
            sys.exit("autotune: no candidate measured successfully")
        path = save_tune_cache(cache, cache_path)
        print(
            f"\nBEST: {sweep['best']['trees_rows_per_s']:.3e} "
            f"trees-rows/s  {sweep['best']['config']}\n"
            f"cache written: {path} (device_kind={device_kind}, "
            f"interpret={interpret})"
        )
        return

    if bucket_sweep:
        # ladder A/B on the jnp interpreter path: flat reference first,
        # then coarser-to-finer positional ladders. Timing methodology
        # matches the kernel grid (n_inner evals inside one jit with the
        # constant-perturbation trick, dispatch overhead subtracted).
        import time as _time

        from symbolicregression_jl_tpu.models.fitness import (
            eval_loss_trees,
        )

        loss_fn = options.elementwise_loss
        y = jnp.asarray(_feynman_data()[1])
        ladders = [
            (),
            (1.0,),
            (0.5, 1.0),
            (0.25, 0.5, 1.0),
            (0.25, 0.5, 0.75, 1.0),
            (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
        ]
        for ladder in ladders:
            def body(i, acc, _ladder=ladder):
                t = trees._replace(cval=trees.cval + acc * 1e-12)
                loss = eval_loss_trees(
                    t, X, y, None, ops, loss_fn, backend="jnp",
                    bucket_ladder=_ladder,
                )
                good = jnp.where(jnp.isfinite(loss), loss, 0.0)
                return acc + jnp.clip(jnp.mean(good), 0.0, 1.0)

            fn = jax.jit(
                lambda _body=body: jax.lax.fori_loop(
                    0, n_inner, _body, jnp.float32(0.0)
                )
            )
            t_c0 = _time.perf_counter()
            assert np.isfinite(float(fn()))
            compile_s = _time.perf_counter() - t_c0
            ts = []
            for _ in range(3):
                t0 = _time.perf_counter()
                float(fn())
                ts.append(_time.perf_counter() - t0)
            per_iter = max(
                (float(np.median(ts)) - overhead) / n_inner, 1e-9
            )
            rate = N_TREES * X.shape[1] / per_iter
            print(json.dumps({
                "sweep": "buckets", "ladder": list(ladder),
                "trees_rows_per_s": rate, "per_iter_s": per_iter,
                "compile_s": compile_s,
                "platform": jax.devices()[0].platform,
            }), flush=True)
            print(
                f"# ladder={ladder or '(flat)'}  {rate:.3e} t-r/s  "
                f"{per_iter*1e3:7.2f} ms/iter  (compile {compile_s:.0f}s)",
                file=sys.stderr, flush=True,
            )
        return

    if rows_sweep:
        # lane-utilization diagnostic: rows under 1024 under-fill the
        # (8, 128) vreg sublanes ((nrows/128) of 8 used); rows beyond
        # 1024 amortize the fixed per-step cost over more row tiles
        # (2026-08-02 capture: 2048 rows -> 1.39e9, ABOVE the 1024-row
        # plateau — hence --rows-max to find the knee)
        rng = np.random.default_rng(0)
        sweep_points = (128, 256, 512, 1024, 2048, 4096, 8192)
        sweep = [r for r in sweep_points if r <= rows_max]
        if not sweep:
            # exiting 0 with no JSON rows would read as a clean-but-empty
            # capture to the watcher; make a filtered-to-nothing sweep an
            # explicit operator error instead
            print(
                f"# --rows-max {rows_max} filters the rows sweep to "
                f"empty (smallest sweep point is {sweep_points[0]}); "
                "no measurements to run",
                file=sys.stderr,
            )
            sys.exit(2)
        for nrows in sweep:
            Xr = jnp.asarray(
                rng.uniform(1.0, 3.0, nrows).astype("f4")[None, :]
            )
            rate, per_iter, compile_s = time_pallas_variant(
                jax, jnp, trees, Xr, ops, overhead, n_inner
            )
            # one JSON line per measurement: the watcher's `json`
            # capture must keep sweep data even when stdout_tail scrolls
            print(json.dumps({
                "sweep": "rows", "rows": nrows,
                "sublanes": min(nrows // 128, 8),
                "trees_rows_per_s": rate, "per_iter_s": per_iter,
                "compile_s": compile_s,
                "platform": jax.devices()[0].platform,
            }), flush=True)
            print(
                f"# rows={nrows:5d}  sublanes={min(nrows // 128, 8)}/8  "
                f"{rate:.3e} t-r/s  {per_iter*1e3:7.2f} ms/iter  "
                f"(compile {compile_s:.0f}s)",
                file=sys.stderr, flush=True,
            )
        return

    results = []
    grid = []
    for dispatch, unroll, sort in itertools.product(
        ["chain", "mux"], [1, 2, 4], [True, False]
    ):
        if not sort and unroll == 4:
            continue  # unsorted+wide group is strictly worse, skip
        grid.append(dict(dispatch=dispatch, tree_unroll=unroll,
                         sort_trees=sort))
    # plus: the full-unroll slot loop with the best-looking combos
    grid.append(dict(dispatch="mux", tree_unroll=2, sort_trees=True,
                     slot_loop="unrolled"))
    grid.append(dict(dispatch="chain", tree_unroll=1, sort_trees=False,
                     slot_loop="unrolled"))
    # t_block sweep on the default variant
    for tb in (128, 512):
        grid.append(dict(dispatch="mux", tree_unroll=2, sort_trees=True,
                         t_block=tb))
    grid.append(dict(dispatch="mux", tree_unroll=8, sort_trees=True))
    grid.append(dict(dispatch="mux", tree_unroll=8, sort_trees=True,
                     t_block=512))
    grid.append(dict(dispatch="mux", tree_unroll=4, sort_trees=True,
                     t_block=512))
    grid.append(dict(dispatch="mux", tree_unroll=4, sort_trees=True,
                     r_block=2048))
    # bf16 compute / f32 accumulate: halves VMEM traffic per slot
    grid.append(dict(dispatch="mux", tree_unroll=4, sort_trees=True,
                     compute_dtype="bfloat16"))
    grid.append(dict(dispatch="mux", tree_unroll=8, sort_trees=True,
                     compute_dtype="bfloat16"))
    # roofline says the kernel is issue-bound with the serial slot chain
    # the latency limiter -> go deeper on interleave
    grid.append(dict(dispatch="mux", tree_unroll=16, sort_trees=True))
    grid.append(dict(dispatch="mux", tree_unroll=16, sort_trees=True,
                     compute_dtype="bfloat16"))
    grid.append(dict(dispatch="mux", tree_unroll=16, sort_trees=True,
                     r_block=512))
    # compressed operator-only instruction program: ~half the steps per
    # tree (leaves become operand fetches instead of executed slots)
    for unroll in (4, 8, 16):
        grid.append(dict(dispatch="mux", tree_unroll=unroll,
                         sort_trees=True, program="instr"))
    grid.append(dict(dispatch="mux", tree_unroll=8, sort_trees=True,
                     program="instr", compute_dtype="bfloat16"))
    # packed-word instr kernel: 3 SMEM reads/step instead of 7 + unified
    # operand scratch — relief for the per-slot scalar-unit bound
    for unroll in (4, 8, 16):
        grid.append(dict(dispatch="mux", tree_unroll=unroll,
                         sort_trees=True, program="instr_packed"))
    grid.append(dict(dispatch="mux", tree_unroll=8, sort_trees=True,
                     program="instr_packed", t_block=512))
    # leaf-skip: scalar-predicated 2-way branch per slot skips the whole
    # operator candidate set on leaf slots (~half the postfix slots).
    # Issue-bound prediction: up to ~1.8x IF Mosaic keeps the interleave
    # pipeline overlapping across the branch — the open question.
    for unroll in (2, 4, 8):
        grid.append(dict(dispatch="mux", tree_unroll=unroll,
                         sort_trees=True, leaf_skip=True))
    grid.append(dict(dispatch="mux", tree_unroll=8, sort_trees=True,
                     leaf_skip=True, compute_dtype="bfloat16"))
    grid.append(dict(dispatch="mux", tree_unroll=16, sort_trees=True,
                     leaf_skip=True))
    # 3-way class split: the binary arm (most operator slots) also skips
    # the transcendental candidates — expected issued vec-ops/slot drop
    # from ~33 to ~7 on this op set IF the branches are cheap
    for unroll in (4, 8):
        grid.append(dict(dispatch="mux", tree_unroll=unroll,
                         sort_trees=True, leaf_skip="class"))
    # packed-scalar postfix: the 2026-08-01 opset_sweep decomposition put
    # the FIXED per-slot cost at ~62% of step time (intercept 5.1ms vs
    # 0.068ms/vec-op slope at 8192x1000); this variant attacks its
    # scalar-fetch share — 1 SMEM word + shifts instead of 4 reads per
    # (slot, tree), dataflow otherwise identical (unlike instr_packed,
    # which also changed the operand mux and was refuted on chip)
    for unroll in (4, 8, 16):
        grid.append(dict(dispatch="mux", tree_unroll=unroll,
                         sort_trees=True, scalar_pack=True))
    # top_carry: the postfix invariant ridx == si-1 lets the top-of-stack
    # operand ride a loop register instead of a dynamic scratch read —
    # one dynamic VMEM read + one scalar read fewer per step AND a
    # shorter serial chain per tree (so the optimal interleave may drop)
    grid.append(dict(dispatch="mux", tree_unroll=8, sort_trees=True,
                     top_carry=True))
    for unroll in (4, 8, 16):
        grid.append(dict(dispatch="mux", tree_unroll=unroll,
                         sort_trees=True, top_carry=True,
                         scalar_pack=True))

    if tail_n is not None:  # only the last N grid entries (quick probes)
        grid = grid[-tail_n:]

    for kw in grid:
        try:
            rate, per_iter, compile_s = run_variant(**kw)
        except Exception as e:
            print(f"FAIL {kw}: {type(e).__name__}: {e}", file=sys.stderr)
            continue
        results.append((rate, kw))
        print(
            f"{rate:.3e} t-r/s  {per_iter*1e3:7.2f} ms/iter  "
            f"(compile {compile_s:.0f}s)  {kw}",
            flush=True,
        )

    results.sort(key=lambda x: x[0])
    if results:
        best_rate, best_kw = results[-1]
        print(f"\nBEST: {best_rate:.3e} trees-rows/s  {best_kw}")
        # achieved fraction of the kernel's VPU/VMEM roofline (the bound
        # the tuning is chasing — see roofline.py for the cost model)
        from roofline import report

        from symbolicregression_jl_tpu.ops.pallas_eval import _SLOT_UNROLL

        program = best_kw.get("program", "postfix")
        if program.startswith("instr"):
            from symbolicregression_jl_tpu.ops.pallas_eval import (
                instruction_schedule,
            )

            _, n_instr = instruction_schedule(trees, ops)
            lens = np.asarray(jax.device_get(n_instr), dtype=np.float64)
        else:
            lens = np.asarray(
                jax.device_get(trees.length), dtype=np.float64
            )
        avg_slots = float(
            np.mean(np.ceil(lens / _SLOT_UNROLL) * _SLOT_UNROLL)
        )
        cdt = best_kw.get("compute_dtype", "float32")
        print(report(ops, avg_slots, best_rate, cdt, program=program))
        if best_kw.get("leaf_skip"):
            print(
                "# note: the roofline model charges the FULL candidate "
                "mux per slot; a leaf_skip/class variant issues fewer "
                "vec-ops, so its true bound is lower and the printed "
                "fraction understates how close the kernel is to it"
            )


if __name__ == "__main__":
    main()
