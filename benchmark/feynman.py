#!/usr/bin/env python
"""Feynman-equation recovery benchmark: full `equation_search` runs on
synthetic datasets generated from Feynman-symbolic-regression formulas
(the reference's north-star workload family — BASELINE.json configs 1-2),
reporting per-case solved/loss/time as one JSON line each.

Quality metric = normalized loss of the best frontier member (loss /
var(y)); a case counts as solved below 1e-4. Usage:

    python benchmark/feynman.py [--fast] [--seed N] [--data-seed M]

--fast shrinks the search budget (CI smoke); default budget aims at
recovery on every case on a single chip. --seed seeds BOTH the dataset
sampling and the search; --data-seed pins the dataset independently, so
`--seed 1 --data-seed 0` reproduces the seed-marginality sweeps in
BASELINE.md (same data as the benchmark, different search stream).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (name, n_vars, formula, sampling ranges)
CASES = [
    (
        "I.6.2a",  # exp(-theta^2/2)/sqrt(2*pi)
        1,
        lambda v: np.exp(-(v[0] ** 2) / 2.0) / np.sqrt(2 * np.pi),
        [(1.0, 3.0)],
    ),
    (
        "I.12.5",  # q2 * Ef
        2,
        lambda v: v[0] * v[1],
        [(1.0, 5.0), (1.0, 5.0)],
    ),
    (
        "I.29.4",  # omega / c
        2,
        lambda v: v[0] / v[1],
        [(1.0, 10.0), (1.0, 10.0)],
    ),
    (
        "I.39.1",  # 3/2 * pr * V
        2,
        lambda v: 1.5 * v[0] * v[1],
        [(1.0, 5.0), (1.0, 5.0)],
    ),
    (
        "II.8.31",  # epsilon * Ef^2 / 2
        2,
        lambda v: v[0] * v[1] ** 2 / 2.0,
        [(1.0, 5.0), (1.0, 5.0)],
    ),
    (
        "I.25.13",  # q / C
        2,
        lambda v: v[0] / v[1],
        [(1.0, 10.0), (1.0, 10.0)],
    ),
    (
        "I.6.2",  # exp(-(theta/sigma)^2/2) / (sqrt(2*pi)*sigma)
        2,
        lambda v: np.exp(-((v[0] / v[1]) ** 2) / 2.0)
        / (np.sqrt(2 * np.pi) * v[1]),
        [(1.0, 3.0), (1.0, 3.0)],
    ),
    (
        "I.27.6",  # 1 / (1/d1 + n/d2)
        3,
        lambda v: 1.0 / (1.0 / v[0] + v[2] / v[1]),
        [(1.0, 5.0), (1.0, 5.0), (1.0, 5.0)],
    ),
    (
        "II.3.24",  # Pwr / (4 pi r^2)
        2,
        lambda v: v[0] / (4.0 * np.pi * v[1] ** 2),
        [(1.0, 5.0), (1.0, 5.0)],
    ),
    (
        "I.8.14",  # sqrt((x2-x1)^2 + (y2-y1)^2)
        4,
        lambda v: np.sqrt((v[1] - v[0]) ** 2 + (v[3] - v[2]) ** 2),
        [(1.0, 5.0), (1.0, 5.0), (1.0, 5.0), (1.0, 5.0)],
    ),
    (
        "II.38.14",  # Y / (2 (1 + sigma))
        2,
        lambda v: v[0] / (2.0 + 2.0 * v[1]),
        [(1.0, 5.0), (0.0, 1.0)],
    ),
    (
        "I.34.27",  # (h / (2 pi)) * omega
        2,
        lambda v: v[0] * v[1] / (2.0 * np.pi),
        [(1.0, 5.0), (1.0, 5.0)],
    ),
]


def main():
    from bench import _devices_or_cpu_fallback

    _devices_or_cpu_fallback(verbose=True, use_memo=True)  # hung-tunnel watchdog

    import symbolicregression_jl_tpu as sr

    fast = "--fast" in sys.argv
    seed = 0
    if "--seed" in sys.argv:
        seed = int(sys.argv[sys.argv.index("--seed") + 1])
    data_seed = seed
    if "--data-seed" in sys.argv:
        data_seed = int(sys.argv[sys.argv.index("--data-seed") + 1])

    budget = dict(
        niterations=4 if fast else 12,
        npop=33,
        npopulations=4 if fast else 16,
        ncycles_per_iteration=60 if fast else 300,
        maxsize=16,
    )
    n_rows = 256

    solved = 0
    for name, n_vars, fn, ranges in CASES:
        rng = np.random.default_rng(data_seed)
        X = np.stack(
            [rng.uniform(lo, hi, n_rows) for lo, hi in ranges]
        ).astype(np.float32)
        y = fn(X).astype(np.float32)
        var = float(np.var(y))

        t0 = time.time()
        res = sr.equation_search(
            X,
            y,
            binary_operators=["+", "-", "*", "/"],
            unary_operators=["cos", "exp", "sqrt"],
            seed=seed,
            verbosity=0,
            progress=False,
            runtests=False,
            early_stop_condition=1e-6 * var,
            **budget,
        )
        dt = time.time() - t0
        best = res.best_loss()
        norm_loss = best.loss / max(var, 1e-12)
        ok = norm_loss < 1e-4
        solved += ok
        print(
            json.dumps(
                {
                    "case": name,
                    "solved": bool(ok),
                    "norm_loss": float(f"{norm_loss:.3e}"),
                    "complexity": best.complexity,
                    "equation": best.equation,
                    "seconds": round(dt, 1),
                    "num_evals": round(res.num_evals),
                }
            ),
            flush=True,
        )
    print(
        json.dumps({"suite": "feynman", "solved": solved, "of": len(CASES)}),
        flush=True,
    )


if __name__ == "__main__":
    main()
